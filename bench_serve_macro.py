"""Macro serving benchmark: the cluster witness. Writes
BENCH_SERVE_MACRO.json.

The micro benches (bench_serve.py, bench_serve_ft.py) measure one
mechanism at a time; this one drives the whole stack the way traffic
actually arrives — open-loop arrivals against multi-replica streaming
deployments, multi-tenant heavy-tailed request shapes, chaos replayed
from the trace itself — and then audits the stack's own story: the
observatory's six-phase attribution is reconciled against client stamp
cards, so lost time cannot hide server-side. Three probes, each with
an explicit pass/fail gate:

  1. trace record/replay: a ramp + flash-crowd + chaos scenario is
     generated, written to JSONL, and regenerated from its own header.
     Gate: the bytes match exactly (byte-identical replay).
  2. sustained macro run: a 3-replica streaming app takes an open-loop
     Poisson trace at sustained QPS; every client stamp card is joined
     by rid against the server's phase records. Gates: p99
     gap_fraction <= 0.05 (at most 5% of client-observed latency
     unattributed), and >= 95% of offered requests complete ok.
  3. chaos macro run: the autoscaler-managed app replays a ramp trace
     whose header carries kill_replica@t and drop_controller@t; the
     signals-driven autoscaler (PR 11) tracks the curve while the
     faults fire on schedule. Gates: client TTFB p99 stays bounded,
     the longest client-observed success-free window after the kill
     (recovery) stays under RECOVERY_LIMIT_S, zero lost non-shed
     requests, and both scheduled faults actually fired.

Run: python bench_serve_macro.py [--quick]  (--quick: shorter phases,
no artifact). Exits non-zero when a gate fails.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import threading
import time

SUSTAIN_QPS = 8.0           # flat offered rate, probe 2
SUSTAIN_S = 12.0            # probe 2 duration
RAMP_FROM_QPS = 4.0         # probe 3 ramp start
RAMP_TO_QPS = 12.0          # probe 3 ramp end
CHAOS_S = 14.0              # probe 3 duration
KILL_AT_S = 5.0             # replica SIGKILL offset in the trace
CTRL_DROP_AT_S = 8.0        # controller kill+restart offset
WORKERS = 32                # open-loop dispatch pool
TTFB_LIMIT_S = 3.0          # chaos-phase client TTFB p99 bound
RECOVERY_LIMIT_S = 5.0      # longest success-free window after the kill

# The simulated model: prefill scales with prompt tokens, decode is a
# fixed per-token cadence. Tuned so a typical request runs a few
# hundred ms — long enough that client-side dispatch overhead must be
# well-attributed to pass the 5% gap gate, short enough to keep the
# bench under a couple of minutes.
PREFILL_FLOOR_S = 0.08
PREFILL_S_PER_TOKEN = 2e-4
DECODE_S_PER_TOKEN = 0.012
MAX_DECODE_TOKENS = 24


def _pct(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def _blend():
    """A bounded version of the stock two-tenant blend: same shape
    (interactive 80% short, batch 20% long heavy-tail) with token caps
    that keep the simulated run inside the bench budget."""
    from ray_tpu.loadgen import LengthMix, TenantBlend

    return TenantBlend([
        {"name": "interactive", "weight": 0.8,
         "prompt": LengthMix(median=48, sigma=0.5, lo=8, hi=256,
                             tail_p=0.0),
         "output": LengthMix(median=8, sigma=0.4, lo=2, hi=24,
                             tail_p=0.0)},
        {"name": "batch", "weight": 0.2,
         "prompt": LengthMix(median=256, sigma=0.6, lo=32, hi=1024,
                             tail_p=0.05, tail_lo=512, tail_hi=1024),
         "output": LengthMix(median=16, sigma=0.5, lo=4, hi=24,
                             tail_p=0.0)},
    ])


def _witness_deployment(name, **kwargs):
    """A streaming deployment that simulates LLM work: prompt-scaled
    prefill sleep, then a fixed per-token decode cadence."""
    from ray_tpu import serve

    @serve.deployment(name=name, **kwargs)
    class Witness:
        def __call__(self, request):
            p = int(request.get("prompt_tokens", 64))
            n = min(int(request.get("max_tokens", 8)), MAX_DECODE_TOKENS)
            time.sleep(PREFILL_FLOOR_S + p * PREFILL_S_PER_TOKEN)
            for i in range(max(n, 1)):
                time.sleep(DECODE_S_PER_TOKEN)
                yield i

    return Witness


def probe_trace_replay(results, quick: bool):
    """Record a full scenario and replay it from its own header —
    byte-identically, chaos schedule included."""
    from ray_tpu.loadgen import RateCurve, TraceSpec
    from ray_tpu.loadgen import trace as trace_mod

    curve = RateCurve(
        base_qps=RAMP_FROM_QPS, ramp_to_qps=RAMP_TO_QPS,
        ramp_s=CHAOS_S * 0.7, diurnal_amplitude=0.2,
        diurnal_period_s=60.0,
        flash=[(CHAOS_S * 0.5, 2.0, 2.0)])
    spec = TraceSpec(
        seed=20260807, duration_s=CHAOS_S, curve=curve, blend=_blend(),
        chaos=[
            {"kind": "kill_replica", "t": KILL_AT_S,
             "kwargs": {"app": "Macro"}},
            {"kind": "drop_controller", "t": CTRL_DROP_AT_S,
             "kwargs": {"restart": True}},
        ])
    header, records = trace_mod.generate(spec)
    header2, records2 = trace_mod.generate(
        TraceSpec.from_header(header))
    same_regen = trace_mod.dumps(header, records) == trace_mod.dumps(
        header2, records2)
    with tempfile.NamedTemporaryFile(
            mode="w", suffix=".jsonl", delete=False) as f:
        path = f.name
        f.write(trace_mod.dumps(header, records))
    try:
        with open(path, "rb") as f:
            on_disk = f.read()
        replayed = trace_mod.regenerate_bytes(path)
    finally:
        os.unlink(path)
    entry = {
        "metric": "trace record/replay byte identity",
        "requests": len(records),
        "trace_bytes": len(on_disk),
        "chaos_entries": len(header["chaos"]),
        "same_spec_regenerates_identically": same_regen,
        "replay_bytes_match": replayed == on_disk,
        "gate": "replay_bytes_match and same_spec_regenerates_identically",
        "pass": same_regen and replayed == on_disk,
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_sustained(results, quick: bool):
    """Sustained open-loop QPS with full client<->server latency
    reconciliation — the gap-fraction gate."""
    from ray_tpu import serve
    from ray_tpu.loadgen import (
        GAP_FRACTION_LIMIT,
        RateCurve,
        TraceSpec,
        collect_server_records,
        reconcile,
        run_trace,
        serve_call_fn,
    )
    from ray_tpu.loadgen import trace as trace_mod

    dur = 6.0 if quick else SUSTAIN_S
    qps = 5.0 if quick else SUSTAIN_QPS
    spec = TraceSpec(seed=7, duration_s=dur, curve=RateCurve(qps),
                     blend=_blend())
    header, records = trace_mod.generate(spec)

    dep = _witness_deployment("Witness", num_replicas=3)
    h = serve.run(dep.bind(), name="Witness")
    list(h.options(stream=True).remote({"prompt_tokens": 8,
                                        "max_tokens": 2}))  # warm

    result = run_trace(header, records, serve_call_fn("Witness"),
                       workers=WORKERS)
    server_records = collect_server_records("Witness")
    report = reconcile(result.cards, server_records)
    run = result.summary()
    rec = report["summary"]
    ok_fraction = run["ok"] / run["issued"] if run["issued"] else 0.0
    entry = {
        "metric": "sustained macro QPS with latency reconciliation",
        "duration_s": dur,
        "offered_qps": round(len(records) / dur, 2),
        "achieved_qps": round(run["achieved_qps"], 2),
        "issued": run["issued"],
        "ok": run["ok"],
        "errors": run["errors"],
        "shed": run["shed"],
        "by_tenant": run["by_tenant"],
        "client_e2e_p50_ms": round(run["client_e2e_s"]["p50"] * 1e3, 1),
        "client_e2e_p99_ms": round(run["client_e2e_s"]["p99"] * 1e3, 1),
        "client_ttfb_p50_ms": round(run["client_ttfb_s"]["p50"] * 1e3, 1),
        "client_ttfb_p99_ms": round(run["client_ttfb_s"]["p99"] * 1e3, 1),
        "reconciled": rec["matched"],
        "unmatched": rec["unmatched"],
        "gap_p50_ms": round(rec["gap_s"]["p50"] * 1e3, 2),
        "gap_p99_ms": round(rec["gap_s"]["p99"] * 1e3, 2),
        "gap_fraction_p50": round(rec["gap_fraction"]["p50"], 4),
        "gap_fraction_p99": round(rec["gap_fraction"]["p99"], 4),
        "gap_limit": GAP_FRACTION_LIMIT,
        "gate": "gap_fraction_p99 <= 0.05 (reconciler gate_pass) and "
                "ok/issued >= 0.95",
        "pass": bool(rec["gate_pass"]) and ok_fraction >= 0.95,
    }
    print(json.dumps(entry))
    results.append(entry)
    serve.delete("Witness")


def probe_chaos_macro(results, quick: bool):
    """Ramp + flash-crowd trace replayed against an autoscaled app
    while the trace's own chaos schedule kills a replica and the
    controller mid-run."""
    import ray_tpu as rt
    from ray_tpu import serve
    from ray_tpu._private import chaos
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.loadgen import (
        RateCurve,
        TraceSpec,
        apply_chaos_schedule,
        collect_server_records,
        reconcile,
        run_trace,
        serve_call_fn,
    )
    from ray_tpu.loadgen import trace as trace_mod
    from ray_tpu.serve.deployment import AutoscalingConfig
    from ray_tpu.serve.observatory import SIGNALS_KEY

    dur = 8.0 if quick else CHAOS_S
    kill_at = min(KILL_AT_S, dur * 0.4)
    drop_at = min(CTRL_DROP_AT_S, dur * 0.6)
    curve = RateCurve(
        base_qps=RAMP_FROM_QPS, ramp_to_qps=RAMP_TO_QPS,
        ramp_s=dur * 0.7, flash=[(dur * 0.75, 2.0, 1.5)])
    spec = TraceSpec(
        seed=11, duration_s=dur, curve=curve, blend=_blend(),
        chaos=[
            {"kind": "kill_replica", "t": kill_at,
             "kwargs": {"app": "Macro"}},
            {"kind": "drop_controller", "t": drop_at,
             "kwargs": {"restart": True}},
        ])
    header, records = trace_mod.generate(spec)

    dep = _witness_deployment(
        "Macro", num_replicas=2,
        autoscaling_config=AutoscalingConfig(
            min_replicas=2, max_replicas=4,
            target_ongoing_requests=2.0, upscale_delay_s=1.0,
            downscale_delay_s=60.0))
    h = serve.run(dep.bind(), name="Macro")
    list(h.options(stream=True).remote({"prompt_tokens": 8,
                                        "max_tokens": 2}))  # warm

    # Sample the autoscaler's view (the published ServeSignals doc)
    # through the run — the recorded trajectory shows it tracking the
    # offered curve through both faults.
    trajectory, stop = [], threading.Event()

    def sampler():
        t0 = time.perf_counter()
        while not stop.is_set():
            try:
                raw = worker_mod.get_client().kv_get(
                    SIGNALS_KEY, ns="serve")
                if raw:
                    app = json.loads(raw).get("apps", {}).get("Macro")
                    if app:
                        trajectory.append({
                            "t": round(time.perf_counter() - t0, 1),
                            "target": app.get("target_replicas"),
                            "running": app.get("running_replicas"),
                        })
            except Exception:  # noqa: BLE001 — the controller is being
                # chaos-killed mid-run; a missed sample is expected.
                pass
            stop.wait(1.0)

    st = threading.Thread(target=sampler, daemon=True)
    st.start()
    chaos.enable()
    try:
        apply_chaos_schedule(header)
        result = run_trace(header, records, serve_call_fn("Macro"),
                           workers=WORKERS)
        faults = chaos.scheduled_faults()
    finally:
        stop.set()
        st.join(timeout=5)
        chaos.disable()
        chaos.clear()

    # The restarted controller re-adopts the app; give collection a
    # few tries while it comes back.
    server_records = []
    for _ in range(10):
        try:
            server_records = collect_server_records("Macro")
            break
        except Exception:  # noqa: BLE001 — controller restart race is
            # the scenario under test; retry until it answers.
            time.sleep(1.0)
    report = reconcile(result.cards, server_records)

    run = result.summary()
    ok = result.ok_cards
    lost = [c for c in result.cards
            if c.error and "ServeOverloadedError" not in c.error]
    # Client-observed recovery: the longest window after the replica
    # kill in which no request completed.
    kill_epoch = result.t0_epoch + kill_at
    completions = sorted(c.send_t + c.client_e2e_s for c in ok)
    after = [t for t in completions if t >= kill_epoch]
    recovery = 0.0
    prev = kill_epoch
    for t in after:
        recovery = max(recovery, t - prev)
        prev = t
    ttfb_p99 = run["client_ttfb_s"]["p99"]
    fired = sum(1 for f in faults if f["fired"])
    targets = [s["target"] for s in trajectory
               if s.get("target") is not None]
    entry = {
        "metric": "chaos macro run: replica + controller death mid-ramp",
        "duration_s": dur,
        "offered_qps_curve": f"{RAMP_FROM_QPS}->{RAMP_TO_QPS} "
                             f"ramp + 1.5x flash",
        "issued": run["issued"],
        "ok": run["ok"],
        "shed": run["shed"],
        "lost_non_shed": len(lost),
        "lost_samples": [c.error for c in lost[:5]],
        "faults_scheduled": len(faults),
        "faults_fired": fired,
        "client_ttfb_p50_ms": round(run["client_ttfb_s"]["p50"] * 1e3, 1),
        "client_ttfb_p99_ms": round(ttfb_p99 * 1e3, 1),
        "client_e2e_p99_ms": round(run["client_e2e_s"]["p99"] * 1e3, 1),
        "recovery_s": round(recovery, 3),
        "reconciled": report["summary"]["matched"],
        "unmatched_dead_replica": report["summary"]["unmatched"],
        "autoscaler_trajectory": trajectory,
        "autoscaler_max_target": max(targets) if targets else None,
        "gate": f"lost_non_shed == 0 and faults_fired == 2 and "
                f"client_ttfb_p99 <= {TTFB_LIMIT_S}s and "
                f"recovery_s <= {RECOVERY_LIMIT_S}",
        "pass": (not lost and fired == len(faults)
                 and ttfb_p99 <= TTFB_LIMIT_S
                 and recovery <= RECOVERY_LIMIT_S),
    }
    print(json.dumps(entry))
    results.append(entry)

    # -- black-box postmortem: the dead replica must have produced an
    # AUTOMATIC bundle (controller replace_dead / breaker-open trigger),
    # and assembling it must reconstruct the injection -> client-observed
    # causal chain across >= 4 distinct processes in one HLC order.
    from ray_tpu.util import journal as journal_mod

    bundle = None
    for _ in range(20):
        try:
            client = worker_mod.get_client()
            pms = client._run(client._gcs_call("get_postmortems", {}))
            cands = [p for p in pms.get("postmortems", [])
                     if p["ts"] >= result.t0_epoch]
            if cands:
                bundle = cands[-1]["bundle"]
                break
        except Exception:  # noqa: BLE001 — controller/GCS still
            # recovering from the injected faults; retry.
            pass
        time.sleep(0.5)
    events, metas, chain = [], [], []
    if bundle:
        # Processes dump asynchronously on the pubsub push; wait for
        # the bundle to stop growing.
        deadline = time.monotonic() + 8.0
        last_n, last_change = -1, time.monotonic()
        while time.monotonic() < deadline:
            try:
                n = len([f for f in os.listdir(bundle)
                         if f.endswith(".jsonl")])
            except OSError:
                n = 0
            if n != last_n:
                last_n, last_change = n, time.monotonic()
            elif n > 0 and time.monotonic() - last_change >= 0.6:
                break
            time.sleep(0.1)
        events, metas = journal_mod.load_bundle(bundle)
        chain = journal_mod.causal_chain(events)
    procs = {(m.get("proc"), m.get("pid")) for m in metas}
    chain_kinds = [e.get("kind") for e in chain]
    entry = {
        "metric": "chaos postmortem: auto-captured causal chain",
        "bundle": os.path.basename(bundle) if bundle else None,
        "events": len(events),
        "processes": len(procs),
        "process_labels": sorted(str(p[0]) for p in procs),
        "chain": chain_kinds,
        "gate": "auto bundle exists, >= 4 processes in one HLC-merged "
                "timeline, chain seeds at the chaos injection",
        "pass": (bundle is not None and len(procs) >= 4
                 and len(chain) >= 3
                 and bool(chain_kinds)
                 and chain_kinds[0].startswith("chaos.")),
    }
    print(json.dumps(entry))
    results.append(entry)
    serve.delete("Macro")


def main():
    quick = "--quick" in sys.argv
    # Size the observatory ring to hold every record of the macro run
    # (satellite of this bench: the ring is env-tunable; replicas
    # inherit the setting).
    os.environ.setdefault("RT_SERVE_OBS_RING", "16384")
    import ray_tpu as rt
    from ray_tpu import serve

    results = []
    probe_trace_replay(results, quick)
    rt.init(num_cpus=8)
    try:
        probe_sustained(results, quick)
        probe_chaos_macro(results, quick)
    finally:
        serve.shutdown()
        rt.shutdown()
    failed = [r["metric"] for r in results if r.get("pass") is False]
    summary = {
        "metric": "macro witness summary",
        "probes": len(results),
        "failed": failed,
        "gate": "all probe gates pass",
        "pass": not failed,
    }
    print(json.dumps(summary))
    results.append(summary)
    if not quick:
        with open("BENCH_SERVE_MACRO.json", "w") as f:
            json.dump(results, f, indent=1)
    if failed:
        print(f"GATE FAILURES: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
