# Repo-level entry points. `make lint` is the pre-merge gate: the
# rtlint static pass over the default target set (ray_tpu/, tools/,
# bench_*.py — against the committed baseline) plus the native store's
# sanitizer stress tests.

PY ?= python
LINT_JOBS ?= 4

.PHONY: lint rtlint lint-stats lint-changed lint-fix sanitizers test \
  fast-test \
  bench-data bench-obs bench-scale bench-serve-obs bench-serve-ft \
  bench-collective bench-multitenant bench-paged-kv bench-serve-macro \
  bench-rollup

lint: rtlint sanitizers

# The gate also drops a SARIF artifact for code-scanning upload.
RTLINT_SARIF ?= rtlint.sarif
rtlint:
	$(PY) -m tools.rtlint --jobs $(LINT_JOBS) --sarif-out $(RTLINT_SARIF)

# Apply the mechanical autofixes (RT004 ref leash, RT013 boundary
# tuple-freeze) in place, then report what is left for a human.
lint-fix:
	$(PY) -m tools.rtlint --jobs $(LINT_JOBS) --fix

# Per-rule found/suppressed/baselined counts over the default targets;
# MIGRATION.md pins these via tools/check_claims.py.
lint-stats:
	$(PY) -m tools.rtlint --jobs $(LINT_JOBS) --stats

# Lint only files changed vs HEAD (plus untracked) — the fast
# inner-loop variant of the gate.
lint-changed:
	$(PY) -m tools.rtlint --changed

# Regenerates BENCH_DATA.json (data->device feed probes); run
# tools/check_claims.py afterwards — MIGRATION.md pins these numbers.
bench-data:
	JAX_PLATFORMS=cpu $(PY) bench_data.py

# Regenerates BENCH_OBS.json (flight-recorder overhead probes); run
# tools/check_claims.py afterwards — MIGRATION.md pins these numbers.
bench-obs:
	JAX_PLATFORMS=cpu $(PY) bench_obs.py

# Appends one bench_rollup trajectory record (every BENCH_*.json gate
# headline) to PROGRESS.jsonl.
bench-rollup:
	$(PY) bench.py --rollup

# Regenerates BENCH_SCALE.json (scalability envelope + control-plane
# profiler decomposition); run tools/check_claims.py afterwards —
# MIGRATION.md pins these numbers.
bench-scale:
	JAX_PLATFORMS=cpu $(PY) bench_scale.py

# Regenerates BENCH_SERVE_OBS.json (request-observatory overhead +
# phase-coverage + HOL probes); run tools/check_claims.py afterwards —
# MIGRATION.md pins these numbers.
bench-serve-obs:
	JAX_PLATFORMS=cpu $(PY) bench_serve_obs.py

# Regenerates BENCH_SERVE_FT.json (survival-plane probes: chaos TTFT,
# shed latency, drain, controller failover); run tools/check_claims.py
# afterwards — MIGRATION.md pins these numbers.
bench-serve-ft:
	JAX_PLATFORMS=cpu $(PY) bench_serve_ft.py

# Regenerates BENCH_MULTITENANT.json (priority preemption: graceful
# reclamation, chip return, three-tenant SLO accounting, hard-kill
# deadline under mid-drain chaos); the bench asserts its own gates. Run
# tools/check_claims.py afterwards — MIGRATION.md pins these numbers.
bench-multitenant:
	JAX_PLATFORMS=cpu $(PY) bench_multitenant.py

# Regenerates BENCH_COLLECTIVE.json (topology-native collectives:
# algorithm selection, sharded-hier DCN bytes, quantized wire); the
# bench asserts its own gates. Run tools/check_claims.py afterwards —
# MIGRATION.md pins these numbers.
bench-collective:
	JAX_PLATFORMS=cpu $(PY) bench_collective.py

# Regenerates BENCH_PAGED_KV.json (paged KV engine: mixed-length
# concurrency at equal HBM, shared-prefix TTFT, HOL, autoscaler ramp,
# page-leak gate); the bench asserts its own gates. Run
# tools/check_claims.py afterwards — MIGRATION.md pins these numbers.
bench-paged-kv:
	JAX_PLATFORMS=cpu $(PY) bench_paged_kv.py

# Regenerates BENCH_SERVE_MACRO.json (the cluster witness: trace
# record/replay byte identity, sustained-QPS client<->server latency
# reconciliation, chaos replay with autoscaler tracking); the bench
# asserts its own gates. Run tools/check_claims.py afterwards —
# MIGRATION.md pins these numbers.
bench-serve-macro:
	JAX_PLATFORMS=cpu $(PY) bench_serve_macro.py

sanitizers:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_native_sanitizers.py \
	  -q -m sanitizer -p no:cacheprovider

fast-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m "not slow" \
	  -p no:cacheprovider

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -p no:cacheprovider
