# Repo-level entry points. `make lint` is the pre-merge gate: the
# rtlint static pass over ray_tpu/ (against the committed baseline)
# plus the native store's sanitizer stress tests.

PY ?= python

.PHONY: lint rtlint sanitizers test fast-test

lint: rtlint sanitizers

rtlint:
	$(PY) -m tools.rtlint ray_tpu/

sanitizers:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/test_native_sanitizers.py \
	  -q -m sanitizer -p no:cacheprovider

fast-test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m "not slow" \
	  -p no:cacheprovider

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -p no:cacheprovider
