"""Serve survival-plane benchmarks under sustained load. Writes
BENCH_SERVE_FT.json.

Fault tolerance is only worth its complexity if the plane keeps its
latency shape while things die, so every probe here runs REAL traffic
against the full serve stack (controller, replicas, handles) and injects
the failure mid-stream — each with an explicit pass/fail gate:

  1. sustained QPS through replica chaos: closed-loop streaming clients
     drive a 3-replica app for a no-chaos baseline phase, then the same
     load while a chaos loop SIGKILLs a replica every ~2 s (the
     controller respawns them; handles resume streams at the delivered
     chunk offset). Gates: p99 TTFT under chaos <= 3x the no-chaos
     baseline, and ZERO lost non-shed requests.
  2. overload burst shed latency: one saturated single-slot replica, a
     burst of requests that must all shed handle-side. The shed decision
     is synchronous and RPC-free, so its price is the admission math
     itself. Gates: every burst request sheds typed, p99 shed decision
     < 5 ms.
  3. graceful drain: replicas with in-flight work are drained directly;
     the drain must wait for the work (duration >= remaining work) and
     the in-flight results must all land. Gate: zero lost in-flight.
  4. controller kill+restart under traffic: a client hammers an app
     while the controller is chaos-killed (restart=True). Handles serve
     cached routes through the outage. Gates: zero failed requests,
     controller back (status() answers) before the phase ends.

Run: python bench_serve_ft.py [--quick]  (--quick: shorter phases, no
artifact). Exits non-zero when a gate fails.
"""

from __future__ import annotations

import json
import statistics
import sys
import threading
import time

BASE_PHASE_S = 8.0        # per traffic phase (baseline / chaos)
CLIENTS = 4               # closed-loop client threads
BURSTS = 300              # shed-latency burst size
KILL_PERIOD_S = 2.0       # replica kill cadence under chaos


def _pct(xs, q):
    if not xs:
        return 0.0
    xs = sorted(xs)
    i = min(len(xs) - 1, int(round(q * (len(xs) - 1))))
    return xs[i]


def probe_chaos_ttft(results, quick: bool):
    """Streaming TTFT under replica chaos vs a clean baseline."""
    from ray_tpu import serve
    from ray_tpu._private import chaos

    phase_s = 3.0 if quick else BASE_PHASE_S

    @serve.deployment(num_replicas=3)
    class Gen:
        def __call__(self, n=4):
            time.sleep(0.1)  # model work before the first token
            yield 0
            for i in range(1, n):
                time.sleep(0.01)
                yield i

    h = serve.run(Gen.bind())
    # Warm: routes cached, replicas imported.
    list(h.options(stream=True).remote(2))

    def run_phase(chaos_on):
        ttfts, lost, done = [], [], [0]
        stop = threading.Event()

        def client():
            while not stop.is_set():
                t0 = time.perf_counter()
                try:
                    it = iter(h.options(stream=True).remote(4))
                    next(it)
                    ttfts.append(time.perf_counter() - t0)
                    for _ in it:
                        pass
                    done[0] += 1
                except Exception as e:  # noqa: BLE001 — tally, gate below
                    from ray_tpu.exceptions import ServeOverloadedError
                    if isinstance(e, ServeOverloadedError):
                        nonlocal_shed[0] += 1
                    else:
                        lost.append(f"{type(e).__name__}: {e}")

        nonlocal_shed = [0]
        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(CLIENTS)]
        kills = [0]

        def killer():
            while not stop.is_set():
                time.sleep(KILL_PERIOD_S)
                if stop.is_set():
                    break
                try:
                    chaos.kill_replica("Gen", 0)
                    kills[0] += 1
                except Exception:  # noqa: BLE001 — replica set in flux
                    pass

        for t in threads:
            t.start()
        kt = None
        if chaos_on:
            kt = threading.Thread(target=killer, daemon=True)
            kt.start()
        time.sleep(phase_s)
        stop.set()
        for t in threads:
            t.join(timeout=60)
        if kt:
            kt.join(timeout=10)
        return ttfts, lost, nonlocal_shed[0], done[0], kills[0]

    base_ttfts, base_lost, _, base_done, _ = run_phase(False)
    chaos.enable()
    try:
        chaos_ttfts, chaos_lost, chaos_shed, chaos_done, kills = \
            run_phase(True)
    finally:
        chaos.disable()
        chaos.clear()
    base_p99 = _pct(base_ttfts, 0.99)
    chaos_p99 = _pct(chaos_ttfts, 0.99)
    ratio = chaos_p99 / base_p99 if base_p99 else float("inf")
    lost = base_lost + chaos_lost
    entry = {
        "metric": "sustained streaming QPS through replica chaos",
        "phase_s": phase_s,
        "clients": CLIENTS,
        "requests_baseline": base_done,
        "requests_chaos": chaos_done,
        "replicas_killed": kills,
        "shed": chaos_shed,
        "baseline_ttft_p50_ms": round(_pct(base_ttfts, 0.5) * 1e3, 2),
        "baseline_ttft_p99_ms": round(base_p99 * 1e3, 2),
        "chaos_ttft_p50_ms": round(_pct(chaos_ttfts, 0.5) * 1e3, 2),
        "chaos_ttft_p99_ms": round(chaos_p99 * 1e3, 2),
        "chaos_over_baseline_p99": round(ratio, 3),
        "lost_non_shed": len(lost),
        "lost_samples": lost[:5],
        "gate": "chaos_over_baseline_p99 <= 3 and lost_non_shed == 0 "
                "and replicas_killed >= 1",
        "pass": ratio <= 3.0 and not lost and kills >= 1,
    }
    print(json.dumps(entry))
    results.append(entry)
    serve.delete("Gen")


def probe_shed_latency(results, quick: bool):
    """Handle-side shed decision latency under an overload burst."""
    from ray_tpu import serve
    from ray_tpu._private.config import get_config
    from ray_tpu.exceptions import ServeOverloadedError

    cfg = get_config()
    saved = cfg.serve_max_queued_per_replica
    cfg.serve_max_queued_per_replica = 1

    @serve.deployment(max_ongoing_requests=1)
    class Busy:
        def __call__(self, s=0.0):
            time.sleep(s)
            return s

    try:
        h = serve.run(Busy.bind())
        h.remote(0.0).result(timeout=60)  # warm route cache
        admitted = [h.remote(3.0), h.remote(3.0)]  # saturate: 1 run + 1 queue
        n = 50 if quick else BURSTS
        shed_lat, not_shed = [], 0
        for _ in range(n):
            t0 = time.perf_counter()
            try:
                h.remote(0.0)  # rtlint: disable=RT004 — fire-and-forget on purpose: the probe only cares about shed latency, not results
                not_shed += 1
            except ServeOverloadedError:
                shed_lat.append(time.perf_counter() - t0)
        for r in admitted:
            r.result(timeout=60)
        p99_ms = _pct(shed_lat, 0.99) * 1e3
        entry = {
            "metric": "overload burst shed decision latency (handle-side)",
            "burst": n,
            "shed": len(shed_lat),
            "not_shed": not_shed,
            "shed_p50_us": round(_pct(shed_lat, 0.5) * 1e6, 1),
            "shed_p99_ms": round(p99_ms, 4),
            "gate": "shed == burst and shed_p99_ms < 5",
            "pass": len(shed_lat) == n and p99_ms < 5.0,
        }
        print(json.dumps(entry))
        results.append(entry)
        serve.delete("Busy")
    finally:
        cfg.serve_max_queued_per_replica = saved


def probe_drain(results, quick: bool):
    """Graceful drain waits for in-flight work; nothing is lost."""
    import ray_tpu as rt
    from ray_tpu.serve.replica import ReplicaActor

    def napper(s):
        time.sleep(s)
        return s

    rounds = 2 if quick else 4
    durations, lost = [], 0
    for i in range(rounds):
        work_s = 0.3 + 0.15 * i
        rep = ReplicaActor.options(max_concurrency=8).remote(napper, (), {})
        refs = [rep.handle_request.remote("__call__", (work_s,), {})
                for _ in range(3)]
        time.sleep(0.1)  # the requests are admitted and executing
        d = rt.get(rep.drain.remote(10.0), timeout=30)
        durations.append(d["duration_s"])
        for ref in refs:
            try:
                assert rt.get(ref, timeout=10) == work_s
            except Exception:  # noqa: BLE001 — a loss is the gate failure
                lost += 1
        rt.kill(rep)
    entry = {
        "metric": "graceful drain with in-flight requests",
        "drains": rounds,
        "inflight_per_drain": 3,
        "drain_p50_s": round(_pct(durations, 0.5), 3),
        "drain_max_s": round(max(durations), 3),
        "lost_inflight": lost,
        "gate": "lost_inflight == 0 and drain_max_s < 10",
        "pass": lost == 0 and max(durations) < 10.0,
    }
    print(json.dumps(entry))
    results.append(entry)


def probe_controller_failover(results, quick: bool):
    """Traffic must flow through a controller kill + restart."""
    from ray_tpu import serve
    from ray_tpu._private import chaos

    phase_s = 4.0 if quick else BASE_PHASE_S

    @serve.deployment(num_replicas=2)
    def echo(x):
        return x + 1

    h = serve.run(echo.bind())
    assert h.remote(1).result(timeout=60) == 2  # routes cached
    ok, failed = [0], []
    stop = threading.Event()

    def client():
        i = 0
        while not stop.is_set():
            try:
                if h.remote(i).result(timeout=60) == i + 1:
                    ok[0] += 1
                else:
                    failed.append("wrong result")
            except Exception as e:  # noqa: BLE001 — tally, gate below
                failed.append(f"{type(e).__name__}: {e}")
            i += 1

    threads = [threading.Thread(target=client, daemon=True)
               for _ in range(2)]
    for t in threads:
        t.start()
    time.sleep(1.0)
    chaos.enable()
    down_t = time.perf_counter()
    try:
        chaos.drop_controller(restart=True)
        # Wait for the restarted controller to answer status() again.
        recovered_s = None
        deadline = time.time() + phase_s
        while time.time() < deadline:
            try:
                if "echo" in serve.status():
                    recovered_s = time.perf_counter() - down_t
                    break
            except Exception:  # noqa: BLE001 — restart races are the probe
                pass
            time.sleep(0.1)
        time.sleep(1.0)  # more traffic against the restored controller
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=60)
        chaos.disable()
        chaos.clear()
    entry = {
        "metric": "controller kill+restart under traffic",
        "requests_ok": ok[0],
        "requests_failed": len(failed),
        "failed_samples": failed[:5],
        "controller_recovery_s": round(recovered_s, 3)
        if recovered_s is not None else None,
        "gate": "requests_failed == 0 and controller_recovery_s != None",
        "pass": not failed and recovered_s is not None,
    }
    print(json.dumps(entry))
    results.append(entry)
    serve.delete("echo")


def main():
    quick = "--quick" in sys.argv
    import ray_tpu as rt
    from ray_tpu import serve

    rt.init(num_cpus=8)
    results = []
    try:
        probe_chaos_ttft(results, quick)
        probe_shed_latency(results, quick)
        probe_drain(results, quick)
        probe_controller_failover(results, quick)
    finally:
        serve.shutdown()
        rt.shutdown()
    total_lost = sum(
        r.get("lost_non_shed", 0) + r.get("lost_inflight", 0)
        + r.get("requests_failed", 0) for r in results
    )
    summary = {
        "metric": "survival plane summary",
        "lost_requests_total": total_lost,
        "gate": "lost_requests_total == 0",
        "pass": total_lost == 0,
    }
    print(json.dumps(summary))
    results.append(summary)
    if not quick:
        with open("BENCH_SERVE_FT.json", "w") as f:
            json.dump(results, f, indent=1)
    failed = [r["metric"] for r in results if r.get("pass") is False]
    if failed:
        print(f"GATE FAILURES: {failed}", file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
