"""Async RL: IMPALA with V-trace on CartPole, plus offline BC reuse.

Env runners sample continuously while the learner consumes whichever
rollouts finish first (no barrier); V-trace corrects the resulting
off-policyness. The collected experience then trains a behavior-cloning
policy offline through ray_tpu.data.

Run: python examples/rl_impala.py
"""

import numpy as np

import ray_tpu as rt
from ray_tpu.rl import BCConfig, IMPALAConfig, episodes_to_dataset


def main():
    rt.init(num_cpus=4)
    algo = (
        IMPALAConfig()
        .environment(lambda: __import__("gymnasium").make("CartPole-v1"),
                     obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=128)
        .training(lr=3e-3, updates_per_iteration=8, rollouts_per_update=2)
        .build()
    )
    for i in range(6):
        result = algo.train()
        print(
            f"iter {result['training_iteration']}: "
            f"return={result['episode_return_mean']:.1f} "
            f"episodes={result['episodes_total']} "
            f"loss={result.get('learner/total_loss', float('nan')):.3f}"
        )
        if result["episode_return_mean"] >= 100.0:
            break
    # Harvest one more round of experience for the offline stage.
    rollouts = algo.pending_rollouts(num=2)
    algo.stop()

    # Offline: clone the final policy's behavior from the collected data.
    ds = episodes_to_dataset(rollouts)
    print(f"offline dataset: {ds.count()} transitions")
    bc = BCConfig().module(obs_dim=4, num_actions=2).build()
    metrics = bc.train_on_dataset(ds, num_epochs=10)
    print(f"behavior cloning accuracy vs collected actions: "
          f"{metrics['accuracy']:.2f}")
    rt.shutdown()


if __name__ == "__main__":
    main()
