"""Serve an LLM with dynamic batching + token streaming over HTTP.

Run: python examples/serve_llm.py
Then:  curl -X POST localhost:8000/llm -d '{"prompt": [1, 7, 42]}'
       curl -N -X POST 'localhost:8000/llm?stream=1' -d '{"prompt": [1, 7, 42]}'
"""

import time
from dataclasses import replace

import numpy as np

import ray_tpu as rt
from ray_tpu import serve


@serve.deployment(max_ongoing_requests=16)
class LLM:
    def __init__(self):
        import jax

        from ray_tpu.models import configs, init_params

        self.cfg = replace(configs.tiny, dtype=np.float32)
        self.params = init_params(jax.random.PRNGKey(0), self.cfg)

    @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
    def generate_batch(self, prompts):
        import jax.numpy as jnp

        from ray_tpu.models import generate

        batch = jnp.asarray(np.stack(prompts), dtype=jnp.int32)
        out = generate(self.params, batch, self.cfg, max_new_tokens=16)
        return [np.asarray(r).tolist() for r in out]

    def __call__(self, prompt):
        return self.generate_batch(np.asarray(prompt, dtype=np.int32))


def main():
    rt.init(num_cpus=4)
    serve.run(LLM.bind(), name="llm")
    addr = serve.start_http_proxy(port=8000)
    print(f"serving at {addr}/llm — ctrl-c to stop")
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        serve.shutdown()
        rt.shutdown()


if __name__ == "__main__":
    main()
