"""Hyperparameter search with Population Based Training.

Run: python examples/tune_pbt.py
"""

import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.train.config import RunConfig
from ray_tpu.tune import PopulationBasedTraining, TuneConfig, Tuner


def objective(config):
    import json, os, tempfile, time

    from ray_tpu.train.checkpoint import Checkpoint

    score, step = 0.0, 0
    ckpt = tune.get_checkpoint()
    if ckpt:
        st = json.load(open(os.path.join(ckpt.path, "s.json")))
        score, step = st["score"], st["step"]
    # The checkpoint was written after completing `step` — resume AFTER it.
    for step in range(step + 1 if ckpt else step, 40):
        score += config["lr"]
        d = tempfile.mkdtemp()
        json.dump({"score": score, "step": step},
                  open(os.path.join(d, "s.json"), "w"))
        tune.report({"score": score},
                    checkpoint=Checkpoint.from_directory(d))
        time.sleep(0.05)


def main():
    rt.init(num_cpus=4)
    pbt = PopulationBasedTraining(
        metric="score", mode="max", perturbation_interval=4,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
    )
    grid = Tuner(
        objective,
        param_space={"lr": tune.grid_search([0.1, 1.0, 10.0, 10.0])},
        tune_config=TuneConfig(metric="score", mode="max", scheduler=pbt),
        run_config=RunConfig(name="pbt-demo"),
    ).fit()
    print("best:", grid.get_best_result().metrics)
    rt.shutdown()


if __name__ == "__main__":
    main()
