"""Distributed data pipeline: read -> transform -> shuffle -> train ingest.

Run: python examples/data_pipeline.py
"""

import numpy as np

import ray_tpu as rt
from ray_tpu import data as rtd


def main():
    rt.init(num_cpus=4)
    ds = (
        rtd.range(10_000, parallelism=8)
        .map(lambda r: {"x": r["id"] / 10_000.0})
        .add_column("y", lambda r: 2.0 * r["x"] + 1.0)
        .random_shuffle(seed=0)
    )
    for i, batch in enumerate(ds.iter_batches(batch_size=1024)):
        x = np.asarray(batch["x"], dtype=np.float32)
        print(f"batch {i}: {len(x)} rows, mean x={x.mean():.3f}")
        if i >= 3:
            break
    rt.shutdown()


if __name__ == "__main__":
    main()
