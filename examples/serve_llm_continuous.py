"""Continuous-batching LLM serving: requests join a running decode loop.

The upgrade over examples/serve_llm.py's static batcher (the reference's
serve.batching model): a slotted KV cache lets requests enter at any
decode-step boundary and leave when they finish, so mixed arrival times
keep the chip busy — measured 4.4x static batch=1 tokens/s on a v5e chip
(BENCH_INFER.json). Per-request sampling (temperature/top_k/top_p)
shares the same decode batch as greedy requests.

Run: python examples/serve_llm_continuous.py
"""

import threading
import time
from dataclasses import replace

import numpy as np

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.serve.llm import llm_deployment


def load_model():
    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, dtype=np.float32)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def main():
    rt.init(num_cpus=4)
    app = llm_deployment(load_model, num_slots=4, max_len=128,
                         default_max_new_tokens=16)
    handle = serve.run(app, name="llm")

    # Mixed arrivals: three clients fire at staggered times; each joins
    # the running decode loop at the next step boundary.
    results = {}

    def client(name, prompt, delay, **sampling):
        time.sleep(delay)
        t0 = time.perf_counter()
        toks = rt.get(handle.remote(prompt, **sampling), timeout=300)
        results[name] = (toks, time.perf_counter() - t0)

    threads = [
        threading.Thread(target=client, args=("greedy", [1, 7, 42], 0.0)),
        threading.Thread(target=client, args=("sampled", [9, 3], 0.1),
                         kwargs={"temperature": 0.8, "top_k": 40}),
        threading.Thread(target=client, args=("late", [5, 5, 5, 5], 0.3)),
    ]
    [t.start() for t in threads]
    [t.join() for t in threads]
    for name, (toks, dt) in results.items():
        print(f"{name:8s} {dt:5.2f}s  tokens={toks}")

    # Token streaming rides the same engine.
    print("stream:", list(
        handle.options(stream=True, method_name="stream").remote([2, 4, 8])
    ))
    serve.shutdown()
    rt.shutdown()


if __name__ == "__main__":
    main()
