"""Train a Llama-family model with JaxTrainer over a sharded mesh.

Run (CPU mesh):  JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
                     python examples/train_llama.py
On a TPU host the same script uses the real chips.
"""

from dataclasses import replace

import jax
import jax.numpy as jnp
import optax

from ray_tpu.models import configs, init_params, loss_fn, param_logical_axes
from ray_tpu.parallel import MeshConfig, build_mesh, shard_params


def main():
    n = len(jax.devices())
    mesh = build_mesh(MeshConfig.for_devices(n, tp=2 if n % 2 == 0 else 1))
    cfg = replace(
        configs.tiny if jax.devices()[0].platform == "cpu"
        else configs.get_config("llama2-1b"),
        remat=True,
        remat_policy="dots_nobatch",
    )
    params = shard_params(
        init_params(jax.random.PRNGKey(0), cfg), param_logical_axes(cfg), mesh
    )
    opt = optax.adamw(3e-4)
    state = jax.jit(opt.init)(params)

    def step(p, s, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(p, tokens, cfg, mesh)
        updates, s = opt.update(grads, s, p)
        return optax.apply_updates(p, updates), s, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    tokens = jax.random.randint(
        jax.random.PRNGKey(1), (8, min(cfg.max_seq, 128) + 1), 0,
        cfg.vocab_size,
    )
    for i in range(10):
        params, state, loss = jstep(params, state, tokens)
        print(f"step {i}: loss {float(jax.device_get(loss)):.4f}")


if __name__ == "__main__":
    main()
