"""Rainbow-style DQN online, then conservative offline RL from its replay.

Part 1 trains DQN with every extension on (double-Q, dueling or C51,
n-step returns, prioritized replay) on CartPole. Part 2 takes the
continuous-control side: a behavior dataset collected on Pendulum trains
a CQL policy fully offline — the conservative penalty keeps the learned
Q honest on actions the dataset never tried.

Run: python examples/rl_rainbow_offline.py
"""

import gymnasium as gym
import numpy as np

import ray_tpu as rt
from ray_tpu.rl import CQLConfig, DQNConfig, episodes_to_dataset


def rainbow_online():
    algo = (
        DQNConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4,
                     num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=200)
        .training(lr=1e-3, train_batch_size=64, updates_per_iteration=64,
                  learning_starts=400,
                  double_q=True, dueling=True, n_step=3,
                  prioritized_replay=True)
        .exploration(epsilon_start=1.0, epsilon_end=0.05,
                     epsilon_decay_iters=6)
        .build()
    )
    try:
        for _ in range(12):
            r = algo.train()
            print(f"  iter {r['training_iteration']}: "
                  f"return={r['episode_return_mean']:.1f} "
                  f"eps={r['epsilon']:.2f} buffer={r['buffer_size']}")
            if r["episode_return_mean"] >= 150.0:
                break
    finally:
        algo.stop()


def cql_offline():
    # Collect a mediocre behavior dataset: random Pendulum actions.
    env = gym.make("Pendulum-v1")
    rng = np.random.default_rng(0)
    obs, _ = env.reset(seed=0)
    rows = {"obs": [], "actions": [], "rewards": [], "next_obs": [],
            "dones": []}
    for _ in range(2048):
        a_norm = rng.uniform(-1, 1, 1).astype(np.float32)
        nxt, r, term, trunc, _ = env.step(a_norm * 2.0)  # scale to [-2, 2]
        rows["obs"].append(np.asarray(obs, dtype=np.float32))
        rows["actions"].append(a_norm)
        rows["rewards"].append(float(r) / 10.0)
        rows["next_obs"].append(np.asarray(nxt, dtype=np.float32))
        rows["dones"].append(0.0)
        obs = nxt
        if term or trunc:
            obs, _ = env.reset()
    batch = {k: np.stack(v) if k in ("obs", "actions", "next_obs")
             else np.asarray(v, dtype=np.float32) for k, v in rows.items()}
    ds = episodes_to_dataset([batch])
    print(f"  dataset: {ds.count()} transitions")

    algo = (
        CQLConfig()
        .module(obs_dim=3, action_dim=1, action_low=-2.0, action_high=2.0)
        .training(lr=3e-4, cql_alpha=2.0, minibatch_size=256)
        .build()
    )
    for epoch in range(3):
        m = algo.train_on_dataset(ds, num_epochs=1)
        print(f"  epoch {epoch}: q_loss={m['q_loss']:.3f} "
              f"cql_loss={m['cql_loss']:.3f} actor_loss={m['actor_loss']:.3f}")
    acts = algo.compute_actions(batch["obs"][:5])
    print(f"  policy actions on 5 states: {acts[:, 0].round(2)}")


def main():
    rt.init(num_cpus=4)
    try:
        print("Rainbow DQN on CartPole:")
        rainbow_online()
        print("CQL offline on Pendulum:")
        cql_offline()
    finally:
        rt.shutdown()


if __name__ == "__main__":
    main()
