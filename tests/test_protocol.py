"""Wire-protocol unit tests: framed RPC, raw binary responses, write
coalescing/atomicity.

Reference analogs: src/ray/rpc/grpc_server.h request/response framing and
the object-manager chunk streaming path (object_manager.cc) that the
BinResponse fast path replaces.
"""

import asyncio

import pytest

from ray_tpu._private.protocol import (
    BinResponse,
    RpcServer,
    connect,
)


def run(coro):
    return asyncio.run(coro)


def test_basic_call_roundtrip():
    async def main():
        srv = RpcServer()

        async def echo(d, conn):
            return {"got": d}

        srv.register("echo", echo)
        port = await srv.start()
        conn = await connect("127.0.0.1", port)
        try:
            out = await conn.call("echo", {"x": 1, "b": b"\x00\xff"})
            assert out == {"got": {"x": 1, "b": b"\x00\xff"}}
        finally:
            await conn.close()
            await srv.stop()

    run(main())


def test_bin_response_payload_rides_raw():
    """A BinResponse handler returns (header, raw payload) to the caller
    — the payload bytes follow the frame without a msgpack pass."""

    async def main():
        srv = RpcServer()
        payload = bytes(range(256)) * 1024  # 256KB, all byte values

        async def fetch(d, conn):
            off, n = d["offset"], d["size"]
            return BinResponse({"n": n}, payload[off:off + n])

        srv.register("fetch", fetch)
        port = await srv.start()
        conn = await connect("127.0.0.1", port)
        try:
            header, data = await conn.call(
                "fetch", {"offset": 1000, "size": 70000}
            )
            assert header == {"n": 70000}
            assert data == payload[1000:71000]
        finally:
            await conn.close()
            await srv.stop()

    run(main())


def test_bin_responses_interleaved_with_small_frames():
    """Concurrent bin responses + ordinary responses on ONE connection
    must never interleave a foreign frame between a bin header and its
    payload (send_pair atomicity)."""

    async def main():
        srv = RpcServer()
        blob = b"\xab" * (300 * 1024)

        async def big(d, conn):
            return BinResponse({"k": d["k"]}, blob)

        async def small(d, conn):
            return {"k": d["k"]}

        srv.register("big", big)
        srv.register("small", small)
        port = await srv.start()
        conn = await connect("127.0.0.1", port)
        try:
            calls = []
            for i in range(40):
                if i % 3 == 0:
                    calls.append(conn.call("big", {"k": i}))
                else:
                    calls.append(conn.call("small", {"k": i}))
            results = await asyncio.gather(*calls)
            for i, r in enumerate(results):
                if i % 3 == 0:
                    header, data = r
                    assert header == {"k": i}
                    assert data == blob
                else:
                    assert r == {"k": i}
        finally:
            await conn.close()
            await srv.stop()

    run(main())


def test_error_propagates_and_connection_survives():
    async def main():
        srv = RpcServer()

        async def boom(d, conn):
            raise ValueError("kapow")

        async def ok(d, conn):
            return 7

        srv.register("boom", boom)
        srv.register("ok", ok)
        port = await srv.start()
        conn = await connect("127.0.0.1", port)
        try:
            from ray_tpu._private.protocol import RpcError

            with pytest.raises(RpcError, match="kapow"):
                await conn.call("boom", {})
            assert await conn.call("ok", {}) == 7
        finally:
            await conn.close()
            await srv.stop()

    run(main())


def test_large_frame_respects_stream_limit():
    """Frames far beyond asyncio's 64KiB default reader limit flow
    through (rpc_stream_buffer_limit raises it)."""

    async def main():
        srv = RpcServer()

        async def jumbo(d, conn):
            return {"data": b"z" * (8 * 1024 * 1024)}

        srv.register("jumbo", jumbo)
        port = await srv.start()
        conn = await connect("127.0.0.1", port)
        try:
            out = await conn.call("jumbo", {})
            assert len(out["data"]) == 8 * 1024 * 1024
        finally:
            await conn.close()
            await srv.stop()

    run(main())
