"""Parallelism primitive tests on the virtual 8-device CPU mesh.

This is the test strategy SURVEY.md §4.2 calls for: sharding/collective
code paths execute on xla_force_host_platform_device_count=8 CPU devices,
no TPU required.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.parallel import (
    MeshConfig,
    build_mesh,
    logical_to_physical,
    moe_layer,
    pipeline_stages,
    ring_attention,
    shard_params,
    top_k_routing,
    ulysses_attention,
)
from ray_tpu.parallel.ring_attention import reference_attention

pytestmark = pytest.mark.slow  # jax-compile-heavy compute-path tier


def test_mesh_config_factorization():
    cfg = MeshConfig.for_devices(8, tp=2)
    assert cfg.tp == 2 and cfg.fsdp == 4 and cfg.num_devices == 8
    with pytest.raises(ValueError):
        MeshConfig.for_devices(8, tp=3)


def test_build_mesh():
    mesh = build_mesh(MeshConfig(fsdp=4, tp=2))
    assert mesh.shape["fsdp"] == 4
    assert mesh.shape["tp"] == 2
    assert mesh.shape["dp"] == 1


def test_logical_to_physical():
    spec = logical_to_physical(("batch", "seq", "act_heads"))
    assert spec == jax.sharding.PartitionSpec(("dp", "fsdp"), "sp", "tp")


def test_shard_params_places_on_mesh():
    mesh = build_mesh(MeshConfig(fsdp=4, tp=2))
    params = {"w": jnp.ones((8, 16)), "b": jnp.ones((16,))}
    axes = {"w": ("embed", "mlp"), "b": None}
    sharded = shard_params(params, axes, mesh)
    # w: embed->fsdp, mlp->tp
    shard_shape = sharded["w"].sharding.shard_shape(sharded["w"].shape)
    assert shard_shape == (2, 8)  # 8/4, 16/2


@pytest.mark.parametrize("causal", [True, False])
def test_ring_attention_matches_reference(causal):
    mesh = build_mesh(MeshConfig(sp=8))
    key = jax.random.PRNGKey(0)
    b, l, h, d = 2, 64, 4, 16
    q, k, v = (
        jax.random.normal(kk, (b, l, h, d), dtype=jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    expected = reference_attention(q, k, v, causal=causal)
    got = ring_attention(q, k, v, mesh, axis_name="sp", causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_ring_attention_jit_grad():
    mesh = build_mesh(MeshConfig(sp=8))
    b, l, h, d = 1, 32, 2, 8
    q = jax.random.normal(jax.random.PRNGKey(1), (b, l, h, d))

    @jax.jit
    def loss(q):
        out = ring_attention(q, q, q, mesh, axis_name="sp")
        return (out ** 2).sum()

    g = jax.grad(loss)(q)
    assert g.shape == q.shape
    assert bool(jnp.isfinite(g).all())


def test_ulysses_matches_reference():
    mesh = build_mesh(MeshConfig(sp=8))
    key = jax.random.PRNGKey(2)
    b, l, h, d = 2, 64, 8, 16  # heads divisible by sp
    q, k, v = (
        jax.random.normal(kk, (b, l, h, d), dtype=jnp.float32)
        for kk in jax.random.split(key, 3)
    )
    expected = reference_attention(q, k, v, causal=True)
    got = ulysses_attention(q, k, v, mesh, axis_name="sp", causal=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_pipeline_matches_sequential():
    mesh = build_mesh(MeshConfig(pp=4))
    S, M, mb, dim = 4, 8, 4, 16
    key = jax.random.PRNGKey(3)
    ws = jax.random.normal(key, (S, dim, dim)) * 0.1

    def stage_fn(w, x):
        # w is the device-local stack shard: [layers_per_stage=1, dim, dim].
        return jnp.tanh(x @ w[0])

    xs = jax.random.normal(jax.random.PRNGKey(4), (M, mb, dim))
    got = pipeline_stages(stage_fn, ws, xs, mesh, axis_name="pp")
    # Sequential reference
    expected = xs
    for s in range(S):
        expected = jax.vmap(lambda x: stage_fn(ws[s:s + 1], x))(expected)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_top_k_routing_capacity():
    logits = jnp.array([[10.0, 0.0], [10.0, 0.0], [10.0, 0.0], [0.0, 10.0]])
    dispatch, combine, aux = top_k_routing(logits, k=1, capacity=2)
    # Expert 0 over-subscribed (3 tokens, capacity 2): one token dropped.
    assert float(dispatch[:, 0].sum()) == 2.0
    assert float(dispatch[:, 1].sum()) == 1.0
    assert float(aux) > 0


def test_moe_layer_runs_and_balances():
    key = jax.random.PRNGKey(5)
    tokens, d, experts = 32, 16, 4
    x = jax.random.normal(key, (tokens, d))
    router_w = jax.random.normal(jax.random.PRNGKey(6), (d, experts)) * 0.1
    w_experts = jax.random.normal(jax.random.PRNGKey(7), (experts, d, d)) * 0.1

    def expert_fn(w, xin):  # xin: [E, C, D]
        return jnp.einsum("ecd,edf->ecf", xin, w)

    out, aux = moe_layer(x, router_w, expert_fn, w_experts, k=2)
    assert out.shape == (tokens, d)
    assert bool(jnp.isfinite(out).all())


def test_moe_layer_sharded_over_ep():
    mesh = build_mesh(MeshConfig(ep=4))
    from jax.sharding import NamedSharding, PartitionSpec as P

    tokens, d, experts = 32, 16, 4
    x = jax.random.normal(jax.random.PRNGKey(8), (tokens, d))
    router_w = jax.random.normal(jax.random.PRNGKey(9), (d, experts)) * 0.1
    w_experts = jax.device_put(
        jax.random.normal(jax.random.PRNGKey(10), (experts, d, d)) * 0.1,
        NamedSharding(mesh, P("ep")),
    )

    def expert_fn(w, xin):
        return jnp.einsum("ecd,edf->ecf", xin, w)

    @jax.jit
    def run(x, router_w, w_experts):
        out, aux = moe_layer(x, router_w, expert_fn, w_experts, k=2)
        return out, aux

    out, aux = run(x, router_w, w_experts)
    assert out.shape == (tokens, d)

@pytest.mark.slow
def test_pipeline_transformer_trains_and_matches_single_device():
    """The REAL model under pp: loss AND grads must match a single-device
    run (VERDICT r1 weak #4 — pp must be a training capability, not a toy)."""
    import functools
    from dataclasses import replace

    from ray_tpu.models import (
        configs, init_params, loss_fn, param_logical_axes,
    )

    cfg = replace(
        configs.tiny,
        n_layers=4,
        d_model=32,
        d_ff=64,
        vocab_size=128,
        dtype=jnp.float32,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)

    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)

    mesh = build_mesh(MeshConfig(pp=4))
    sharded = shard_params(params, param_logical_axes(cfg), mesh)
    pp_step = jax.jit(
        jax.value_and_grad(functools.partial(loss_fn, cfg=cfg, mesh=mesh))
    )
    pp_loss, pp_grads = pp_step(sharded, tokens)

    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5)
    for path, ref_leaf in jax.tree_util.tree_leaves_with_path(ref_grads):
        pp_leaf = jax.tree_util.tree_leaves_with_path(pp_grads)
        np.testing.assert_allclose(
            np.asarray(jax.device_get(
                dict(jax.tree_util.tree_leaves_with_path(pp_grads))[path]
            )),
            np.asarray(ref_leaf),
            rtol=5e-4, atol=1e-5,
            err_msg=f"grad mismatch at {jax.tree_util.keystr(path)}",
        )

def test_pipeline_composes_with_dp():
    """pp x dp: microbatch batch dims split over dp inside the pipeline;
    loss and grads still match a single-device run."""
    import functools
    from dataclasses import replace

    from ray_tpu.models import (
        configs, init_params, loss_fn, param_logical_axes,
    )

    cfg = replace(
        configs.tiny,
        n_layers=2,
        d_model=32,
        d_ff=64,
        vocab_size=128,
        dtype=jnp.float32,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)

    mesh = build_mesh(MeshConfig(dp=2, pp=2))
    sharded = shard_params(params, param_logical_axes(cfg), mesh)
    pp_loss, pp_grads = jax.jit(
        jax.value_and_grad(functools.partial(loss_fn, cfg=cfg, mesh=mesh))
    )(sharded, tokens)

    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5)
    ref_leaves = jax.tree_util.tree_leaves(ref_grads)
    pp_leaves = jax.tree_util.tree_leaves(jax.device_get(pp_grads))
    for r, p in zip(ref_leaves, pp_leaves):
        np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                   rtol=5e-4, atol=1e-5)


@pytest.mark.slow
def test_pipeline_composes_with_tp():
    """pp x tp: tensor-parallel weight shards inside each pipeline stage;
    loss and grads still match a single-device run."""
    import functools
    from dataclasses import replace

    from ray_tpu.models import (
        configs, init_params, loss_fn, param_logical_axes,
    )

    cfg = replace(
        configs.tiny,
        n_layers=2,
        d_model=32,
        d_ff=64,
        vocab_size=128,
        dtype=jnp.float32,
        remat=False,
    )
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 17), 0,
                                cfg.vocab_size)
    ref_loss, ref_grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)

    mesh = build_mesh(MeshConfig(tp=2, pp=2))
    sharded = shard_params(params, param_logical_axes(cfg), mesh)
    pp_loss, pp_grads = jax.jit(
        jax.value_and_grad(functools.partial(loss_fn, cfg=cfg, mesh=mesh))
    )(sharded, tokens)

    np.testing.assert_allclose(float(pp_loss), float(ref_loss), rtol=1e-5)
    ref_leaves = jax.tree_util.tree_leaves(ref_grads)
    pp_leaves = jax.tree_util.tree_leaves(jax.device_get(pp_grads))
    for r, p in zip(ref_leaves, pp_leaves):
        np.testing.assert_allclose(np.asarray(p), np.asarray(r),
                                   rtol=5e-4, atol=1e-5)


def test_tp_sharded_decode_matches_single_device():
    """KV-cache prefill+decode under tensor parallelism produces the
    SAME tokens as the unsharded model (GSPMD shards heads/hidden; the
    cache follows by propagation) — the serving-on-pods layout."""
    from dataclasses import replace

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs, init_params, param_logical_axes
    from ray_tpu.models.generate import decode_step, init_kv_cache, prefill
    from ray_tpu.parallel import MeshConfig, build_mesh, shard_params

    devices = jax.devices()[:8]
    cfg = replace(configs.tiny, d_model=64, d_ff=128, vocab_size=128,
                  n_layers=2, n_heads=8, n_kv_heads=8, max_seq=64,
                  remat=False)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0,
                                cfg.vocab_size)

    def run(p):
        cache = init_kv_cache(cfg, 2, 48)
        logits, cache = jax.jit(
            lambda pp, t, c: prefill(pp, t, c, cfg)
        )(p, prompt, cache)
        toks = []
        step = jax.jit(lambda pp, t, c: decode_step(pp, t, c, cfg))
        tok = jnp.argmax(logits, -1).astype(jnp.int32)
        for _ in range(6):
            toks.append(np.asarray(tok))
            logits, cache = step(p, tok, cache)
            tok = jnp.argmax(logits, -1).astype(jnp.int32)
        return np.stack(toks)

    base = run(params)
    mesh = build_mesh(MeshConfig(tp=8), devices)
    sharded = shard_params(params, param_logical_axes(cfg), mesh)
    tp = run(sharded)
    np.testing.assert_array_equal(base, tp)
