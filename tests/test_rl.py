"""RL stack tests: learner math, GAE, and a CartPole PPO smoke run.

Reference model: rllib per-algorithm learning tests checked for reward
thresholds (SURVEY.md §4.1) — scaled down for a 1-CPU CI box.
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl import (
    DiscretePolicyModule,
    Learner,
    PPOConfig,
    RLModuleSpec,
    compute_gae,
    ppo_loss,
)


def test_module_forward_shapes():
    spec = RLModuleSpec(obs_dim=4, num_actions=2)
    module = DiscretePolicyModule(spec)
    import jax

    params = module.init(jax.random.PRNGKey(0))
    obs = np.zeros((7, 4), dtype=np.float32)
    out = module.forward(params, obs)
    assert out["action_logits"].shape == (7, 2)
    assert out["value"].shape == (7,)


def test_learner_update_reduces_loss():
    import jax

    spec = RLModuleSpec(obs_dim=4, num_actions=2)
    module = DiscretePolicyModule(spec)
    learner = Learner(module, ppo_loss, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(64, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=64).astype(np.int32),
        "logp": np.full(64, -0.69, dtype=np.float32),
        "advantages": rng.normal(size=64).astype(np.float32),
        "returns": rng.normal(size=64).astype(np.float32),
    }
    m1 = learner.update_from_batch(batch)
    for _ in range(10):
        m2 = learner.update_from_batch(batch)
    assert m2["vf_loss"] < m1["vf_loss"]
    assert np.isfinite(m2["total_loss"])


def test_gae_simple_case():
    batch = {
        "rewards": np.array([1.0, 1.0, 1.0], dtype=np.float32),
        "values": np.array([0.0, 0.0, 0.0], dtype=np.float32),
        "dones": np.array([0.0, 0.0, 1.0], dtype=np.float32),
        "last_value": 5.0,
    }
    out = compute_gae(batch, gamma=1.0, lam=1.0)
    # Terminal at t=2 cuts the bootstrap; returns are reward-to-go.
    np.testing.assert_allclose(out["returns"], [3.0, 2.0, 1.0])


def test_gae_bootstrap_on_truncation():
    batch = {
        "rewards": np.array([0.0, 0.0], dtype=np.float32),
        "values": np.array([0.0, 0.0], dtype=np.float32),
        "dones": np.array([0.0, 0.0], dtype=np.float32),
        "last_value": 10.0,
    }
    out = compute_gae(batch, gamma=0.5, lam=1.0)
    # No terminal: value bootstraps through gamma.
    np.testing.assert_allclose(out["returns"], [2.5, 5.0])


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_ppo_cartpole_improves():
    import gymnasium as gym

    config = (
        PPOConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=256)
        .training(lr=3e-3, num_epochs=4, minibatch_size=128)
    )
    algo = config.build()
    try:
        first = algo.train()
        best = 0.0
        for _ in range(6):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
        # CartPole random policy gets ~20; learning shows clear improvement.
        assert best > first["episode_return_mean"] or best > 60.0, (
            f"no improvement: first={first['episode_return_mean']}, best={best}"
        )
        assert result["episodes_total"] > 0
    finally:
        algo.stop()

def test_vtrace_reduces_to_td_lambda_on_policy():
    """With rho = c = 1 (on-policy, ratios un-clipped), V-trace targets
    equal the lambda=1 discounted-return bootstrap (per the IMPALA paper's
    on-policy special case)."""
    import jax.numpy as jnp

    from ray_tpu.rl import vtrace

    T = 5
    rng = np.random.default_rng(0)
    logp = jnp.asarray(rng.normal(size=T).astype(np.float32))
    rewards = jnp.asarray(rng.normal(size=T).astype(np.float32))
    values = jnp.asarray(rng.normal(size=T).astype(np.float32))
    dones = jnp.zeros(T, dtype=jnp.float32)
    bootstrap = jnp.float32(0.7)
    gamma = 0.9
    vs, _ = vtrace(logp, logp, rewards, values, bootstrap, dones, gamma=gamma)
    # On-policy, no terminals: vs_t = sum_k gamma^k r_{t+k} + gamma^{T-t} * bootstrap.
    expected = np.zeros(T, dtype=np.float64)
    acc = float(bootstrap)
    for t in reversed(range(T)):
        acc = float(rewards[t]) + gamma * acc
        expected[t] = acc
    np.testing.assert_allclose(np.asarray(vs), expected, rtol=1e-5)


def test_vtrace_terminal_cuts_bootstrap():
    import jax.numpy as jnp

    from ray_tpu.rl import vtrace

    logp = jnp.zeros(2, dtype=jnp.float32)
    rewards = jnp.asarray([1.0, 2.0], dtype=jnp.float32)
    values = jnp.zeros(2, dtype=jnp.float32)
    dones = jnp.asarray([0.0, 1.0], dtype=jnp.float32)
    vs, _ = vtrace(logp, logp, rewards, values, jnp.float32(100.0), dones,
                   gamma=1.0)
    # Terminal at t=1: the 100.0 bootstrap must not leak in.
    np.testing.assert_allclose(np.asarray(vs), [3.0, 2.0], rtol=1e-6)


@pytest.mark.slow
def test_impala_cartpole_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import IMPALAConfig

    algo = (
        IMPALAConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=128)
        .training(lr=3e-3, updates_per_iteration=8, rollouts_per_update=2)
        .build()
    )
    try:
        first = algo.train()
        best = 0.0
        for _ in range(8):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert best > first["episode_return_mean"] or best > 60.0, (
            f"no improvement: first={first['episode_return_mean']}, best={best}"
        )
    finally:
        algo.stop()


def test_replay_buffer_ring_and_sampling():
    from ray_tpu.rl import ReplayBuffer

    buf = ReplayBuffer(capacity=10, obs_dim=2, seed=0)
    for start in (0, 6):  # second add wraps the ring
        buf.add_batch({
            "obs": np.full((6, 2), start, dtype=np.float32),
            "next_obs": np.full((6, 2), start + 1, dtype=np.float32),
            "actions": np.arange(start, start + 6, dtype=np.int32),
            "rewards": np.ones(6, dtype=np.float32),
            "dones": np.zeros(6, dtype=np.float32),
        })
    assert len(buf) == 10
    mb = buf.sample(32)
    assert mb["obs"].shape == (32, 2)
    assert set(mb["actions"]) <= set(range(12))


@pytest.mark.slow
def test_dqn_cartpole_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import DQNConfig

    algo = (
        DQNConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=200)
        .training(lr=1e-3, train_batch_size=64, updates_per_iteration=64,
                  learning_starts=400, target_update_freq=2)
        .exploration(epsilon_start=1.0, epsilon_end=0.05,
                     epsilon_decay_iters=6)
        .build()
    )
    try:
        first = None
        best = -1.0
        for _ in range(30):
            result = algo.train()
            if first is None and result["episodes_total"] > 0:
                first = result["episode_return_mean"]
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert result["buffer_size"] > 400
        assert best >= 75.0, (
            f"DQN failed to learn CartPole: first={first} best={best}"
        )
    finally:
        algo.stop()


def test_vector_env_runner_shapes_and_stats(rt_start):
    """N envs per runner, one batched policy call per step: output is
    time-major (T, N, ...) with per-env bootstraps and real episode
    bookkeeping across auto-resets (rllib vectorized EnvRunner analog)."""
    import gymnasium as gym

    from ray_tpu.rl import (
        DiscretePolicyModule,
        RLModuleSpec,
        VectorEnvRunner,
    )
    from ray_tpu.rl.core.learner import Learner

    spec = RLModuleSpec(4, 2, (32,))
    runner = VectorEnvRunner.options(num_cpus=0.5).remote(
        lambda: gym.make("CartPole-v1"),
        lambda: DiscretePolicyModule(spec),
        num_envs=4,
        rollout_length=64,
        seed=3,
    )
    learner = Learner(DiscretePolicyModule(spec), None, seed=0)
    rt.get(runner.set_weights.remote(learner.get_weights()), timeout=120)
    batch = rt.get(runner.sample.remote(), timeout=300)
    assert batch["obs"].shape == (64, 4, 4)
    assert batch["actions"].shape == (64, 4)
    assert batch["logp"].shape == (64, 4)
    assert batch["rewards"].shape == (64, 4)
    assert batch["dones"].shape == (64, 4)
    assert batch["last_values"].shape == (4,)
    assert batch["last_obs"].shape == (4, 4)
    # A 64*4=256-step random CartPole rollout sees episode ends.
    assert batch["dones"].sum() > 0
    stats = rt.get(runner.episode_stats.remote(), timeout=60)
    assert stats["episodes"] > 0
    rt.kill(runner)


@pytest.mark.slow
def test_appo_cartpole_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import APPOConfig

    algo = (
        APPOConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4,
                     num_actions=2)
        .env_runners(num_env_runners=2, num_envs_per_runner=4,
                     rollout_length=64)
        .training(lr=3e-3, updates_per_iteration=8, rollouts_per_update=1)
        .build()
    )
    try:
        first = algo.train()
        best = 0.0
        for _ in range(8):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert best > first["episode_return_mean"] or best > 60.0, (
            f"no improvement: first={first['episode_return_mean']}, "
            f"best={best}"
        )
    finally:
        algo.stop()


class _PickleCartPole:
    """Classic cart-pole dynamics on plain numpy with the gymnasium API
    (reset -> (obs, info), step -> 5-tuple). Env runners cloudpickle the
    live env — RNG state and all — into checkpoints for exact resume
    (env_runner.py:140); gym's own envs may hold unpicklable handles
    depending on build, which used to skip the restore test below. This
    env always pickles, so the bit-identical-resume assertion always
    runs."""

    _GRAV, _MASS_CART, _MASS_POLE = 9.8, 1.0, 0.1
    _HALF_LEN, _FORCE, _DT = 0.5, 10.0, 0.02
    _X_LIM, _THETA_LIM = 2.4, 12 * np.pi / 180.0

    def __init__(self, seed=0):
        self._rng = np.random.default_rng(seed)
        self._state = np.zeros(4, dtype=np.float64)
        self._t = 0

    def reset(self, *, seed=None, options=None):
        if seed is not None:
            self._rng = np.random.default_rng(seed)
        self._state = self._rng.uniform(-0.05, 0.05, size=4)
        self._t = 0
        return self._state.astype(np.float32), {}

    def step(self, action):
        x, x_dot, theta, theta_dot = self._state
        force = self._FORCE if int(action) == 1 else -self._FORCE
        total_m = self._MASS_CART + self._MASS_POLE
        pole_ml = self._MASS_POLE * self._HALF_LEN
        cos_t, sin_t = np.cos(theta), np.sin(theta)
        tmp = (force + pole_ml * theta_dot**2 * sin_t) / total_m
        theta_acc = (self._GRAV * sin_t - cos_t * tmp) / (
            self._HALF_LEN
            * (4.0 / 3.0 - self._MASS_POLE * cos_t**2 / total_m)
        )
        x_acc = tmp - pole_ml * theta_acc * cos_t / total_m
        self._state = np.array([
            x + self._DT * x_dot,
            x_dot + self._DT * x_acc,
            theta + self._DT * theta_dot,
            theta_dot + self._DT * theta_acc,
        ])
        self._t += 1
        terminated = bool(
            abs(self._state[0]) > self._X_LIM
            or abs(self._state[2]) > self._THETA_LIM
        )
        truncated = self._t >= 200
        return self._state.astype(np.float32), 1.0, terminated, truncated, {}


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_ppo_evaluation_and_checkpoint_restore(tmp_path):
    """VERDICT r3 item 6: periodic evaluation on dedicated runners with
    eval metrics in results (reference: algorithm.py:795 +
    evaluation/worker_set.py:82), and Algorithm.save/restore continuing
    mid-train with an identical learning curve. Uses _PickleCartPole so
    the exact-resume path is always exercised (no picklability skip)."""

    def build():
        return (
            PPOConfig()
            .environment(lambda: _PickleCartPole(),
                         obs_dim=4, num_actions=2)
            .env_runners(num_env_runners=1, rollout_length=128)
            .training(lr=3e-3, num_epochs=2, minibatch_size=64)
            .evaluation(evaluation_interval=2,
                        evaluation_num_env_runners=1,
                        evaluation_duration=3)
            .build()
        )

    algo_a = build()
    try:
        r1 = algo_a.train()
        assert "evaluation" not in r1
        r2 = algo_a.train()
        assert "evaluation" in r2, "interval=2 must evaluate on iter 2"
        ev = r2["evaluation"]
        assert ev["episodes_this_eval"] == 3
        assert np.isfinite(ev["episode_return_mean"])
        assert ev["episode_return_max"] >= ev["episode_return_mean"] >= (
            ev["episode_return_min"]
        )

        ckpt = algo_a.save(str(tmp_path / "ckpt"))
        r3a = algo_a.train()
    finally:
        algo_a.stop()

    algo_b = build()
    try:
        algo_b.restore(ckpt)
        assert algo_b._iteration == 2
        r3b = algo_b.train()
        assert r3b["training_iteration"] == r3a["training_iteration"] == 3
        # Identical continuation: same rollout stream + same learner state
        # => same losses and same episode statistics.
        for k in r3a:
            if k.startswith("learner/"):
                np.testing.assert_allclose(
                    r3b[k], r3a[k], rtol=1e-4,
                    err_msg=f"{k} diverged after restore",
                )
        assert r3b["episode_return_mean"] == pytest.approx(
            r3a["episode_return_mean"], rel=1e-6
        )
    finally:
        algo_b.stop()


class PixelSideEnv:
    """Tiny image-observation env: a bright dot appears on the left or
    right half of a 12x12 frame; action must name the side (0=left,
    1=right) for +1. Gymnasium-shaped API (reset/step 5-tuple).
    Episodes are 16 steps; random policy averages ~8."""

    H = W = 12

    def __init__(self, seed=0):
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._side = 0

    def _obs(self):
        img = np.zeros((self.H, self.W, 1), dtype=np.float32)
        row = int(self._rng.integers(2, self.H - 2))
        col_half = int(self._rng.integers(1, self.W // 2 - 1))
        col = col_half if self._side == 0 else self.W // 2 + col_half
        img[row - 1:row + 2, col - 1:col + 2, 0] = 1.0
        return img

    def reset(self, *, seed=None, options=None):
        self._t = 0
        self._side = int(self._rng.integers(2))
        return self._obs(), {}

    def step(self, action):
        reward = 1.0 if int(action) == self._side else 0.0
        self._t += 1
        self._side = int(self._rng.integers(2))
        done = self._t >= 16
        return self._obs(), reward, done, False, {}


def test_conv_module_forward_shapes():
    import jax

    from ray_tpu.rl.core.rl_module import ConvModuleSpec, ConvPolicyModule

    spec = ConvModuleSpec((12, 12, 1), num_actions=2)
    mod = ConvPolicyModule(spec)
    params = mod.init(jax.random.PRNGKey(0))
    obs = np.zeros((5, 12, 12, 1), dtype=np.float32)
    out = mod.forward(params, obs)
    assert out["action_logits"].shape == (5, 2)
    assert out["value"].shape == (5,)
    a, logp, v = mod.sample_action(params, obs, jax.random.PRNGKey(1))
    assert a.shape == (5,) and logp.shape == (5,) and v.shape == (5,)


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_ppo_pixel_env_improves():
    """Image-observation PPO (the 'RLlib PPO Atari' north-star shape,
    BASELINE.json configs: conv torso via obs_shape= instead of
    obs_dim=). Random policy scores ~8/16 on PixelSideEnv; a learned
    conv policy must clearly beat it."""
    config = (
        PPOConfig()
        .environment(lambda: PixelSideEnv(), obs_shape=(12, 12, 1),
                     num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=128)
        .training(lr=3e-3, num_epochs=4, minibatch_size=64)
    )
    algo = config.build()
    try:
        best = 0.0
        for _ in range(14):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 12.0:
                break
        assert best >= 12.0, f"conv policy failed to learn: best={best}"
    finally:
        algo.stop()


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_dqn_pixel_env_learns():
    """Pixel DQN smoke: conv Q-network + image replay buffer wire up
    and improve on PixelSideEnv."""
    from ray_tpu.rl.algorithms.dqn import DQNConfig

    config = (
        DQNConfig()
        .environment(lambda: PixelSideEnv(), obs_shape=(12, 12, 1),
                     num_actions=2)
        .env_runners(num_env_runners=1, rollout_length=128)
        .training(lr=3e-3, train_batch_size=64, updates_per_iteration=16,
                  learning_starts=128, buffer_capacity=4096)
        .exploration(epsilon_start=1.0, epsilon_end=0.05,
                     epsilon_decay_iters=4)
    )
    algo = config.build()
    try:
        best = 0.0
        for _ in range(8):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 12.0:
                break
        assert best >= 12.0, f"pixel DQN failed to learn: best={best}"
    finally:
        algo.stop()


class CueRecallEnv:
    """Memory task: step 0 shows a cue (+1 or -1 in obs[0]); all later
    observations are zeros except a countdown in obs[1]. At the FINAL
    step the agent must pick the action matching the cue for +1. A
    memoryless (MLP) policy sees identical observations at decision
    time for both cues, so it cannot exceed 0.5 mean return; a
    recurrent policy carries the cue in its state."""

    LEN = 4

    def __init__(self, seed=0):
        self._rng = np.random.default_rng(seed)
        self._t = 0
        self._cue = 1

    def _obs(self):
        o = np.zeros(3, dtype=np.float32)
        if self._t == 0:
            o[0] = float(self._cue)
        o[1] = (self.LEN - self._t) / self.LEN
        o[2] = 1.0 if self._t == self.LEN - 1 else 0.0
        return o

    def reset(self, *, seed=None, options=None):
        self._t = 0
        self._cue = 1 if self._rng.integers(2) else -1
        return self._obs(), {}

    def step(self, action):
        reward = 0.0
        done = False
        if self._t == self.LEN - 1:
            reward = 1.0 if (int(action) == (1 if self._cue > 0 else 0)) \
                else 0.0
            done = True
        self._t += 1
        return self._obs(), reward, done, False, {}


def test_recurrent_module_seq_matches_steps():
    """forward_seq replays exactly what step-wise collection computed,
    including a done-driven state reset mid-window."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl.core.rl_module import (
        RecurrentModuleSpec, RecurrentPolicyModule,
    )

    spec = RecurrentModuleSpec(obs_dim=3, num_actions=2, state_dim=8)
    mod = RecurrentPolicyModule(spec)
    params = mod.init(jax.random.PRNGKey(0))
    T = 6
    rng = np.random.default_rng(0)
    obs = rng.normal(size=(T, 3)).astype(np.float32)
    dones = np.array([0, 0, 1, 0, 0, 0], dtype=np.float32)

    # Step-wise, resetting after the done step (as the runner does).
    h = mod.initial_state(1)
    step_values = []
    for t in range(T):
        out, h = mod.forward_step(params, obs[t][None], h)
        step_values.append(float(out["value"][0]))
        if dones[t]:
            h = mod.initial_state(1)

    seq = mod.forward_seq(
        params, jnp.asarray(obs)[None], mod.initial_state(1),
        jnp.asarray(dones)[None],
    )
    np.testing.assert_allclose(
        np.asarray(seq["value"])[0], step_values, rtol=1e-5
    )


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_recurrent_ppo_learns_memory_task_where_mlp_fails():
    """CueRecallEnv: recurrent PPO must clearly beat the 0.5 ceiling of
    any memoryless policy; plain (MLP) PPO must stay at that ceiling —
    the pairing that proves the state is doing the work."""
    from ray_tpu.rl.algorithms.recurrent_ppo import RecurrentPPOConfig

    mlp = (
        PPOConfig()
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=128)
        .training(lr=3e-3, num_epochs=4, minibatch_size=64)
    ).build()
    try:
        tail = []
        for _ in range(6):
            r = mlp.train()
            tail.append(r["episode_return_mean"])
    finally:
        mlp.stop()
    # Mean over the last 3 iterations: a single 20-episode window of
    # Bernoulli(0.5) episodes has std ~0.11, so a one-shot max would
    # false-positive on noise.
    mlp_level = float(np.mean(tail[-3:]))
    assert mlp_level <= 0.75, (
        f"memoryless PPO should cap near 0.5 on CueRecallEnv, got "
        f"{mlp_level} — the env leaks the cue"
    )

    rec = (
        RecurrentPPOConfig(state_dim=16)
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=128)
        .training(lr=5e-3, num_epochs=6)
    ).build()
    try:
        best = 0.0
        for _ in range(14):
            r = rec.train()
            best = max(best, r["episode_return_mean"])
            if best >= 0.9:
                break
        assert best >= 0.9, (
            f"recurrent PPO failed the memory task: best={best}"
        )
    finally:
        rec.stop()


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_recurrent_ppo_evaluation_and_runner_state():
    """Recurrent evaluation threads the GRU state (greedy path), and
    runner checkpoint state round-trips the policy state."""
    from ray_tpu.rl.algorithms.recurrent_ppo import RecurrentPPOConfig

    algo = (
        RecurrentPPOConfig(state_dim=8)
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=1, rollout_length=32)
        .training(lr=3e-3, num_epochs=1)
        .evaluation(evaluation_interval=1, evaluation_duration=3)
    ).build()
    try:
        result = algo.train()
        assert "evaluation" in result
        assert result["evaluation"]["episodes_this_eval"] == 3
        # Runner state round-trip carries the GRU state.
        states = rt.get(
            [r.get_runner_state.remote() for r in algo.env_runners],
            timeout=120,
        )
        assert states[0]["policy_state"] is not None
        assert rt.get(
            algo.env_runners[0].set_runner_state.remote(states[0]),
            timeout=120,
        )
    finally:
        algo.stop()


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_ppo_tuned_via_tuner(tmp_path):
    """RL algorithms ride Tune like the reference (Algorithm is a Tune
    Trainable): Tuner grid-searches PPO's lr on CueRecallEnv and the
    best trial's config is recoverable."""
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    config = (
        PPOConfig()
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=1, rollout_length=64)
        .training(num_epochs=2, minibatch_size=32)
    )
    tuner = tune.Tuner(
        config.as_trainable(stop_iters=2),
        param_space={"lr": tune.grid_search([1e-3, 3e-3])},
        tune_config=tune.TuneConfig(
            metric="episode_return_mean", mode="max"
        ),
        run_config=RunConfig(name="rl_tune", storage_path=str(tmp_path)),
    )
    results = tuner.fit()
    best = results.get_best_result()
    assert "episode_return_mean" in best.metrics
    # Both lr trials ran with their sampled configs.
    lrs = {row["config/lr"] for row in results.get_dataframe()}
    assert lrs == {1e-3, 3e-3}
    # param_space keys are validated against config fields.
    bad = tune.Tuner(
        config.as_trainable(stop_iters=1),
        param_space={"not_a_field": tune.grid_search([1])},
        run_config=RunConfig(name="rl_bad", storage_path=str(tmp_path)),
    )
    bad_results = bad.fit()
    errs = [r.error for r in bad_results if r.error is not None]
    assert errs and "not_a_field" in str(errs[0])


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_rl_trainable_checkpoints_and_resources():
    """The RL Tune adapter reports Algorithm.save checkpoints (so trial
    restarts resume from learned state), rejects builder-method keys,
    and carries with_resources through the config dispatch."""
    from ray_tpu import tune
    from ray_tpu.train.config import RunConfig

    config = (
        PPOConfig()
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=1, rollout_length=32)
        .training(num_epochs=1, minibatch_size=32)
    )
    # with_resources rides the config's as_trainable dispatch.
    pinned = tune.with_resources(config, {"CPU": 0.5})
    fn = pinned.as_trainable(stop_iters=1)
    assert fn._tune_resources == {"CPU": 0.5}

    # Builder-method names are rejected as param_space keys.
    bad = tune.Tuner(
        config.as_trainable(stop_iters=1),
        param_space={"training": tune.grid_search([0.1])},
        run_config=RunConfig(name="rl_bad2",
                             storage_path="/tmp/rl_bad2_store"),
    )
    errs = [r.error for r in bad.fit() if r.error is not None]
    assert errs and "training" in str(errs[0])
