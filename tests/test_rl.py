"""RL stack tests: learner math, GAE, and a CartPole PPO smoke run.

Reference model: rllib per-algorithm learning tests checked for reward
thresholds (SURVEY.md §4.1) — scaled down for a 1-CPU CI box.
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl import (
    DiscretePolicyModule,
    Learner,
    PPOConfig,
    RLModuleSpec,
    compute_gae,
    ppo_loss,
)


def test_module_forward_shapes():
    spec = RLModuleSpec(obs_dim=4, num_actions=2)
    module = DiscretePolicyModule(spec)
    import jax

    params = module.init(jax.random.PRNGKey(0))
    obs = np.zeros((7, 4), dtype=np.float32)
    out = module.forward(params, obs)
    assert out["action_logits"].shape == (7, 2)
    assert out["value"].shape == (7,)


def test_learner_update_reduces_loss():
    import jax

    spec = RLModuleSpec(obs_dim=4, num_actions=2)
    module = DiscretePolicyModule(spec)
    learner = Learner(module, ppo_loss, seed=0)
    rng = np.random.default_rng(0)
    batch = {
        "obs": rng.normal(size=(64, 4)).astype(np.float32),
        "actions": rng.integers(0, 2, size=64).astype(np.int32),
        "logp": np.full(64, -0.69, dtype=np.float32),
        "advantages": rng.normal(size=64).astype(np.float32),
        "returns": rng.normal(size=64).astype(np.float32),
    }
    m1 = learner.update_from_batch(batch)
    for _ in range(10):
        m2 = learner.update_from_batch(batch)
    assert m2["vf_loss"] < m1["vf_loss"]
    assert np.isfinite(m2["total_loss"])


def test_gae_simple_case():
    batch = {
        "rewards": np.array([1.0, 1.0, 1.0], dtype=np.float32),
        "values": np.array([0.0, 0.0, 0.0], dtype=np.float32),
        "dones": np.array([0.0, 0.0, 1.0], dtype=np.float32),
        "last_value": 5.0,
    }
    out = compute_gae(batch, gamma=1.0, lam=1.0)
    # Terminal at t=2 cuts the bootstrap; returns are reward-to-go.
    np.testing.assert_allclose(out["returns"], [3.0, 2.0, 1.0])


def test_gae_bootstrap_on_truncation():
    batch = {
        "rewards": np.array([0.0, 0.0], dtype=np.float32),
        "values": np.array([0.0, 0.0], dtype=np.float32),
        "dones": np.array([0.0, 0.0], dtype=np.float32),
        "last_value": 10.0,
    }
    out = compute_gae(batch, gamma=0.5, lam=1.0)
    # No terminal: value bootstraps through gamma.
    np.testing.assert_allclose(out["returns"], [2.5, 5.0])


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_ppo_cartpole_improves():
    import gymnasium as gym

    config = (
        PPOConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=256)
        .training(lr=3e-3, num_epochs=4, minibatch_size=128)
    )
    algo = config.build()
    try:
        first = algo.train()
        best = 0.0
        for _ in range(6):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
        # CartPole random policy gets ~20; learning shows clear improvement.
        assert best > first["episode_return_mean"] or best > 60.0, (
            f"no improvement: first={first['episode_return_mean']}, best={best}"
        )
        assert result["episodes_total"] > 0
    finally:
        algo.stop()
