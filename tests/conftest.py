"""Test fixtures.

Mirrors the reference's python/ray/tests/conftest.py fixture strategy
(ray_start_regular at conftest.py:411, ray_start_cluster at :492) and its
CPU-device collective testing approach (SURVEY.md §4.2): JAX runs on a
virtual 8-device CPU mesh so all sharding/collective code paths execute
without TPU hardware.
"""

import os

# Tests always run on a virtual 8-device CPU mesh. The environment may
# preset a live TPU tunnel (JAX_PLATFORMS=axon via sitecustomize, which
# imports jax before this file runs) — so override through jax.config, not
# just env vars.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["PALLAS_AXON_POOL_IPS"] = ""  # worker subprocesses skip the tunnel
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("RT_TPU_CHIPS", "0")  # no fake TPU detection in tests

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


def pytest_collection_modifyitems(config, items):
    """`sanitizer` tests compile the native store under TSan/UBSan and
    run a multithreaded stress binary — minutes of compiler time that
    the default (and even `slow`) tiers shouldn't pay. They run only
    when explicitly selected: `-m sanitizer` (what `make lint` does)."""
    if "sanitizer" in (config.option.markexpr or ""):
        return
    skip = pytest.mark.skip(reason="opt-in: select with -m sanitizer")
    for item in items:
        if "sanitizer" in item.keywords:
            item.add_marker(skip)


@pytest.fixture
def rt_local():
    import ray_tpu as rt

    rt.init(local_mode=True)
    yield rt
    rt.shutdown()


@pytest.fixture
def rt_start(request):
    """A real single-node runtime: in-process GCS+raylet, subprocess workers."""
    import ray_tpu as rt

    kwargs = getattr(request, "param", {}) or {}
    kwargs.setdefault("num_cpus", 4)
    rt.init(**kwargs)
    yield rt
    rt.shutdown()


@pytest.fixture
def rt_cluster():
    """Multi-raylet cluster harness (reference: cluster_utils.Cluster)."""
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    yield cluster
    cluster.shutdown()
