"""Cluster launcher (`rt up/down/exec`) tests.

Reference analogs: `ray up/down/attach/exec` (scripts.py:566) + the
command-runner layer (autoscaler/_private/command_runner.py) and its
local/fake provider test pattern.
"""

import os
import subprocess
import sys
import time

import pytest
import yaml

from ray_tpu.autoscaler.launcher import (
    ClusterLauncher,
    LocalCommandRunner,
    SSHCommandRunner,
)

pytestmark = pytest.mark.slow


def test_ssh_runner_builds_commands():
    r = SSHCommandRunner("10.0.0.7", "tpuuser", key="/tmp/k.pem", port=2222)
    attach = r.attach_command()
    assert "tpuuser@10.0.0.7" in attach
    assert "-i /tmp/k.pem" in attach.replace("'", "")
    assert "-p 2222" in attach


def test_local_runner_run_and_put(tmp_path):
    r = LocalCommandRunner()
    assert r.run("echo hello").strip() == "hello"
    with pytest.raises(RuntimeError):
        r.run("exit 3")
    src = tmp_path / "src.txt"
    src.write_text("payload")
    r.put(str(src), str(tmp_path / "dst" / "copy.txt"))
    assert (tmp_path / "dst" / "copy.txt").read_text() == "payload"


def test_launcher_up_exec_down_local(tmp_path):
    """Full `rt up` -> cluster forms (head + worker) -> `rt exec` ->
    `rt down` with the local provider (the reference's fake/local
    provider e2e pattern)."""
    import ray_tpu as rt

    port = 17937
    mounted = tmp_path / "mounted"
    payload = tmp_path / "payload.txt"
    payload.write_text("mounted-ok")
    config = {
        "cluster_name": "launch-e2e",
        "provider": {
            "type": "local",
            "head_ip": "127.0.0.1",
            "worker_ips": ["127.0.0.1"],
        },
        "port": port,
        "file_mounts": {str(mounted / "payload.txt"): str(payload)},
        "setup_commands": ["echo setup-ran"],
        "head_start_commands": [
            "{python} -m ray_tpu start --head --port {port} --num-cpus 2"
            " --no-dashboard"
        ],
        "worker_start_commands": [
            "{python} -m ray_tpu start --address {head_address} --num-cpus 2"
        ],
    }
    cfg_path = tmp_path / "cluster.yaml"
    cfg_path.write_text(yaml.safe_dump(config))

    launcher = ClusterLauncher.from_yaml(str(cfg_path))
    logs = []
    try:
        address = launcher.up(log=logs.append)
        assert address == f"127.0.0.1:{port}"
        assert any("setup-ran" in ln for ln in logs), logs
        assert (mounted / "payload.txt").read_text() == "mounted-ok"

        # The cluster formed: both nodes visible, tasks run.
        rt.init(address=address)
        try:
            # head + launched worker (+ this driver's own node from
            # rt.init(address=...)).
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                alive = [n for n in rt.nodes() if n["state"] == "ALIVE"]
                if len(alive) >= 3:
                    break
                time.sleep(0.5)
            assert len(alive) >= 3, alive
            assert sum(1 for n in alive if n.get("is_head")) == 1

            @rt.remote
            def f(x):
                return x * 2

            assert rt.get(f.remote(21), timeout=60) == 42
        finally:
            rt.shutdown()

        out = launcher.exec("echo from-head", log=logs.append)
        assert out and out[0].strip() == "from-head"
    finally:
        launcher.down(log=logs.append)

    # Everything `rt start` spawned is gone (best-effort check: the GCS
    # port is closed).
    import socket

    time.sleep(1.0)
    with socket.socket() as s:
        assert s.connect_ex(("127.0.0.1", port)) != 0, "GCS still listening"
