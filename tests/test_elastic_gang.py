"""Elastic gang tests: shard remapping, resize policy, partial
reclamation, and chaos-driven live resize.

The tentpole invariant under test: a gang hit by partial chip
reclamation shrinks in place (survivors re-shard state through the
object store), keeps stepping, and grows back when the claimant lifts
the fence — instead of the evict-checkpoint-restart cycle. Modeled on
the fault-tolerance suite's determinism rules: faults fire via the
shared chaos API, waits poll observable GCS state, never bare timers.
"""

import threading
import time

import numpy as np
import pytest

from ray_tpu.train import (
    JaxConfig,
    JaxTrainer,
    ResizePolicy,
    RunConfig,
    ScalingConfig,
    ShardRemapPlan,
    ShardedState,
)


def _wait_for(predicate, timeout=30.0, interval=0.05, desc="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return
        time.sleep(interval)
    raise TimeoutError(f"timed out waiting for {desc}")


# -- shard remap plan: bijection ---------------------------------------------
def _tree(seed=0):
    rng = np.random.RandomState(seed)
    return {
        "w": rng.standard_normal(67).astype(np.float64),  # non-divisor size
        "m": np.arange(64, dtype=np.float32).reshape(8, 8),
        "v": rng.randint(0, 1 << 30, size=13).astype(np.int32),
        "step": 41,  # int scalar must survive as a scalar
        "lr": 0.125,
    }


def _tree_equal(a, b):
    import jax

    la, lb = jax.tree_util.tree_leaves(a), jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        if isinstance(x, (int, float, bool)):
            assert type(x) is type(y) and x == y, (x, y)
        else:
            assert x.dtype == y.dtype, (x.dtype, y.dtype)
            assert np.array_equal(x, y)


@pytest.mark.parametrize("old_world,new_world",
                         [(8, 4), (8, 6), (4, 8), (3, 5)])
def test_shard_remap_bijection(old_world, new_world):
    """Remapping old_world shards to new_world covers every element of
    every leaf exactly once: new-rank slices equal a direct shard at the
    new world size, and reassembly is bit-for-bit the original tree."""
    tree = _tree()
    old = {r: ShardedState.create(tree, r, old_world)
           for r in range(old_world)}
    meta = old[0].meta
    plan = ShardRemapPlan(old_world, new_world, meta["sizes"],
                          meta["dtypes"])

    new_shards = {}
    for nr in range(new_world):
        # Only the declared sources are handed over — the object-store
        # transfer in sync_resize fetches exactly this set.
        srcs = {r: old[r].slices for r in plan.sources_for(nr)}
        new_shards[nr] = plan.remap(nr, srcs)
        direct = ShardedState.create(tree, nr, new_world).slices
        for got, want in zip(new_shards[nr], direct):
            assert got.dtype == want.dtype
            assert np.array_equal(got, want)

    _tree_equal(ShardedState.assemble(meta, new_shards), tree)


def test_shrink_grow_roundtrip_bit_equality():
    """Optimizer state sharded at 8, remapped to 4 (shrink), then back
    to 8 (grow) reassembles bit-for-bit — remapping only moves bytes."""
    tree = _tree(seed=7)
    full8 = {r: ShardedState.create(tree, r, 8) for r in range(8)}
    meta = full8[0].meta

    down = ShardRemapPlan(8, 4, meta["sizes"], meta["dtypes"])
    at4 = {nr: down.remap(nr, {r: full8[r].slices
                               for r in down.sources_for(nr)})
           for nr in range(4)}
    up = ShardRemapPlan(4, 8, meta["sizes"], meta["dtypes"])
    at8 = {nr: up.remap(nr, {r: at4[r] for r in up.sources_for(nr)})
           for nr in range(8)}

    _tree_equal(ShardedState.assemble(meta, at8), tree)
    for r in range(8):
        for got, want in zip(at8[r], full8[r].slices):
            assert got.tobytes() == want.tobytes()


def test_sharded_state_save_load_roundtrip(tmp_path):
    """Departing ranks persist their slice through the drain plane; a
    cold restore reassembles the full tree from the shard files."""
    tree = _tree(seed=3)
    for r in range(3):
        ShardedState.create(tree, r, 3).save(str(tmp_path))
    loaded = ShardedState.load_all(str(tmp_path))
    assert sorted(loaded) == [0, 1, 2]
    _tree_equal(
        ShardedState.assemble(loaded[0].meta,
                              {r: s.slices for r, s in loaded.items()}),
        tree)


# -- epoch fence across a resize ---------------------------------------------
def test_epoch_fence_rejects_stale_rank_mid_resize():
    """A departing rank that lingers past the resize can neither find
    the rebuilt ring (rendezvous keys are stamped with the bumped
    epoch) nor pass the ident handshake with its stale epoch."""
    import socket

    from ray_tpu.exceptions import CollectiveTimeoutError
    from ray_tpu.util.collective.dcn_group import _IDENT, _LEN, DcnGroup
    from tests.test_train_fault_tolerance import FakeKV

    kv = FakeKV()
    # The resize shrank 4 -> 3 and bumped the gang epoch 0 -> 1; old
    # rank 3 was told to exit but is still around.
    resized = DcnGroup(kv, 3, 0, "elastic", timeout=0.5, epoch=1)
    stale = DcnGroup(kv, 4, 3, "elastic", timeout=0.3, epoch=0)
    try:
        with pytest.raises(TimeoutError):
            stale._peer_out(0)

        s = socket.create_connection(tuple(resized.addr), timeout=2)
        s.sendall(_LEN.pack(_IDENT.size) + _IDENT.pack(3, 0, 0, 0))
        with pytest.raises(CollectiveTimeoutError):
            resized._peer_in(3)
        s.close()

        s2 = socket.create_connection(tuple(resized.addr), timeout=2)
        s2.sendall(_LEN.pack(_IDENT.size) + _IDENT.pack(2, 1, 0, 0))
        assert resized._peer_in(2) is not None
        s2.close()
    finally:
        resized.destroy()
        stale.destroy()


# -- resize policy -----------------------------------------------------------
def test_resize_policy_cooldown_and_floor():
    """The governor floors shrinks at min_world_size, spaces resizes by
    the cooldown, and only grows back toward the configured baseline.
    Deterministic via the injectable clock."""
    from ray_tpu.train.trainer import _ResizeGovernor

    t = [100.0]
    gov = _ResizeGovernor(
        ResizePolicy(min_world_size=2, resize_cooldown_s=10.0), 4,
        clock=lambda: t[0])

    assert gov.shrink_target(4, 1) == 3
    gov.note_resized()
    assert gov.shrink_target(3, 1) is None        # inside the cooldown
    assert gov.want_grow(3) is False
    t[0] += 10.0
    assert gov.shrink_target(3, 1) == 2           # cooled down
    assert gov.shrink_target(3, 2) is None        # would cross the floor
    assert gov.shrink_target(2, 1) is None
    gov.note_resized()
    t[0] += 10.0
    assert gov.want_grow(2) is True
    assert gov.want_grow(4) is False              # already at baseline

    frozen = _ResizeGovernor(
        ResizePolicy(min_world_size=2, grow_back=False), 4,
        clock=lambda: t[0])
    assert frozen.want_grow(2) is False


# -- restart leak fix --------------------------------------------------------
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_restart_shutdown_verifies_pg_release(rt_start, monkeypatch):
    """shutdown(verify=True) — the restart path — raises when the GCS
    never confirms the placement group removal, instead of silently
    leaking a gang's worth of reserved chips."""
    from ray_tpu.exceptions import PlacementGroupSchedulingError
    from ray_tpu.train import worker_group as wg_mod

    wg = wg_mod.WorkerGroup(1, {"CPU": 1})
    try:
        assert wg_mod.placement_group_state(wg._pg) == "CREATED"
    except Exception:
        wg.shutdown()
        raise

    class _Jumpy:
        """time shim: every monotonic() call advances 3s so the 5s
        verification window burns out in a handful of iterations."""
        def __init__(self):
            self.t = 0.0

        def monotonic(self):
            self.t += 3.0
            return self.t

        def sleep(self, _):
            pass

    pg = wg._pg
    monkeypatch.setattr(wg_mod, "placement_group_state",
                        lambda _pg: "CREATED")
    monkeypatch.setattr(wg_mod, "time", _Jumpy())
    with pytest.raises(PlacementGroupSchedulingError,
                       match="still reserved after shutdown"):
        wg.shutdown(verify=True)
    monkeypatch.undo()
    # The real removal did go through despite the pessimistic probe.
    _wait_for(lambda: wg_mod.placement_group_state(pg) in (None, "REMOVED"),
              desc="pg removal")


# -- partial reclamation at the GCS ------------------------------------------
@pytest.mark.chaos
def test_partial_reclamation_arms_and_lifts_obligation(rt_cluster):
    """A claimant needing fewer chips than a whole gang drains exactly
    the claimed bundles; releasing them arms a resize obligation that
    blocks re-reserve until the claimant lets go."""
    from ray_tpu._private import chaos
    from ray_tpu.exceptions import PlacementGroupSchedulingError
    from ray_tpu.util.placement_group import (
        placement_group,
        placement_group_resize_state,
        release_placement_group_bundles,
        reserve_placement_group_bundles,
    )

    rt_cluster.add_node(num_cpus=4)
    for _ in range(4):
        rt_cluster.add_node(num_cpus=1, num_tpus=4)
    rt_cluster.connect()
    chaos.enable()
    try:
        pg = placement_group([{"TPU": 4}] * 4, strategy="SPREAD",
                             name="gang", priority=0)
        assert pg.ready(timeout=30)

        victims = chaos.reclaim_chips(4, bundle_chips=4)
        assert victims == [{"victim_pg_id": pg.id.binary(),
                            "partial": True, "bundle_indices": [3]}]

        release_placement_group_bundles(pg, [3])
        state = placement_group_resize_state(pg)
        assert state["released_bundles"] == [3]
        (ob,) = state["obligations"]
        assert ob["state"] == "armed"
        assert ob["bundle_indices"] == [3]
        assert ob["claimant_tenant"] == "chaos_reclaim"

        with pytest.raises(PlacementGroupSchedulingError,
                           match="obligation not lifted"):
            reserve_placement_group_bundles(pg, [3])

        assert chaos.lift_fence() == 1
        (ob,) = placement_group_resize_state(pg)["obligations"]
        assert ob["state"] == "lifted"
        reserve_placement_group_bundles(pg, [3])
        state = placement_group_resize_state(pg)
        assert state == {"obligations": [], "released_bundles": []}
    finally:
        chaos.disable()


# -- tentpole acceptance: live resize under chaos ----------------------------
def _elastic_loop(config):
    import time as _time

    import numpy as np

    from ray_tpu import train

    state = {"w": np.zeros(8, dtype=np.float64), "steps_done": 0}
    shards = train.shard_state(
        {"m": np.arange(60, dtype=np.float64)}, name="opt")
    while state["steps_done"] < config["steps"]:
        ev = train.sync_resize(state, shards)
        if ev.exiting:
            return  # departing rank: shard persisted, exit clean
        state, shards = ev.state, ev.shards
        state["w"] += 1.0
        state["steps_done"] += 1
        if train.get_world_rank() == 0:
            train.report({
                "step": state["steps_done"],
                "world": ev.world_size,
                "opt_sum": float(sum(float(s.sum())
                                     for s in shards["opt"].slices)),
            })
        _time.sleep(0.02)


@pytest.mark.chaos
def test_chaos_resize_under_active_step(rt_cluster, tmp_path):
    """Partial reclamation mid-training shrinks the gang in place and
    the fence lift grows it back — losing not a single step: the step
    history is gapless and repeat-free across both resizes, and the
    re-sharded optimizer state stays exact."""
    from ray_tpu._private import chaos

    rt_cluster.add_node(num_cpus=8)
    for _ in range(3):
        rt_cluster.add_node(num_cpus=2, num_tpus=4)
    rt_cluster.connect()
    gcs = rt_cluster.gcs
    chaos.enable()
    try:
        trainer = JaxTrainer(
            _elastic_loop, train_loop_config={"steps": 600},
            jax_config=JaxConfig(dp_sync="none"),
            scaling_config=ScalingConfig(
                num_workers=3, use_tpu=True, tpus_per_worker=4,
                placement_strategy="SPREAD",
                elastic=ResizePolicy(min_world_size=2)),
            run_config=RunConfig(name="el", storage_path=str(tmp_path)),
        )
        holder = {}
        t = threading.Thread(
            target=lambda: holder.update(r=trainer.fit()), daemon=True)
        t.start()

        _wait_for(lambda: any(p["state"] == "CREATED"
                              for p in gcs.placement_groups.values()),
                  desc="gang placement")
        victims = chaos.reclaim_chips(4, bundle_chips=4)
        assert victims and victims[0]["partial"]

        # Shrink completed: the partial record closed with the elastic
        # outcome (bundles released by the live gang, not evicted).
        _wait_for(lambda: any(r.get("outcome") == "resized"
                              for r in gcs.preemptions.values()),
                  desc="elastic shrink")
        assert chaos.lift_fence() == 1
        # Grow-back completed: the obligation was consumed by re-reserve.
        _wait_for(lambda: not gcs.resize_obligations,
                  desc="grow back")

        t.join(timeout=120)
        assert not t.is_alive(), "trainer did not finish"
    finally:
        chaos.disable()

    r = holder["r"]
    assert r.error is None, r.error
    steps = [m["step"] for m in r.metrics_history]
    worlds = [m["world"] for m in r.metrics_history]
    # <1 step lost: gapless, repeat-free, monotonic — the resize moved
    # live state through the object store, not back to an old checkpoint.
    assert steps == list(range(1, 601))
    assert sorted(set(worlds)) == [2, 3] and worlds[-1] == 3
    # Rank 0's slice of arange(60) at world 3 is elements [0, 20).
    assert r.metrics["opt_sum"] == float(np.arange(60)[:20].sum())
