"""Native shared-memory store tests.

Modeled on the reference plasma test intents
(src/ray/object_manager/plasma/test/): create/seal/get/release lifecycle,
eviction under pressure, allocator reuse, and cross-process visibility.
"""

import multiprocessing as mp
import os

import numpy as np
import pytest

from ray_tpu._private import serialization as ser
from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStore


@pytest.fixture
def store():
    name = f"/rtstore_ut_{os.getpid()}_{os.urandom(4).hex()}"
    s = ObjectStore(name, 32 * 1024 * 1024, create=True)
    yield s
    s.destroy()


def test_create_seal_get_release(store):
    oid = ObjectID.from_random()
    buf = store.create(oid, 100)
    buf[:5] = b"hello"
    del buf
    store.seal(oid)
    view = store.get(oid)
    assert bytes(view[:5]) == b"hello"
    del view
    store.release(oid)
    assert store.contains(oid)


def test_get_missing_returns_none(store):
    assert store.get(ObjectID.from_random()) is None


def test_unsealed_not_gettable(store):
    oid = ObjectID.from_random()
    store.create(oid, 10)
    assert store.get(oid) is None
    store.abort(oid)
    assert not store.contains(oid)


def test_duplicate_create_rejected(store):
    oid = ObjectID.from_random()
    store.create(oid, 10)
    store.seal(oid)
    with pytest.raises(ValueError):
        store.create(oid, 10)


def test_delete_and_refcount(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"x" * 1000)
    view = store.get(oid)
    assert not store.delete(oid)  # pinned
    del view
    store.release(oid)
    assert store.delete(oid)
    assert not store.contains(oid)


def test_lru_eviction_under_pressure(store):
    ids = []
    for _ in range(60):
        oid = ObjectID.from_random()
        store.put_bytes(oid, os.urandom(1024 * 1024))
        ids.append(oid)
    stats = store.stats()
    assert stats["num_evictions"] > 0
    # Oldest objects evicted first; the most recent one must survive.
    assert store.contains(ids[-1])
    assert not store.contains(ids[0])


def test_pinned_objects_survive_eviction(store):
    pinned = ObjectID.from_random()
    store.put_bytes(pinned, b"p" * (1024 * 1024))
    view = store.get(pinned)  # pin
    for _ in range(60):
        store.put_bytes(ObjectID.from_random(), os.urandom(1024 * 1024))
    assert store.contains(pinned)
    assert bytes(view[:1]) == b"p"
    del view
    store.release(pinned)


def test_allocator_reuse_after_delete(store):
    # Fill, delete all, then the space must be reusable (coalescing works).
    for _ in range(3):
        ids = []
        for _ in range(20):
            oid = ObjectID.from_random()
            store.put_bytes(oid, os.urandom(1024 * 1024))
            ids.append(oid)
        for oid in ids:
            store.delete(oid)
    assert store.stats()["num_objects"] == 0


def test_zero_copy_numpy_roundtrip(store):
    oid = ObjectID.from_random()
    arr = np.arange(100_000, dtype=np.float32).reshape(100, 1000)
    store.put_serialized(oid, ser.serialize({"w": arr}))
    view = store.get(oid)
    out = ser.deserialize(view)["w"]
    assert not out.flags.owndata  # zero-copy view over shared memory
    assert np.array_equal(out, arr)
    del out, view
    store.release(oid)


def _child_read(name, oid_hex, q):
    s = ObjectStore(name)
    v = s.get(ObjectID.from_hex(oid_hex))
    q.put(bytes(v[:5]))
    del v
    s.release(ObjectID.from_hex(oid_hex))
    s.close()


def test_cross_process_get(store):
    oid = ObjectID.from_random()
    store.put_bytes(oid, b"world")
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    p = ctx.Process(target=_child_read, args=(store.name, oid.hex(), q))
    p.start()
    p.join(30)
    assert q.get(timeout=5) == b"world"
