"""Flight recorder tests: per-step breakdown, straggler attribution,
unified memory accounting, CLI rendering, serving latency histograms.

The contract under test (PAPER.md observability story): every training
step decomposes into data/compute/collective/checkpoint/other that sums
to the step wall time; per-rank records ride the existing report/poll
stream so the DRIVER names the slowest rank; `rt top` and `rt memory
--devices` render the same numbers from the GCS metrics stream.
"""

import time
from dataclasses import replace

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu._private import chaos
from ray_tpu._private import worker as worker_mod


def _wait_for(fn, timeout=10.0, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(poll)
    raise TimeoutError("condition not met")


# -- StepProfiler core (no runtime needed) -------------------------------

def test_step_breakdown_sums_to_wall():
    """Named phases + other == wall, per record, by construction."""
    from ray_tpu.train import StepProfiler

    prof = StepProfiler(ring=16, rank=0, emit_metrics=False)
    for _ in range(5):
        with prof.step(tokens=64):
            with prof.phase("data"):
                time.sleep(0.002)
            with prof.phase("compute"):
                time.sleep(0.004)
    recs = prof.records()
    assert len(recs) == 5
    for r in recs:
        named = (r["data_s"] + r["compute_s"] + r["collective_s"]
                 + r["checkpoint_s"] + r["other_s"])
        assert abs(r["wall_s"] - named) < 1e-6
        assert r["compute_s"] >= 0.004
        assert r["data_s"] >= 0.002
        assert r["tokens_per_s"] > 0


def test_ring_buffer_bounds_memory():
    from ray_tpu.train import StepProfiler

    prof = StepProfiler(ring=4, rank=0, emit_metrics=False)
    for _ in range(10):
        with prof.step():
            pass
    assert len(prof.records()) == 4
    assert prof.summary()["steps"] == 10
    # Pending drains at most ring entries, then empties.
    assert len(prof.drain_records()) == 4
    assert prof.drain_records() == []


def test_collective_time_attributed_via_observer():
    """The collective op wrappers report wall time into the active step
    through the observer hook — no loop annotation needed."""
    from ray_tpu.train import StepProfiler
    from ray_tpu.util.collective import collective as col

    prof = StepProfiler(ring=4, rank=0, emit_metrics=False)
    with prof.step():
        col._observed("allreduce", lambda: time.sleep(0.01))
    rec = prof.records()[-1]
    assert rec["collective_s"] >= 0.01
    assert rec["collective_s"] <= rec["wall_s"] + 1e-9


def test_feed_wait_lands_in_data_phase():
    """attach_feed: the pipeline's measured consumer wait becomes the
    step's data_s when the loop doesn't time data explicitly."""
    from ray_tpu.data.feed import FeedStats
    from ray_tpu.train import StepProfiler

    stats = FeedStats()
    prof = StepProfiler(ring=4, rank=0, emit_metrics=False)
    prof.attach_feed(stats)
    with prof.step():
        # The stall happens inside the step (blocked in next(batch)).
        stats.add_wait(0.03)
        time.sleep(0.035)
    rec = prof.records()[-1]
    assert abs(rec["feed_wait_s"] - 0.03) < 1e-9
    assert rec["feed_stalls"] == 1
    assert abs(rec["data_s"] - 0.03) < 1e-9
    # The breakdown still sums to wall: the remainder is other_s.
    assert rec["other_s"] == pytest.approx(rec["wall_s"] - 0.03, abs=1e-6)
    # Next step: no new wait -> no data time.
    with prof.step():
        pass
    assert prof.records()[-1]["data_s"] == 0.0


def test_compile_counting_flags_retraces():
    import jax
    import jax.numpy as jnp

    from ray_tpu.train import StepProfiler

    f = jax.jit(lambda x: x * 2)
    prof = StepProfiler(ring=8, rank=0, emit_metrics=False)
    prof.watch_jit(f)
    with prof.step():
        f(jnp.ones((4,)))
    assert prof.records()[-1]["compiles"] == 1
    with prof.step():
        f(jnp.ones((4,)))
    assert prof.records()[-1]["compiles"] == 0
    with prof.step():
        f(jnp.ones((8,)))  # new shape: retrace
    assert prof.records()[-1]["compiles"] == 1


def test_mfu_estimate_uses_env_peak(monkeypatch):
    from ray_tpu.train import StepProfiler
    from ray_tpu.train import flight_recorder

    monkeypatch.setenv("RT_PEAK_FLOPS_PER_S", "1e12")
    assert flight_recorder.peak_flops_per_s() == 1e12
    prof = StepProfiler(ring=4, rank=0, emit_metrics=False,
                        flops_per_step=1e9)
    with prof.step():
        time.sleep(0.002)
    rec = prof.records()[-1]
    # mfu = 1e9 / (wall * 1e12); wall >= 2ms -> mfu <= 0.5
    assert 0 < rec["mfu"] <= 1e9 / (0.002 * 1e12) + 1e-6


def test_chaos_delay_steps_consumed_once():
    from ray_tpu.train import StepProfiler

    chaos.enable()
    try:
        chaos.delay_steps(0.05, count=1)
        prof = StepProfiler(ring=4, rank=0, emit_metrics=False)
        t0 = time.perf_counter()
        with prof.step():
            pass
        assert time.perf_counter() - t0 >= 0.05
        t0 = time.perf_counter()
        with prof.step():
            pass
        assert time.perf_counter() - t0 < 0.04  # injection exhausted
    finally:
        chaos.disable()


def test_compute_skew_names_slowest_rank():
    from ray_tpu.train import compute_skew

    fast = {"steps": 10, "wall_s": 1.0, "compute_s": 0.9}
    slow = {"steps": 10, "wall_s": 3.0, "compute_s": 0.9,
            "collective_s": 2.0}
    out = compute_skew([fast, slow, None])
    assert out["straggler_rank"] == 1
    assert abs(out["skew_s"] - 0.2) < 1e-9
    assert out["straggler_breakdown"]["collective_s"] == pytest.approx(0.2)
    # Fewer than two reporting ranks: no attribution.
    assert compute_skew([fast, None]) is None


# -- end-to-end: gang straggler attribution ------------------------------

def _profiled_loop(config):
    import time as _t

    from ray_tpu import train
    from ray_tpu._private import chaos as _chaos

    prof = train.StepProfiler(ring=64)
    rank = train.get_world_rank()
    if rank == config["slow_rank"]:
        _chaos.enable()
        _chaos.delay_steps(config["delay_s"], count=config["steps"])
    for step in range(config["steps"]):
        with prof.step(tokens=32):
            with prof.phase("compute"):
                _t.sleep(0.004)
        train.report({"step": step, "rank": rank})


def test_straggler_attribution_two_node_gang():
    """A chaos-slowed rank on a 2-node gang is named as the straggler in
    Result.metrics_history, with per-phase breakdown and per-rank walls.
    The delay is injected INSIDE rank 1's step loop (process-local,
    deterministic), exactly where a real straggler would lose time."""
    from ray_tpu.cluster_utils import Cluster
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        trainer = JaxTrainer(
            _profiled_loop,
            train_loop_config={"steps": 8, "slow_rank": 1,
                               "delay_s": 0.05},
            scaling_config=ScalingConfig(
                num_workers=2, placement_strategy="SPREAD"
            ),
        )
        result = trainer.fit()
        assert result.error is None
        enriched = [m for m in result.metrics_history
                    if "train_straggler_rank" in m]
        assert enriched, (
            f"no skew-enriched entries in {result.metrics_history}"
        )
        last = enriched[-1]
        assert last["train_straggler_rank"] == 1
        # ~50ms injected per step dominates the ~4ms compute.
        assert last["train_step_skew_s"] > 0.02
        walls = last["train_step_wall_by_rank"]
        assert set(walls) == {0, 1}
        assert walls[1] > walls[0]
        # Per-phase breakdown of the straggler: the injected delay is
        # un-attributed time (it models unknown slowness), so it shows
        # up as other_s, not compute_s.
        br = last["train_straggler_breakdown"]
        assert br["other_s"] > br["compute_s"]
        # Rank-0 reports carry per-step records -> breakdown in history.
        with_br = [m for m in result.metrics_history
                   if "train_step_breakdown" in m]
        assert with_br
        b = with_br[-1]["train_step_breakdown"]
        assert abs(
            b["wall_s"] - (b["data_s"] + b["compute_s"] + b["collective_s"]
                           + b["checkpoint_s"] + b["other_s"])
        ) < 1e-4
    finally:
        cluster.shutdown()


# -- memory accountant + CLI against a live runtime ----------------------

def test_memory_accounting_and_cli(rt_start, capsys):
    """sample_once() publishes HBM gauges; rt top / rt memory --devices
    render training + memory state from the live GCS."""
    import jax.numpy as jnp

    from ray_tpu.scripts.scripts import build_parser
    from ray_tpu.train import StepProfiler
    from ray_tpu.util import memory, metrics

    # Hold live device arrays and an object-store object.
    arr = jnp.ones((256, 256), dtype=jnp.float32)
    ref = rt.put(np.zeros(100_000, dtype=np.uint8))
    sample = memory.sample_once()
    assert sample and sample[0]["live_bytes"] >= arr.nbytes

    # A profiled "training" step in this process, rank-tagged.
    prof = StepProfiler(ring=8, rank=0)
    for _ in range(3):
        with prof.step(tokens=16):
            with prof.phase("compute"):
                time.sleep(0.002)
    metrics._flush_once()

    addr = worker_mod._global_node.gcs_address
    parser = build_parser()

    def gauges_visible():
        args = parser.parse_args(["memory", "--devices", "--address", addr])
        args.fn(args)
        out = capsys.readouterr().out
        return out if "MB live" in out else None

    out = _wait_for(gauges_visible, timeout=15.0)
    assert "HBM (live jax arrays)" in out
    assert "object store" in out

    summary = memory.memory_summary(address=addr)
    assert summary["hbm_live_bytes"] >= arr.nbytes
    assert summary["objects"]["count"] >= 1
    assert summary["objects"]["bytes"] >= 100_000

    args = parser.parse_args(["top", "--address", addr])
    args.fn(args)
    top_out = capsys.readouterr().out
    assert "nodes alive" in top_out
    assert "rank 0: 3 steps" in top_out
    assert "hbm" in top_out
    del ref


# -- serving latency histograms ------------------------------------------

def test_serve_ttft_tpot_and_occupancy():
    """TTFT/TPOT histograms and the occupancy gauge populate from real
    engine traffic, riding the existing stats() plumbing."""
    import jax

    from ray_tpu.models import configs, init_params
    from ray_tpu.serve.llm import ContinuousBatchingEngine, _engine_metrics

    cfg = replace(configs.tiny, dtype=np.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    m = _engine_metrics()
    ttft_before = m["ttft_s"].summary()["count"]
    tpot_before = m["tpot_s"].summary()["count"]

    eng = ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=64)
    try:
        handles = [eng.submit([1 + i, 7, 3], max_new_tokens=6)
                   for i in range(2)]
        for h in handles:
            toks = h.result(timeout=180)
            assert len(toks) >= 1
        stats = eng.stats()
        lat = stats["latency"]
        assert lat["ttft"]["count"] >= ttft_before + 2
        assert lat["ttft"]["max"] > 0
        assert lat["tpot"]["count"] >= tpot_before + 2
        assert lat["tpot"]["avg"] > 0
        assert 0.0 <= lat["occupancy"] <= 1.0
    finally:
        eng.shutdown()
