"""Topology-native collectives: cost-model selection, recursive
doubling, sharded hierarchical allreduce, and the quantized DCN wire.

These run IN-PROCESS (threaded DcnGroups rendezvousing through a
dict-backed fake KV) — the transport only needs kv_put/kv_get/kv_del, so
no cluster is spun up and a whole ring lives in one pytest worker. The
actor-level API path is covered by test_collective.py.
"""

import os
import threading

import numpy as np
import pytest

from ray_tpu._private import chaos
from ray_tpu.exceptions import CollectiveTimeoutError
from ray_tpu.util.collective import quant
from ray_tpu.util.collective.dcn_group import DcnGroup
from ray_tpu.util.collective.topology import (
    ALGO_HIER,
    ALGO_RD,
    ALGO_RING,
    Topology,
)
from ray_tpu.util.collective.types import ReduceOp


class FakeKV:
    """The slice of the GCS KV client DcnGroup rendezvous uses."""

    def __init__(self):
        self.d = {}
        self.lock = threading.Lock()

    def kv_put(self, k, v, ns=None):
        with self.lock:
            self.d[(ns, k)] = v

    def kv_get(self, k, ns=None):
        with self.lock:
            return self.d.get((ns, k))

    def kv_del(self, k, ns=None):
        with self.lock:
            self.d.pop((ns, k), None)


def _run_ring(n, make_group, fn):
    """Construct n group members on threads, run fn(group, rank) on each,
    destroy, and return (results, groups). Any member's exception fails
    the whole call."""
    groups, errs, results = [None] * n, [None] * n, [None] * n

    def mk(r):
        try:
            groups[r] = make_group(r)
        except Exception as e:  # noqa: BLE001 — surfaced via assert below
            errs[r] = e

    threads = [threading.Thread(target=mk, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not any(errs), errs

    def work(r):
        try:
            results[r] = fn(groups[r], r)
        except Exception as e:  # noqa: BLE001
            errs[r] = e

    threads = [threading.Thread(target=work, args=(r,)) for r in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    for g in groups:
        if g is not None:
            g.destroy()
    assert not any(errs), errs
    return results, groups


def _dcn_ring(n, fn, name, kv=None, **kw):
    kv = kv if kv is not None else FakeKV()
    kw.setdefault("timeout", 15)
    kw.setdefault("op_timeout", 15)
    return _run_ring(
        n, lambda r: DcnGroup(kv, n, r, name, epoch=0, **kw), fn
    )


# -- topology -> algorithm selection ------------------------------------

class TestSelection:
    def test_selection_table(self):
        """The modeled 2-host x 4-chip topology picks recursive doubling
        under the crossover and sharded-hier above it; a flat topology
        keeps the bandwidth-optimal ring for large messages."""
        two_tier = Topology.detect(2, n_local=4)
        cross = two_tier.crossover_nbytes()
        assert two_tier.select("allreduce", 64) == ALGO_RD
        assert two_tier.select("allreduce", cross // 2) == ALGO_RD
        assert two_tier.select("allreduce", 64 << 20) == ALGO_HIER

        flat = Topology.detect(4, n_local=1)
        assert flat.select("allreduce", 64 << 20) == ALGO_RING
        assert flat.select("allreduce", 8) == ALGO_RD
        # non-sharding collectives never pick hier
        assert two_tier.select("broadcast", 64 << 20) in (ALGO_RING, ALGO_RD)

    def test_env_override_wins_and_validates(self, monkeypatch):
        topo = Topology.detect(2, n_local=4)
        monkeypatch.setenv("RT_COLLECTIVE_ALGO", "ring")
        assert topo.select("allreduce", 8) == ALGO_RING
        monkeypatch.setenv("RT_COLLECTIVE_ALGO", "auto")
        assert topo.select("allreduce", 8) == ALGO_RD
        monkeypatch.setenv("RT_COLLECTIVE_ALGO", "warp")
        with pytest.raises(ValueError, match="RT_COLLECTIVE_ALGO"):
            topo.select("allreduce", 8)
        # forcing hier on a flat topology degrades to ring, not a crash
        monkeypatch.setenv("RT_COLLECTIVE_ALGO", "hier")
        assert Topology.detect(3, n_local=1).select("allreduce", 8) == ALGO_RING

    def test_cost_model_shape(self):
        """Sanity on the alpha-beta forms the selection rests on: rd is
        latency-bound (flat in nbytes -> wins small), ring is bandwidth-
        bound (wins large on flat), hier cuts the DCN term by n_local."""
        t = Topology.detect(2, n_local=4)
        small, large = 64.0, float(64 << 20)
        assert t.cost_rd_allreduce(small) < t.cost_ring_allreduce(small)
        assert t.cost_hier_allreduce(large) < t.cost_ring_allreduce(large)
        assert t.cost_hier_allreduce(large) < t.cost_rd_allreduce(large)
        flat = Topology.detect(2, n_local=1)
        assert flat.cost_hier_allreduce(large) == float("inf")


# -- quantized codec ----------------------------------------------------

class TestQuantCodec:
    def test_int8_roundtrip_bound(self):
        rng = np.random.default_rng(7)
        x = rng.standard_normal(5000).astype(np.float32)
        # int8 absmax/127 grid: per-element error <= scale/2 = absmax/254
        assert quant.roundtrip_error(x, "int8") <= 1.0 / 254 + 1e-6

    def test_fp8_roundtrip_bound(self):
        pytest.importorskip("ml_dtypes")
        rng = np.random.default_rng(8)
        x = rng.standard_normal(5000).astype(np.float32)
        # e4m3: 3 mantissa bits -> relative rounding radius 2^-4
        assert quant.roundtrip_error(x, "fp8") <= 2.0 ** -4 + 1e-6

    @pytest.mark.parametrize("size", [1, 255, 256, 257, 1000])
    def test_truncated_wire_roundtrip(self, size):
        """Codes are truncated to the element count on the wire; decode
        re-pads — shapes that straddle block boundaries must survive."""
        rng = np.random.default_rng(size)
        x = rng.standard_normal(size).astype(np.float32)
        p = quant.encode(x, "int8")
        assert p.codes.size == size  # no pad on the wire
        out = quant.decode(p)
        assert out.shape == x.shape
        assert np.abs(out - x).max() <= np.abs(x).max() / 254 + 1e-6

    def test_wire_bytes_ratio(self):
        x = np.zeros(64 * 1024, dtype=np.float32)
        p = quant.encode(x, "int8")
        assert x.nbytes / p.wire_bytes >= 3.8

    def test_validate_scheme(self):
        with pytest.raises(ValueError, match="unknown quant scheme"):
            quant.validate_scheme("int4")


class TestErrorFeedback:
    def test_residual_bank_and_apply(self):
        ef = quant.ErrorFeedback()
        ef.add("w", 0, np.array([0.5, -0.5], dtype=np.float32), 4)
        ef.add("w", 2, np.array([1.0], dtype=np.float32), 4)
        out = ef.apply("w", np.ones(4, dtype=np.float32))
        np.testing.assert_allclose(out, [1.5, 0.5, 2.0, 1.0])
        # apply() claims the residual: second call sees none
        np.testing.assert_allclose(
            ef.apply("w", np.ones(4, dtype=np.float32)), np.ones(4)
        )

    def test_size_mismatch_drops_residual(self):
        ef = quant.ErrorFeedback()
        ef.add("w", 0, np.ones(2, dtype=np.float32), 2)
        np.testing.assert_allclose(
            ef.apply("w", np.zeros(3, dtype=np.float32)), np.zeros(3)
        )


# -- DCN transport: new algorithms --------------------------------------

class TestDcnAlgorithms:
    @pytest.mark.parametrize("n", [2, 3, 4, 5])
    def test_rd_matches_ring(self, n):
        """Recursive doubling is bit-equivalent to the ring on integer-
        valued input (both including the non-power-of-2 fold)."""
        data = [np.arange(16.0) * (r + 1) for r in range(n)]
        rd, _ = _dcn_ring(
            n, lambda g, r: g.allreduce(data[r], algo=ALGO_RD), f"rd{n}"
        )
        ring, _ = _dcn_ring(
            n, lambda g, r: g.allreduce(data[r], algo=ALGO_RING), f"ri{n}"
        )
        for a, b in zip(rd, ring):
            np.testing.assert_array_equal(a, b)

    def test_rd_max_op(self):
        res, groups = _dcn_ring(
            3,
            lambda g, r: g.allreduce(
                np.full(5, float(r)), op=ReduceOp.MAX, algo=ALGO_RD
            ),
            "rdmax",
        )
        for out in res:
            np.testing.assert_array_equal(out, np.full(5, 2.0))
        assert groups[0].last_op_info["algo"] == ALGO_RD

    def test_quantized_allreduce_bounded_and_consistent(self):
        rng = np.random.default_rng(3)
        data = [rng.standard_normal(4096).astype(np.float32)
                for _ in range(3)]
        exact = data[0] + data[1] + data[2]
        res, groups = _dcn_ring(
            3, lambda g, r: g.allreduce(data[r], quant="int8"), "q3"
        )
        for out in res:
            rel = np.abs(out - exact).max() / np.abs(exact).max()
            assert rel <= 1e-2
            # the two-pass forwards codes verbatim: every rank decodes
            # the identical result, bit for bit
            np.testing.assert_array_equal(out, res[0])
        info = groups[0].last_op_info
        assert info["quant"] == "int8" and info["algo"] == ALGO_RING

    def test_quantized_min_op(self):
        """The two-pass reduces decoded fp32, never codes — non-SUM ops
        stay correct under quantization."""
        rng = np.random.default_rng(4)
        data = [rng.standard_normal(512).astype(np.float32)
                for _ in range(3)]
        exact = np.minimum(np.minimum(data[0], data[1]), data[2])
        res, _ = _dcn_ring(
            3,
            lambda g, r: g.allreduce(data[r], op=ReduceOp.MIN, quant="int8"),
            "qmin",
        )
        rel = np.abs(res[0] - exact).max() / np.abs(exact).max()
        assert rel <= 2e-2

    def test_quant_wire_reduction(self):
        rng = np.random.default_rng(5)
        data = [rng.standard_normal(8192).astype(np.float32)
                for _ in range(2)]
        _, qg = _dcn_ring(
            2, lambda g, r: g.allreduce(data[r], quant="int8"), "qw"
        )
        _, fg = _dcn_ring(2, lambda g, r: g.allreduce(data[r]), "fw")
        ratio = fg[0].last_op_info["bytes"] / qg[0].last_op_info["bytes"]
        assert ratio >= 3.5

    def test_error_feedback_requires_sum_and_quant(self):
        g = DcnGroup(FakeKV(), 1, 0, "efv2", timeout=5, op_timeout=5)
        try:
            with pytest.raises(ValueError, match="error_feedback requires"):
                g.allreduce(np.ones(4), error_feedback=True)
            with pytest.raises(ValueError, match="EF-safe"):
                g.allreduce(np.ones(4), op=ReduceOp.MAX, quant="int8",
                            error_feedback=True)
        finally:
            g.destroy()

    def test_error_feedback_toy_sgd_converges(self):
        """EF-SGD on a toy quadratic: each 'rank' holds a shard of the
        objective, gradients cross the quantized wire. With error
        feedback the final iterate lands essentially on the fp32
        optimum; without it the quantization bias is visible."""
        n, dim, steps, lr = 2, 256, 40, 0.1
        rng = np.random.default_rng(11)
        targets = [rng.standard_normal(dim).astype(np.float32)
                   for _ in range(n)]
        opt = sum(targets) / n  # argmin of mean ||x - t_r||^2

        def sgd(g, r, ef):
            x = np.zeros(dim, dtype=np.float32)
            for _ in range(steps):
                grad = 2 * (x - targets[r])
                gsum = g.allreduce(grad, quant="int8",
                                   error_feedback=ef, ef_key="g")
                x = x - lr * (gsum / n)
            return x

        res_ef, _ = _dcn_ring(n, lambda g, r: sgd(g, r, True), "sgd_ef")
        err_ef = np.abs(res_ef[0] - opt).max()
        res_fp, _ = _dcn_ring(n, sgd_fp, "sgd_fp")
        err_fp = np.abs(res_fp[0] - opt).max()
        # EF tracks the exact-gradient trajectory to within a small
        # multiple of fp32 rounding at this scale.
        assert err_ef <= err_fp + 5e-3, (err_ef, err_fp)

    def test_rd_deadline_raises_typed_timeout(self):
        """A peer that never joins the rd exchange trips the op deadline
        as CollectiveTimeoutError — the PR 2 fault contract holds on the
        new algorithm path."""
        kv = FakeKV()
        groups, errs = [None] * 3, [None] * 3

        def mk(r):
            groups[r] = DcnGroup(kv, 3, r, "rddead", timeout=3, op_timeout=1)

        ts = [threading.Thread(target=mk, args=(r,)) for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()

        def work(r):
            # rank 2 (the fold's surplus rank) never shows up
            if r == 2:
                return
            try:
                groups[r].allreduce(np.ones(4), algo=ALGO_RD)
            except Exception as e:  # noqa: BLE001
                errs[r] = e

        ts = [threading.Thread(target=work, args=(r,)) for r in range(3)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        for g in groups:
            g.destroy()
        # rank 0 waits on rank 2's fold contribution and must get the
        # typed error, not a hang or a bare socket.timeout
        assert isinstance(errs[0], CollectiveTimeoutError)

    def test_epoch_fence_holds_on_new_paths(self):
        """A member carrying a stale epoch cannot rendezvous with the
        new ring (keys are epoch-stamped), so no rd/quant exchange can
        ever splice attempts."""
        kv = FakeKV()
        fresh = DcnGroup(kv, 2, 0, "fence", timeout=1, op_timeout=1, epoch=2)
        try:
            with pytest.raises(TimeoutError):
                DcnGroup(kv, 2, 1, "fence", timeout=1, op_timeout=1,
                         epoch=1)._lookup(0)
        finally:
            fresh.destroy()


def sgd_fp(g, r):
    """fp32 companion loop for the EF convergence test."""
    n, dim, steps, lr = 2, 256, 40, 0.1
    rng = np.random.default_rng(11)
    targets = [rng.standard_normal(dim).astype(np.float32)
               for _ in range(n)]
    x = np.zeros(dim, dtype=np.float32)
    for _ in range(steps):
        grad = 2 * (x - targets[r])
        gsum = g.allreduce(grad)
        x = x - lr * (gsum / n)
    return x


# -- sharded hierarchical allreduce -------------------------------------

class TestShardedHier:
    N_LOCAL = 4

    def _hier(self, name, fn, kv=None):
        from ray_tpu.util.collective.hier_group import HierarchicalGroup

        kv = kv if kv is not None else FakeKV()
        return _run_ring(
            2,
            lambda r: HierarchicalGroup(
                kv, 2, r, name, num_local_devices=self.N_LOCAL, epoch=0
            ),
            fn,
        )

    def _data(self):
        # integer-valued fp32: SUM must be bit-exact however it is
        # scheduled, so hier vs flat comparisons can demand equality
        return {
            r: [np.arange(64, dtype=np.float32) + 64 * d + 1000 * r
                for d in range(self.N_LOCAL)]
            for r in range(2)
        }

    def test_bit_equivalent_with_flat_ring(self):
        data = self._data()
        exact = sum(sum(data[r]) for r in range(2))
        res, groups = self._hier(
            "hbit", lambda g, r: g.allreduce(data[r], algo=ALGO_HIER)
        )
        for r in range(2):
            for d in range(self.N_LOCAL):
                np.testing.assert_array_equal(np.asarray(res[r][d]), exact)
        info = groups[0].last_op_info
        assert info["algo"] == ALGO_HIER and info["tier"] == "ici+dcn"

        # flat baseline: all 8 devices as individual DCN ring members
        flat_in = [data[r][d] for r in range(2) for d in range(self.N_LOCAL)]
        flat_res, _ = _dcn_ring(
            8, lambda g, r: g.allreduce(flat_in[r], algo=ALGO_RING), "hflat"
        )
        np.testing.assert_array_equal(flat_res[0], exact)

    def test_dcn_bytes_cut_to_one_over_n_local(self):
        """The acceptance gate, in miniature: total DCN bytes of the
        sharded-hier exchange <= (1/n_local + 10%) of the flat ring in
        which every device is a DCN member."""
        size = 16 * 1024  # large enough that headers are noise
        data = {r: [np.full(size, float(r * self.N_LOCAL + d),
                            dtype=np.float32)
                    for d in range(self.N_LOCAL)] for r in range(2)}
        _, hg = self._hier(
            "hbytes", lambda g, r: g.allreduce(data[r], algo=ALGO_HIER)
        )
        hier_total = sum(g.dcn.bytes_sent for g in hg)
        flat_in = [data[r][d] for r in range(2)
                   for d in range(self.N_LOCAL)]
        _, fg = _dcn_ring(
            8, lambda g, r: g.allreduce(flat_in[r], algo=ALGO_RING), "hbf"
        )
        flat_total = sum(g.bytes_sent for g in fg)
        assert hier_total / flat_total <= 1 / self.N_LOCAL + 0.10

    def test_hier_quantized(self):
        rng = np.random.default_rng(21)
        data = {r: [rng.standard_normal(1024).astype(np.float32)
                    for _ in range(self.N_LOCAL)] for r in range(2)}
        exact = sum(sum(data[r]) for r in range(2))
        res, groups = self._hier(
            "hq",
            lambda g, r: g.allreduce(data[r], algo=ALGO_HIER, quant="int8"),
        )
        rel = (np.abs(np.asarray(res[0][0]) - exact).max()
               / np.abs(exact).max())
        assert rel <= 1e-2
        assert groups[0].last_op_info["quant"] == "int8"


# -- chaos DCN injections ------------------------------------------------

class TestChaosDcn:
    def test_requires_enabled(self):
        chaos.disable()
        with pytest.raises(RuntimeError, match="RT_CHAOS"):
            chaos.delay_dcn_send(0.1)
        with pytest.raises(RuntimeError, match="RT_CHAOS"):
            chaos.cap_dcn_bandwidth(1000)

    def test_delay_and_cap_consumed_on_send_path(self):
        chaos.enable()
        try:
            chaos.delay_dcn_send(0.05, count=2)
            assert chaos.take_dcn_send_delay() == 0.05
            assert chaos.take_dcn_send_delay() == 0.05
            assert chaos.take_dcn_send_delay() is None
            chaos.cap_dcn_bandwidth(1e6)
            assert chaos.dcn_bandwidth_cap() == 1e6
            chaos.clear()
            assert chaos.dcn_bandwidth_cap() is None
        finally:
            chaos.disable()

    def test_delay_slows_ring_deterministically(self):
        """Injected per-send latency shows up in op wall time but never
        in the byte accounting."""
        import time as time_mod

        chaos.enable()
        try:
            data = np.ones(64, dtype=np.float32)

            def timed(g, r):
                if r == 0:
                    chaos.delay_dcn_send(0.05, count=2)
                t0 = time_mod.perf_counter()
                g.allreduce(data, algo=ALGO_RING)
                return time_mod.perf_counter() - t0

            res, groups = _dcn_ring(2, timed, "cdel")
            assert max(res) >= 0.05
            # bytes identical across ranks: injection is time-only
            assert groups[0].bytes_sent == groups[1].bytes_sent
        finally:
            chaos.disable()
            chaos.clear()


# -- observer/metrics surface -------------------------------------------

class TestObserverInfo:
    def test_observer_receives_tier_algo_bytes(self):
        from ray_tpu.util.collective import collective as col

        class G:
            last_op_info = {"op": "allreduce", "tier": "dcn",
                            "algo": "ring", "bytes": 123,
                            "dtype": "float32", "quant": None}

        seen = []
        col.add_op_observer(lambda op, dt, info: seen.append((op, info)))
        try:
            col._observed("allreduce", lambda: 1, G())
        finally:
            col._op_observers.clear()
        assert seen and seen[0][0] == "allreduce"
        assert seen[0][1]["tier"] == "dcn"
        assert seen[0][1]["bytes"] == 123

    def test_legacy_two_arg_observer_still_served(self):
        from ray_tpu.util.collective import collective as col

        seen = []

        def legacy(op, dt):
            seen.append(op)

        col.add_op_observer(legacy)
        try:
            col._observed("barrier", lambda: None)
        finally:
            col._op_observers.clear()
        assert seen == ["barrier"]

    def test_metrics_emitted(self):
        from ray_tpu.util import metrics as m
        from ray_tpu.util.collective import collective as col

        class G:
            last_op_info = {"op": "allreduce", "tier": "dcn",
                            "algo": "rd", "bytes": 64,
                            "dtype": "float32", "quant": None}

        col._observed("allreduce", lambda: 1, G())
        mm = col._collective_metrics()
        assert mm["bytes"]._name == "collective_bytes_total"
        key = mm["bytes"]._key({"tier": "dcn", "algo": "rd",
                                "dtype": "float32"})
        with mm["bytes"]._lock:
            assert mm["bytes"]._deltas.get(key, 0) >= 64
        assert mm["seconds"].summary()["count"] >= 1

    def test_xla_group_records_ici_tier(self):
        from ray_tpu.util.collective.xla_group import XlaLocalGroup

        g = XlaLocalGroup(4)
        g.allreduce([np.full(8, float(i), dtype=np.float32)
                     for i in range(4)])
        info = g.last_op_info
        assert info["tier"] == "ici" and info["algo"] == "psum"
        assert info["bytes"] == 32
