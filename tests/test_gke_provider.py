"""GKETPUNodeProvider against a recorded/mock GKE API surface.

The provider's only IO is transport.request(method, url, body); this mock
models node pools + instance-group managers + async setSize operations the
way the container/compute APIs answer (the reference tests its providers
against fakes the same way: autoscaler/_private/fake_multi_node)."""

import re

import pytest

from ray_tpu.autoscaler.node_provider import GKETPUNodeProvider


class MockGKE:
    def __init__(self):
        self.pools = {
            "tpu-v5e-16": {"size": 0, "instances": [], "slice_hosts": 4},
            "cpu-pool": {"size": 1, "instances": ["zones/z/instances/cpu-0"],
                         "slice_hosts": 1},
        }
        self._op_counter = 0
        self._pending_ops = {}  # op name -> remaining polls until DONE
        self.calls = []  # recorded (method, url, body)

    def _pool_of(self, url):
        m = re.search(r"nodePools/([^:/]+)", url)
        if m:
            return m.group(1)
        m = re.search(r"instanceGroupManagers/([^/]+)", url)
        return m.group(1)

    def request(self, method, url, body=None):
        self.calls.append((method, url, body))
        if ":setSize" in url:
            pool = self.pools[self._pool_of(url)]
            target = body["nodeCount"]
            while len(pool["instances"]) < target:
                pool["instances"].append(
                    f"zones/z/instances/{self._pool_of(url)}-{len(pool['instances'])}"
                )
            pool["size"] = target
            self._op_counter += 1
            name = f"operation-{self._op_counter}"
            self._pending_ops[name] = 2  # DONE after 2 polls
            return {"name": name, "status": "RUNNING"}
        if "/operations/" in url:
            name = url.rsplit("/", 1)[1]
            self._pending_ops[name] -= 1
            done = self._pending_ops[name] <= 0
            return {"name": name, "status": "DONE" if done else "RUNNING"}
        if url.endswith("/listManagedInstances"):
            pool = self.pools[self._pool_of(url)]
            return {
                "managedInstances": [
                    {"instance": u} for u in pool["instances"]
                ]
            }
        if url.endswith("/deleteInstances"):
            pool = self.pools[self._pool_of(url)]
            for u in body["instances"]:
                if u in pool["instances"]:
                    pool["instances"].remove(u)
                    pool["size"] -= 1
            return {"status": "DONE"}
        if url.endswith("/nodePools") and method == "GET":
            return {"nodePools": [{"name": n} for n in self.pools]}
        if "/nodePools/" in url and method == "GET":
            name = self._pool_of(url)
            pool = self.pools[name]
            return {
                "name": name,
                "initialNodeCount": pool["size"],
                "instanceGroupUrls": [
                    f"https://compute.googleapis.com/compute/v1/projects/p/"
                    f"zones/z/instanceGroupManagers/{name}"
                ],
            }
        raise AssertionError(f"unexpected GKE call: {method} {url}")


@pytest.fixture
def provider():
    mock = MockGKE()
    p = GKETPUNodeProvider(
        "proj", "us-central2-b", "tpu-cluster",
        transport=mock, poll_interval_s=0.0,
    )
    return p, mock


def test_create_tpu_slice_is_whole_slice_atomic(provider):
    p, mock = provider
    ids = p.create_node(
        "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, count=1
    )
    # One slice = 4 hosts created together; pool resized 0 -> 4 in ONE call.
    assert len(ids) == 4
    resizes = [c for c in mock.calls if ":setSize" in c[1]]
    assert len(resizes) == 1
    assert resizes[0][2] == {"nodeCount": 4}
    assert mock.pools["tpu-v5e-16"]["size"] == 4
    for nid in ids:
        assert p.node_tags(nid)["rt-node-type"] == "v5e-16"


def test_create_two_slices(provider):
    p, mock = provider
    ids = p.create_node(
        "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, count=2
    )
    assert len(ids) == 8
    assert mock.pools["tpu-v5e-16"]["size"] == 8


def test_setsize_operation_is_polled_to_done(provider):
    p, mock = provider
    p.create_node("v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 1)
    op_polls = [c for c in mock.calls if "/operations/" in c[1]]
    assert len(op_polls) >= 2, "async setSize must be polled until DONE"


def test_terminate_deletes_instance_via_instance_group(provider):
    p, mock = provider
    ids = p.create_node(
        "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 1
    )
    p.terminate_node(ids[0])
    deletes = [c for c in mock.calls if c[1].endswith("/deleteInstances")]
    assert len(deletes) == 1
    assert deletes[0][2]["instances"] == [ids[0].split("|", 1)[1]]
    assert mock.pools["tpu-v5e-16"]["size"] == 3
    assert ids[0] not in p.non_terminated_nodes()


def test_non_terminated_reflects_live_pool_state():
    mock = MockGKE()
    p = GKETPUNodeProvider(
        "proj", "us-central2-b", "tpu-cluster",
        transport=mock, poll_interval_s=0.0,
        managed_pools=["tpu-v5e-16"],  # scope to the TPU pool
    )
    ids = p.create_node(
        "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 1
    )
    live = p.non_terminated_nodes()
    assert sorted(live) == sorted(ids)
    # An instance that dies out-of-band disappears from the listing.
    mock.pools["tpu-v5e-16"]["instances"].pop()
    assert len(p.non_terminated_nodes()) == 3


def test_restarted_provider_still_sees_nodes(provider):
    """Node enumeration must come from the live API, not in-process
    memory: a head restart creates a fresh provider that still has to
    see (and be able to terminate) running TPU slices."""
    p, mock = provider
    ids = p.create_node(
        "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 1
    )
    fresh = GKETPUNodeProvider(
        "proj", "us-central2-b", "tpu-cluster",
        transport=mock, poll_interval_s=0.0,
    )
    live = fresh.non_terminated_nodes()
    assert set(ids) <= set(live)
    assert "cpu-pool|zones/z/instances/cpu-0" in live
    fresh.terminate_node(ids[0])
    assert mock.pools["tpu-v5e-16"]["size"] == 3


def test_cpu_pool_single_host(provider):
    p, mock = provider
    ids = p.create_node("cpu", {"node_pool": "cpu-pool"}, count=2)
    assert len(ids) == 2
    assert mock.pools["cpu-pool"]["size"] == 3


def test_quota_denied_operation_raises(provider):
    """A setSize whose operation completes with an error (quota denial)
    must surface as an exception, not silently return zero nodes."""
    p, mock = provider
    real_request = mock.request

    def request(method, url, body=None):
        out = real_request(method, url, body)
        if "/operations/" in url and out.get("status") == "DONE":
            out["error"] = {"code": 8, "message":
                            "RESOURCE_EXHAUSTED: TPU quota exceeded"}
        return out

    mock.request = request
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        p.create_node(
            "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 1
        )


def test_partial_resize_returns_only_new_instances(provider):
    """A node-pool resize the platform only partially honors (stockout:
    target 8, delivered 6) must report exactly the instances that exist
    — the autoscaler re-requests the shortfall next tick rather than
    double-counting phantom hosts."""
    p, mock = provider
    real_request = mock.request

    def request(method, url, body=None):
        if ":setSize" in url:
            body = dict(body)
            body["nodeCount"] = min(body["nodeCount"], 6)  # stockout at 6
        return real_request(method, url, body)

    mock.request = request
    ids = p.create_node(
        "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 2
    )
    assert len(ids) == 6  # what actually exists, not the 8 requested
    assert len(p.non_terminated_nodes()) >= 6


def test_operation_timeout_raises(provider):
    p, mock = provider
    p.op_timeout_s = 0.01
    real_request = mock.request

    def request(method, url, body=None):
        out = real_request(method, url, body)
        if "/operations/" in url:
            out["status"] = "RUNNING"  # never completes
        return out

    mock.request = request
    with pytest.raises(TimeoutError):
        p.create_node(
            "v5e-16", {"node_pool": "tpu-v5e-16", "slice_hosts": 4}, 1
        )
