"""Serve survival plane: overload shed, deadline propagation, replica
death recovery, graceful drain, and controller failover.

The fault-tolerance mirror of test_serve.py: every scenario kills,
overloads, or expires something mid-flight and asserts the plane degrades
with a TYPED answer — 429-shaped ServeOverloadedError, 504-shaped
RequestCancelledError, streams that resume at the delivered-chunk offset,
replicas that drain before dying, handles that keep routing on cached
routes while the controller is down — instead of a generic failure.
"""

import json
import os
import signal
import time
import urllib.error
import urllib.request
from dataclasses import replace

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.exceptions import (
    RequestCancelledError,
    ServeOverloadedError,
    TaskError,
)
from ray_tpu.serve import context as request_context


@pytest.fixture
def serve_session(rt_start):
    yield rt_start
    serve.shutdown()


@pytest.fixture
def cfg_override():
    """Mutate the config singleton for this (test) process; restore on
    exit. Worker processes are unaffected — use for handle/engine-side
    knobs only."""
    cfg = get_config()
    saved = {}

    def override(**kw):
        for k, v in kw.items():
            if k not in saved:
                saved[k] = getattr(cfg, k)
            setattr(cfg, k, v)

    yield override
    for k, v in saved.items():
        setattr(cfg, k, v)


def _tiny_model():
    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, dtype=np.float32)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


# -- admission control + deadline, at the engine ------------------------

def test_engine_admission_shed_wfq_and_deadline(cfg_override, monkeypatch):
    """One engine, three survival behaviors: (1) the bounded WFQ
    admission queue sheds past serve_max_queued_per_engine with a typed,
    Retry-After-carrying error; (2) per-tenant queues exist (WFQ
    accounting visible in stats); (3) deadlines reach the engine — a
    pre-expired submit is refused, an in-flight request whose deadline
    passes mid-decode is cancelled and its slot evicted."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    cfg_override(serve_max_queued_per_engine=3)
    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=1, max_len=512)
    handles = []
    try:
        # Occupy the single slot so subsequent submits stay queued.
        with request_context.bind(request_context.RequestMeta(tenant="a")):
            h0 = eng.submit([3, 7, 11], max_new_tokens=256)
        handles.append(h0)
        deadline = time.time() + 60
        while eng.stats()["active"] < 1:
            assert time.time() < deadline, "slot was never granted"
            time.sleep(0.01)
        # Fill the admission queue to its bound, split across tenants.
        for tenant in ("a", "b", "a"):
            with request_context.bind(
                    request_context.RequestMeta(tenant=tenant)):
                handles.append(eng.submit([1, 2], max_new_tokens=1))
        st = eng.stats()
        assert st["waiting"] == 3
        assert set(st["waiting_tenants"]) == {"a", "b"}
        # Past the bound: typed shed, never enqueued.
        with request_context.bind(request_context.RequestMeta(tenant="c")):
            with pytest.raises(ServeOverloadedError) as ei:
                eng.submit([1, 2], max_new_tokens=1)
        assert ei.value.retry_after_s > 0
        assert eng.stats()["shed_total"] >= 1
        # Pre-expired deadline: refused at submit, not executed.
        with request_context.bind(
                request_context.RequestMeta(deadline_ts=time.time() - 1.0)):
            with pytest.raises(RequestCancelledError):
                eng.submit([1, 2], max_new_tokens=1)
        # In-flight expiry: a chaos prefill stretch burns the request's
        # budget inside the engine, so the post-stretch deadline check
        # cancels it and evicts the slot — deterministically, regardless
        # of how fast the tiny model decodes.
        for h in handles:
            h.cancel()
        deadline = time.time() + 60
        while eng.stats()["active"] > 0:
            assert time.time() < deadline, "cancelled slots never evicted"
            time.sleep(0.01)
        monkeypatch.setenv("RT_CHAOS", "1")
        chaos.delay_prefills(0.8, count=1)
        with request_context.bind(
                request_context.RequestMeta(deadline_ts=time.time() + 0.3)):
            h_exp = eng.submit([5, 9], max_new_tokens=8)
        with pytest.raises(RequestCancelledError):
            h_exp.result(timeout=60)
        assert eng.stats()["deadline_expired"] >= 1
    finally:
        chaos.clear()
        for h in handles:
            if not h._done:
                h.cancel()
        eng.shutdown()


# -- admission control at the handle ------------------------------------

def test_handle_shed_is_synchronous_and_typed(serve_session, cfg_override):
    """When every replica is past max_ongoing + queue bound by this
    handle's own in-flight counts, .remote() sheds synchronously (zero
    RPCs) with ServeOverloadedError; the already-admitted requests still
    complete."""
    cfg_override(serve_max_queued_per_replica=1)

    @serve.deployment(max_ongoing_requests=1)
    class Slow:
        def __call__(self, s):
            time.sleep(s)
            return s

    h = serve.run(Slow.bind())
    admitted = [h.remote(1.0), h.remote(1.0)]  # bound = 1 ongoing + 1 queued
    t0 = time.perf_counter()
    with pytest.raises(ServeOverloadedError) as ei:
        h.remote(1.0)
    shed_ms = (time.perf_counter() - t0) * 1e3
    assert ei.value.retry_after_s > 0
    assert shed_ms < 50, f"shed decision took {shed_ms:.1f} ms"
    assert [r.result(timeout=60) for r in admitted] == [1.0, 1.0]


def test_handle_deadline_bounds_result(serve_session):
    """options(deadline_s=...) propagates an absolute deadline;
    .result() without an explicit timeout stops at the deadline with the
    typed cancellation instead of the fixed 60 s wait."""

    @serve.deployment
    def napper(s):
        time.sleep(s)
        return s

    h = serve.run(napper.bind())
    assert h.remote(0.01).result(timeout=60) == 0.01  # warm route cache
    r = h.options(deadline_s=0.3).remote(10.0)
    t0 = time.monotonic()
    with pytest.raises(RequestCancelledError):
        r.result()
    assert time.monotonic() - t0 < 5.0


# -- replica death recovery ---------------------------------------------

def test_stream_resumes_at_offset_after_replica_death(serve_session):
    """Kill the replica serving a stream mid-flight: the handle restarts
    the request on another replica and resumes AT THE CHUNK OFFSET
    already delivered — the client sees every value exactly once."""

    @serve.deployment(num_replicas=2)
    class Gen:
        def __call__(self, n):
            yield os.getpid()  # chunk 0 identifies the serving replica
            for i in range(n):
                time.sleep(0.05)
                yield i

    h = serve.run(Gen.bind())
    it = iter(h.options(stream=True).remote(12))
    pid = next(it)
    out = [next(it) for _ in range(3)]  # deliver chunks 1..3 -> [0, 1, 2]
    os.kill(pid, signal.SIGKILL)
    out.extend(it)  # resume replays deterministically, skips 4 delivered
    assert out == list(range(12))


def test_unary_redispatch_after_replica_kill(serve_session, monkeypatch):
    """chaos.kill_replica murders one of two replicas while unary
    requests are in flight: every request still resolves (redispatch to
    the surviving replica under a stable idempotency key) — zero lost."""
    monkeypatch.setenv("RT_CHAOS", "1")

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            time.sleep(0.4)
            return x * 2

    h = serve.run(Echo.bind())
    rs = [h.remote(i) for i in range(6)]
    time.sleep(0.15)  # let dispatches land on both replicas
    chaos.kill_replica("Echo", 0)
    assert sorted(r.result(timeout=90) for r in rs) == [0, 2, 4, 6, 8, 10]


# -- graceful drain ------------------------------------------------------

def test_drain_completes_inflight_then_sheds(rt_start):
    """drain() stops new admissions, waits for in-flight work, and
    reports the drain duration; the in-flight request completes normally
    and post-drain requests are refused with ReplicaDrainingError."""
    from ray_tpu.serve.replica import ReplicaActor

    def napper(s):
        time.sleep(s)
        return s

    rep = ReplicaActor.options(max_concurrency=4).remote(napper, (), {})
    ref = rep.handle_request.remote("__call__", (0.8,), {})
    time.sleep(0.2)  # the request is admitted and executing
    d = rt.get(rep.drain.remote(10.0), timeout=30)
    assert d["drained"] is True and d["remaining"] == 0
    assert d["duration_s"] >= 0.3  # it actually waited for the request
    assert rt.get(ref, timeout=10) == 0.8  # in-flight work was NOT lost
    with pytest.raises(TaskError) as ei:
        rt.get(rep.handle_request.remote("__call__", (0.1,), {}), timeout=10)
    assert ei.value.cause_cls_name == "ReplicaDrainingError"
    rt.kill(rep)


# -- controller failover -------------------------------------------------

def test_traffic_survives_controller_death(serve_session, monkeypatch):
    """Kill the controller under traffic: handles keep routing on cached
    routes while it is down, and the restarted controller restores its
    checkpoint so FRESH handles (no cache) route again."""
    monkeypatch.setenv("RT_CHAOS", "1")

    @serve.deployment
    def echo(x):
        return x + 1

    h = serve.run(echo.bind())
    assert h.remote(1).result(timeout=60) == 2  # populate the route cache
    chaos.drop_controller(restart=True)
    for i in range(5):  # cached routes carry traffic through the outage
        assert h.remote(i).result(timeout=60) == i + 1
    deadline = time.time() + 60
    while True:  # the restarted controller restores from its checkpoint
        try:
            if "echo" in serve.status():
                break
        except Exception:  # noqa: BLE001 — restart races are the test
            pass
        assert time.time() < deadline, "controller never came back"
        time.sleep(0.2)
    h2 = serve.get_app_handle("echo")
    assert h2.remote(7).result(timeout=60) == 8


# -- proxy error mapping -------------------------------------------------

def _post(addr, app, body, headers=None):
    req = urllib.request.Request(
        f"{addr}/{app}", data=json.dumps(body).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    try:
        with urllib.request.urlopen(req, timeout=30) as r:
            return r.status, dict(r.headers), json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, dict(e.headers), json.loads(e.read())


def test_proxy_maps_typed_errors_to_status_codes(serve_session):
    """429 + Retry-After for shed, 504 for deadline expiry (enforced by
    the proxy's bounded await via the serve_deadline_ms header), 200 for
    success — never a generic 500 for a typed failure."""

    @serve.deployment
    def overloaded():
        raise ServeOverloadedError("busy", retry_after_s=3.0)

    @serve.deployment
    def napper(s=0.0):
        time.sleep(s)
        return s

    serve.run(overloaded.bind())
    serve.run(napper.bind())
    addr = serve.start_http_proxy(port=0)

    code, hdrs, body = _post(addr, "overloaded", {})
    assert code == 429
    assert body["kind"] == "shed"
    assert int(hdrs["Retry-After"]) >= 3

    code, _, body = _post(addr, "napper", {"s": 5.0},
                          {"serve_deadline_ms": "200"})
    assert code == 504
    assert body["kind"] == "deadline"

    code, _, body = _post(addr, "napper", {"s": 0.0})
    assert code == 200
    assert body["result"] == 0.0
