"""Remote (rt://) driver protocol — the reference's Ray Client
(util/client/worker.py:81): a driver with NO local node and NO shared
memory drives the cluster entirely over TCP.

The remote driver runs in a subprocess so it genuinely cannot share
memory with the cluster's store.
"""

import subprocess
import sys
import textwrap

import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster

_DRIVER = textwrap.dedent(
    """
    import sys
    sys.path.insert(0, {repo!r})
    import numpy as np
    import ray_tpu as rt

    rt.init(address="rt://" + sys.argv[1])

    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(2, 3), timeout=60) == 5

    # Large object round trip through the raylet proxy (no local shm).
    # > object_transfer_chunk_size (5MB): exercises chunked put
    arr = np.arange(1_000_000, dtype=np.float64)
    ref = rt.put(arr)
    out = rt.get(ref, timeout=60)
    assert out.sum() == arr.sum()

    # Large TASK RETURN fetched remotely.
    @rt.remote
    def big():
        return np.ones(400_000)

    assert rt.get(big.remote(), timeout=60).sum() == 400_000.0

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0
        def inc(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert rt.get(c.inc.remote(), timeout=60) == 1
    assert rt.get(c.inc.remote(), timeout=60) == 2

    rt.shutdown()
    print("REMOTE DRIVER OK")
    """
)

import os

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_remote_driver_over_tcp(tmp_path):
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        script = tmp_path / "remote_driver.py"
        script.write_text(_DRIVER.format(repo=_REPO))
        p = subprocess.run(
            [sys.executable, str(script), f"127.0.0.1:{cluster.gcs_port}"],
            capture_output=True, timeout=240, text=True,
        )
        assert p.returncode == 0, (
            f"remote driver failed rc={p.returncode}\n"
            f"stdout: {p.stdout}\nstderr: {p.stderr[-3000:]}"
        )
        assert "REMOTE DRIVER OK" in p.stdout
    finally:
        cluster.shutdown()
