"""Memory monitor + OOM worker-killing policy.

Reference analog: raylet MemoryMonitor + worker_killing_policy.cc — at
memory_usage_threshold the raylet kills the newest retriable task's worker
(so it retries) instead of letting the OS OOM-killer take the node.
Memory pressure is simulated by overriding the raylet's usage probe.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster


def test_oom_kills_newest_retriable_and_task_retries():
    cluster = Cluster()
    head = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        fake = {"frac": 0.5}
        head._memory_usage_fraction = lambda: fake["frac"]

        @rt.remote(max_retries=3)
        def hog():
            time.sleep(1.0)
            return "survived"

        ref = hog.remote()
        time.sleep(0.4)  # task is inflight
        fake["frac"] = 0.99  # cross the threshold: monitor must kill
        time.sleep(0.8)
        fake["frac"] = 0.5   # pressure gone: retry can complete

        assert rt.get(ref, timeout=60) == "survived"
        # The kill is surfaced in the task-event stream for the state API.
        events = [e for e in head._task_events] + [
            e for e in cluster.gcs.task_events
        ]
        assert any(e.get("state") == "OOM_KILLED" for e in events), (
            "no OOM_KILLED task event recorded"
        )
    finally:
        cluster.shutdown()


def test_oom_prefers_retriable_over_nonretriable():
    cluster = Cluster()
    head = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        fake = {"frac": 0.5}
        head._memory_usage_fraction = lambda: fake["frac"]

        @rt.remote(max_retries=0)
        def precious():
            time.sleep(2.5)
            return "precious"

        @rt.remote(max_retries=3)
        def expendable():
            time.sleep(2.5)
            return "expendable"

        p_ref = precious.remote()
        time.sleep(0.3)
        e_ref = expendable.remote()  # newer AND retriable: the victim
        time.sleep(0.5)
        fake["frac"] = 0.99
        # Drop pressure as soon as the first kill lands: under SUSTAINED
        # pressure the policy correctly escalates to non-retriable tasks
        # once no retriable candidates remain.
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            seen = list(head._task_events) + list(cluster.gcs.task_events)
            if any(e.get("state") == "OOM_KILLED" for e in seen):
                break
            time.sleep(0.05)
        fake["frac"] = 0.5

        # The non-retriable task must NOT have been chosen while a
        # retriable candidate existed.
        assert rt.get(p_ref, timeout=60) == "precious"
        assert rt.get(e_ref, timeout=60) == "expendable"  # retried
        events = [e for e in head._task_events] + [
            e for e in cluster.gcs.task_events
        ]
        oom = [e for e in events if e.get("state") == "OOM_KILLED"]
        assert oom, "monitor never fired"
        assert all(e.get("name") != "precious" for e in oom)
    finally:
        cluster.shutdown()
