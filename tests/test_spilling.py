"""Object spilling + lineage reconstruction tests.

Reference analogs: python/ray/tests/test_object_spilling*.py and
test_reconstruction*.py (owner-side lineage re-execution).
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ObjectID


SMALL_STORE = 48 * 1024 * 1024  # 48 MB store


@pytest.fixture
def rt_small_store(tmp_path, monkeypatch):
    monkeypatch.setenv("RT_SPILL_DIR", str(tmp_path / "spill"))
    import ray_tpu._private.config as config_mod

    config_mod._config = None  # re-read env
    rt.init(num_cpus=2, object_store_memory=SMALL_STORE)
    yield rt
    rt.shutdown()
    config_mod._config = None


def _raylet():
    return worker_mod._global_node.raylet



def _force_delete(raylet, oid):
    """Forcibly remove EVERY local copy of an object for loss-injection
    tests. Under full-suite load the async primary-copy registration can
    re-pin between release and delete (retry), and the spill loop can
    win the race and spill the copy instead — a spilled copy is
    restorable, so its record must go too or the "loss" silently fails
    to inject."""
    deadline = time.monotonic() + 10
    while raylet.store.contains(ObjectID(oid)):
        if oid in raylet._primary_pins:
            raylet.store.release(ObjectID(oid))
            raylet._primary_pins.pop(oid)
        if raylet.store.delete(ObjectID(oid)):
            break
        assert time.monotonic() < deadline, "store delete never succeeded"
        time.sleep(0.1)
    raylet._spilled.pop(oid, None)

def test_put_beyond_capacity_spills(rt_small_store):
    """Total puts exceed the store; older primaries spill and restore."""
    arrays = [np.full(2_000_000, i, dtype=np.float64) for i in range(5)]
    refs = [rt.put(a) for a in arrays]  # 5 x 16MB > 48MB store
    assert _raylet()._spilled, "expected at least one spilled object"

    # Every object is still retrievable (spilled ones restore on get).
    for i, ref in enumerate(refs):
        out = rt.get(ref, timeout=60)
        assert out[0] == i and out.shape == (2_000_000,)


def test_spill_keeps_data_exact(rt_small_store):
    rng = np.random.default_rng(0)
    payload = rng.standard_normal(2_000_000)
    ref = rt.put(payload)
    # Force pressure so `payload`'s object spills.
    pressure = [rt.put(np.zeros(2_000_000)) for _ in range(4)]
    time.sleep(1.0)  # let the spill loop run
    out = rt.get(ref, timeout=60)
    np.testing.assert_array_equal(out, payload)
    del pressure


def test_task_returns_spill(rt_small_store):
    """Task returns that exceed capacity spill; each is retrievable (one
    at a time — zero-copy reads pin store memory while the value lives)."""

    @rt.remote
    def make(i):
        return np.full(2_000_000, i, dtype=np.float64)

    refs = [make.remote(i) for i in range(5)]
    for i, ref in enumerate(refs):
        v = rt.get(ref, timeout=120)
        assert v[0] == i
        del v  # release the zero-copy pin so the object stays spillable


def test_lineage_reconstruction(rt_start):
    """Losing every copy of a task return re-executes the task."""
    calls = {"n": 0}

    @rt.remote
    def produce():
        return np.arange(1_000_000, dtype=np.float64)  # 8MB -> store

    ref = produce.remote()
    first = rt.get(ref, timeout=60)
    assert first.sum() == pytest.approx(999999 * 1000000 / 2)

    # Simulate total loss: delete the local copy + directory entry.
    client = worker_mod.get_client()
    oid = ref.id.binary()
    raylet = _raylet()
    # Drop client pin, raylet pin, then the object itself.
    pin = client._pins.pop(oid, None)
    if pin is not None:
        pin.release()
    del first
    _force_delete(raylet, oid)
    client._in_store.discard(oid)
    client._run(
        client.gcs.call(
            "object_location_remove",
            {"object_id": oid, "node_id": raylet.node_id.binary(),
             "clear_spilled": True},
        )
    )

    out = rt.get(ref, timeout=60)  # must reconstruct via lineage
    assert out.sum() == pytest.approx(999999 * 1000000 / 2)


def test_put_objects_not_reconstructable(rt_start):
    """rt.put data has no lineage: losing it raises ObjectLostError."""
    ref = rt.put(np.ones(1_000_000))
    client = worker_mod.get_client()
    oid = ref.id.binary()
    raylet = _raylet()
    pin = client._pins.pop(oid, None)
    if pin is not None:
        pin.release()
    _force_delete(raylet, oid)
    client._in_store.discard(oid)
    client._run(
        client.gcs.call(
            "object_location_remove",
            {"object_id": oid, "node_id": raylet.node_id.binary(),
             "clear_spilled": True},
        )
    )
    with pytest.raises(rt.exceptions.ObjectLostError):
        rt.get(ref, timeout=5)


# ---------------------------------------------------------------------------
# URI (cloud-shaped) spill backend — VERDICT r3 item 5
# ---------------------------------------------------------------------------


def test_uri_storage_s3_shaped_fake_fs(tmp_path):
    """s3://-shaped spill URIs against an injected local filesystem
    (reference: external_storage.py:445 smart_open S3 impl; here the
    same pyarrow.fs layer train/storage.py drives)."""
    import pyarrow.fs as pafs

    from ray_tpu._private.external_storage import UriStorage, create_storage

    fake_s3 = pafs.SubTreeFileSystem(str(tmp_path), pafs.LocalFileSystem())
    store = UriStorage("s3://bucket/spill", filesystem=fake_s3,
                       base_path="bucket/spill")

    payload = np.arange(1000, dtype=np.float64).tobytes()
    uri = store.spill(b"\x01" * 16, memoryview(payload))
    assert uri.startswith("s3://bucket/spill/") and uri.endswith(".bin")
    assert store.restore(uri) == payload
    store.delete([uri])
    with pytest.raises(Exception):
        store.restore(uri)

    # create_storage routes cloud-shaped URIs onto UriStorage.
    st2 = create_storage("ab" * 8, "s3://bucket/spill", filesystem=fake_s3)
    assert isinstance(st2, UriStorage)
    uri2 = st2.spill(b"\x02" * 16, memoryview(b"xyz"))
    assert st2.restore(uri2) == b"xyz"


def test_spill_e2e_through_uri_backend(tmp_path, monkeypatch):
    """End-to-end raylet spill+restore through the pyarrow.fs URI
    backend (file:// exercises the identical UriStorage code path the
    cloud schemes take, without credentials)."""
    monkeypatch.setenv("RT_SPILL_DIR", "file://" + str(tmp_path / "spill"))
    import ray_tpu._private.config as config_mod

    config_mod._config = None
    rt.init(num_cpus=2, object_store_memory=SMALL_STORE)
    try:
        arrays = [np.full(2_000_000, i, dtype=np.float64) for i in range(5)]
        refs = [rt.put(a) for a in arrays]
        assert _raylet()._spilled, "expected at least one spilled object"
        spilled_uris = list(_raylet()._spilled.values())
        assert any(str(u).startswith("file://") for u in spilled_uris), spilled_uris
        for i, ref in enumerate(refs):
            out = rt.get(ref, timeout=60)
            assert out[0] == i and out.shape == (2_000_000,)
    finally:
        rt.shutdown()
        config_mod._config = None
