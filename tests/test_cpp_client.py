"""C++ user API tests: the native client against a live cluster.

Reference model: the C++ user API test suite (cpp/src/ray/test/ in the
reference) — here the rt_demo binary drives connect/KV/objects/
cross-language tasks over the wire protocol, and Python-side tests verify
interop in both directions (C++ put read by Python, Python xlang objects
read back, RTX1 round trip).
"""

import os
import subprocess

import pytest

import ray_tpu as rt

CPP_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "cpp")
DEMO = os.path.join(CPP_DIR, "build", "rt_demo")


def _build_demo():
    if not os.path.exists(DEMO):
        subprocess.run(
            ["make", "-s", "-C", CPP_DIR], check=True, timeout=300
        )
    return DEMO


def test_rtx1_roundtrip_python_side():
    from ray_tpu._private import serialization as ser

    value = {"kind": "xlang", "nums": [1, 2, 3.5], "blob": b"\x00\x01"}
    raw = ser.serialize_xlang(value)
    assert raw[:4] == b"1XTR"  # u32 0x52545831 ("RTX1") little-endian
    out = ser.deserialize_from_bytes(raw)
    assert out == value


def test_rtx1_tiny_payloads():
    """RTX1 frames can be shorter than the RTP1 12-byte header — None is
    5 bytes; deserialize must dispatch on the 4-byte magic first."""
    from ray_tpu._private import serialization as ser

    for value in (None, 0, 5, True, "", b""):
        assert ser.deserialize_from_bytes(ser.serialize_xlang(value)) == value


def test_cross_language_task_returning_none(rt_start):
    """A fn_name task whose result msgpack-encodes under 12 bytes must
    round-trip (regression: deserialize crashed on short RTX1 frames)."""
    import os as _os

    client = rt._worker.get_client()
    spec = {
        "task_id": _os.urandom(16),
        "job_id": client.job_id.binary(),
        "name": "builtins:print",
        "fn_name": "builtins:print",
        "plain_args": ["xlang"],
        "deps": [],
        "num_returns": 1,
        "resources": {"CPU": 1.0},
        "retriable": False,
    }
    result = client._run(client.raylet.call("submit_task", spec, timeout=120))
    assert result["status"] == "ok"
    from ray_tpu._private import serialization as ser

    assert ser.deserialize_from_bytes(result["returns"][0]["data"]) is None


def test_cross_language_task_from_python(rt_start):
    """The fn_name task path works from any frontend; drive it from
    Python by submitting a raw spec through the driver's raylet."""
    client = rt._worker.get_client()
    import os as _os

    from ray_tpu._private.ids import TaskID

    spec = {
        "task_id": _os.urandom(16),
        "job_id": client.job_id.binary(),
        "name": "math:hypot",
        "fn_name": "math:hypot",
        "plain_args": [3.0, 4.0],
        "deps": [],
        "num_returns": 1,
        "resources": {"CPU": 1.0},
        "retriable": False,
    }
    result = client._run(
        client.raylet.call("submit_task", spec, timeout=120)
    )
    assert result["status"] == "ok"
    [entry] = result["returns"]
    assert entry["kind"] == "inline"
    from ray_tpu._private import serialization as ser

    assert ser.deserialize_from_bytes(entry["data"]) == 5.0


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_cpp_demo_end_to_end(rt_start):
    """Build and run the C++ demo binary against the live cluster: KV,
    object put/get, cross-language submit, error propagation, and a
    direct cross-language ACTOR call (stateful, across two calls)."""
    demo = _build_demo()

    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def add(self, x):
            self.n += x
            return self.n

    c = Counter.options(name="cpp-counter").remote()
    rt.get(c.add.remote(0), timeout=60)  # ensure ready + addressable

    node = rt._node
    out = subprocess.run(
        [demo, node.gcs_host, str(node.gcs_port), "cpp-counter"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "CPP ACTOR OK" in out.stdout
    assert "CPP CLIENT OK" in out.stdout
    # The C++ calls mutated the SAME actor instance Python sees.
    assert rt.get(c.add.remote(0), timeout=60) == 42


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_cpp_put_readable_from_python(rt_start):
    """Interop: RTX1 objects written through the client_put path (what
    the C++ client's Put does) read back identically through
    client_get_info/fetch_chunk (what its Get does), and Python's
    deserializer understands them."""
    from ray_tpu._private import serialization as ser
    from ray_tpu._private.ids import ObjectID

    client = rt._worker.get_client()
    oid = ObjectID.from_random()
    raw = ser.serialize_xlang({"who": "python", "n": 7})
    ok = client._run(
        client.raylet.call(
            "client_put", {"object_id": oid.binary(), "data": raw},
            timeout=60,
        )
    )
    assert ok["ok"]
    info = client._run(
        client.raylet.call(
            "client_get_info", {"object_id": oid.binary()}, timeout=60
        )
    )
    assert info["ok"] and info["size"] == len(raw)
    chunk = client._run(
        client.raylet.call(
            "fetch_chunk",
            {"object_id": oid.binary(), "offset": 0, "size": info["size"]},
            timeout=60,
        )
    )
    assert ser.deserialize_from_bytes(chunk["data"]) == {
        "who": "python", "n": 7,
    }


def test_cpp_msgpack_unit_tests():
    """The native codec's own unit suite (format edges, length tiers,
    truncation rejection) — built and run via make -C cpp test."""
    out = subprocess.run(
        ["make", "-s", "-C", CPP_DIR, "test"],
        capture_output=True, text=True, timeout=300,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "MSGPACK TESTS OK" in out.stdout
