"""rtlint: per-rule fixtures (positive + negative twin + suppression),
baseline round-trip, and the repo-wide gate.

Each rule's positive fixture is the minimal reproduction of the bug
class; its negative twin is the same code with the one property that
makes it safe (a timeout, a lock, an epoch, a hoisted jit). The
suppression case proves `# rtlint: disable=RTxxx` works at both line
and def granularity.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.rtlint import Baseline, lint_paths, lint_source
from tools.rtlint.rules import ALL_RULES, rule_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings(src: str, path: str = "ray_tpu/serve/x.py"):
    return lint_source(textwrap.dedent(src), path)


def rule_ids(src: str, path: str = "ray_tpu/serve/x.py"):
    return [f.rule for f in findings(src, path)]


# -- RT001: host sync ------------------------------------------------------
RT001_POS = """
    import jax

    @jax.jit
    def step(x):
        return float(x.sum())
"""

RT001_NEG = """
    import jax

    @jax.jit
    def step(x):
        return x.sum()

    def report(x):
        return float(step(x))
"""


def test_rt001_traced_sync():
    assert "RT001" in rule_ids(RT001_POS)


def test_rt001_negative_twin():
    assert "RT001" not in rule_ids(RT001_NEG)


def test_rt001_loop_sync():
    src = """
        def drain(xs):
            out = []
            for x in xs:
                out.append(x.item())
            return out
    """
    fs = findings(src)
    assert [f.rule for f in fs] == ["RT001"]
    assert fs[0].token == ".item()"


def test_rt001_item_outside_loop_ok():
    assert "RT001" not in rule_ids("def f(x):\n    return x.item()\n")


# -- RT002: retrace risk ---------------------------------------------------
RT002_POS = """
    import jax

    def train(fns, x):
        for f in fns:
            y = jax.jit(f)(x)
        return y
"""

RT002_NEG = """
    import jax

    def train(fns, x):
        compiled = [jax.jit(f) for f in fns]
        return [g(x) for g in compiled]
"""


def test_rt002_jit_in_loop():
    assert "RT002" in rule_ids(RT002_POS)


def test_rt002_negative_twin():
    # List comprehensions build the wrappers once per fn, not per call.
    assert "RT002" not in rule_ids(
        "import jax\n\ndef f(g, x):\n    h = jax.jit(g)\n    return h(x)\n"
    )


def test_rt002_mutable_static_argnums():
    src = """
        import jax

        def build(f):
            return jax.jit(f, static_argnums=[0, 1])
    """
    fs = findings(src)
    assert [f.rule for f in fs] == ["RT002"]
    assert fs[0].token == "static-static_argnums"
    assert "RT002" not in rule_ids(src.replace("[0, 1]", "(0, 1)"))


def test_rt002_jit_def_in_loop():
    src = """
        import jax

        def outer(xs):
            for x in xs:
                @jax.jit
                def inner(y):
                    return y + x
                inner(x)
    """
    assert "jit-def-in-loop" in [f.token for f in findings(src)]


# -- RT003: unbounded blocking get ----------------------------------------
RT003_POS = """
    import ray_tpu as rt

    @rt.remote
    class Worker:
        def run(self, ref):
            return rt.get(ref)
"""

RT003_NEG = RT003_POS.replace("rt.get(ref)", "rt.get(ref, timeout=30)")


def test_rt003_actor_get_without_timeout():
    fs = findings(RT003_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT003"]
    assert fs[0].token == "rt.get"


def test_rt003_negative_twin():
    assert "RT003" not in rule_ids(RT003_NEG, path="ray_tpu/rl/x.py")


def test_rt003_control_plane_free_function():
    src = """
        import ray_tpu as rt

        def bootstrap(refs):
            rt.get(refs)
    """
    assert "RT003" in rule_ids(src, path="ray_tpu/util/collective/x.py")
    # Same helper outside the control-plane scopes: not flagged.
    assert "RT003" not in rule_ids(src, path="ray_tpu/rl/x.py")


def test_rt003_bare_result():
    src = """
        @rt.remote
        class A:
            def m(self, fut):
                return fut.result()
    """
    src = "import ray_tpu as rt\n" + textwrap.dedent(src)
    assert "RT003" in [f.rule for f in lint_source(src, "ray_tpu/rl/x.py")]


# -- RT004: discarded ObjectRef -------------------------------------------
RT004_POS = """
    def push(workers, w):
        for r in workers:
            r.set_weights.remote(w)
"""

RT004_NEG = """
    import ray_tpu as rt

    def push(workers, w):
        refs = [r.set_weights.remote(w) for r in workers]
        rt.get(refs, timeout=60)
"""


def test_rt004_discarded_ref():
    fs = findings(RT004_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT004"]
    assert fs[0].token == "set_weights"


def test_rt004_negative_twin():
    assert "RT004" not in rule_ids(RT004_NEG, path="ray_tpu/rl/x.py")


# -- RT005: unfenced collective -------------------------------------------
RT005_POS = """
    from ray_tpu.util import collective as col

    def setup(ws, rank):
        col.init_collective_group(ws, rank, "dcn", "g")
"""

RT005_NEG = RT005_POS.replace('"g")', '"g", epoch=0)')


def test_rt005_missing_epoch():
    fs = findings(RT005_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT005"]
    assert fs[0].token == "init_collective_group"


def test_rt005_negative_twin():
    # Explicit epoch=0 is the call site asserting "never rebuilt".
    assert "RT005" not in rule_ids(RT005_NEG, path="ray_tpu/rl/x.py")


# -- RT006: cross-thread race ---------------------------------------------
RT006_POS = """
    import threading

    class Engine:
        def __init__(self):
            self._running = True
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while self._running:
                pass

        def shutdown(self):
            self._running = False
"""

RT006_NEG_LOCK = """
    import threading

    class Engine:
        def __init__(self):
            self._running = True
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while True:
                with self._lock:
                    if not self._running:
                        return

        def shutdown(self):
            with self._lock:
                self._running = False
"""

RT006_NEG_EVENT = """
    import threading

    class Engine:
        def __init__(self):
            self._stop_event = threading.Event()
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while not self._stop_event.is_set():
                pass

        def shutdown(self):
            self._stop_event.set()
"""


def test_rt006_unlocked_flag():
    fs = findings(RT006_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT006"]
    assert fs[0].token == "_running"


def test_rt006_lock_negative_twin():
    assert "RT006" not in rule_ids(RT006_NEG_LOCK, path="ray_tpu/rl/x.py")


def test_rt006_event_negative_twin():
    assert "RT006" not in rule_ids(RT006_NEG_EVENT, path="ray_tpu/rl/x.py")


def test_rt006_init_writes_exempt():
    # Writes before the thread starts happen-before it; only the
    # post-start caller-side write races.
    src = RT006_POS.replace(
        "def shutdown(self):\n            self._running = False",
        "def status(self):\n            return True",
    )
    assert "RT006" not in rule_ids(src, path="ray_tpu/rl/x.py")


# -- RT007: swallowed exception -------------------------------------------
RT007_POS = """
    def teardown(group):
        try:
            group.destroy()
        except Exception:
            pass
"""

RT007_NEG = """
    import logging

    def teardown(group):
        try:
            group.destroy()
        except OSError:
            pass
"""


def test_rt007_swallow_in_control_plane():
    fs = findings(RT007_POS, path="ray_tpu/train/x.py")
    assert [f.rule for f in fs] == ["RT007"]


def test_rt007_narrow_negative_twin():
    assert "RT007" not in rule_ids(RT007_NEG, path="ray_tpu/train/x.py")


def test_rt007_logging_body_ok():
    src = """
        import logging

        def teardown(group):
            try:
                group.destroy()
            except Exception:
                logging.warning("destroy failed", exc_info=True)
    """
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_rt007_scoped_to_control_plane():
    assert "RT007" not in rule_ids(RT007_POS, path="ray_tpu/rl/x.py")


# -- suppressions ----------------------------------------------------------
def test_line_suppression():
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable=RT007")
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_def_suppression_covers_body():
    src = RT006_POS.replace(
        "def shutdown(self):",
        "def shutdown(self):  # rtlint: disable=RT006",
    )
    assert "RT006" not in rule_ids(src, path="ray_tpu/rl/x.py")


def test_suppression_is_rule_specific():
    # Disabling RT001 does not hide the RT007.
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable=RT001")
    assert "RT007" in rule_ids(src, path="ray_tpu/train/x.py")


def test_blanket_suppression():
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable")
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


# -- engine behavior -------------------------------------------------------
def test_syntax_error_yields_rt000():
    fs = lint_source("def broken(:\n", "ray_tpu/x.py")
    assert [f.rule for f in fs] == ["RT000"]


def test_fingerprint_is_line_independent():
    fs1 = findings(RT007_POS, path="ray_tpu/train/x.py")
    fs2 = findings("\n\n\n" + textwrap.dedent(RT007_POS),
                   path="ray_tpu/train/x.py")
    assert fs1[0].fingerprint == fs2[0].fingerprint
    assert fs1[0].line != fs2[0].line


def test_baseline_roundtrip(tmp_path):
    fs = findings(RT007_POS, path="ray_tpu/train/x.py")
    bl = Baseline.from_findings(fs)
    p = tmp_path / "baseline.json"
    bl.save(str(p))
    loaded = Baseline.load(str(p))
    assert loaded.counts == bl.counts
    assert loaded.new_findings(fs) == []
    # A second identical violation exceeds the baselined count.
    doubled = fs + fs
    assert len(loaded.new_findings(doubled)) == len(fs)
    # JSON on disk is the documented shape.
    data = json.loads(p.read_text())
    assert set(data) == {"comment", "findings"}


def test_baseline_stale_entries():
    bl = Baseline({"RT007|gone.py|f|swallow": 1})
    assert bl.stale_entries([]) == ["RT007|gone.py|f|swallow"]


def test_rule_catalog():
    ids = [r.id for r in ALL_RULES]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert ids == [f"RT{i:03d}" for i in range(1, 14)]
    assert rule_by_id("rt003").id == "RT003"
    assert rule_by_id("rt013").id == "RT013"
    for r in ALL_RULES:
        assert r.name and r.__doc__


# -- repo-wide gate --------------------------------------------------------
def test_repo_is_clean_against_baseline():
    """The tier-1 gate: linting ray_tpu/ yields no findings beyond the
    committed baseline. New violations fail here, with the finding text
    in the assertion message."""
    bl = Baseline.load(os.path.join(REPO, "tools", "rtlint",
                                    "baseline.json"))
    fs = lint_paths([os.path.join(REPO, "ray_tpu")], root=REPO)
    new = bl.new_findings(fs)
    assert not new, "new rtlint findings:\n" + "\n".join(map(str, new))


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    env = dict(os.environ, PYTHONPATH=REPO)
    run = lambda *a: subprocess.run(  # noqa: E731
        [sys.executable, "-m", "tools.rtlint", *a],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    clean = run("--no-baseline", str(tmp_path / "nothing"))
    assert clean.returncode == 0
    dirty = run("--no-baseline", str(bad))
    assert dirty.returncode == 1
    assert "RT004" in dirty.stdout
    assert run("--explain", "RT006").returncode == 0
    assert run("--explain", "RT999").returncode == 2


# -- RT008: blocking call in async ----------------------------------------
RT008_POS = """
    import time

    async def handler():
        time.sleep(1.0)
"""

RT008_NEG = """
    import asyncio

    async def handler():
        await asyncio.sleep(1.0)
"""


def test_rt008_sleep_in_async():
    assert "RT008" in rule_ids(RT008_POS)


def test_rt008_negative_twin():
    assert "RT008" not in rule_ids(RT008_NEG)


def test_rt008_popen_in_async():
    src = """
        import subprocess

        async def launch(cmd):
            return subprocess.Popen(cmd)
    """
    assert "RT008" in rule_ids(src)


def test_rt008_executor_shipped_ok():
    src = """
        import asyncio, time

        async def handler(loop):
            await loop.run_in_executor(None, time.sleep, 1.0)
    """
    assert "RT008" not in rule_ids(src)


def test_rt008_suppression():
    src = """
        import time

        async def handler():
            time.sleep(1.0)  # rtlint: disable=RT008 — test hook
    """
    assert "RT008" not in rule_ids(src)


# -- RT009: deadline taint drop -------------------------------------------
RT009_POS = """
    def dispatch(handle, payload, meta):
        return handle.remote(payload)
"""

RT009_NEG = """
    def dispatch(handle, payload, meta):
        return handle.remote(payload, meta=meta)
"""


def test_rt009_dropped_meta():
    assert "RT009" in rule_ids(RT009_POS)


def test_rt009_negative_twin():
    assert "RT009" not in rule_ids(RT009_NEG)


def test_rt009_bind_counts_as_forwarding():
    src = """
        def dispatch(handle, payload, meta):
            with bind(meta):
                return handle.remote(payload)
    """
    assert "RT009" not in rule_ids(src)


def test_rt009_local_deadline_taint():
    src = """
        import time

        def handle_request(handle, payload, deadline_ms):
            deadline_ts = time.time() + deadline_ms / 1000.0
            return handle.remote(payload)
    """
    assert "RT009" in rule_ids(src)


def test_rt009_closure_hop_is_outer_functions():
    src = """
        def handle_request(handle, payload, meta):
            def go():
                return handle.remote(payload)
            return go()
    """
    assert "RT009" in rule_ids(src)


def test_rt009_annotation_taint():
    src = """
        def dispatch(handle, payload, card: "RequestMeta"):
            return handle.remote(payload)
    """
    assert "RT009" in rule_ids(src)


def test_rt009_suppression():
    src = """
        def dispatch(handle, payload, meta):
            return handle.remote(payload)  # rtlint: disable=RT009 — rides .options
    """
    assert "RT009" not in rule_ids(src)


# -- RT010: lock discipline ------------------------------------------------
RT010_POS = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def reset(self):
            self.n = 0
"""

RT010_NEG = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def reset(self):
            with self._lock:
                self.n = 0
"""


def test_rt010_bare_access():
    assert "RT010" in rule_ids(RT010_POS)


def test_rt010_negative_twin():
    assert "RT010" not in rule_ids(RT010_NEG)


def test_rt010_locked_suffix_exempt():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._reset_locked()
                    self.n += 1

            def _reset_locked(self):
                self.n = 0
    """
    assert "RT010" not in rule_ids(src)


def test_rt010_init_exempt():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1
    """
    assert "RT010" not in rule_ids(src)


def test_rt010_suppression():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def peek(self):
                return self.n  # rtlint: disable=RT010 — single-writer snapshot
    """
    assert "RT010" not in rule_ids(src)


# -- RT011: clock domains --------------------------------------------------
RT011_POS = """
    import time

    def elapsed(deadline_ts):
        t0 = time.monotonic()
        return deadline_ts - t0
"""

RT011_NEG = """
    import time

    def elapsed():
        t0 = time.monotonic()
        return time.monotonic() - t0
"""


def test_rt011_cross_domain_sub():
    assert "RT011" in rule_ids(RT011_POS)


def test_rt011_negative_twin():
    assert "RT011" not in rule_ids(RT011_NEG)


def test_rt011_monotonic_deadline_ok():
    src = """
        import time

        def waiter(timeout):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                pass
    """
    assert "RT011" not in rule_ids(src)


def test_rt011_wall_anchor_shape():
    src = """
        import time

        def stamp(dur_unknowable):
            return time.time() - dur_unknowable
    """
    assert "RT011" in rule_ids(src)


def test_rt011_suppression():
    src = """
        import time

        def stamp(mono_t):
            return time.time() - mono_t  # rtlint: disable=RT011 — wall anchor
    """
    assert "RT011" not in rule_ids(src)


# -- RT012: donated buffer reuse ------------------------------------------
RT012_POS = """
    import jax

    step = jax.jit(_step, donate_argnums=(0,))

    def loop(kv, x):
        out = step(kv, x)
        return kv.sum()
"""

RT012_NEG = """
    import jax

    step = jax.jit(_step, donate_argnums=(0,))

    def loop(kv, x):
        kv = step(kv, x)
        return kv.sum()
"""


def test_rt012_use_after_donate():
    assert "RT012" in rule_ids(RT012_POS)


def test_rt012_negative_twin():
    assert "RT012" not in rule_ids(RT012_NEG)


def test_rt012_swallowing_handler_without_rebind():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            try:
                kv = step(kv, x)
            except RuntimeError:
                log("oops")
            return kv.sum()
    """
    assert "RT012" in rule_ids(src)


def test_rt012_handler_rebuilds_donated_state():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            try:
                kv = step(kv, x)
            except RuntimeError:
                kv = fresh_cache()
            return kv.sum()
    """
    assert "RT012" not in rule_ids(src)


def test_rt012_reraising_handler_ok():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            try:
                kv = step(kv, x)
            except RuntimeError:
                raise
            return kv.sum()
    """
    assert "RT012" not in rule_ids(src)


def test_rt012_suppression():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            out = step(kv, x)
            return kv.sum()  # rtlint: disable=RT012 — loop rebinds first
    """
    assert "RT012" not in rule_ids(src)


# -- RT013: metrics discipline --------------------------------------------
RT013_POS = """
    BOUNDARIES = [0.1, 0.5, 1.0]

    def widen():
        BOUNDARIES.append(5.0)
"""

RT013_NEG = """
    BOUNDARIES = (0.1, 0.5, 1.0)

    def widen():
        return BOUNDARIES + (5.0,)
"""


def test_rt013_boundary_mutation():
    assert "RT013" in rule_ids(RT013_POS)


def test_rt013_negative_twin():
    assert "RT013" not in rule_ids(RT013_NEG)


def test_rt013_boundaries_list_literal():
    src = """
        h = Histogram("latency", boundaries=[0.1, 0.5, 1.0])
    """
    assert "RT013" in rule_ids(src)


def test_rt013_boundaries_tuple_ok():
    src = """
        h = Histogram("latency", boundaries=(0.1, 0.5, 1.0))
    """
    assert "RT013" not in rule_ids(src)


def test_rt013_per_request_label():
    src = """
        def record(m, rid):
            m.inc(1, tags={"rid": rid})
    """
    assert "RT013" in rule_ids(src)


def test_rt013_bounded_label_ok():
    src = """
        def record(m, model):
            m.inc(1, tags={"model": model})
    """
    assert "RT013" not in rule_ids(src)


def test_rt013_suppression():
    src = """
        def record(m, tenant):
            m.inc(1, tags={"tenant": tenant})  # rtlint: disable=RT013 — admission-bounded
    """
    assert "RT013" not in rule_ids(src)


# -- project model / call graph -------------------------------------------
def _write(tree, base):
    for rel, src in tree.items():
        p = base / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))


def test_callgraph_actor_reach_across_files(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "helpers.py": """
            import ray_tpu as rt

            def fetch(ref):
                return rt.get(ref)
        """,
        "actors.py": """
            import ray_tpu as rt
            from helpers import fetch

            @rt.remote
            class A:
                def m(self, ref):
                    return fetch(ref)
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    hits = [f for f in res.findings if f.rule == "RT003"]
    assert hits and hits[0].path == "helpers.py"
    assert "A.m" in hits[0].message


def test_callgraph_reexport_and_self_method_resolution(tmp_path):
    """One chain exercising both: `from pkg import work` resolves
    through pkg/__init__'s re-export, and async context propagates
    through a self-method call (`run` -> self._go -> work)."""
    from tools.rtlint import analyze_paths
    _write({
        "pkg/__init__.py": "from pkg.impl import work\n",
        "pkg/impl.py": """
            import time

            def work():
                time.sleep(1)
        """,
        "loop.py": """
            from pkg import work

            class Srv:
                async def run(self):
                    self._go()

                def _go(self):
                    work()
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    hits = [f for f in res.findings if f.rule == "RT008"]
    assert hits and hits[0].path == "pkg/impl.py"


def test_callgraph_import_cycle_terminates(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "a_mod.py": """
            import b_mod

            def fa():
                return b_mod.fb()
        """,
        "b_mod.py": """
            import a_mod

            def fb():
                return a_mod.fa()
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert res.files == 2
    assert not [f for f in res.findings if f.rule == "RT000"]


def test_crash_safety_rt000_on_syntax_error(tmp_path):
    from tools.rtlint import analyze_paths
    (tmp_path / "broken.py").write_text("def broken(:\n")
    (tmp_path / "fine.py").write_text("x = 1\n")
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    rt000 = [f for f in res.findings if f.rule == "RT000"]
    assert len(rt000) == 1 and rt000[0].path == "broken.py"
    assert res.files == 2


# -- CLI: formats, jobs, cache, changed, stats -----------------------------
def _cli(*args, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "tools.rtlint", *args],
        capture_output=True, text=True, env=env, cwd=cwd,
    )


def test_cli_json_format(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--format", "json", str(bad))
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["tool"] == "rtlint"
    assert doc["new_findings"] and \
        doc["new_findings"][0]["rule"] == "RT004"


def test_cli_sarif_format(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--format", "sarif", str(bad))
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert results and results[0]["ruleId"] == "RT004"
    assert results[0]["partialFingerprints"]["rtlint/v1"]


def test_cli_jobs_matches_serial(tmp_path):
    serial = _cli("--no-baseline", "--no-cache", "--format", "json",
                  "ray_tpu/serve/")
    par = _cli("--no-baseline", "--no-cache", "--format", "json",
               "--jobs", "4", "ray_tpu/serve/")
    assert serial.returncode == par.returncode
    a, b = json.loads(serial.stdout), json.loads(par.stdout)
    key = lambda f: (f["rule"], f["path"], f["line"])  # noqa: E731
    assert sorted(map(key, a["new_findings"])) == \
        sorted(map(key, b["new_findings"]))
    assert a["total_findings"] == b["total_findings"]


def test_cli_cache_warm_run_consistent(tmp_path):
    cache = tmp_path / "cache.json"
    cold = _cli("--no-baseline", "--cache", str(cache), "ray_tpu/util/")
    assert cache.exists()
    warm = _cli("--no-baseline", "--cache", str(cache), "ray_tpu/util/")
    assert cold.stdout == warm.stdout
    assert cold.returncode == warm.returncode


def test_cli_changed_mode(tmp_path):
    git = lambda *a: subprocess.run(  # noqa: E731
        ["git", *a], cwd=tmp_path, capture_output=True, text=True,
        env=dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                 GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t"),
    )
    git("init", "-q")
    (tmp_path / "clean.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    # no changed files: exits clean without linting anything
    out = _cli("--no-baseline", "--no-cache", "--changed", str(tmp_path),
               "--root", str(tmp_path), cwd=str(tmp_path))
    assert out.returncode == 0 and "no changed" in out.stdout
    # an untracked offender is picked up by --changed
    (tmp_path / "bad.py").write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--changed", str(tmp_path),
               "--root", str(tmp_path), cwd=str(tmp_path))
    assert out.returncode == 1 and "RT004" in out.stdout


def test_cli_stats(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--stats", str(bad))
    assert out.returncode == 1
    assert "RT004" in out.stdout and "total" in out.stdout


def test_cli_usage_errors():
    assert _cli("--jobs", "0").returncode == 2
    assert _cli("--rules", "RT999").returncode == 2


def test_default_targets_cover_tools_and_benches():
    from tools.rtlint import DEFAULT_TARGETS
    assert "ray_tpu" in DEFAULT_TARGETS
    assert "tools" in DEFAULT_TARGETS
    assert any(t.startswith("bench_") for t in DEFAULT_TARGETS)


def test_repo_default_targets_clean_against_baseline():
    """The full gate over the v2 default target set (ray_tpu/, tools/,
    bench_*.py), exactly what `make lint` runs."""
    out = _cli("--no-cache")
    assert out.returncode == 0, out.stdout + out.stderr
