"""rtlint: per-rule fixtures (positive + negative twin + suppression),
baseline round-trip, and the repo-wide gate.

Each rule's positive fixture is the minimal reproduction of the bug
class; its negative twin is the same code with the one property that
makes it safe (a timeout, a lock, an epoch, a hoisted jit). The
suppression case proves `# rtlint: disable=RTxxx` works at both line
and def granularity.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.rtlint import Baseline, lint_paths, lint_source
from tools.rtlint.rules import ALL_RULES, rule_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings(src: str, path: str = "ray_tpu/serve/x.py"):
    return lint_source(textwrap.dedent(src), path)


def rule_ids(src: str, path: str = "ray_tpu/serve/x.py"):
    return [f.rule for f in findings(src, path)]


# -- RT001: host sync ------------------------------------------------------
RT001_POS = """
    import jax

    @jax.jit
    def step(x):
        return float(x.sum())
"""

RT001_NEG = """
    import jax

    @jax.jit
    def step(x):
        return x.sum()

    def report(x):
        return float(step(x))
"""


def test_rt001_traced_sync():
    assert "RT001" in rule_ids(RT001_POS)


def test_rt001_negative_twin():
    assert "RT001" not in rule_ids(RT001_NEG)


def test_rt001_loop_sync():
    src = """
        def drain(xs):
            out = []
            for x in xs:
                out.append(x.item())
            return out
    """
    fs = findings(src)
    assert [f.rule for f in fs] == ["RT001"]
    assert fs[0].token == ".item()"


def test_rt001_item_outside_loop_ok():
    assert "RT001" not in rule_ids("def f(x):\n    return x.item()\n")


# -- RT002: retrace risk ---------------------------------------------------
RT002_POS = """
    import jax

    def train(fns, x):
        for f in fns:
            y = jax.jit(f)(x)
        return y
"""

RT002_NEG = """
    import jax

    def train(fns, x):
        compiled = [jax.jit(f) for f in fns]
        return [g(x) for g in compiled]
"""


def test_rt002_jit_in_loop():
    assert "RT002" in rule_ids(RT002_POS)


def test_rt002_negative_twin():
    # List comprehensions build the wrappers once per fn, not per call.
    assert "RT002" not in rule_ids(
        "import jax\n\ndef f(g, x):\n    h = jax.jit(g)\n    return h(x)\n"
    )


def test_rt002_mutable_static_argnums():
    src = """
        import jax

        def build(f):
            return jax.jit(f, static_argnums=[0, 1])
    """
    fs = findings(src)
    assert [f.rule for f in fs] == ["RT002"]
    assert fs[0].token == "static-static_argnums"
    assert "RT002" not in rule_ids(src.replace("[0, 1]", "(0, 1)"))


def test_rt002_jit_def_in_loop():
    src = """
        import jax

        def outer(xs):
            for x in xs:
                @jax.jit
                def inner(y):
                    return y + x
                inner(x)
    """
    assert "jit-def-in-loop" in [f.token for f in findings(src)]


# -- RT003: unbounded blocking get ----------------------------------------
RT003_POS = """
    import ray_tpu as rt

    @rt.remote
    class Worker:
        def run(self, ref):
            return rt.get(ref)
"""

RT003_NEG = RT003_POS.replace("rt.get(ref)", "rt.get(ref, timeout=30)")


def test_rt003_actor_get_without_timeout():
    fs = findings(RT003_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT003"]
    assert fs[0].token == "rt.get"


def test_rt003_negative_twin():
    assert "RT003" not in rule_ids(RT003_NEG, path="ray_tpu/rl/x.py")


def test_rt003_control_plane_free_function():
    src = """
        import ray_tpu as rt

        def bootstrap(refs):
            rt.get(refs)
    """
    assert "RT003" in rule_ids(src, path="ray_tpu/util/collective/x.py")
    # Same helper outside the control-plane scopes: not flagged.
    assert "RT003" not in rule_ids(src, path="ray_tpu/rl/x.py")


def test_rt003_bare_result():
    src = """
        @rt.remote
        class A:
            def m(self, fut):
                return fut.result()
    """
    src = "import ray_tpu as rt\n" + textwrap.dedent(src)
    assert "RT003" in [f.rule for f in lint_source(src, "ray_tpu/rl/x.py")]


# -- RT004: discarded ObjectRef -------------------------------------------
RT004_POS = """
    def push(workers, w):
        for r in workers:
            r.set_weights.remote(w)
"""

RT004_NEG = """
    import ray_tpu as rt

    def push(workers, w):
        refs = [r.set_weights.remote(w) for r in workers]
        rt.get(refs, timeout=60)
"""


def test_rt004_discarded_ref():
    fs = findings(RT004_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT004"]
    assert fs[0].token == "set_weights"


def test_rt004_negative_twin():
    assert "RT004" not in rule_ids(RT004_NEG, path="ray_tpu/rl/x.py")


# -- RT005: unfenced collective -------------------------------------------
RT005_POS = """
    from ray_tpu.util import collective as col

    def setup(ws, rank):
        col.init_collective_group(ws, rank, "dcn", "g")
"""

RT005_NEG = RT005_POS.replace('"g")', '"g", epoch=0)')


def test_rt005_missing_epoch():
    fs = findings(RT005_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT005"]
    assert fs[0].token == "init_collective_group"


def test_rt005_negative_twin():
    # Explicit epoch=0 is the call site asserting "never rebuilt".
    assert "RT005" not in rule_ids(RT005_NEG, path="ray_tpu/rl/x.py")


# -- RT006: cross-thread race ---------------------------------------------
RT006_POS = """
    import threading

    class Engine:
        def __init__(self):
            self._running = True
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while self._running:
                pass

        def shutdown(self):
            self._running = False
"""

RT006_NEG_LOCK = """
    import threading

    class Engine:
        def __init__(self):
            self._running = True
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while True:
                with self._lock:
                    if not self._running:
                        return

        def shutdown(self):
            with self._lock:
                self._running = False
"""

RT006_NEG_EVENT = """
    import threading

    class Engine:
        def __init__(self):
            self._stop_event = threading.Event()
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while not self._stop_event.is_set():
                pass

        def shutdown(self):
            self._stop_event.set()
"""


def test_rt006_unlocked_flag():
    fs = findings(RT006_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT006"]
    assert fs[0].token == "_running"


def test_rt006_lock_negative_twin():
    assert "RT006" not in rule_ids(RT006_NEG_LOCK, path="ray_tpu/rl/x.py")


def test_rt006_event_negative_twin():
    assert "RT006" not in rule_ids(RT006_NEG_EVENT, path="ray_tpu/rl/x.py")


def test_rt006_init_writes_exempt():
    # Writes before the thread starts happen-before it; only the
    # post-start caller-side write races.
    src = RT006_POS.replace(
        "def shutdown(self):\n            self._running = False",
        "def status(self):\n            return True",
    )
    assert "RT006" not in rule_ids(src, path="ray_tpu/rl/x.py")


# -- RT007: swallowed exception -------------------------------------------
RT007_POS = """
    def teardown(group):
        try:
            group.destroy()
        except Exception:
            pass
"""

RT007_NEG = """
    import logging

    def teardown(group):
        try:
            group.destroy()
        except OSError:
            pass
"""


def test_rt007_swallow_in_control_plane():
    fs = findings(RT007_POS, path="ray_tpu/train/x.py")
    assert [f.rule for f in fs] == ["RT007"]


def test_rt007_narrow_negative_twin():
    assert "RT007" not in rule_ids(RT007_NEG, path="ray_tpu/train/x.py")


def test_rt007_logging_body_ok():
    src = """
        import logging

        def teardown(group):
            try:
                group.destroy()
            except Exception:
                logging.warning("destroy failed", exc_info=True)
    """
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_rt007_scoped_to_control_plane():
    assert "RT007" not in rule_ids(RT007_POS, path="ray_tpu/rl/x.py")


# -- suppressions ----------------------------------------------------------
def test_line_suppression():
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable=RT007")
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_def_suppression_covers_body():
    src = RT006_POS.replace(
        "def shutdown(self):",
        "def shutdown(self):  # rtlint: disable=RT006",
    )
    assert "RT006" not in rule_ids(src, path="ray_tpu/rl/x.py")


def test_suppression_is_rule_specific():
    # Disabling RT001 does not hide the RT007.
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable=RT001")
    assert "RT007" in rule_ids(src, path="ray_tpu/train/x.py")


def test_blanket_suppression():
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable")
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


# -- engine behavior -------------------------------------------------------
def test_syntax_error_yields_rt000():
    fs = lint_source("def broken(:\n", "ray_tpu/x.py")
    assert [f.rule for f in fs] == ["RT000"]


def test_fingerprint_is_line_independent():
    fs1 = findings(RT007_POS, path="ray_tpu/train/x.py")
    fs2 = findings("\n\n\n" + textwrap.dedent(RT007_POS),
                   path="ray_tpu/train/x.py")
    assert fs1[0].fingerprint == fs2[0].fingerprint
    assert fs1[0].line != fs2[0].line


def test_baseline_roundtrip(tmp_path):
    fs = findings(RT007_POS, path="ray_tpu/train/x.py")
    bl = Baseline.from_findings(fs)
    p = tmp_path / "baseline.json"
    bl.save(str(p))
    loaded = Baseline.load(str(p))
    assert loaded.counts == bl.counts
    assert loaded.new_findings(fs) == []
    # A second identical violation exceeds the baselined count.
    doubled = fs + fs
    assert len(loaded.new_findings(doubled)) == len(fs)
    # JSON on disk is the documented shape.
    data = json.loads(p.read_text())
    assert set(data) == {"comment", "findings"}


def test_baseline_stale_entries():
    bl = Baseline({"RT007|gone.py|f|swallow": 1})
    assert bl.stale_entries([]) == ["RT007|gone.py|f|swallow"]


def test_rule_catalog():
    ids = [r.id for r in ALL_RULES]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert ids == [f"RT{i:03d}" for i in range(1, 18)]
    assert rule_by_id("rt003").id == "RT003"
    assert rule_by_id("rt013").id == "RT013"
    assert rule_by_id("rt017").id == "RT017"
    for r in ALL_RULES:
        assert r.name and r.__doc__


# -- repo-wide gate --------------------------------------------------------
def test_repo_is_clean_against_baseline():
    """The tier-1 gate: linting ray_tpu/ yields no findings beyond the
    committed baseline. New violations fail here, with the finding text
    in the assertion message."""
    bl = Baseline.load(os.path.join(REPO, "tools", "rtlint",
                                    "baseline.json"))
    fs = lint_paths([os.path.join(REPO, "ray_tpu")], root=REPO)
    new = bl.new_findings(fs)
    assert not new, "new rtlint findings:\n" + "\n".join(map(str, new))


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    env = dict(os.environ, PYTHONPATH=REPO)
    run = lambda *a: subprocess.run(  # noqa: E731
        [sys.executable, "-m", "tools.rtlint", *a],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    clean = run("--no-baseline", str(tmp_path / "nothing"))
    assert clean.returncode == 0
    dirty = run("--no-baseline", str(bad))
    assert dirty.returncode == 1
    assert "RT004" in dirty.stdout
    assert run("--explain", "RT006").returncode == 0
    assert run("--explain", "RT999").returncode == 2


# -- RT008: blocking call in async ----------------------------------------
RT008_POS = """
    import time

    async def handler():
        time.sleep(1.0)
"""

RT008_NEG = """
    import asyncio

    async def handler():
        await asyncio.sleep(1.0)
"""


def test_rt008_sleep_in_async():
    assert "RT008" in rule_ids(RT008_POS)


def test_rt008_negative_twin():
    assert "RT008" not in rule_ids(RT008_NEG)


def test_rt008_popen_in_async():
    src = """
        import subprocess

        async def launch(cmd):
            return subprocess.Popen(cmd)
    """
    assert "RT008" in rule_ids(src)


def test_rt008_executor_shipped_ok():
    src = """
        import asyncio, time

        async def handler(loop):
            await loop.run_in_executor(None, time.sleep, 1.0)
    """
    assert "RT008" not in rule_ids(src)


def test_rt008_suppression():
    src = """
        import time

        async def handler():
            time.sleep(1.0)  # rtlint: disable=RT008 — test hook
    """
    assert "RT008" not in rule_ids(src)


# -- RT009: deadline taint drop -------------------------------------------
RT009_POS = """
    def dispatch(handle, payload, meta):
        return handle.remote(payload)
"""

RT009_NEG = """
    def dispatch(handle, payload, meta):
        return handle.remote(payload, meta=meta)
"""


def test_rt009_dropped_meta():
    assert "RT009" in rule_ids(RT009_POS)


def test_rt009_negative_twin():
    assert "RT009" not in rule_ids(RT009_NEG)


def test_rt009_bind_counts_as_forwarding():
    src = """
        def dispatch(handle, payload, meta):
            with bind(meta):
                return handle.remote(payload)
    """
    assert "RT009" not in rule_ids(src)


def test_rt009_local_deadline_taint():
    src = """
        import time

        def handle_request(handle, payload, deadline_ms):
            deadline_ts = time.time() + deadline_ms / 1000.0
            return handle.remote(payload)
    """
    assert "RT009" in rule_ids(src)


def test_rt009_closure_hop_is_outer_functions():
    src = """
        def handle_request(handle, payload, meta):
            def go():
                return handle.remote(payload)
            return go()
    """
    assert "RT009" in rule_ids(src)


def test_rt009_annotation_taint():
    src = """
        def dispatch(handle, payload, card: "RequestMeta"):
            return handle.remote(payload)
    """
    assert "RT009" in rule_ids(src)


def test_rt009_suppression():
    src = """
        def dispatch(handle, payload, meta):
            return handle.remote(payload)  # rtlint: disable=RT009 — rides .options
    """
    assert "RT009" not in rule_ids(src)


# -- RT010: lock discipline ------------------------------------------------
RT010_POS = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def reset(self):
            self.n = 0
"""

RT010_NEG = """
    import threading

    class Counter:
        def __init__(self):
            self._lock = threading.Lock()
            self.n = 0

        def bump(self):
            with self._lock:
                self.n += 1

        def reset(self):
            with self._lock:
                self.n = 0
"""


def test_rt010_bare_access():
    assert "RT010" in rule_ids(RT010_POS)


def test_rt010_negative_twin():
    assert "RT010" not in rule_ids(RT010_NEG)


def test_rt010_locked_suffix_exempt():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self._reset_locked()
                    self.n += 1

            def _reset_locked(self):
                self.n = 0
    """
    assert "RT010" not in rule_ids(src)


def test_rt010_init_exempt():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1
    """
    assert "RT010" not in rule_ids(src)


def test_rt010_suppression():
    src = """
        import threading

        class Counter:
            def __init__(self):
                self._lock = threading.Lock()
                self.n = 0

            def bump(self):
                with self._lock:
                    self.n += 1

            def peek(self):
                return self.n  # rtlint: disable=RT010 — single-writer snapshot
    """
    assert "RT010" not in rule_ids(src)


# -- RT011: clock domains --------------------------------------------------
RT011_POS = """
    import time

    def elapsed(deadline_ts):
        t0 = time.monotonic()
        return deadline_ts - t0
"""

RT011_NEG = """
    import time

    def elapsed():
        t0 = time.monotonic()
        return time.monotonic() - t0
"""


def test_rt011_cross_domain_sub():
    assert "RT011" in rule_ids(RT011_POS)


def test_rt011_negative_twin():
    assert "RT011" not in rule_ids(RT011_NEG)


def test_rt011_monotonic_deadline_ok():
    src = """
        import time

        def waiter(timeout):
            deadline = time.monotonic() + timeout
            while time.monotonic() < deadline:
                pass
    """
    assert "RT011" not in rule_ids(src)


def test_rt011_wall_anchor_shape():
    src = """
        import time

        def stamp(dur_unknowable):
            return time.time() - dur_unknowable
    """
    assert "RT011" in rule_ids(src)


def test_rt011_suppression():
    src = """
        import time

        def stamp(mono_t):
            return time.time() - mono_t  # rtlint: disable=RT011 — wall anchor
    """
    assert "RT011" not in rule_ids(src)


# -- RT012: donated buffer reuse ------------------------------------------
RT012_POS = """
    import jax

    step = jax.jit(_step, donate_argnums=(0,))

    def loop(kv, x):
        out = step(kv, x)
        return kv.sum()
"""

RT012_NEG = """
    import jax

    step = jax.jit(_step, donate_argnums=(0,))

    def loop(kv, x):
        kv = step(kv, x)
        return kv.sum()
"""


def test_rt012_use_after_donate():
    assert "RT012" in rule_ids(RT012_POS)


def test_rt012_negative_twin():
    assert "RT012" not in rule_ids(RT012_NEG)


def test_rt012_swallowing_handler_without_rebind():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            try:
                kv = step(kv, x)
            except RuntimeError:
                log("oops")
            return kv.sum()
    """
    assert "RT012" in rule_ids(src)


def test_rt012_handler_rebuilds_donated_state():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            try:
                kv = step(kv, x)
            except RuntimeError:
                kv = fresh_cache()
            return kv.sum()
    """
    assert "RT012" not in rule_ids(src)


def test_rt012_reraising_handler_ok():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            try:
                kv = step(kv, x)
            except RuntimeError:
                raise
            return kv.sum()
    """
    assert "RT012" not in rule_ids(src)


def test_rt012_suppression():
    src = """
        import jax

        step = jax.jit(_step, donate_argnums=(0,))

        def loop(kv, x):
            out = step(kv, x)
            return kv.sum()  # rtlint: disable=RT012 — loop rebinds first
    """
    assert "RT012" not in rule_ids(src)


# -- RT013: metrics discipline --------------------------------------------
RT013_POS = """
    BOUNDARIES = [0.1, 0.5, 1.0]

    def widen():
        BOUNDARIES.append(5.0)
"""

RT013_NEG = """
    BOUNDARIES = (0.1, 0.5, 1.0)

    def widen():
        return BOUNDARIES + (5.0,)
"""


def test_rt013_boundary_mutation():
    assert "RT013" in rule_ids(RT013_POS)


def test_rt013_negative_twin():
    assert "RT013" not in rule_ids(RT013_NEG)


def test_rt013_boundaries_list_literal():
    src = """
        h = Histogram("latency", boundaries=[0.1, 0.5, 1.0])
    """
    assert "RT013" in rule_ids(src)


def test_rt013_boundaries_tuple_ok():
    src = """
        h = Histogram("latency", boundaries=(0.1, 0.5, 1.0))
    """
    assert "RT013" not in rule_ids(src)


def test_rt013_per_request_label():
    src = """
        def record(m, rid):
            m.inc(1, tags={"rid": rid})
    """
    assert "RT013" in rule_ids(src)


def test_rt013_bounded_label_ok():
    src = """
        def record(m, model):
            m.inc(1, tags={"model": model})
    """
    assert "RT013" not in rule_ids(src)


def test_rt013_suppression():
    src = """
        def record(m, tenant):
            m.inc(1, tags={"tenant": tenant})  # rtlint: disable=RT013 — admission-bounded
    """
    assert "RT013" not in rule_ids(src)


# -- project model / call graph -------------------------------------------
def _write(tree, base):
    for rel, src in tree.items():
        p = base / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))


def test_callgraph_actor_reach_across_files(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "helpers.py": """
            import ray_tpu as rt

            def fetch(ref):
                return rt.get(ref)
        """,
        "actors.py": """
            import ray_tpu as rt
            from helpers import fetch

            @rt.remote
            class A:
                def m(self, ref):
                    return fetch(ref)
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    hits = [f for f in res.findings if f.rule == "RT003"]
    assert hits and hits[0].path == "helpers.py"
    assert "A.m" in hits[0].message


def test_callgraph_reexport_and_self_method_resolution(tmp_path):
    """One chain exercising both: `from pkg import work` resolves
    through pkg/__init__'s re-export, and async context propagates
    through a self-method call (`run` -> self._go -> work)."""
    from tools.rtlint import analyze_paths
    _write({
        "pkg/__init__.py": "from pkg.impl import work\n",
        "pkg/impl.py": """
            import time

            def work():
                time.sleep(1)
        """,
        "loop.py": """
            from pkg import work

            class Srv:
                async def run(self):
                    self._go()

                def _go(self):
                    work()
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    hits = [f for f in res.findings if f.rule == "RT008"]
    assert hits and hits[0].path == "pkg/impl.py"


def test_callgraph_import_cycle_terminates(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "a_mod.py": """
            import b_mod

            def fa():
                return b_mod.fb()
        """,
        "b_mod.py": """
            import a_mod

            def fb():
                return a_mod.fa()
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert res.files == 2
    assert not [f for f in res.findings if f.rule == "RT000"]


def test_crash_safety_rt000_on_syntax_error(tmp_path):
    from tools.rtlint import analyze_paths
    (tmp_path / "broken.py").write_text("def broken(:\n")
    (tmp_path / "fine.py").write_text("x = 1\n")
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    rt000 = [f for f in res.findings if f.rule == "RT000"]
    assert len(rt000) == 1 and rt000[0].path == "broken.py"
    assert res.files == 2


# -- CLI: formats, jobs, cache, changed, stats -----------------------------
def _cli(*args, cwd=REPO):
    env = dict(os.environ, PYTHONPATH=REPO)
    return subprocess.run(
        [sys.executable, "-m", "tools.rtlint", *args],
        capture_output=True, text=True, env=env, cwd=cwd,
    )


def test_cli_json_format(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--format", "json", str(bad))
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["tool"] == "rtlint"
    assert doc["new_findings"] and \
        doc["new_findings"][0]["rule"] == "RT004"


def test_cli_sarif_format(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--format", "sarif", str(bad))
    assert out.returncode == 1
    doc = json.loads(out.stdout)
    assert doc["version"] == "2.1.0"
    results = doc["runs"][0]["results"]
    assert results and results[0]["ruleId"] == "RT004"
    assert results[0]["partialFingerprints"]["rtlint/v1"]


def test_cli_jobs_matches_serial(tmp_path):
    serial = _cli("--no-baseline", "--no-cache", "--format", "json",
                  "ray_tpu/serve/")
    par = _cli("--no-baseline", "--no-cache", "--format", "json",
               "--jobs", "4", "ray_tpu/serve/")
    assert serial.returncode == par.returncode
    a, b = json.loads(serial.stdout), json.loads(par.stdout)
    key = lambda f: (f["rule"], f["path"], f["line"])  # noqa: E731
    assert sorted(map(key, a["new_findings"])) == \
        sorted(map(key, b["new_findings"]))
    assert a["total_findings"] == b["total_findings"]


def test_cli_cache_warm_run_consistent(tmp_path):
    cache = tmp_path / "cache.json"
    cold = _cli("--no-baseline", "--cache", str(cache), "ray_tpu/util/")
    assert cache.exists()
    warm = _cli("--no-baseline", "--cache", str(cache), "ray_tpu/util/")
    assert cold.stdout == warm.stdout
    assert cold.returncode == warm.returncode


def test_cli_changed_mode(tmp_path):
    git = lambda *a: subprocess.run(  # noqa: E731
        ["git", *a], cwd=tmp_path, capture_output=True, text=True,
        env=dict(os.environ, GIT_AUTHOR_NAME="t", GIT_AUTHOR_EMAIL="t@t",
                 GIT_COMMITTER_NAME="t", GIT_COMMITTER_EMAIL="t@t"),
    )
    git("init", "-q")
    (tmp_path / "clean.py").write_text("x = 1\n")
    git("add", "-A")
    git("commit", "-q", "-m", "seed")
    # no changed files: exits clean without linting anything
    out = _cli("--no-baseline", "--no-cache", "--changed", str(tmp_path),
               "--root", str(tmp_path), cwd=str(tmp_path))
    assert out.returncode == 0 and "no changed" in out.stdout
    # an untracked offender is picked up by --changed
    (tmp_path / "bad.py").write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--changed", str(tmp_path),
               "--root", str(tmp_path), cwd=str(tmp_path))
    assert out.returncode == 1 and "RT004" in out.stdout


def test_cli_stats(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    out = _cli("--no-baseline", "--no-cache", "--stats", str(bad))
    assert out.returncode == 1
    assert "RT004" in out.stdout and "total" in out.stdout


def test_cli_usage_errors():
    assert _cli("--jobs", "0").returncode == 2
    assert _cli("--rules", "RT999").returncode == 2


def test_default_targets_cover_tools_and_benches():
    from tools.rtlint import DEFAULT_TARGETS
    assert "ray_tpu" in DEFAULT_TARGETS
    assert "tools" in DEFAULT_TARGETS
    assert any(t.startswith("bench_") for t in DEFAULT_TARGETS)


def test_repo_default_targets_clean_against_baseline():
    """The full gate over the v2 default target set (ray_tpu/, tools/,
    bench_*.py), exactly what `make lint` runs."""
    out = _cli("--no-cache")
    assert out.returncode == 0, out.stdout + out.stderr


# =========================================================================
# v3: path-sensitive lifecycle rules (RT014-RT016), protocol conformance
# (RT017), CFG twins, and the --fix autofixer.
# =========================================================================

# -- RT014: PagePool pages ------------------------------------------------
RT014_POS = """
    class KV:
        def grab(self, n):
            pages = self._pool.alloc(n)
            if n > 4:
                return None
            self._pool.release(pages)
"""

RT014_NEG = """
    class KV:
        def grab(self, n):
            pages = self._pool.alloc(n)
            if n > 4:
                self._pool.release(pages)
                return None
            self._pool.release(pages)
"""


def test_rt014_early_return_leak():
    ids = rule_ids(RT014_POS)
    assert "RT014" in ids


def test_rt014_negative_twin():
    assert "RT014" not in rule_ids(RT014_NEG)


def test_rt014_exception_path_leak_and_finally_twin():
    """The PR 11 incident shape: a step between alloc and release
    raises, and the pages never come back. try/finally (with its
    re-raise edge) is the negative twin."""
    pos = """
        class KV:
            def grab(self, n):
                pages = self._pool.alloc(n)
                self._log(n)
                self._pool.release(pages)
    """
    fs = findings(pos)
    assert any(f.rule == "RT014" and "exception path" in f.message
               for f in fs)
    neg = """
        class KV:
            def grab(self, n):
                pages = self._pool.alloc(n)
                try:
                    self._log(n)
                finally:
                    self._pool.release(pages)
    """
    assert "RT014" not in rule_ids(neg)


def test_rt014_double_free():
    src = """
        class KV:
            def drop(self, n, err):
                pages = self._pool.alloc(n)
                self._pool.release(pages)
                if err:
                    self._pool.release(pages)
    """
    fs = findings(src)
    assert any(f.rule == "RT014" and "released twice" in f.message
               for f in fs)


def test_rt014_rollback_twin():
    """Release on the except edge (all-or-nothing rollback) is clean."""
    src = """
        class KV:
            def grab(self, n):
                pages = self._pool.alloc(n)
                try:
                    self._fill(n)
                except Exception:
                    self._pool.release(pages)
                    raise
                return pages
    """
    assert "RT014" not in rule_ids(src)


def test_rt014_loop_carried_acquire_twins():
    """CFG twin: rebinding the holding variable on the loop back edge
    leaks one allocation per iteration."""
    pos = """
        class KV:
            def churn(self, xs):
                for x in xs:
                    pages = self._pool.alloc(x)
                self._pool.release(pages)
    """
    fs = findings(pos)
    assert any(f.rule == "RT014" and "rebound" in f.message for f in fs)
    neg = """
        class KV:
            def churn(self, xs):
                for x in xs:
                    pages = self._pool.alloc(x)
                    self._pool.release(pages)
    """
    assert "RT014" not in rule_ids(neg)


def test_rt014_with_suppress_twins():
    """CFG twin: contextlib.suppress turns the raise edge into a fall-
    through exit, so the leak survives the with block."""
    pos = """
        import contextlib

        class KV:
            def grab(self, n):
                with contextlib.suppress(ValueError):
                    pages = self._pool.alloc(n)
                    self._step(n)
                return None
    """
    fs = findings(pos)
    assert any(f.rule == "RT014" for f in fs)
    neg = """
        import contextlib

        class KV:
            def grab(self, n):
                with contextlib.suppress(ValueError):
                    pages = self._pool.alloc(n)
                    try:
                        self._step(n)
                    finally:
                        self._pool.release(pages)
                return None
    """
    assert "RT014" not in rule_ids(neg)


def test_rt014_generator_early_close_twins():
    """CFG twin: a generator can be close()d at any yield
    (GeneratorExit), so pages held across a yield leak unless a
    try/finally releases them."""
    pos = """
        class KV:
            def stream(self, n):
                pages = self._pool.alloc(n)
                yield n
                self._pool.release(pages)
    """
    fs = findings(pos)
    assert any(f.rule == "RT014" for f in fs)
    neg = """
        class KV:
            def stream(self, n):
                pages = self._pool.alloc(n)
                try:
                    yield n
                finally:
                    self._pool.release(pages)
    """
    assert "RT014" not in rule_ids(neg)


def test_rt014_incref_obligation_twins():
    """Arg-form acquire: `pool.incref(tok)` owes a decref on every
    path that can raise before the handoff."""
    pos = """
        class KV:
            def pin(self, tok):
                self._pool.incref(tok)
                self._check_capacity()
                self._table.adopt(tok)
    """
    fs = findings(pos)
    assert any(f.rule == "RT014" and "exception path" in f.message
               for f in fs)
    neg = """
        class KV:
            def pin(self, tok):
                self._pool.incref(tok)
                try:
                    self._check_capacity()
                except Exception:
                    self._pool.decref(tok)
                    raise
                self._table.adopt(tok)
    """
    assert "RT014" not in rule_ids(neg)


def test_rt014_suppression():
    src = """
        class KV:
            def grab(self, n):
                pages = self._pool.alloc(n)  # rtlint: disable=RT014
                if n > 4:
                    return None
                self._pool.release(pages)
    """
    assert "RT014" not in rule_ids(src)


# -- RT015: bundles + fences ----------------------------------------------
def test_rt015_release_leak():
    """The PR 14 shape: reserved bundles never released on the early
    exit, wedging the placement group."""
    src = """
        def scale(idx, err):
            b = reserve_pg_bundles(idx)
            if err:
                return None
            release_pg_bundles(b)
            return b
    """
    fs = findings(src, path="ray_tpu/train/x.py")
    assert any(f.rule == "RT015" and "still held" in f.message
               for f in fs)


def test_rt015_double_credit():
    """The PR 10 cancel_bundle shape: one bundle credited twice."""
    src = """
        def teardown(idx, force):
            b = reserve_pg_bundles(idx)
            cancel_bundle(b)
            if force:
                cancel_bundle(b)
    """
    fs = findings(src, path="ray_tpu/train/x.py")
    assert any(f.rule == "RT015" and "released twice" in f.message
               for f in fs)


def test_rt015_negative_twin():
    src = """
        def scale(idx, err):
            b = reserve_pg_bundles(idx)
            if err:
                release_pg_bundles(b)
                return None
            release_pg_bundles(b)
            return None
    """
    assert "RT015" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_rt015_fence_obligation_twins():
    """Fences are arg-form: arming owes a lift on every exit path even
    though the token keeps circulating as a plain id."""
    pos = """
        class GCS:
            def claim(self, job):
                self.arm_fence(job)
                self._audit(job)
                if self._stale(job):
                    return False
                self.lift_fence(job)
                return True
    """
    fs = findings(pos, path="ray_tpu/gcs.py")
    assert any(f.rule == "RT015" and "fence" in f.message for f in fs)
    neg = """
        class GCS:
            def claim(self, job):
                self.arm_fence(job)
                try:
                    self._audit(job)
                    if self._stale(job):
                        return False
                    return True
                finally:
                    self.lift_fence(job)
    """
    assert "RT015" not in rule_ids(neg, path="ray_tpu/gcs.py")


# -- RT016: refs + locks --------------------------------------------------
def test_rt016_dropped_ref():
    src = """
        def kick(f, x):
            r = f.remote(x)
            return None
    """
    fs = findings(src)
    assert any(f.rule == "RT016" and "ObjectRef" in f.message
               for f in fs)


def test_rt016_got_ref_twin():
    src = """
        import ray_tpu as rt

        def kick(f, x):
            r = f.remote(x)
            return rt.get(r)
    """
    assert "RT016" not in rule_ids(src)


def test_rt016_stored_ref_twin():
    """Storing the ref somewhere it will be reaped counts as an escape,
    not a leak."""
    src = """
        def kick(self, f, x):
            r = f.remote(x)
            self._inflight.append(r)
    """
    assert "RT016" not in rule_ids(src)


def test_rt016_actor_handle_not_a_ref():
    """`Actor.options().remote()` builds a handle and `rt.remote(cls)`
    wraps a class — neither is an ObjectRef."""
    src = """
        import ray_tpu as rt

        def boot(cls):
            actor = Worker.options(num_cpus=1).remote()
            wrapped = rt.remote(cls)
            return None
    """
    assert "RT016" not in rule_ids(src)


def test_rt016_lock_across_yield_twins():
    pos = """
        class Buf:
            def drain(self):
                self._lock.acquire()
                for item in self._q:
                    yield item
                self._lock.release()
    """
    fs = findings(pos)
    assert any(f.rule == "RT016" and "yield" in f.message for f in fs)
    neg = """
        class Buf:
            def drain(self):
                while True:
                    with self._lock:
                        item = self._q.pop()
                    yield item
    """
    assert "RT016" not in rule_ids(neg)


def test_rt016_lock_exception_path():
    pos = """
        class Buf:
            def push(self, x):
                self._lock.acquire()
                self._validate(x)
                self._lock.release()
    """
    fs = findings(pos)
    assert any(f.rule == "RT016" and "lock" in f.message for f in fs)
    neg = """
        class Buf:
            def push(self, x):
                self._lock.acquire()
                try:
                    self._validate(x)
                finally:
                    self._lock.release()
    """
    assert "RT016" not in rule_ids(neg)


def test_rt016_suppression():
    src = """
        def kick(f, x):
            r = f.remote(x)  # rtlint: disable=RT016 — reaped by GC test
            return None
    """
    assert "RT016" not in rule_ids(src)


def test_lifecycle_interprocedural_release(tmp_path):
    """A helper that releases counts: `self._cleanup(pages)` is the
    release when _cleanup reaches pool.release, project-wide."""
    from tools.rtlint import analyze_paths
    _write({
        "kv.py": """
            class KV:
                def grab(self, n):
                    pages = self._pool.alloc(n)
                    if n > 4:
                        self._cleanup(pages)
                        return None
                    self._pool.release(pages)

                def _cleanup(self, pages):
                    self._pool.release(pages)
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert not [f for f in res.findings if f.rule == "RT014"]


def test_lifecycle_interprocedural_returns_fresh(tmp_path):
    """`pages = self._grab(n)` starts tracking when _grab returns a
    fresh alloc two frames down."""
    from tools.rtlint import analyze_paths
    _write({
        "kv.py": """
            class KV:
                def _grab(self, n):
                    return self._pool.alloc(n)

                def use(self, n):
                    pages = self._grab(n)
                    if n > 4:
                        return None
                    self._pool.release(pages)
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert [f for f in res.findings if f.rule == "RT014"]


def test_lifecycle_path_in_message():
    """Findings carry the exact leaking line sequence."""
    fs = findings(RT014_POS)
    leak = [f for f in fs if f.rule == "RT014"]
    assert leak and "path" in leak[0].message
    assert "->" in leak[0].message or leak[0].message.count("path")


# -- RT017: protocol conformance ------------------------------------------
def test_rt017_gcs_field_drift(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "server.py": """
            class GCS:
                def h_frob(self, d):
                    job = d["job"]
                    return {"ok": True, "seq": 1}
        """,
        "client.py": """
            class Client:
                def frob(self):
                    resp = self._gcs_call("frob", {"jbo": 1})
                    return resp["seq"]

                def nope(self):
                    return self._gcs_call("norb", {})
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    msgs = [f.message for f in res.findings if f.rule == "RT017"]
    assert any("omits key(s) ['job']" in m for m in msgs)
    assert any("['jbo']" in m and "never reads" in m for m in msgs)
    assert any("h_norb" in m for m in msgs)


def test_rt017_gcs_negative_twin(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "server.py": """
            class GCS:
                def h_frob(self, d):
                    job = d["job"]
                    extra = d.get("extra")
                    return {"ok": True, "seq": 1}
        """,
        "client.py": """
            class Client:
                def frob(self):
                    resp = self._gcs_call("frob", {"job": 1, "extra": 2})
                    return resp["seq"]
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert not [f for f in res.findings if f.rule == "RT017"]


def test_rt017_gcs_response_key_drift(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "server.py": """
            class GCS:
                def h_frob(self, d):
                    job = d["job"]
                    return {"ok": True}
        """,
        "client.py": """
            class Client:
                def frob(self):
                    resp = self._gcs_call("frob", {"job": 1})
                    return resp["seq"]
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    msgs = [f.message for f in res.findings if f.rule == "RT017"]
    assert any("'seq'" in m and "only returns" in m for m in msgs)


def test_rt017_gcs_conditional_read_is_optional(tmp_path):
    """A d["k"] read only reachable under a branch is optional from the
    client's view — the h_actor_ready error-path shape."""
    from tools.rtlint import analyze_paths
    _write({
        "server.py": """
            class GCS:
                def h_ready(self, d):
                    if d.get("error"):
                        return {"ok": False}
                    else:
                        addr = d["address"]
                        return {"ok": True}
        """,
        "client.py": """
            class Client:
                def fail(self):
                    return self._gcs_call("ready", {"error": "boom"})
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    assert not [f for f in res.findings if f.rule == "RT017"]


def test_rt017_chaos_table_twins(tmp_path):
    from tools.rtlint import analyze_paths
    pos = '''
        """Chaos hooks.

        Injection table:

          drop_gcs(p)        | gcs        | drops p of RPCs
          ghost_hook(x)      | nowhere    | stale row
        """

        def drop_gcs(p):
            _require_enabled()
            return p

        def undocumented_hook(q):
            _require_enabled()
            return q
    '''
    _write({"pkg/_private/chaos.py": pos}, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    msgs = [f.message for f in res.findings if f.rule == "RT017"]
    assert any("undocumented_hook" in m and "missing from" in m
               for m in msgs)
    assert any("ghost_hook" in m and "stale row" in m for m in msgs)
    neg = '''
        """Chaos hooks.

        Injection table:

          drop_gcs(p)        | gcs        | drops p of RPCs
        """

        def drop_gcs(p):
            _require_enabled()
            return p
    '''
    _write({"pkg2/_private/chaos.py": neg}, tmp_path)
    res = analyze_paths([str(tmp_path / "pkg2")], root=str(tmp_path))
    assert not [f for f in res.findings if f.rule == "RT017"]


def test_rt017_panel_metric_drift(tmp_path):
    from tools.rtlint import analyze_paths
    _write({
        "metrics.py": """
            from ray_tpu.util.metrics import Counter

            REQS = Counter("requests")
        """,
        "dashboard/grafana.py": """
            PANELS = [
                {"title": "good", "expr": "rate(requests_total[5m])"},
                {"title": "bad", "expr": "rate(gone_metric_total[5m])"},
            ]
        """,
    }, tmp_path)
    res = analyze_paths([str(tmp_path)], root=str(tmp_path))
    msgs = [f.message for f in res.findings if f.rule == "RT017"]
    assert any("gone_metric_total" in m for m in msgs)
    assert not any("requests" in m for m in msgs)


def test_rt017_version_literal_twins():
    pos = """
        def read(doc):
            if doc.get("schema") == 2:
                return doc
        def write():
            return {"schema": 2, "x": 1}
    """
    fs = findings(pos)
    assert sum(1 for f in fs if f.rule == "RT017") == 2
    neg = """
        SCHEMA_VERSION = 2
        def read(doc):
            if doc.get("schema") == SCHEMA_VERSION:
                return doc
        def write():
            return {"schema": SCHEMA_VERSION, "x": 1}
    """
    assert "RT017" not in rule_ids(neg)


def test_rt017_suppression():
    src = """
        def read(doc):
            if doc.get("schema") == 2:  # rtlint: disable=RT017 — v2 migration shim
                return doc
    """
    assert "RT017" not in rule_ids(src)


# -- CFG builder ----------------------------------------------------------
def test_cfg_try_finally_reraise_edges():
    """The finally body must be reachable on the exceptional path and
    that copy must re-raise (edge toward the raise exit), not fall
    through to the normal tail."""
    import ast as _ast
    from tools.rtlint.cfg import build_cfg
    src = textwrap.dedent("""
        def f(self, n):
            self.step(n)
            try:
                self.work(n)
            finally:
                self.cleanup(n)
            return n
    """)
    fn = _ast.parse(src).body[0]
    cfg = build_cfg(fn)
    # at least two copies of the finally body exist (normal + exc)
    cleanup_line = fn.body[1].finalbody[0].lineno
    cleanups = [i for i, s in enumerate(cfg.stmts)
                if getattr(s, "lineno", None) == cleanup_line]
    assert len(cleanups) >= 2


def test_cfg_loop_back_edge():
    import ast as _ast
    from tools.rtlint.cfg import build_cfg
    src = textwrap.dedent("""
        def f(self, xs):
            for x in xs:
                self.step(x)
            return None
    """)
    fn = _ast.parse(src).body[0]
    cfg = build_cfg(fn)
    # some edge points backward (to an earlier node): the loop
    assert any(dst < src_i for src_i, dsts in cfg.succ.items()
               for dst, _label in dsts)


# -- --fix autofixer ------------------------------------------------------
def test_fix_rt004_leash_and_idempotency():
    from tools.rtlint.fix import fix_source
    src = textwrap.dedent("""
        import ray_tpu as rt

        def kick(f, xs):
            for x in xs:
                f.remote(x)
    """)
    out, notes = fix_source(src, "t.py")
    assert "rt.wait([_reaped], timeout=0)" in out
    assert any("RT004" in n for n in notes)
    # the rewritten form is clean under both RT004 and RT016
    ids = [f.rule for f in lint_source(out, "ray_tpu/serve/x.py")]
    assert "RT004" not in ids and "RT016" not in ids
    # idempotent: fix(fix(s)) == fix(s)
    out2, notes2 = fix_source(out, "t.py")
    assert out2 == out and not notes2


def test_fix_rt004_requires_rt_import():
    from tools.rtlint.fix import fix_source
    src = "def kick(f):\n    f.remote()\n"
    out, notes = fix_source(src, "t.py")
    assert out == src
    assert any("skipped" in n for n in notes)


def test_fix_rt013_tuple_freeze_and_idempotency():
    from tools.rtlint.fix import fix_source
    src = textwrap.dedent("""
        H = Histogram("lat", boundaries=[0.1, 1.0])
        ONE = get_or_create("n", boundaries=[5])
    """)
    out, notes = fix_source(src, "t.py")
    assert 'boundaries=(0.1, 1.0)' in out
    assert 'boundaries=(5,)' in out          # single elt stays a tuple
    assert "RT013" not in [f.rule for f in lint_source(
        out, "ray_tpu/serve/x.py")]
    out2, notes2 = fix_source(out, "t.py")
    assert out2 == out and not notes2


def test_fix_respects_line_restriction():
    """Driven by finding lines: sites not in the restriction set (e.g.
    suppressed ones) stay untouched."""
    from tools.rtlint.fix import fix_source
    src = textwrap.dedent("""
        import ray_tpu as rt

        def kick(f, x):
            f.remote(x)
            f.remote(x)
    """)
    out, _ = fix_source(src, "t.py", rt004_lines={5}, rt013_lines=set())
    assert out.count("rt.wait") == 1


def test_cli_fix_applies_and_exits_clean(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent("""
        import ray_tpu as rt

        def kick(f, x):
            f.remote(x)
    """))
    out = _cli("--no-baseline", "--no-cache", "--fix", str(bad),
               "--root", str(tmp_path))
    assert out.returncode == 0, out.stdout + out.stderr
    assert "rt.wait" in bad.read_text()


def test_cli_sarif_out_artifact(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    art = tmp_path / "out.sarif"
    out = _cli("--no-baseline", "--no-cache", "--sarif-out", str(art),
               str(bad))
    assert out.returncode == 1
    doc = json.loads(art.read_text())
    assert doc["runs"][0]["results"][0]["ruleId"] == "RT004"
