"""rtlint: per-rule fixtures (positive + negative twin + suppression),
baseline round-trip, and the repo-wide gate.

Each rule's positive fixture is the minimal reproduction of the bug
class; its negative twin is the same code with the one property that
makes it safe (a timeout, a lock, an epoch, a hoisted jit). The
suppression case proves `# rtlint: disable=RTxxx` works at both line
and def granularity.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import textwrap

import pytest

from tools.rtlint import Baseline, lint_paths, lint_source
from tools.rtlint.rules import ALL_RULES, rule_by_id

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def findings(src: str, path: str = "ray_tpu/serve/x.py"):
    return lint_source(textwrap.dedent(src), path)


def rule_ids(src: str, path: str = "ray_tpu/serve/x.py"):
    return [f.rule for f in findings(src, path)]


# -- RT001: host sync ------------------------------------------------------
RT001_POS = """
    import jax

    @jax.jit
    def step(x):
        return float(x.sum())
"""

RT001_NEG = """
    import jax

    @jax.jit
    def step(x):
        return x.sum()

    def report(x):
        return float(step(x))
"""


def test_rt001_traced_sync():
    assert "RT001" in rule_ids(RT001_POS)


def test_rt001_negative_twin():
    assert "RT001" not in rule_ids(RT001_NEG)


def test_rt001_loop_sync():
    src = """
        def drain(xs):
            out = []
            for x in xs:
                out.append(x.item())
            return out
    """
    fs = findings(src)
    assert [f.rule for f in fs] == ["RT001"]
    assert fs[0].token == ".item()"


def test_rt001_item_outside_loop_ok():
    assert "RT001" not in rule_ids("def f(x):\n    return x.item()\n")


# -- RT002: retrace risk ---------------------------------------------------
RT002_POS = """
    import jax

    def train(fns, x):
        for f in fns:
            y = jax.jit(f)(x)
        return y
"""

RT002_NEG = """
    import jax

    def train(fns, x):
        compiled = [jax.jit(f) for f in fns]
        return [g(x) for g in compiled]
"""


def test_rt002_jit_in_loop():
    assert "RT002" in rule_ids(RT002_POS)


def test_rt002_negative_twin():
    # List comprehensions build the wrappers once per fn, not per call.
    assert "RT002" not in rule_ids(
        "import jax\n\ndef f(g, x):\n    h = jax.jit(g)\n    return h(x)\n"
    )


def test_rt002_mutable_static_argnums():
    src = """
        import jax

        def build(f):
            return jax.jit(f, static_argnums=[0, 1])
    """
    fs = findings(src)
    assert [f.rule for f in fs] == ["RT002"]
    assert fs[0].token == "static-static_argnums"
    assert "RT002" not in rule_ids(src.replace("[0, 1]", "(0, 1)"))


def test_rt002_jit_def_in_loop():
    src = """
        import jax

        def outer(xs):
            for x in xs:
                @jax.jit
                def inner(y):
                    return y + x
                inner(x)
    """
    assert "jit-def-in-loop" in [f.token for f in findings(src)]


# -- RT003: unbounded blocking get ----------------------------------------
RT003_POS = """
    import ray_tpu as rt

    @rt.remote
    class Worker:
        def run(self, ref):
            return rt.get(ref)
"""

RT003_NEG = RT003_POS.replace("rt.get(ref)", "rt.get(ref, timeout=30)")


def test_rt003_actor_get_without_timeout():
    fs = findings(RT003_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT003"]
    assert fs[0].token == "rt.get"


def test_rt003_negative_twin():
    assert "RT003" not in rule_ids(RT003_NEG, path="ray_tpu/rl/x.py")


def test_rt003_control_plane_free_function():
    src = """
        import ray_tpu as rt

        def bootstrap(refs):
            rt.get(refs)
    """
    assert "RT003" in rule_ids(src, path="ray_tpu/util/collective/x.py")
    # Same helper outside the control-plane scopes: not flagged.
    assert "RT003" not in rule_ids(src, path="ray_tpu/rl/x.py")


def test_rt003_bare_result():
    src = """
        @rt.remote
        class A:
            def m(self, fut):
                return fut.result()
    """
    src = "import ray_tpu as rt\n" + textwrap.dedent(src)
    assert "RT003" in [f.rule for f in lint_source(src, "ray_tpu/rl/x.py")]


# -- RT004: discarded ObjectRef -------------------------------------------
RT004_POS = """
    def push(workers, w):
        for r in workers:
            r.set_weights.remote(w)
"""

RT004_NEG = """
    import ray_tpu as rt

    def push(workers, w):
        refs = [r.set_weights.remote(w) for r in workers]
        rt.get(refs, timeout=60)
"""


def test_rt004_discarded_ref():
    fs = findings(RT004_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT004"]
    assert fs[0].token == "set_weights"


def test_rt004_negative_twin():
    assert "RT004" not in rule_ids(RT004_NEG, path="ray_tpu/rl/x.py")


# -- RT005: unfenced collective -------------------------------------------
RT005_POS = """
    from ray_tpu.util import collective as col

    def setup(ws, rank):
        col.init_collective_group(ws, rank, "dcn", "g")
"""

RT005_NEG = RT005_POS.replace('"g")', '"g", epoch=0)')


def test_rt005_missing_epoch():
    fs = findings(RT005_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT005"]
    assert fs[0].token == "init_collective_group"


def test_rt005_negative_twin():
    # Explicit epoch=0 is the call site asserting "never rebuilt".
    assert "RT005" not in rule_ids(RT005_NEG, path="ray_tpu/rl/x.py")


# -- RT006: cross-thread race ---------------------------------------------
RT006_POS = """
    import threading

    class Engine:
        def __init__(self):
            self._running = True
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while self._running:
                pass

        def shutdown(self):
            self._running = False
"""

RT006_NEG_LOCK = """
    import threading

    class Engine:
        def __init__(self):
            self._running = True
            self._lock = threading.Lock()
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while True:
                with self._lock:
                    if not self._running:
                        return

        def shutdown(self):
            with self._lock:
                self._running = False
"""

RT006_NEG_EVENT = """
    import threading

    class Engine:
        def __init__(self):
            self._stop_event = threading.Event()
            self._t = threading.Thread(target=self._loop)

        def _loop(self):
            while not self._stop_event.is_set():
                pass

        def shutdown(self):
            self._stop_event.set()
"""


def test_rt006_unlocked_flag():
    fs = findings(RT006_POS, path="ray_tpu/rl/x.py")
    assert [f.rule for f in fs] == ["RT006"]
    assert fs[0].token == "_running"


def test_rt006_lock_negative_twin():
    assert "RT006" not in rule_ids(RT006_NEG_LOCK, path="ray_tpu/rl/x.py")


def test_rt006_event_negative_twin():
    assert "RT006" not in rule_ids(RT006_NEG_EVENT, path="ray_tpu/rl/x.py")


def test_rt006_init_writes_exempt():
    # Writes before the thread starts happen-before it; only the
    # post-start caller-side write races.
    src = RT006_POS.replace(
        "def shutdown(self):\n            self._running = False",
        "def status(self):\n            return True",
    )
    assert "RT006" not in rule_ids(src, path="ray_tpu/rl/x.py")


# -- RT007: swallowed exception -------------------------------------------
RT007_POS = """
    def teardown(group):
        try:
            group.destroy()
        except Exception:
            pass
"""

RT007_NEG = """
    import logging

    def teardown(group):
        try:
            group.destroy()
        except OSError:
            pass
"""


def test_rt007_swallow_in_control_plane():
    fs = findings(RT007_POS, path="ray_tpu/train/x.py")
    assert [f.rule for f in fs] == ["RT007"]


def test_rt007_narrow_negative_twin():
    assert "RT007" not in rule_ids(RT007_NEG, path="ray_tpu/train/x.py")


def test_rt007_logging_body_ok():
    src = """
        import logging

        def teardown(group):
            try:
                group.destroy()
            except Exception:
                logging.warning("destroy failed", exc_info=True)
    """
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_rt007_scoped_to_control_plane():
    assert "RT007" not in rule_ids(RT007_POS, path="ray_tpu/rl/x.py")


# -- suppressions ----------------------------------------------------------
def test_line_suppression():
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable=RT007")
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


def test_def_suppression_covers_body():
    src = RT006_POS.replace(
        "def shutdown(self):",
        "def shutdown(self):  # rtlint: disable=RT006",
    )
    assert "RT006" not in rule_ids(src, path="ray_tpu/rl/x.py")


def test_suppression_is_rule_specific():
    # Disabling RT001 does not hide the RT007.
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable=RT001")
    assert "RT007" in rule_ids(src, path="ray_tpu/train/x.py")


def test_blanket_suppression():
    src = RT007_POS.replace("except Exception:",
                            "except Exception:  # rtlint: disable")
    assert "RT007" not in rule_ids(src, path="ray_tpu/train/x.py")


# -- engine behavior -------------------------------------------------------
def test_syntax_error_yields_rt000():
    fs = lint_source("def broken(:\n", "ray_tpu/x.py")
    assert [f.rule for f in fs] == ["RT000"]


def test_fingerprint_is_line_independent():
    fs1 = findings(RT007_POS, path="ray_tpu/train/x.py")
    fs2 = findings("\n\n\n" + textwrap.dedent(RT007_POS),
                   path="ray_tpu/train/x.py")
    assert fs1[0].fingerprint == fs2[0].fingerprint
    assert fs1[0].line != fs2[0].line


def test_baseline_roundtrip(tmp_path):
    fs = findings(RT007_POS, path="ray_tpu/train/x.py")
    bl = Baseline.from_findings(fs)
    p = tmp_path / "baseline.json"
    bl.save(str(p))
    loaded = Baseline.load(str(p))
    assert loaded.counts == bl.counts
    assert loaded.new_findings(fs) == []
    # A second identical violation exceeds the baselined count.
    doubled = fs + fs
    assert len(loaded.new_findings(doubled)) == len(fs)
    # JSON on disk is the documented shape.
    data = json.loads(p.read_text())
    assert set(data) == {"comment", "findings"}


def test_baseline_stale_entries():
    bl = Baseline({"RT007|gone.py|f|swallow": 1})
    assert bl.stale_entries([]) == ["RT007|gone.py|f|swallow"]


def test_rule_catalog():
    ids = [r.id for r in ALL_RULES]
    assert ids == sorted(ids) and len(set(ids)) == len(ids)
    assert ids == [f"RT00{i}" for i in range(1, 8)]
    assert rule_by_id("rt003").id == "RT003"
    for r in ALL_RULES:
        assert r.name and r.__doc__


# -- repo-wide gate --------------------------------------------------------
def test_repo_is_clean_against_baseline():
    """The tier-1 gate: linting ray_tpu/ yields no findings beyond the
    committed baseline. New violations fail here, with the finding text
    in the assertion message."""
    bl = Baseline.load(os.path.join(REPO, "tools", "rtlint",
                                    "baseline.json"))
    fs = lint_paths([os.path.join(REPO, "ray_tpu")], root=REPO)
    new = bl.new_findings(fs)
    assert not new, "new rtlint findings:\n" + "\n".join(map(str, new))


def test_cli_exit_codes(tmp_path):
    bad = tmp_path / "x.py"
    bad.write_text(textwrap.dedent(RT004_POS))
    env = dict(os.environ, PYTHONPATH=REPO)
    run = lambda *a: subprocess.run(  # noqa: E731
        [sys.executable, "-m", "tools.rtlint", *a],
        capture_output=True, text=True, env=env, cwd=REPO,
    )
    clean = run("--no-baseline", str(tmp_path / "nothing"))
    assert clean.returncode == 0
    dirty = run("--no-baseline", str(bad))
    assert dirty.returncode == 1
    assert "RT004" in dirty.stdout
    assert run("--explain", "RT006").returncode == 0
    assert run("--explain", "RT999").returncode == 2
