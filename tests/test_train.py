"""JaxTrainer tests on CPU gangs.

Modeled on the reference's python/ray/train/tests (tiny models, CPU
workers, gloo-role collectives — here the DCN TCP group), per SURVEY.md
§4.2.
"""

import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.train import (
    Checkpoint,
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)

pytestmark = pytest.mark.usefixtures("rt_start")


def _simple_loop(config):
    from ray_tpu import train

    rank = train.get_world_rank()
    for step in range(config["steps"]):
        train.report({"step": step, "rank": rank, "loss": 1.0 / (step + 1)})


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_single_worker_reports(tmp_path):
    trainer = JaxTrainer(
        _simple_loop,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="t1", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["step"] == 2
    assert len(result.metrics_history) == 3


def _dp_loop(config):
    """Real data-parallel training: grads sync over the DCN group."""
    import jax
    import jax.numpy as jnp

    from ray_tpu import train
    from ray_tpu.models.mlp import init_mlp, mlp_classifier_loss
    from ray_tpu.train import allreduce_gradients

    rank = train.get_world_rank()
    world = train.get_world_size()

    params = init_mlp(jax.random.PRNGKey(0), [4, 16, 2])  # same init all ranks
    # Rank-dependent data shard.
    key = jax.random.PRNGKey(100 + rank)
    x = jax.random.normal(key, (32, 4))
    y = (x.sum(axis=1) > 0).astype(jnp.int32)

    grad_fn = jax.value_and_grad(mlp_classifier_loss, has_aux=True)
    lr = 0.1
    for step in range(config["steps"]):
        (loss, metrics), grads = grad_fn(params, {"x": x, "y": y})
        if world > 1:
            grads = allreduce_gradients(grads)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        train.report({"loss": float(loss), "rank": rank, "step": step})


@pytest.mark.slow
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_data_parallel_two_workers(tmp_path):
    trainer = JaxTrainer(
        _dp_loop,
        train_loop_config={"steps": 4},
        jax_config=JaxConfig(dp_sync="dcn"),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="dp", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    # Loss decreased over training.
    losses = [m["loss"] for m in result.metrics_history]
    assert losses[-1] < losses[0]


def _ckpt_loop(config):
    import os

    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start = ckpt.to_dict()["step"] + 1
    for step in range(start, config["steps"]):
        if train.get_world_rank() == 0:
            c = Checkpoint.from_dict({"step": step})
            train.report({"step": step}, checkpoint=c)
        else:
            train.report({"step": step})


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_checkpoints_and_resume(tmp_path):
    trainer = JaxTrainer(
        _ckpt_loop,
        train_loop_config={"steps": 3},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="ck",
            storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.checkpoint is not None
    assert result.checkpoint.to_dict()["step"] == 2

    # Resume continues from the saved step.
    trainer2 = JaxTrainer(
        _ckpt_loop,
        train_loop_config={"steps": 5},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ck2", storage_path=str(tmp_path)),
        resume_from_checkpoint=result.checkpoint,
    )
    result2 = trainer2.fit()
    assert result2.error is None
    steps = [m["step"] for m in result2.metrics_history]
    assert steps == [3, 4]


def _fail_once_loop(config):
    import os

    from ray_tpu import train

    marker = os.path.join(config["dir"], "failed_once")
    if not os.path.exists(marker):
        open(marker, "w").close()
        raise RuntimeError("injected failure")
    train.report({"recovered": True})


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_failure_recovery(tmp_path):
    trainer = JaxTrainer(
        _fail_once_loop,
        train_loop_config={"dir": str(tmp_path)},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="fr",
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["recovered"] is True


def _pytree_ckpt_loop(config):
    import jax.numpy as jnp

    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    tree = {"w": jnp.arange(8.0), "step": jnp.array(7)}
    c = Checkpoint.from_pytree(
        tree, os.path.join(train.get_trial_dir(), "ptc")
    )
    train.report({"saved": True}, checkpoint=c)


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_orbax_pytree_checkpoint(tmp_path):
    trainer = JaxTrainer(
        _pytree_ckpt_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="ptc", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    restored = result.checkpoint.to_pytree()
    assert list(np.asarray(restored["w"])) == list(range(8))

def test_async_checkpointer_overlaps_and_restores(tmp_path):
    import jax
    import jax.numpy as jnp

    from ray_tpu.train.checkpoint import AsyncCheckpointer, Checkpoint

    tree = {"w": jnp.arange(1000, dtype=jnp.float32).reshape(10, 100),
            "step": jnp.asarray(7)}
    ck = AsyncCheckpointer()
    try:
        # Two overlapping saves: the second forces serialization of both.
        c1 = ck.save(str(tmp_path / "c1"), tree)
        tree2 = jax.tree.map(lambda x: x + 1, tree)
        ck.wait()
        c2 = ck.save(str(tmp_path / "c2"), tree2)
        ck.wait()  # barrier BEFORE reporting: no partial writes observable
        r1 = Checkpoint(c1.path).to_pytree()
        r2 = Checkpoint(c2.path).to_pytree()
        assert float(r1["w"][0, 1]) == 1.0 and int(r1["step"]) == 7
        assert float(r2["w"][0, 1]) == 2.0 and int(r2["step"]) == 8
    finally:
        ck.close()


def test_storage_context_roundtrip(tmp_path):
    """Checkpoints persist to a storage URI via pyarrow.fs and download
    back intact (reference: StorageContext, train/_internal/storage.py)."""
    import os

    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.train.storage import StorageContext

    src = tmp_path / "local_ckpt"
    (src / "nested").mkdir(parents=True)
    (src / "weights.bin").write_bytes(b"\x00\x01\x02" * 100)
    (src / "nested" / "meta.json").write_text('{"step": 7}')

    storage = StorageContext(f"file://{tmp_path}/remote", "exp1")
    uri = storage.persist(Checkpoint.from_directory(str(src)), "ckpt_000")
    assert uri.endswith("exp1/ckpt_000")
    assert storage.list_checkpoints() == ["ckpt_000"]

    back = storage.download("ckpt_000", str(tmp_path / "dl"))
    assert open(os.path.join(back.path, "weights.bin"), "rb").read() == \
        b"\x00\x01\x02" * 100
    assert "step" in open(
        os.path.join(back.path, "nested", "meta.json")
    ).read()


def test_trainer_persists_to_storage_uri(rt_start, tmp_path):
    """A URI storage_path makes the trainer upload every registered
    checkpoint; the run itself works from local scratch."""
    import json
    import os

    from ray_tpu.train import JaxTrainer
    from ray_tpu.train.config import RunConfig, ScalingConfig
    from ray_tpu.train.storage import StorageContext

    def loop(config):
        from ray_tpu import train as train_mod
        from ray_tpu.train.checkpoint import Checkpoint

        ckpt = Checkpoint.from_dict({"w": 1})
        train_mod.report({"loss": 0.5}, checkpoint=ckpt)

    uri = f"file://{tmp_path}/bucket"
    result = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="sp-test", storage_path=uri),
    ).fit()
    assert result.error is None
    names = StorageContext(uri, "sp-test").list_checkpoints()
    assert names, "no checkpoint persisted to the storage URI"
    back = StorageContext(uri, "sp-test").download(names[-1])
    from ray_tpu.train.checkpoint import Checkpoint as C

    assert C.from_directory(back.path).to_dict() == {"w": 1}


def test_storage_retention_prunes_remote(tmp_path):
    """num_to_keep retention removes pruned checkpoints from the storage
    URI too (orphaned uploads would grow remote storage without bound),
    and storage names are sequential regardless of local dir names."""
    from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
    from ray_tpu.train.storage import StorageContext

    storage = StorageContext(f"file://{tmp_path}/bucket", "exp")
    mgr = CheckpointManager(
        str(tmp_path / "local"), num_to_keep=2, storage=storage
    )
    for step in range(4):
        ckpt = Checkpoint.from_dict({"step": step})  # random tempdir name
        mgr.register(ckpt, {"step": step})
    names = storage.list_checkpoints()
    # Only the 2 newest remain, in sequential-name order.
    assert names == ["checkpoint_000002", "checkpoint_000003"]
    assert storage.download("checkpoint_000003").to_dict() == {"step": 3}


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_torch_trainer_ddp_gloo(tmp_path):
    """TorchTrainer over the gloo process group (BASELINE.md reference
    config: TorchTrainer, 2 CPU workers, gloo): DDP-wrapped training on a
    sharded loader; worker params must stay bit-identical (gradient
    allreduce) and the loss must drop."""
    import numpy as np

    import ray_tpu as rt
    from ray_tpu.train import RunConfig, ScalingConfig
    from ray_tpu.train.torch import TorchTrainer

    def loop(config):
            import torch
            import torch.distributed as dist
            from torch.utils.data import DataLoader, TensorDataset

            from ray_tpu import train
            from ray_tpu.train.torch import prepare_data_loader, prepare_model

            torch.manual_seed(0)  # identical init on every worker
            assert dist.is_initialized()
            assert dist.get_world_size() == 2

            g = torch.Generator().manual_seed(7)
            x = torch.randn(256, 4, generator=g)
            w_true = torch.tensor([[1.0], [-2.0], [0.5], [3.0]])
            y = x @ w_true
            loader = prepare_data_loader(
                DataLoader(TensorDataset(x, y), batch_size=32)
            )
            model = prepare_model(torch.nn.Linear(4, 1))
            opt = torch.optim.SGD(model.parameters(), lr=0.1)
            first = last = None
            for _epoch in range(12):
                for xb, yb in loader:
                    opt.zero_grad()
                    loss = ((model(xb) - yb) ** 2).mean()
                    loss.backward()
                    opt.step()
                    if first is None:
                        first = float(loss)
                    last = float(loss)
            flat = torch.cat(
                [p.detach().reshape(-1) for p in model.parameters()]
            )
            train.report({
                "first": first, "last": last,
                "psum": float(flat.sum()),
                "rank": train.get_world_rank(),
            })

    trainer = TorchTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="torch", storage_path=str(tmp_path)),
    )
    result = trainer.fit()
    assert result.error is None
    m = result.metrics
    assert m["last"] < m["first"] * 0.2, m


def test_sklearn_trainer_fits_scores_and_checkpoints(rt_start):
    """SklearnTrainer (reference: train/sklearn/sklearn_trainer.py):
    remote fit + validation scoring + cv metrics + model checkpoint."""
    import numpy as np
    from sklearn.linear_model import LogisticRegression

    from ray_tpu.train.sklearn import SklearnTrainer

    rng = np.random.default_rng(0)
    X = rng.normal(size=(200, 4)).astype(np.float64)
    y = (X[:, 0] + 0.5 * X[:, 1] > 0).astype(int)
    trainer = SklearnTrainer(
        estimator=LogisticRegression(max_iter=200),
        datasets={"train": (X[:150], y[:150]), "valid": (X[150:], y[150:])},
        cv=3,
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["train_score"] > 0.9
    assert result.metrics["valid_score"] > 0.8
    assert "cv" in result.metrics and "test_score" in result.metrics["cv"]
    model = SklearnTrainer.get_model(result.checkpoint)
    assert model.score(X[150:], y[150:]) == result.metrics["valid_score"]


def test_sklearn_trainer_dataset_input(rt_start):
    import numpy as np

    from ray_tpu import data as rt_data
    from ray_tpu.train.sklearn import SklearnTrainer

    rng = np.random.default_rng(1)
    rows = []
    for _ in range(120):
        a, b = rng.normal(), rng.normal()
        rows.append({"a": a, "b": b, "y": int(a - b > 0)})
    ds = rt_data.from_items(rows)
    from sklearn.tree import DecisionTreeClassifier

    trainer = SklearnTrainer(
        estimator=DecisionTreeClassifier(max_depth=4),
        datasets={"train": ds},
        label_column="y",
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["train_score"] > 0.85


def test_get_context_facade(rt_start):
    """train.get_context() (reference: TrainContext) inside workers."""
    from ray_tpu import train
    from ray_tpu.train import JaxTrainer, ScalingConfig

    def loop(config):
        ctx = train.get_context()
        train.report({
            "rank": ctx.get_world_rank(),
            "size": ctx.get_world_size(),
            "local": ctx.get_local_rank(),
        })

    result = JaxTrainer(
        loop, scaling_config=ScalingConfig(num_workers=2)
    ).fit()
    assert result.error is None
    assert result.metrics["size"] == 2
    with pytest.raises(RuntimeError):
        train.get_context()  # outside a worker: raises like the reference


def _resnet_dp_loop(config):
    """ResNet data-parallel training from a streamed image dataset
    (the 'JaxTrainer ResNet data-parallel' north-star shape,
    BASELINE.json configs: conv model + DataConfig-split image feed +
    gradient allreduce)."""
    import os

    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu import train
    from ray_tpu.models.conv import ResNetConfig, init_resnet, resnet_loss
    from ray_tpu.train import allreduce_gradients

    cfg = ResNetConfig(num_classes=2, stage_sizes=(1, 1), width=8)
    params = init_resnet(jax.random.PRNGKey(0), cfg)  # same init all ranks
    world = train.get_world_size()
    shard = train.get_dataset_shard("train")

    @jax.jit
    def step(params, batch):
        (loss, metrics), grads = jax.value_and_grad(
            resnet_loss, has_aux=True
        )(params, batch, cfg)
        return loss, metrics, grads

    lr = config.get("lr", 0.05)
    for epoch in range(config["epochs"]):
        rows = list(shard.iter_rows())
        xs = np.stack([r["image"] for r in rows]).astype(np.float32) / 255.0
        ys = np.asarray(
            [int(os.path.basename(r["path"]).split("_")[1]) for r in rows],
            dtype=np.int32,
        )
        loss, metrics, grads = step(
            params, {"x": jnp.asarray(xs), "y": jnp.asarray(ys)}
        )
        if world > 1:
            grads = allreduce_gradients(grads)
        params = jax.tree.map(lambda p, g: p - lr * g, params, grads)
        train.report({
            "epoch": epoch,
            "loss": float(loss),
            "accuracy": float(metrics["accuracy"]),
        })


@pytest.mark.slow
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_resnet_dp_from_images(tmp_path):
    """read_images -> DataConfig streaming split -> 2-worker DP ResNet:
    loss decreases on a color-separable toy set (CIFAR-scale shapes on
    CPU CI; reference: vision trainer examples under
    python/ray/train/examples/)."""
    from PIL import Image

    import ray_tpu.data as rtd
    from ray_tpu.train.data_config import DataConfig

    img_dir = tmp_path / "imgs"
    img_dir.mkdir()
    rng = np.random.default_rng(0)
    for i in range(32):
        label = i % 2
        base = np.full((16, 16, 3), 30, dtype=np.uint8)
        # class 0: red-dominant; class 1: blue-dominant (+ noise)
        base[:, :, 0 if label == 0 else 2] = 200
        noisy = np.clip(
            base.astype(np.int16) + rng.integers(-25, 25, base.shape),
            0, 255,
        ).astype(np.uint8)
        Image.fromarray(noisy).save(img_dir / f"img_{label}_{i:03d}.png")

    ds = rtd.read_images(str(img_dir), parallelism=4)
    # lr/epochs picked from the seeded full-batch trajectory: at lr 0.2
    # accuracy crosses 1.0 by epoch 3-4 (0.05 needed ~12 epochs and sat
    # at 0.5 through epoch 9 — the old flake: the assert ran at epoch 4).
    trainer = JaxTrainer(
        _resnet_dp_loop,
        train_loop_config={"epochs": 6, "lr": 0.2},
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="resnet", storage_path=str(tmp_path)),
        datasets={"train": ds},
        dataset_config=DataConfig(datasets_to_split=["train"]),
    )
    result = trainer.fit()
    assert result.error is None
    losses = [m["loss"] for m in result.metrics_history if "loss" in m]
    assert losses[-1] < losses[0], losses
    accs = [m["accuracy"] for m in result.metrics_history if "accuracy" in m]
    assert max(accs) >= 0.75, accs
