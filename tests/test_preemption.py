"""Priority preemption and graceful chip reclamation.

The multi-tenancy contract: priority classes ride task specs, actor
registrations, and placement groups; when higher-priority demand cannot
place, the GCS reclamation pass (gcs.py _maybe_preempt) drains the
lowest-priority gang, fences the freed chips for the claimant, and backs
the graceful window with a hard-kill deadline (RT_PREEMPT_GRACE_S).

Reference analogs: the reference has no in-scheduler preemption — this
subsystem models the TPU-pod reality (one pod, training + serving + RL
sharing it) where spot-style reclamation is routine.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.cluster_utils import Cluster
from ray_tpu.util.placement_group import (
    PlacementGroupConfig,
    placement_group,
    remove_placement_group,
)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


def _wait_for(pred, timeout=10.0, interval=0.05, what="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if pred():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {what}")


# -- priority plumbing ------------------------------------------------------


def test_priority_carried_on_pg_and_actor(rt_cluster):
    cluster = rt_cluster
    cluster.add_node(num_cpus=4)
    cluster.connect()

    pg = PlacementGroupConfig(
        bundles=[{"CPU": 1}], name="tier2", priority=2
    ).create()
    assert pg.ready(timeout=10)
    gpg = cluster.gcs.placement_groups[pg.id.binary()]
    assert gpg["priority"] == 2
    assert gpg["name"] == "tier2"
    assert gpg["seq"] > 0

    @rt.remote(priority=7, num_cpus=1)
    class A:
        def ping(self):
            return "ok"

    a = A.options(name="prio-actor").remote()
    assert rt.get(a.ping.remote(), timeout=30) == "ok"
    ga = cluster.gcs.actors[a._actor_id.binary()]
    assert ga["priority"] == 7
    remove_placement_group(pg)


def test_high_priority_task_dispatched_first(rt_cluster):
    """With one CPU held by a blocker, a later-submitted high-priority
    task must clear the raylet queue before the earlier low-priority one
    (dispatch walks scheduling classes priority-descending)."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=1)
    cluster.connect()

    @rt.remote(num_cpus=1)
    def blocker():
        time.sleep(1.2)
        return "held"

    @rt.remote(num_cpus=1)
    def stamp(tag):
        return (tag, time.monotonic())

    b = blocker.remote()
    time.sleep(0.4)  # let the blocker actually hold the CPU
    low = stamp.options(priority=0).remote("low")
    high = stamp.options(priority=5).remote("high")
    assert rt.get(b, timeout=60) == "held"
    (_, t_low), (_, t_high) = rt.get([low, high], timeout=60)
    assert t_high < t_low, "high-priority task ran after the low one"


# -- reclamation ------------------------------------------------------------


def test_reclamation_graceful_release(rt_cluster):
    """Infeasible high-priority demand drains the low-priority gang;
    when the victim hands its group back the claimant places on the
    freed (fenced) chips and the node un-cordons."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)  # head: CPU only, never preempted
    worker = cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()
    wid = worker.node_id.binary()

    low = placement_group([{"TPU": 4}], name="train-low", priority=0)
    assert low.ready(timeout=10)

    high = placement_group([{"TPU": 4}], name="serve-spike", priority=5)
    _wait_for(
        lambda: cluster.gcs.preemptions.get(low.id.binary()) is not None,
        timeout=10, what="preemption record",
    )
    rec = cluster.gcs.preemptions[low.id.binary()]
    assert rec["state"] == "draining"
    assert rec["reason"] == "priority"
    assert rec["victim_tenant"] == "train-low"
    assert rec["claimant_tenant"] == "serve-spike"
    node_info = cluster.gcs.nodes[wid]
    assert node_info.get("draining") is True
    assert node_info.get("fenced_for") == high.id.binary()
    # The fence blocks everyone but the claimant: a third-party group
    # must not steal the chips mid-handover.
    interloper = placement_group([{"TPU": 4}], name="interloper", priority=1)
    assert not interloper.ready(timeout=1.0)

    # Victim completes its graceful exit (checkpoint done -> group freed).
    remove_placement_group(low)
    assert high.ready(timeout=10)
    assert rec["state"] == "released"
    assert rec["outcome"] == "graceful"
    _wait_for(
        lambda: not cluster.gcs.nodes[wid].get("draining"),
        timeout=5, what="node un-drain",
    )
    assert cluster.gcs.nodes[wid].get("fenced_for") is None
    assert cluster.gcs.preempt_counts.get(
        (("reason", "priority"), ("tenant", "train-low"))
    ) == 1.0
    # The grace histogram observed the drain-to-release window.
    assert cluster.gcs.preempt_grace["count"] == 1
    remove_placement_group(high)
    remove_placement_group(interloper)


def test_equal_priority_never_preempts(rt_cluster):
    """Reclamation only crosses strict priority boundaries: an equal-
    priority pending group waits instead of evicting."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()

    first = placement_group([{"TPU": 4}], name="first", priority=3)
    assert first.ready(timeout=10)
    second = placement_group([{"TPU": 4}], name="second", priority=3)
    assert not second.ready(timeout=1.5)
    assert cluster.gcs.preemptions == {}
    remove_placement_group(first)
    remove_placement_group(second)


def test_hard_kill_deadline(rt_cluster, monkeypatch):
    """A victim that ignores the drain is hard-killed at the grace
    deadline: its actors die, its group is force-removed, and the
    claimant places — the deadline is the guarantee."""
    monkeypatch.setattr(get_config(), "preempt_grace_s", 1.0)
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()

    low = placement_group([{"TPU": 4}], name="deaf-gang", priority=0)
    assert low.ready(timeout=10)

    @rt.remote(num_cpus=0, resources={"TPU": 1})
    class Deaf:
        def ping(self):
            return "ok"

    a = Deaf.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=low, placement_group_bundle_index=0
        )
    ).remote()
    assert rt.get(a.ping.remote(), timeout=30) == "ok"

    t0 = time.monotonic()
    high = placement_group([{"TPU": 4}], name="spike", priority=9)
    assert high.ready(timeout=15)
    took = time.monotonic() - t0
    rec = cluster.gcs.preemptions[low.id.binary()]
    assert rec["outcome"] == "hard_kill"
    assert took >= 0.9, "hard kill fired before the grace window elapsed"
    assert cluster.gcs.placement_groups[low.id.binary()]["state"] == "REMOVED"
    _wait_for(
        lambda: cluster.gcs.actors[a._actor_id.binary()]["state"] == "DEAD",
        timeout=10, what="victim actor death",
    )
    assert cluster.gcs.preempt_counts.get(
        (("reason", "hard_kill"), ("tenant", "deaf-gang"))
    ) == 1.0
    remove_placement_group(high)


def test_claimant_withdrawal_cancels_preemption(rt_cluster, monkeypatch):
    """If the claimant gives up while victims drain, the eviction is
    cancelled: nodes un-cordon and the victim keeps its chips."""
    monkeypatch.setattr(get_config(), "preempt_grace_s", 30.0)
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    worker = cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()
    wid = worker.node_id.binary()

    low = placement_group([{"TPU": 4}], name="steady", priority=0)
    assert low.ready(timeout=10)
    high = placement_group([{"TPU": 4}], name="flash-spike", priority=5)
    _wait_for(
        lambda: cluster.gcs.preemptions.get(low.id.binary()) is not None,
        timeout=10, what="preemption record",
    )
    remove_placement_group(high)  # spike subsides before the victim moved
    _wait_for(
        lambda: cluster.gcs.preemptions[low.id.binary()]["state"]
        == "released",
        timeout=5, what="cancelled record",
    )
    rec = cluster.gcs.preemptions[low.id.binary()]
    assert rec["outcome"] == "cancelled"
    _wait_for(
        lambda: not cluster.gcs.nodes[wid].get("draining")
        and cluster.gcs.nodes[wid].get("fenced_for") is None,
        timeout=5, what="node restored",
    )
    assert cluster.gcs.placement_groups[low.id.binary()]["state"] == "CREATED"
    remove_placement_group(low)


def test_preempt_metrics_in_snapshot(rt_cluster):
    """preempt_total / preempt_grace_seconds / preempt_active /
    tenant_chip_occupancy appear as synthetic series in the GCS metrics
    snapshot (the autoscaler/dashboard feed)."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, num_tpus=4)
    client = cluster.connect()

    low = placement_group([{"TPU": 4}], name="tenant-a", priority=0)
    assert low.ready(timeout=10)
    high = placement_group([{"TPU": 4}], name="tenant-b", priority=5)
    _wait_for(
        lambda: cluster.gcs.preemptions.get(low.id.binary()) is not None,
        timeout=10, what="preemption record",
    )
    snap = client._run(client._gcs_call("metrics_snapshot", {}))["metrics"]
    by_name = {m["name"]: m for m in snap}
    assert by_name["preempt_active"]["series"][0][1] == 1
    tags = dict(
        tuple(t) for t in by_name["preempt_total"]["series"][0][0]
    )
    assert tags == {"reason": "priority", "tenant": "tenant-a"}
    occ = {
        dict(tuple(t) for t in tags_)["tenant"]: v
        for tags_, v in by_name["tenant_chip_occupancy"]["series"]
    }
    assert occ.get("tenant-a") == 4.0
    remove_placement_group(low)
    assert high.ready(timeout=10)
    snap = client._run(client._gcs_call("metrics_snapshot", {}))["metrics"]
    by_name = {m["name"]: m for m in snap}
    assert by_name["preempt_grace_seconds"]["series"][0][1]["count"] == 1
    remove_placement_group(high)


def test_actor_never_oversubscribes_reserved_chips(rt_cluster):
    """A plain actor demanding chips a placement group has reserved stays
    PENDING — node availability must never go negative (regression: the
    GCS used to place actors by node *totals* and the raylet force-
    acquired, double-booking pg-reserved chips and silently bypassing
    the whole preemption plane)."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    worker = cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()

    holder = placement_group([{"TPU": 4}], name="holder", priority=5)
    assert holder.ready(timeout=10)

    @rt.remote(num_cpus=0, resources={"TPU": 4})
    class Chip:
        def ping(self):
            return "ok"

    a = Chip.remote()  # lower priority than the holder: waits, no evict
    deadline = time.monotonic() + 1.5
    wid = worker.node_id.binary()
    while time.monotonic() < deadline:
        avail = cluster.gcs.nodes[wid]["resources_available"]
        assert avail.get("TPU", 0) >= 0, "chip availability went negative"
        time.sleep(0.05)
    assert cluster.gcs.actors[a._actor_id.binary()]["state"] == "PENDING"
    assert cluster.gcs.preemptions == {}
    remove_placement_group(holder)
    assert rt.get(a.ping.remote(), timeout=30) == "ok"


def test_pending_actor_claimant_preempts_gang(rt_cluster):
    """A high-priority pending ACTOR — no placement group of its own —
    is a reclamation claimant too (this is the serve-replica spike path:
    ray_actor_options={"resources": {"TPU": n}, "priority": p})."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()

    low = placement_group([{"TPU": 4}], name="train-low", priority=0)
    assert low.ready(timeout=10)

    @rt.remote(num_cpus=0, resources={"TPU": 4}, priority=9)
    class Spike:
        def ping(self):
            return "ok"

    a = Spike.remote()
    _wait_for(
        lambda: cluster.gcs.preemptions.get(low.id.binary()) is not None,
        timeout=10, what="actor-claimant preemption record",
    )
    rec = cluster.gcs.preemptions[low.id.binary()]
    assert rec["claimant"] == a._actor_id.binary()
    assert rec["reason"] == "priority"
    remove_placement_group(low)  # victim releases gracefully
    assert rt.get(a.ping.remote(), timeout=30) == "ok"
    assert rec["outcome"] == "graceful"


# -- raylet bundle accounting (regression) ----------------------------------


def test_cancel_bundle_no_oversubscription(rt_cluster):
    """Removing a placement group while a task still runs inside it must
    credit only the bundle's unused share; the running task's share
    returns on completion (raylet.py cancel_bundle + release fall-through
    pairing). The old behavior credited the full reservation, transiently
    oversubscribing the node."""
    cluster = rt_cluster
    node = cluster.add_node(num_cpus=2)
    cluster.connect()

    pg = placement_group([{"CPU": 2}], strategy="PACK")
    assert pg.ready(timeout=10)

    @rt.remote(num_cpus=1)
    def hold():
        time.sleep(1.5)
        return "done"

    ref = hold.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    ).remote()
    # Wait until the task actually holds CPU inside the bundle.
    _wait_for(
        lambda: any(
            b["available"].get("CPU") == 1.0 for b in node.bundles.values()
        ),
        timeout=10, what="task holding bundle CPU",
    )
    remove_placement_group(pg)
    _wait_for(lambda: not node.bundles, timeout=5, what="bundle cancel")
    # Unused share (1 CPU) is back; the running task's 1 CPU is not.
    assert node.resources_available.get("CPU", 0) <= 1.0 + 1e-6
    assert rt.get(ref, timeout=30) == "done"
    _wait_for(
        lambda: abs(node.resources_available.get("CPU", 0) - 2.0) < 1e-6,
        timeout=5, what="full CPU release",
    )


def test_task_errors_fast_when_bundle_removed(rt_cluster):
    """A task already queued behind a busy bundle errors out when the
    bundle is cancelled mid-wait instead of wedging its scheduling class
    (raylet _dispatch_class bundle-vanished path)."""
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.connect()

    pg = placement_group([{"CPU": 1}])
    assert pg.ready(timeout=10)

    @rt.remote(num_cpus=1)
    def hold():
        time.sleep(2.0)
        return "held"

    @rt.remote(num_cpus=1)
    def f():
        return 1

    strat = PlacementGroupSchedulingStrategy(
        placement_group=pg, placement_group_bundle_index=0
    )
    blocker = hold.options(scheduling_strategy=strat).remote()
    time.sleep(0.6)  # blocker holds the bundle; f queues behind it
    queued = f.options(scheduling_strategy=strat).remote()
    time.sleep(0.3)
    remove_placement_group(pg)
    with pytest.raises(Exception, match="bundle was removed"):
        rt.get(queued, timeout=15)
    assert rt.get(blocker, timeout=30) == "held"
    # The node is not wedged: plain tasks still dispatch.
    assert rt.get(f.remote(), timeout=30) == 1


# -- chaos hooks ------------------------------------------------------------


@pytest.mark.chaos
def test_chaos_preempt_node(rt_cluster, monkeypatch):
    monkeypatch.setenv("RT_CHAOS", "1")
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    worker = cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()
    wid = worker.node_id.binary()

    pg = placement_group([{"TPU": 4}], name="victim", priority=0)
    assert pg.ready(timeout=10)

    victims = chaos.preempt_node(wid)
    assert victims == [pg.id.hex()]
    assert cluster.gcs.nodes[wid].get("draining") is True
    rec = cluster.gcs.preemptions[pg.id.binary()]
    assert rec["reason"] == "chaos"
    assert rec["claimant"] is None
    remove_placement_group(pg)  # graceful exit closes the record
    assert rec["outcome"] == "graceful"
    # Head node refuses: it cannot drain.
    head_id = cluster.head.node_id.binary()
    with pytest.raises(RuntimeError, match="head node"):
        chaos.preempt_node(head_id)


@pytest.mark.chaos
def test_chaos_kill_victim_mid_drain(rt_cluster, monkeypatch):
    """Compound fault: the victim dies *while* draining. The record
    still converges (here via graceful close when the group is removed;
    the bench exercises the hard-kill convergence)."""
    monkeypatch.setenv("RT_CHAOS", "1")
    monkeypatch.setattr(get_config(), "preempt_grace_s", 30.0)
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    worker = cluster.add_node(num_cpus=2, num_tpus=4)
    cluster.connect()

    pg = placement_group([{"TPU": 4}], name="gang", priority=0)
    assert pg.ready(timeout=10)

    @rt.remote(num_cpus=0, resources={"TPU": 1})
    class W:
        def ping(self):
            return "ok"

    a = W.options(
        scheduling_strategy=PlacementGroupSchedulingStrategy(
            placement_group=pg, placement_group_bundle_index=0
        )
    ).remote()
    assert rt.get(a.ping.remote(), timeout=30) == "ok"

    # No drain in flight yet -> the hook refuses.
    with pytest.raises(RuntimeError, match="no draining victim"):
        chaos.kill_victim_mid_drain()

    chaos.preempt_node(worker.node_id.binary())
    killed = chaos.kill_victim_mid_drain()
    assert killed == a._actor_id.hex()
    _wait_for(
        lambda: cluster.gcs.actors[a._actor_id.binary()]["state"] == "DEAD",
        timeout=10, what="mid-drain victim death",
    )
    remove_placement_group(pg)


def test_chaos_hooks_require_env(monkeypatch):
    monkeypatch.delenv("RT_CHAOS", raising=False)
    with pytest.raises(RuntimeError, match="RT_CHAOS"):
        chaos.preempt_node(b"\x00" * 16)
    with pytest.raises(RuntimeError, match="RT_CHAOS"):
        chaos.kill_victim_mid_drain()


# -- trainer backoff reset --------------------------------------------------


def test_backoff_for_attempt_unit():
    from ray_tpu.train.config import FailureConfig

    fc = FailureConfig(backoff_s=0.5, backoff_max_s=3.0)
    assert fc.backoff_for_attempt(0) == 0.5
    assert fc.backoff_for_attempt(1) == 1.0
    assert fc.backoff_for_attempt(2) == 2.0
    assert fc.backoff_for_attempt(3) == 3.0  # capped
    assert FailureConfig(backoff_s=0).backoff_for_attempt(5) == 0.0


def test_fit_backoff_resets_after_progress(tmp_path, monkeypatch):
    """After an attempt that made progress (new reports/checkpoint), a
    later unrelated failure backs off from backoff_s again — the counter
    tracks consecutive no-progress failures, not total restarts."""
    from ray_tpu.train import trainer as trainer_mod
    from ray_tpu.train.backend_executor import TrainingFailedError
    from ray_tpu.train.config import FailureConfig, RunConfig

    class DummyExecutor:
        def __init__(self, *a, **k):
            pass

        def start(self):
            pass

        def restart(self):
            pass

        def shutdown(self):
            pass

    sleeps = []

    class FakeTime:
        monotonic = staticmethod(time.monotonic)

        @staticmethod
        def sleep(s):
            sleeps.append(round(s, 6))

    monkeypatch.setattr(trainer_mod, "BackendExecutor", DummyExecutor)
    monkeypatch.setattr(trainer_mod, "time", FakeTime)

    attempt_no = {"n": 0}

    def fake_run_attempt(self, executor, manager, checkpoint, trial_dir):
        n = attempt_no["n"]
        attempt_no["n"] += 1
        if n == 1:
            # This attempt trained for a while before dying.
            self._metrics_history.append({"loss": 1.0})
        if n < 3:
            raise TrainingFailedError(f"crash {n}", retryable=True)
        return "ok"

    monkeypatch.setattr(
        trainer_mod.DataParallelTrainer, "_run_attempt", fake_run_attempt
    )
    t = trainer_mod.DataParallelTrainer(
        lambda: None,
        run_config=RunConfig(
            name="backoff-reset",
            storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=3, backoff_s=0.07,
                                         backoff_max_s=10.0),
        ),
    )
    assert t.fit() == "ok"
    # attempt 0 fails cold -> 0.07; attempt 1 made progress -> reset to
    # 0.07; attempt 2 fails cold again -> doubled 0.14. The pre-fix
    # never-resetting counter would have slept [0.07, 0.14, 0.28].
    assert sleeps == [0.07, 0.07, 0.14]
