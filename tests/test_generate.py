"""KV-cache generation tests: cached prefill/decode must match the full
forward exactly (the correctness bar for incremental decoding)."""

from dataclasses import replace

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import configs, forward, init_params
from ray_tpu.models.generate import (
    decode_step,
    generate,
    init_kv_cache,
    prefill,
)

pytestmark = pytest.mark.slow  # jax-compile-heavy compute-path tier


@pytest.fixture(scope="module")
def setup():
    cfg = replace(configs.tiny, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(1), (2, 7), 0, cfg.vocab_size
    )
    return cfg, params, prompt


def test_prefill_matches_full_forward(setup):
    cfg, params, prompt = setup
    cache = init_kv_cache(cfg, 2, 16)
    logits_c, cache = prefill(params, prompt, cache, cfg)
    logits_f, _ = forward(params, prompt, cfg)
    np.testing.assert_allclose(
        np.asarray(logits_c), np.asarray(logits_f[:, -1]),
        rtol=2e-4, atol=2e-4,
    )
    assert int(cache["length"]) == 7


def test_decode_steps_match_full_forward(setup):
    cfg, params, prompt = setup
    cache = init_kv_cache(cfg, 2, 16)
    logits, cache = prefill(params, prompt, cache, cfg)
    seq = prompt
    for _ in range(3):
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)
        logits, cache = decode_step(params, nxt, cache, cfg)
        full, _ = forward(params, seq, cfg)
        np.testing.assert_allclose(
            np.asarray(logits), np.asarray(full[:, -1]),
            rtol=3e-4, atol=3e-4,
        )


def test_greedy_generation_parity(setup):
    cfg, params, prompt = setup
    out = generate(params, prompt, cfg, max_new_tokens=4)
    assert out.shape == (2, 4)
    seq = prompt
    for i in range(4):
        lg, _ = forward(params, seq, cfg)
        nxt = jnp.argmax(lg[:, -1], axis=-1).astype(jnp.int32)
        np.testing.assert_array_equal(np.asarray(out[:, i]), np.asarray(nxt))
        seq = jnp.concatenate([seq, nxt[:, None]], axis=1)


def test_gqa_generation_runs(setup):
    cfg = replace(configs.tiny_gqa, dtype=jnp.float32)
    params = init_params(jax.random.PRNGKey(2), cfg)
    prompt = jax.random.randint(
        jax.random.PRNGKey(3), (1, 5), 0, cfg.vocab_size
    )
    out = generate(params, prompt, cfg, max_new_tokens=3)
    assert out.shape == (1, 3)


def test_eos_stops_and_pads(setup):
    cfg, params, prompt = setup
    out_free = generate(params, prompt, cfg, max_new_tokens=6)
    eos = int(out_free[0, 2])  # force stop after 3 tokens for row 0
    out = generate(params, prompt, cfg, max_new_tokens=6, eos_id=eos)
    row = np.asarray(out[0])
    hit = np.where(row == eos)[0]
    assert len(hit) > 0
    assert (row[hit[0]:] == eos).all(), "post-eos positions must pad with eos"


def test_sampled_generation_respects_temperature(setup):
    cfg, params, prompt = setup
    a = generate(params, prompt, cfg, max_new_tokens=8, temperature=1.5,
                 rng=jax.random.PRNGKey(7))
    b = generate(params, prompt, cfg, max_new_tokens=8, temperature=1.5,
                 rng=jax.random.PRNGKey(8))
    assert a.shape == b.shape == (2, 8)
    assert not np.array_equal(np.asarray(a), np.asarray(b))


def test_top_k_filter_masks_tail():
    import jax.numpy as jnp

    from ray_tpu.models.generate import _filter_top_k

    logits = jnp.asarray([[1.0, 5.0, 3.0, 2.0]])
    out = _filter_top_k(logits, 2)
    # Only the top-2 (5.0, 3.0) survive.
    assert bool(jnp.isneginf(out[0, 0])) and bool(jnp.isneginf(out[0, 3]))
    assert out[0, 1] == 5.0 and out[0, 2] == 3.0


def test_top_p_keeps_crossing_token():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.generate import _filter_top_p

    # probs ~ [0.643, 0.236, 0.087, 0.032]; p=0.5 keeps only the first
    # token (its mass crosses 0.5); p=0.7 keeps the first two.
    logits = jnp.asarray([[4.0, 3.0, 2.0, 1.0]])
    p50 = _filter_top_p(logits, 0.5)
    assert not bool(jnp.isneginf(p50[0, 0]))
    assert bool(jnp.isneginf(p50[0, 1]))
    p70 = _filter_top_p(logits, 0.7)
    assert not bool(jnp.isneginf(p70[0, 1]))
    assert bool(jnp.isneginf(p70[0, 2]))


def test_top_k1_sampling_equals_greedy(setup):
    import jax

    from ray_tpu.models.generate import generate

    cfg, params, _ = setup
    prompt = jax.numpy.asarray([[5, 7, 11]], dtype=jax.numpy.int32)
    greedy = generate(params, prompt, cfg, max_new_tokens=8)
    topk1 = generate(
        params, prompt, cfg, max_new_tokens=8,
        temperature=1.0, top_k=1, rng=jax.random.PRNGKey(3),
    )
    assert (greedy == topk1).all()
