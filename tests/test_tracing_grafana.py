"""Distributed tracing + Grafana dashboard generation tests.

Reference model: tracing_helper's span-injection behavior (spans form a
cross-process tree keyed by trace id) and the grafana_dashboard_factory
output shape.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.util import tracing


@pytest.fixture(autouse=True)
def _tracing_off_after():
    yield
    tracing.disable()


def test_span_nesting_local():
    tracing.enable()
    assert tracing.current() is None
    with tracing.span("outer"):
        outer = tracing.current()
        assert outer is not None
        with tracing.span("inner"):
            inner = tracing.current()
            assert inner["trace_id"] == outer["trace_id"]
            assert inner["span_id"] != outer["span_id"]
        assert tracing.current() == outer
    assert tracing.current() is None


def test_inject_roots_new_trace_when_idle():
    tracing.enable()
    ctx = tracing.inject()
    assert ctx["parent_span_id"] == ""
    assert len(ctx["trace_id"]) == 32
    tracing.disable()
    assert tracing.inject() is None


def test_task_spans_form_cross_process_tree(rt_start):
    tracing.enable()

    @rt.remote
    def child():
        return "ok"

    @rt.remote
    def parent():
        return rt.get(child.remote())

    with tracing.span("request"):
        root_ctx = tracing.current()
        assert rt.get(parent.remote(), timeout=120) == "ok"
    from ray_tpu.util import profiling

    profiling.flush()
    # Worker-side spans ride the bounded-delay batch flush (default
    # 0.25s) rather than an eager per-span RPC — wait out one window.
    time.sleep(0.7)

    spans = tracing.get_trace(root_ctx["trace_id"])
    # Task spans carry the function qualname; match by suffix.
    by_name = {s["name"].rsplit(".", 1)[-1]: s for s in spans}
    assert {"request", "parent", "child"} <= set(by_name), by_name.keys()
    # parent task's span is a child of the driver's "request" span...
    assert by_name["parent"]["parent_id"] == by_name["request"]["span_id"]
    # ...and the nested task's span hangs off the parent task's span.
    assert by_name["child"]["parent_id"] == by_name["parent"]["span_id"]
    assert all(s["dur_s"] >= 0 for s in spans)


def test_actor_call_spans_join_the_trace(rt_start):
    tracing.enable()

    @rt.remote
    class A:
        def work(self):
            return 1

    a = A.remote()
    rt.get(a.work.remote(), timeout=120)  # untraced warmup outside span
    with tracing.span("actor-request"):
        ctx = tracing.current()
        assert rt.get(a.work.remote(), timeout=120) == 1
    from ray_tpu.util import profiling

    profiling.flush()
    # Worker-side spans ride the bounded-delay batch flush (default
    # 0.25s) rather than an eager per-span RPC — wait out one window.
    time.sleep(0.7)
    spans = tracing.get_trace(ctx["trace_id"])
    by_name = {s["name"]: s for s in spans}
    assert by_name["work"]["parent_id"] == by_name["actor-request"]["span_id"]


def test_grafana_dashboard_shape(tmp_path):
    from ray_tpu.dashboard.grafana import generate_dashboard, write_dashboard

    metrics = [
        {"name": "app_requests", "description": "requests", "type": "counter"},
        {"name": "app_depth", "description": "", "type": "gauge"},
        {"name": "app_latency", "description": "latency", "type": "histogram"},
    ]
    dash = generate_dashboard(user_metrics=metrics)
    assert dash["uid"] == "rt-tpu-cluster"
    titles = [p["title"] for p in dash["panels"]]
    assert "Actors by state" in titles
    exprs = [t["expr"] for p in dash["panels"] for t in p["targets"]]
    assert "rate(app_requests[1m])" in exprs
    assert "app_depth" in exprs
    assert any("histogram_quantile(0.99" in e for e in exprs)
    # Every panel is wired to the templated prometheus datasource.
    assert all(
        p["datasource"]["uid"] == "${datasource}" for p in dash["panels"]
    )
    # File output round-trips as JSON.
    import json

    path = write_dashboard(str(tmp_path / "dash.json"), user_metrics=metrics)
    assert json.load(open(path))["panels"]
