"""DAG + compiled DAG tests.

Reference analogs: python/ray/dag/tests and
python/ray/tests/test_accelerated_dag.py (channels, resident exec loops,
error propagation).
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.dag import InputNode, MultiOutputNode
from ray_tpu.experimental.channel import Channel, ChannelClosed


def test_channel_roundtrip():
    ch = Channel(create=True, max_size=1_000_000)
    try:
        ch.write({"x": 1})
        assert ch.read() == {"x": 1}
        ch.write([1, 2, 3])
        assert ch.read() == [1, 2, 3]
        with pytest.raises(ValueError):
            ch.write(b"x" * 2_000_000)
        ch.close()
        with pytest.raises(ChannelClosed):
            ch.write(1)
    finally:
        ch.destroy()


def test_eager_task_dag(rt_start):
    @rt.remote
    def double(x):
        return 2 * x

    @rt.remote
    def add(a, b):
        return a + b

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), double.bind(inp))
    assert dag.execute(5) == 20
    assert dag.execute(7) == 28


def test_eager_actor_dag(rt_start):
    @rt.remote
    class Acc:
        def __init__(self):
            self.total = 0

        def add(self, x):
            self.total += x
            return self.total

    acc = Acc.remote()
    with InputNode() as inp:
        dag = acc.add.bind(inp)
    assert dag.execute(3) == 3
    assert dag.execute(4) == 7  # stateful across executes


def test_compiled_chain(rt_start):
    @rt.remote
    class Stage:
        def __init__(self, mul):
            self.mul = mul

        def fwd(self, x):
            return x * self.mul

    s1, s2 = Stage.remote(2), Stage.remote(10)
    with InputNode() as inp:
        dag = s2.fwd.bind(s1.fwd.bind(inp))
    compiled = dag.experimental_compile()
    try:
        for i in range(10):
            assert compiled.execute(i) == i * 20
    finally:
        compiled.teardown()


def test_compiled_fan_out_fan_in(rt_start):
    @rt.remote
    class Worker:
        def sq(self, x):
            return x * x

        def neg(self, x):
            return -x

    a, b = Worker.remote(), Worker.remote()
    with InputNode() as inp:
        dag = MultiOutputNode([a.sq.bind(inp), b.neg.bind(inp)])
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(4) == [16, -4]
        assert compiled.execute(5) == [25, -5]
    finally:
        compiled.teardown()


def test_compiled_error_propagates(rt_start):
    @rt.remote
    class Boomer:
        def go(self, x):
            if x == 13:
                raise ValueError("unlucky")
            return x

        def fwd(self, x):
            return x

    actor = Boomer.remote()
    with InputNode() as inp:
        dag = actor.fwd.bind(actor.go.bind(inp))
    compiled = dag.experimental_compile()
    try:
        assert compiled.execute(1) == 1
        with pytest.raises(rt.exceptions.TaskError):
            compiled.execute(13)
        # The pipeline survives an error and keeps executing.
        assert compiled.execute(2) == 2
    finally:
        compiled.teardown()


def test_compiled_throughput_faster_than_actor_calls(rt_start):
    """The point of compilation: repeat execution beats per-call RPC."""

    @rt.remote
    class Echo:
        def fwd(self, x):
            return x

    actor = Echo.remote()
    rt.get(actor.fwd.remote(0))  # warm

    n = 200
    t0 = time.perf_counter()
    for i in range(n):
        rt.get(actor.fwd.remote(i))
    eager_s = time.perf_counter() - t0

    with InputNode() as inp:
        dag = actor.fwd.bind(inp)
    compiled = dag.experimental_compile()
    try:
        compiled.execute(0)  # warm the loop
        t0 = time.perf_counter()
        for i in range(n):
            assert compiled.execute(i) == i
        compiled_s = time.perf_counter() - t0
    finally:
        compiled.teardown()
    # Shared-memory handoff must beat the RPC path comfortably.
    assert compiled_s < eager_s, (compiled_s, eager_s)
