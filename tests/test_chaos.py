"""Chaos / fault-injection tests: kill raylets, workers, and the GCS
mid-workload and assert the cluster heals.

Reference analogs: ResourceKillerActor/RayletKiller/WorkerKillerActor
(python/ray/_private/test_utils.py:1396,1446,1527), tests/chaos/, and the
GCS restart story of gcs/store_client/redis_store_client.h:33 +
gcs_redis_failure_detector.cc.
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu.cluster_utils import Cluster

pytestmark = pytest.mark.slow  # chaos/e2e tier — fast runs skip


def test_worker_kill_storm_completes(tmp_path):
    """SIGKILL random workers while a task storm runs: retries must land
    every task (WorkerKillerActor analog)."""
    cluster = Cluster()
    head = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        @rt.remote(max_retries=5)
        def work(i):
            time.sleep(0.05)
            return i

        stop = threading.Event()

        def killer():
            while not stop.is_set():
                time.sleep(0.35)
                victims = [
                    w for w in head.workers.values()
                    if w.proc is not None and w.conn is not None
                    and w.actor_id is None
                ]
                for w in victims[:1]:
                    try:
                        os.kill(w.proc.pid, signal.SIGKILL)
                    except (ProcessLookupError, TypeError):
                        pass

        t = threading.Thread(target=killer, daemon=True)
        t.start()
        try:
            refs = [work.remote(i) for i in range(40)]
            out = rt.get(refs, timeout=180)
        finally:
            stop.set()
            t.join()
        assert out == list(range(40))
    finally:
        cluster.shutdown()


def test_raylet_kill_during_task_storm(tmp_path):
    """Kill a whole raylet (workers die, node marked dead) while tasks that
    were spilled over to it are running: retries reschedule them on the
    surviving node (RayletKiller analog)."""
    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    victim = cluster.add_node(num_cpus=4)
    cluster.connect()
    try:
        @rt.remote(max_retries=5)
        def work(i):
            time.sleep(0.2)
            return i

        refs = [work.remote(i) for i in range(20)]
        time.sleep(1.0)  # let spillover land tasks on the victim
        cluster.kill_raylet(victim)
        out = rt.get(refs, timeout=180)
        assert out == list(range(20))
    finally:
        cluster.shutdown()


def test_raylet_kill_during_pg_commit(tmp_path):
    """Kill a raylet between placement-group prepare and use: the PG must
    either complete on surviving nodes or stay pending — never wedge the
    GCS (the SURVEY §7 'hard part')."""
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    victim = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        from ray_tpu.util.placement_group import placement_group

        pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="PACK")
        cluster.kill_raylet(victim)
        # The PG may have prepared bundles on the dead node; it must either
        # become ready on the survivor or stay pending — and the GCS must
        # keep serving requests either way.
        try:
            pg.ready(timeout=20)
        except Exception:
            pass
        assert rt.cluster_resources().get("CPU") is not None  # GCS alive
    finally:
        cluster.shutdown()


def test_gcs_restart_preserves_state_and_serves(tmp_path):
    """Kill + restart the GCS with persistence: durable state survives,
    raylets re-register, and the cluster keeps running tasks."""
    persist = str(tmp_path / "gcs_snapshot.bin")
    cluster = Cluster(gcs_persist_path=persist)
    cluster.add_node(num_cpus=2)
    client = cluster.connect()
    try:
        @rt.remote
        class Counter:
            def __init__(self):
                self.n = 0

            def inc(self):
                self.n += 1
                return self.n

        c = Counter.options(name="survivor").remote()
        assert rt.get(c.inc.remote()) == 1
        client.kv_put(b"durable-key", b"durable-value")
        time.sleep(0.3)  # let the snapshot debounce flush

        cluster.kill_gcs()
        time.sleep(0.5)
        cluster.restart_gcs()

        # Raylet re-registers within its heartbeat period.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if cluster.gcs.nodes and any(
                n["state"] == "ALIVE" for n in cluster.gcs.nodes.values()
            ):
                break
            time.sleep(0.25)
        else:
            pytest.fail("raylet did not re-register with the restarted GCS")

        # Durable KV survived; the named actor is still resolvable AND
        # callable (its worker process never died).
        assert client.kv_get(b"durable-key") == b"durable-value"
        c2 = rt.get_actor("survivor")
        assert rt.get(c2.inc.remote(), timeout=30) == 2

        # Fresh tasks run after the restart.
        @rt.remote
        def add(a, b):
            return a + b

        assert rt.get(add.remote(2, 3), timeout=60) == 5
    finally:
        cluster.shutdown()

def test_gcs_hard_kill_wal_replay(tmp_path):
    """SIGKILL-equivalent GCS death right after acknowledged writes: the
    debounced snapshot has NOT flushed, so recovery rides the write-ahead
    log alone (gcs.py _wal_append / _replay_wal; reference:
    gcs_table_storage.h + redis_store_client.h:33). Actors registered
    moments before the kill must exist after replay and the cluster must
    heal."""
    persist = str(tmp_path / "gcs.bin")
    cluster = Cluster(gcs_persist_path=persist)
    cluster.add_node(num_cpus=2)
    client = cluster.connect()
    try:
        @rt.remote
        class Reg:
            def ping(self):
                return "pong"

        # Acknowledged writes immediately before the kill — inside the
        # snapshot debounce window, covered only by the WAL.
        actors = [
            Reg.options(name=f"wal-actor-{i}", num_cpus=0.001).remote()
            for i in range(3)
        ]
        for a in actors:
            assert rt.get(a.ping.remote(), timeout=30) == "pong"
        client.kv_put(b"wal-key", b"wal-value")

        cluster.kill_gcs(hard=True)  # no final snapshot
        import os

        assert os.path.exists(persist + ".wal"), "WAL file missing"
        cluster.restart_gcs()

        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if cluster.gcs.nodes and any(
                n["state"] == "ALIVE" for n in cluster.gcs.nodes.values()
            ):
                break
            time.sleep(0.25)
        else:
            pytest.fail("raylet did not re-register after WAL replay")

        # Every pre-kill acknowledged write was replayed from the WAL.
        assert client.kv_get(b"wal-key") == b"wal-value"
        for i in range(3):
            h = rt.get_actor(f"wal-actor-{i}")
            assert rt.get(h.ping.remote(), timeout=30) == "pong"

        @rt.remote
        def add(a, b):
            return a + b

        assert rt.get(add.remote(4, 5), timeout=60) == 9
    finally:
        cluster.shutdown()


def test_gcs_restart_during_task_storm(tmp_path):
    """The GCS dies and restarts WHILE tasks are flowing: in-flight work
    completes (tasks ride raylet connections, not the GCS) and new work
    submits after the raylet re-registers."""
    persist = str(tmp_path / "gcs.bin")
    cluster = Cluster(gcs_persist_path=persist)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        @rt.remote(max_retries=3)
        def work(i):
            time.sleep(0.1)
            return i

        refs = [work.remote(i) for i in range(20)]
        time.sleep(0.4)  # storm in flight
        cluster.kill_gcs()
        time.sleep(0.5)
        cluster.restart_gcs()

        assert rt.get(refs, timeout=120) == list(range(20))

        # Fresh submissions work once the raylet re-registers.
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            if any(n["state"] == "ALIVE" for n in cluster.gcs.nodes.values()):
                break
            time.sleep(0.25)
        assert rt.get(work.remote(99), timeout=60) == 99
    finally:
        cluster.shutdown()


def test_worker_kills_during_distributed_shuffle(tmp_path):
    """SIGKILL workers while a push-based shuffle + hash groupby runs:
    task retries and lineage reconstruction must still produce exact
    aggregates (the nightly shuffle chaos test's assertion, scaled
    down)."""
    cluster = Cluster()
    head = cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        from ray_tpu import data as rtd

        stop = threading.Event()

        def killer(max_kills: int = 6):
            # Bounded like the reference's chaos windows: sustained
            # adversarial kills on a 2-worker node can suppress liveness
            # forever; recovery (not starvation) is what's under test.
            kills = 0
            while not stop.is_set() and kills < max_kills:
                time.sleep(0.4)
                victims = [
                    w for w in head.workers.values()
                    if w.proc is not None and w.conn is not None
                    and w.actor_id is None
                ]
                for w in victims[:1]:
                    try:
                        os.kill(w.proc.pid, signal.SIGKILL)
                        kills += 1
                    except (ProcessLookupError, TypeError):
                        pass

        t = threading.Thread(target=killer, daemon=True)
        t.start()
        try:
            ds = rtd.from_items(
                [{"k": i % 5, "v": float(i)} for i in range(500)],
                parallelism=8,
            )
            rows = (
                ds.map(lambda r: {"k": r["k"], "v": r["v"] * 2})
                .random_shuffle(seed=7)
                .groupby("k")
                .sum("v")
                .take_all()
            )
        finally:
            stop.set()
            t.join()
        got = {r["k"]: r["sum(v)"] for r in rows}
        want = {}
        for i in range(500):
            want[i % 5] = want.get(i % 5, 0.0) + 2.0 * i
        assert got == want
    finally:
        cluster.shutdown()


def test_serve_controller_killed():
    """Kill the Serve controller mid-traffic: requests must keep landing
    (handles route from their cached replica set), the restarted
    controller must recover every deployment from its GCS-KV checkpoint
    and re-adopt the SAME live replicas, and reconciliation/autoscaling
    must keep working afterwards (reference:
    serve/_private/controller.py:91 checkpoint + deployment_state.py:2321
    _recover_from_checkpoint)."""
    from ray_tpu import serve
    from ray_tpu.serve.controller import CONTROLLER_NAME

    rt.init(num_cpus=4)
    try:
        @serve.deployment(num_replicas=2)
        def echo(x):
            return x * 2

        handle = serve.run(echo.bind(), name="ha_app")
        assert handle.remote(21).result(timeout=30) == 42

        before = serve.status()
        assert before["ha_app"]["running_replicas"] == 2
        ctrl = rt.get_actor(CONTROLLER_NAME)
        replicas_before = {
            r._actor_id.hex()
            for r in rt.get(ctrl.get_replicas.remote("ha_app"))["replicas"]
        }

        failures = []
        successes = [0]
        stop = threading.Event()

        def traffic():
            i = 0
            while not stop.is_set():
                try:
                    assert handle.remote(i).result(timeout=20) == 2 * i
                    successes[0] += 1
                except Exception as e:  # noqa: BLE001
                    failures.append(repr(e))
                i += 1
                time.sleep(0.02)

        t = threading.Thread(target=traffic, daemon=True)
        t.start()
        try:
            time.sleep(0.5)
            # Crash the controller (restartable kill: the GCS replays the
            # creation spec and __init__ restores from the checkpoint).
            rt.kill(ctrl, no_restart=False)

            # The controller must come back and report the app, with the
            # SAME replica actors re-adopted (no replica churn).
            deadline = time.monotonic() + 60
            recovered = None
            while time.monotonic() < deadline:
                try:
                    ctrl2 = rt.get_actor(CONTROLLER_NAME)
                    st = rt.get(ctrl2.status.remote(), timeout=10)
                    if st.get("ha_app", {}).get("running_replicas") == 2:
                        recovered = st
                        break
                except Exception:  # noqa: BLE001 — still restarting
                    pass
                time.sleep(0.5)
            assert recovered is not None, "controller never recovered"
            replicas_after = {
                r._actor_id.hex()
                for r in rt.get(ctrl2.get_replicas.remote("ha_app"))["replicas"]
            }
            assert replicas_after == replicas_before, (
                "recovery restarted replicas instead of re-adopting them"
            )
            time.sleep(1.0)
        finally:
            stop.set()
            t.join(timeout=30)

        # Zero route loss through the crash.
        assert not failures, f"requests failed during controller crash: {failures[:3]}"
        assert successes[0] > 10

        # Reconciliation continuity: a scale-up after recovery is honored.
        @serve.deployment(num_replicas=1)
        def echo2(x):
            return x + 1

        h2 = serve.run(echo2.bind(), name="ha_app2")
        assert h2.remote(1).result(timeout=30) == 2
        st = serve.status()
        assert st["ha_app2"]["running_replicas"] == 1
        serve.shutdown()
    finally:
        rt.shutdown()


def test_broadcast_survives_mid_chain_node_death():
    """Kill a broadcast consumer node mid-transfer: pullers that chained
    off its partial copy must re-route to surviving holders and still get
    exact bytes (the partial-location retry path; reference:
    object_manager.cc pull retry over remaining locations)."""
    import numpy as np

    import ray_tpu._private.config as config_mod
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    os.environ["RT_SAME_HOST_SHM_TRANSFER"] = "0"
    config_mod._config = None
    cluster = Cluster()
    cluster.add_node(num_cpus=1, object_store_memory=512 * 1024 * 1024)
    victims = [cluster.add_node(num_cpus=1,
                                object_store_memory=512 * 1024 * 1024)
               for _ in range(3)]
    cluster.connect()
    try:
        rng = np.random.default_rng(13)
        payload = rng.standard_normal(8_000_000)  # 64MB
        ref = rt.put(payload)
        want = float(payload.sum())

        @rt.remote
        def digest(x):
            return float(x.sum())

        refs = [
            digest.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=r.node_id.binary()
                )
            ).remote(ref)
            for r in victims
        ]
        # Kill one consumer node shortly into the broadcast: any puller
        # chained to its partial copy must fail over.
        time.sleep(0.15)
        cluster.remove_node(victims[0])
        done, pending = rt.wait(refs, num_returns=3, timeout=120)
        # The killed node's own task may fail/retry elsewhere; the other
        # two MUST land with exact bytes.
        ok = 0
        for r in refs[1:]:
            try:
                assert abs(rt.get(r, timeout=60) - want) < 1e-6
                ok += 1
            except Exception:  # noqa: BLE001
                pass
        assert ok == 2, f"only {ok}/2 surviving consumers completed"
    finally:
        os.environ.pop("RT_SAME_HOST_SHM_TRANSFER", None)
        config_mod._config = None
        cluster.shutdown()
