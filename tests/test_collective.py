"""Collective library tests.

Modeled on the reference's python/ray/util/collective tests: API-level
allreduce/allgather/reducescatter/broadcast/send/recv across actors (DCN
backend over TCP rings, rendezvous through the GCS KV) and local-device
XLA collectives on the virtual CPU mesh.
"""

import numpy as np
import pytest

import ray_tpu as rt


@rt.remote(num_cpus=0.5)
class CollectiveWorker:
    """An actor participating in eager collectives (reference pattern:
    collective groups are placed on actors, collective.py:151)."""

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        self.col = col
        col.init_collective_group(world_size, rank, backend, group_name)
        self.rank = rank
        return True

    def do_allreduce(self, group_name="default"):
        return self.col.allreduce(
            np.full(1000, float(self.rank + 1)), group_name
        )

    def do_allgather(self, group_name="default"):
        return self.col.allgather(np.array([self.rank]), group_name)

    def do_reducescatter(self, group_name="default"):
        return self.col.reducescatter(
            np.arange(8, dtype=np.float64), group_name
        )

    def do_broadcast(self, group_name="default"):
        value = np.array([42.0]) if self.rank == 0 else np.zeros(1)
        return self.col.broadcast(value, 0, group_name)

    def do_sendrecv(self, group_name="default"):
        if self.rank == 0:
            self.col.send(np.array([7.0, 8.0]), 1, group_name)
            return None
        return self.col.recv((2,), 0, group_name)

    def do_barrier(self, group_name="default"):
        self.col.barrier(group_name)
        return True

    def do_observed_allreduce(self, group_name="default"):
        """Run an allreduce with an op observer attached and return the
        (op, info) records — the flight-recorder attribution path."""
        seen = []

        def obs(op, seconds, info=None):
            seen.append((op, info))

        self.col.add_op_observer(obs)
        try:
            self.col.allreduce(np.full(1000, 1.0, dtype=np.float32),
                               group_name)
        finally:
            self.col.remove_op_observer(obs)
        return seen

    def do_quant_allreduce(self, group_name="default"):
        out = self.col.allreduce(
            np.full(1024, float(self.rank + 1), dtype=np.float32),
            group_name, quant="int8",
        )
        return out, self.col.last_op_info(group_name)


@pytest.fixture
def group(rt_start):
    from ray_tpu.util import collective as col

    n = 3
    workers = [CollectiveWorker.remote() for _ in range(n)]
    col.create_collective_group(
        workers, n, list(range(n)), backend="dcn", group_name="default"
    )
    yield workers


def test_dcn_allreduce(group):
    outs = rt.get([w.do_allreduce.remote() for w in group])
    expected = np.full(1000, 1.0 + 2.0 + 3.0)
    for out in outs:
        assert np.allclose(out, expected)


def test_dcn_allgather(group):
    outs = rt.get([w.do_allgather.remote() for w in group])
    for out in outs:
        assert [int(x[0]) for x in out] == [0, 1, 2]


def test_dcn_reducescatter(group):
    outs = rt.get([w.do_reducescatter.remote() for w in group])
    full = np.arange(8, dtype=np.float64) * 3  # summed over 3 ranks
    chunks = np.array_split(full, 3)
    for rank, out in enumerate(outs):
        assert np.allclose(out, chunks[rank])


def test_dcn_broadcast(group):
    outs = rt.get([w.do_broadcast.remote() for w in group])
    for out in outs:
        assert out[0] == 42.0


def test_dcn_sendrecv(group):
    outs = rt.get([w.do_sendrecv.remote() for w in group[:2]])
    assert outs[0] is None
    assert np.allclose(outs[1], [7.0, 8.0])


def test_dcn_barrier(group):
    assert all(rt.get([w.do_barrier.remote() for w in group]))


def test_dcn_ops_flow_through_observers_with_info(group):
    """Eager DCN ops must reach collective._op_observers carrying
    tier/algo/bytes so the flight recorder can attribute them."""
    outs = rt.get([w.do_observed_allreduce.remote() for w in group])
    for seen in outs:
        assert len(seen) == 1
        op, info = seen[0]
        assert op == "allreduce"
        assert info["tier"] == "dcn"
        assert info["algo"] in ("ring", "rd")
        assert info["bytes"] > 0
        assert info["dtype"] == "float32"


def test_dcn_quantized_allreduce_api(group):
    """quant='int8' through the public API: bounded error and the op
    record says what crossed the wire."""
    outs = rt.get([w.do_quant_allreduce.remote() for w in group])
    expected = np.full(1024, 6.0)  # 1+2+3 per element
    for out, info in outs:
        rel = np.abs(out - expected).max() / 6.0
        assert rel <= 1e-2
        assert info["quant"] == "int8"
        assert info["algo"] == "ring"


def test_xla_local_allreduce():
    """XLA backend over the 8 virtual CPU devices (no cluster needed)."""
    from ray_tpu.util import collective as col

    col.init_collective_group(8, 0, backend="xla", group_name="xla_g")
    try:
        tensors = [np.full((4, 4), float(i)) for i in range(8)]
        outs = col.allreduce(tensors, "xla_g")
        expected = np.full((4, 4), float(sum(range(8))))
        for out in outs:
            assert np.allclose(np.asarray(out), expected)
    finally:
        col.destroy_collective_group("xla_g")


def test_xla_local_max():
    from ray_tpu.util import collective as col
    from ray_tpu.util.collective.types import ReduceOp

    col.init_collective_group(8, 0, backend="xla", group_name="xla_m")
    try:
        tensors = [np.full(16, float(i)) for i in range(8)]
        outs = col.allreduce(tensors, "xla_m", op=ReduceOp.MAX)
        assert np.allclose(np.asarray(outs[0]), 7.0)
    finally:
        col.destroy_collective_group("xla_m")


@rt.remote(num_cpus=0.5)
class HierWorker:
    """A process in a hierarchical (xla-local + dcn-cross) group; its
    "devices" are the virtual CPU mesh the conftest configures."""

    def init_collective(self, world_size, rank, backend, group_name):
        from ray_tpu.util import collective as col

        self.col = col
        self.rank = rank
        self.group = col.init_collective_group(
            world_size, rank, backend, group_name
        )
        return self.group.local.world_size

    def do_allreduce(self, group_name="hier"):
        import numpy as np

        n_local = self.group.local.world_size
        # Device d of process r contributes r * n_local + d (global rank).
        tensors = [
            np.full(4, float(self.rank * n_local + d)) for d in range(n_local)
        ]
        out = self.col.allreduce(tensors, group_name)
        return [np.asarray(o) for o in out]

    def do_broadcast(self, group_name="hier"):
        import numpy as np

        n_local = self.group.local.world_size
        val = 99.0 if self.rank == 0 else 0.0
        out = self.col.broadcast(
            [np.full(2, val) for _ in range(n_local)], 0, group_name
        )
        return np.asarray(out[-1])


def test_hierarchical_allreduce_and_broadcast(rt_start):
    """Two processes x N local devices: the hierarchical allreduce equals
    the flat sum over all 2N global ranks, with one DCN crossing per
    process (the multi-slice two-tier schedule)."""
    from ray_tpu.util import collective as col

    workers = [HierWorker.remote() for _ in range(2)]
    n_locals = rt.get([
        w.init_collective.remote(2, r, "hier", "hier")
        for r, w in enumerate(workers)
    ], timeout=300)
    assert n_locals[0] == n_locals[1] and n_locals[0] >= 1
    n_local = n_locals[0]
    outs = rt.get([w.do_allreduce.remote() for w in workers], timeout=300)
    total_ranks = 2 * n_local
    want = float(sum(range(total_ranks)))  # sum of all global ranks
    for per_process in outs:
        for per_device in per_process:
            np.testing.assert_allclose(per_device, np.full(4, want))

    bcast = rt.get([w.do_broadcast.remote() for w in workers], timeout=300)
    for b in bcast:
        np.testing.assert_allclose(b, np.full(2, 99.0))
