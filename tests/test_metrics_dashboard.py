"""util.metrics + dashboard tests.

Reference analogs: python/ray/tests/test_metrics_agent.py (user metrics →
Prometheus exposition) and dashboard module tests.
"""

import json
import time
import urllib.request

import pytest

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod
from ray_tpu.util.metrics import Counter, Gauge, Histogram


def _wait_for(fn, timeout=10.0, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(poll)
    raise TimeoutError("condition not met")


def _snapshot(client):
    return client._run(client.gcs.call("metrics_snapshot", {}))["metrics"]


def test_metric_validation(rt_start):
    c = Counter("val_counter", tag_keys=("a",))
    with pytest.raises(ValueError):
        c.inc(0)
    with pytest.raises(ValueError):
        c.inc(1, tags={"bogus": "x"})
    with pytest.raises(ValueError):
        Histogram("val_hist", boundaries=[2.0, 1.0])


def test_metrics_flow_to_gcs(rt_start):
    client = worker_mod.get_client()
    c = Counter("req_count", description="requests", tag_keys=("route",))
    g = Gauge("queue_depth")
    h = Histogram("latency_s", boundaries=[0.1, 1.0, 10.0])

    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/b"})
    g.set(7.0)
    for v in (0.05, 0.5, 5.0, 50.0):
        h.observe(v)

    def ready():
        names = {m["name"] for m in _snapshot(client)}
        return {"req_count", "queue_depth", "latency_s"} <= names

    _wait_for(ready)
    snap = {m["name"]: m for m in _snapshot(client)}
    counter_series = {tuple(map(tuple, k)): v for k, v in snap["req_count"]["series"]}
    assert counter_series[(("route", "/a"),)] == 1
    assert counter_series[(("route", "/b"),)] == 2
    assert snap["queue_depth"]["series"][0][1] == 7.0
    hseries = snap["latency_s"]["series"][0][1]
    assert hseries["count"] == 4
    assert hseries["buckets"] == [1, 1, 1, 1]

    # Counters accumulate across flushes.
    c.inc(5, tags={"route": "/a"})
    _wait_for(
        lambda: {
            tuple(map(tuple, k)): v
            for k, v in {m["name"]: m for m in _snapshot(client)}["req_count"][
                "series"
            ]
        }.get((("route", "/a"),)) == 6
    )


def test_metrics_in_tasks(rt_start):
    """Metrics recorded inside worker processes reach the GCS aggregate."""

    @rt.remote
    def work():
        from ray_tpu.util.metrics import Counter

        c = Counter("task_side_counter")
        c.inc(1)
        time.sleep(1.5)  # let the worker's flusher run
        return 1

    assert rt.get(work.remote(), timeout=60) == 1
    client = worker_mod.get_client()
    _wait_for(
        lambda: any(m["name"] == "task_side_counter" for m in _snapshot(client))
    )


@pytest.fixture
def dashboard(rt_start):
    """In-process dashboard against the running GCS."""
    from ray_tpu.dashboard import Dashboard

    node = worker_mod._global_node
    dash = Dashboard(node.gcs_address, port=0)
    port = node.io.run(dash.start())
    yield f"http://127.0.0.1:{port}"
    node.io.run(dash.stop())


def _get(url: str) -> str:
    with urllib.request.urlopen(url, timeout=10) as r:
        return r.read().decode()


def test_dashboard_endpoints(dashboard):
    @rt.remote
    class Pinger:
        def ping(self):
            return "pong"

    p = Pinger.remote()
    assert rt.get(p.ping.remote()) == "pong"

    assert _get(dashboard + "/healthz") == "ok"
    assert "ray_tpu dashboard" in _get(dashboard + "/")

    status = json.loads(_get(dashboard + "/api/cluster_status"))
    assert status["alive_nodes"] == 1
    assert status["resources_total"]["CPU"] == 4

    nodes = json.loads(_get(dashboard + "/api/nodes"))
    assert nodes[0]["state"] == "ALIVE"

    actors = json.loads(_get(dashboard + "/api/actors"))
    assert actors and actors[0]["class_name"] == "Pinger"

    # Profiling drill-down: live worker thread stacks through the UI API
    # (the `rt stack` backend surfaced per node).
    stacks = json.loads(_get(dashboard + "/api/stacks"))
    assert stacks and stacks[0].get("workers"), stacks
    some = stacks[0]["workers"][0]
    assert some.get("threads") and any(
        "stack" in t for t in some["threads"]
    )

    # Serve tab source: controller checkpoint -> /api/serve.
    from ray_tpu import serve as rt_serve

    @rt_serve.deployment(num_replicas=1)
    def dash_echo(x):
        return x

    rt_serve.run(dash_echo.bind(), name="dash_app")
    apps = _wait_for(
        lambda: (lambda a: a if a else None)(
            json.loads(_get(dashboard + "/api/serve"))
        )
    )
    assert any(a["app"] == "dash_app" and a["running_replicas"] == 1
               for a in apps), apps
    rt_serve.shutdown()

    Counter("dash_counter").inc(3)
    body = _wait_for(
        lambda: (lambda t: t if "dash_counter" in t else None)(
            _get(dashboard + "/metrics")
        )
    )
    assert "rt_node_resource_total" in body
    assert "dash_counter 3" in body


def test_dashboard_job_rest(dashboard):
    import sys

    payload = json.dumps(
        {"entrypoint": f"{sys.executable} -c \"print('dash job ran')\""}
    ).encode()
    req = urllib.request.Request(
        dashboard + "/api/jobs", data=payload,
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=10) as r:
        sid = json.loads(r.read())["submission_id"]

    def done():
        info = json.loads(_get(dashboard + f"/api/jobs/{sid}"))
        return info if info["state"] in ("SUCCEEDED", "FAILED", "STOPPED") else None

    info = _wait_for(done, timeout=60)
    assert info["state"] == "SUCCEEDED"
    assert "dash job ran" in _get(dashboard + f"/api/jobs/{sid}/logs")

def test_raylet_runtime_metrics_reach_prometheus(dashboard):
    """Per-component raylet runtime metrics (tasks dispatched, store usage,
    worker count) flow to the GCS aggregate AND render on the dashboard's
    Prometheus exposition endpoint (reference: stats/metric_defs.h:46-61)."""

    @rt.remote
    def touch():
        return 1

    rt.get([touch.remote() for _ in range(3)])
    client = worker_mod.get_client()

    def dispatched_counted():
        snap = {m["name"]: m for m in _snapshot(client)}
        m = snap.get("rt_raylet_tasks_dispatched_total")
        return m and sum(v for _t, v in m["series"]) >= 3

    _wait_for(dispatched_counted)
    names = {m["name"] for m in _snapshot(client)}
    assert {"rt_raylet_store_used_bytes", "rt_raylet_workers",
            "rt_raylet_tasks_queued"} <= names

    text = urllib.request.urlopen(dashboard + "/metrics", timeout=30).read().decode()
    assert "rt_raylet_tasks_dispatched_total{" in text
    assert "rt_raylet_store_used_bytes{" in text


def test_gcs_runtime_metrics_reach_prometheus(dashboard):
    """GCS-internal per-component metrics (rpc volume by method, table
    sizes) render on /metrics (reference: the GCS rows of
    stats/metric_defs.h)."""

    @rt.remote
    def touch():
        return 1

    rt.get(touch.remote())
    client = worker_mod.get_client()
    stats = client._run(client._gcs_call("gcs_stats", {}))
    assert stats["rpc_counts"].get("register_node", 0) >= 1
    assert stats["nodes_alive"] >= 1
    assert stats["rpc_counts"].get("gcs_stats", 0) >= 1  # self-counting

    text = urllib.request.urlopen(
        dashboard + "/metrics", timeout=30
    ).read().decode()
    # get_nodes is guaranteed counted: the exposition handler itself
    # calls it (heartbeat-dependent methods would race a fresh cluster).
    assert 'rt_gcs_rpc_total{method="get_nodes"}' in text
    assert 'rt_gcs_rpc_total{method="register_node"}' in text
    assert "rt_gcs_kv_entries" in text
    assert "rt_gcs_task_events" in text


def test_structured_events_and_proc_stats(tmp_path, monkeypatch):
    """RAY_EVENT analog: components append JSON-line event files; the
    dashboard merges them at /api/events. Per-process stats (cpu%%, rss
    from /proc) flow raylet -> GCS node view."""
    monkeypatch.setenv("RT_EVENT_DIR", str(tmp_path / "events"))
    from ray_tpu.util.event import read_events, record_event

    record_event("testcomp", "hello world", severity="WARNING", extra=7)
    record_event("othercomp", "second")
    evts = read_events()
    assert len(evts) == 2
    assert evts[0]["message"] == "hello world"
    assert evts[0]["severity"] == "WARNING" and evts[0]["extra"] == 7
    only = read_events(source="othercomp")
    assert len(only) == 1 and only[0]["source"] == "othercomp"

    # Live cluster: a killed worker emits a raylet event, and the GCS
    # node view carries aggregated per-process stats within a heartbeat.
    rt.init(num_cpus=2)
    try:
        @rt.remote
        def hold():
            import time as _t

            _t.sleep(30)

        ref = hold.remote()
        import time as _t

        from ray_tpu._private import worker as worker_mod

        client = worker_mod.get_client()
        deadline = _t.monotonic() + 30
        stats = {}
        while _t.monotonic() < deadline:
            nodes = client._run(client._gcs_call("get_nodes", {}))["nodes"]
            stats = nodes[0].get("proc_stats") or {}
            if stats.get("workers", 0) >= 1 and stats.get("rss_bytes", 0) > 0:
                break
            _t.sleep(0.5)
        assert stats.get("workers", 0) >= 1, stats
        assert stats.get("rss_bytes", 0) > 0, stats

        # SIGKILL the worker running `hold`: an unexpected-death event
        # must appear in the raylet's structured log.
        import os
        import signal

        infos = client._run(
            client.raylet.call("get_info", {}), timeout=10
        )["workers"]
        busy = [w for w in infos if w["current_task"] is not None]
        assert busy
        os.kill(busy[0]["pid"], signal.SIGKILL)
        deadline = _t.monotonic() + 30
        while _t.monotonic() < deadline:
            evts = [e for e in read_events(source="raylet")
                    if "died unexpectedly" in e["message"]]
            if evts:
                break
            _t.sleep(0.5)
        assert evts, "worker death produced no structured event"
    finally:
        rt.shutdown()
