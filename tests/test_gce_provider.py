"""GCETPUNodeProvider against a recorded/mock Cloud TPU API surface.

The provider's only IO is transport.request(method, url, body); this mock
models the tpu.googleapis.com v2 node lifecycle (create/delete as async
operations, list with states + labels) the way the real API answers —
the same recorded-surface pattern as test_gke_provider.py (reference:
autoscaler/_private/gcp/node_provider.py drives the identical REST
surface in production).
"""

import re

import pytest

from ray_tpu.autoscaler.node_provider import GCETPUNodeProvider


class MockTPUAPI:
    def __init__(self):
        self.nodes = {}  # node_id -> node dict
        self._op_counter = 0
        self._pending = {}  # op name -> (polls_left, error_or_None, finalize)
        self.calls = []
        self.quota_denied = False

    def request(self, method, url, body=None):
        self.calls.append((method, url, body))
        if method == "POST" and "/nodes?nodeId=" in url:
            node_id = url.rsplit("nodeId=", 1)[1]
            if self.quota_denied:
                return self._op(error={"code": 8, "message":
                                       "RESOURCE_EXHAUSTED: quota"})

            def finalize():
                self.nodes[node_id] = {
                    "name": f"projects/p/locations/z/nodes/{node_id}",
                    "state": "READY",
                    "labels": body.get("labels", {}),
                    "acceleratorType": body["acceleratorType"],
                    "networkEndpoints": [
                        {"ipAddress": f"10.0.0.{i}"} for i in range(4)
                    ],
                }
            return self._op(finalize=finalize)
        if method == "DELETE" and "/nodes/" in url:
            # The provider fires-and-forgets deletes; model the node
            # leaving the fleet once the request is accepted.
            node_id = url.rsplit("/", 1)[1]
            self.nodes.pop(node_id, None)
            return self._op()
        if method == "GET" and url.endswith("/nodes"):
            return {"nodes": list(self.nodes.values())}
        if method == "GET" and "/operations/" in url:
            name = url.split("/projects/", 1)[1]
            name = "projects/" + name
            polls, error, finalize = self._pending[name]
            polls -= 1
            if polls > 0:
                self._pending[name] = (polls, error, finalize)
                return {"name": name, "done": False}
            if error:
                return {"name": name, "done": True, "error": error}
            if finalize:
                finalize()
            return {"name": name, "done": True, "response": {}}
        raise AssertionError(f"unexpected TPU API call: {method} {url}")

    def _op(self, error=None, finalize=None):
        self._op_counter += 1
        name = f"projects/p/locations/z/operations/op-{self._op_counter}"
        self._pending[name] = (2, error, finalize)  # done after 2 polls
        return {"name": name, "done": False}


@pytest.fixture
def provider():
    api = MockTPUAPI()
    p = GCETPUNodeProvider("p", "z", transport=api, poll_interval_s=0.0)
    return p, api


def test_create_is_slice_atomic(provider):
    p, api = provider
    ids = p.create_node("tpu_v5e_16", {"accelerator_type": "v5litepod-16"}, 2)
    assert len(ids) == 2
    assert set(p.non_terminated_nodes()) == set(ids)
    # Each created node is one whole slice with its worker endpoints.
    for nid in ids:
        tags = p.node_tags(nid)
        assert tags["rt-node-type"] == "tpu_v5e_16"
        assert tags["rt-workers"] == "4"
        assert tags["rt-state"] == "READY"


def test_create_passes_config_through(provider):
    p, api = provider
    p.create_node(
        "tpu", {
            "accelerator_type": "v5litepod-16",
            "runtime_version": "tpu-vm-v4-base",
            "network": "projects/p/global/networks/default",
            "metadata": {"startup-script": "rt start --join"},
            "labels": {"team": "ml"},
        }, 1,
    )
    method, url, body = api.calls[0]
    assert body["runtimeVersion"] == "tpu-vm-v4-base"
    assert body["networkConfig"]["network"].endswith("default")
    assert body["metadata"]["startup-script"].startswith("rt start")
    assert body["labels"]["rt-managed"] == "1"
    assert body["labels"]["team"] == "ml"


def test_terminate_removes_slice(provider):
    p, api = provider
    (nid,) = p.create_node("tpu", {"accelerator_type": "v5litepod-16"}, 1)
    p.terminate_node(nid)
    assert p.non_terminated_nodes() == []


def test_restarted_provider_rediscovers_fleet(provider):
    """Node enumeration comes from the live API + labels, never from
    in-process memory (a restarted head must still see running slices)."""
    p, api = provider
    ids = p.create_node("tpu", {"accelerator_type": "v5litepod-16"}, 2)
    fresh = GCETPUNodeProvider("p", "z", transport=api, poll_interval_s=0.0)
    assert set(fresh.non_terminated_nodes()) == set(ids)
    assert fresh.node_tags(ids[0])["rt-node-type"] == "tpu"


def test_unmanaged_and_dead_nodes_excluded(provider):
    p, api = provider
    (nid,) = p.create_node("tpu", {"accelerator_type": "v5litepod-16"}, 1)
    # Someone else's TPU in the same zone: no rt-managed label.
    api.nodes["other"] = {
        "name": "projects/p/locations/z/nodes/other",
        "state": "READY", "labels": {},
    }
    # A slice the platform already tore down.
    api.nodes["dead"] = {
        "name": "projects/p/locations/z/nodes/dead",
        "state": "TERMINATED", "labels": {"rt-managed": "1"},
    }
    assert p.non_terminated_nodes() == [nid]


def test_quota_denial_raises_with_slice_attribution(provider):
    p, api = provider
    api.quota_denied = True
    with pytest.raises(RuntimeError, match="RESOURCE_EXHAUSTED"):
        p.create_node("tpu", {"accelerator_type": "v5litepod-16"}, 3)
    assert p.non_terminated_nodes() == []


def test_op_timeout_raises(provider):
    p, api = provider
    p.op_timeout_s = 0.01

    # An op that never completes.
    def never_done(method, url, body=None):
        api.calls.append((method, url, body))
        if method == "POST":
            return {"name": "projects/p/locations/z/operations/op-hang",
                    "done": False}
        return {"name": url, "done": False}

    p.transport = type("T", (), {"request": staticmethod(never_done)})()
    with pytest.raises(TimeoutError):
        p.create_node("tpu", {"accelerator_type": "v5litepod-16"}, 1)


def test_provider_registry():
    from ray_tpu.autoscaler.node_provider import (
        GCETPUNodeProvider as GCE,
        GKETPUNodeProvider as GKE,
        make_node_provider,
    )

    api = MockTPUAPI()
    p = make_node_provider(
        {"type": "gce_tpu", "project": "p", "zone": "z", "transport": api}
    )
    assert isinstance(p, GCE)
    g = make_node_provider(
        {"type": "gke", "project": "p", "zone": "z", "cluster": "c",
         "transport": api}
    )
    assert isinstance(g, GKE)
    with pytest.raises(ValueError, match="unknown provider"):
        make_node_provider({"type": "azure"})
