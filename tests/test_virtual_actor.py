"""Virtual actor tests: durable state addressed by id.

Reference model: the workflow virtual-actor semantics — get_or_create by
string id, state survives process loss, method calls are atomic state
transitions, readonly methods don't advance state.
"""

import multiprocessing
import os
import pickle

import pytest

from ray_tpu import workflow


@workflow.virtual_actor
class Counter:
    def __init__(self, start=0):
        self.value = start

    def add(self, n):
        self.value += n
        return self.value

    def fail_after_mutating(self):
        self.value += 1000
        raise RuntimeError("boom")

    @workflow.readonly
    def get(self):
        return self.value


def test_state_survives_handle_loss(tmp_path):
    storage = str(tmp_path)
    c = Counter.get_or_create("counter-1", start=10, storage=storage)
    assert c.add(5) == 15
    assert c.add(1) == 16
    # A "new process": fresh handle against the same id + storage.
    c2 = Counter.get_or_create("counter-1", storage=storage)
    assert c2.get() == 16
    assert c2.seq == 2


def test_get_or_create_ignores_init_args_when_existing(tmp_path):
    storage = str(tmp_path)
    Counter.get_or_create("c", start=7, storage=storage)
    again = Counter.get_or_create("c", start=999, storage=storage)
    assert again.get() == 7  # existing state wins, like the reference


def test_readonly_does_not_advance_state(tmp_path):
    c = Counter.get_or_create("ro", storage=str(tmp_path))
    before = c.seq
    assert c.get() == 0
    assert c.seq == before


def test_failed_call_is_rolled_back(tmp_path):
    """A method that raises after mutating in-memory state must not
    persist the mutation — the atomic-transition contract."""
    c = Counter.get_or_create("atomic", start=1, storage=str(tmp_path))
    with pytest.raises(RuntimeError, match="boom"):
        c.fail_after_mutating()
    assert c.get() == 1
    assert c.seq == 0


def test_exists(tmp_path):
    storage = str(tmp_path)
    assert not Counter.exists("nope", storage=storage)
    Counter.get_or_create("yep", storage=storage)
    assert Counter.exists("yep", storage=storage)


def _worker_add(storage, n, reps):
    c = Counter.get_or_create("shared", storage=storage)
    for _ in range(reps):
        c.add(n)


def test_cross_process_calls_serialize(tmp_path):
    """Two OS processes hammer the same actor id; the lock makes every
    transition atomic, so no increments are lost."""
    storage = str(tmp_path)
    Counter.get_or_create("shared", start=0, storage=storage)
    ps = [
        multiprocessing.Process(target=_worker_add, args=(storage, 1, 10))
        for _ in range(2)
    ]
    for p in ps:
        p.start()
    for p in ps:
        p.join(60)
    c = Counter.get_or_create("shared", storage=storage)
    assert c.get() == 20
    assert c.seq == 20


def test_unknown_method_raises(tmp_path):
    c = Counter.get_or_create("m", storage=str(tmp_path))
    with pytest.raises(AttributeError):
        c.not_a_method
