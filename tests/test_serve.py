"""Serve tests: deployments, handles, composition, scaling, HTTP.

Reference model: python/ray/serve/tests (handle path + real HTTP against
local proxies).
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def serve_session(rt_start):
    yield rt_start
    serve.shutdown()


def test_function_deployment(serve_session):
    @serve.deployment
    def echo(x):
        return {"echo": x}

    handle = serve.run(echo.bind())
    assert rt.get(handle.remote("hi"), timeout=60) == {"echo": "hi"}


def test_class_deployment_with_state(serve_session):
    @serve.deployment
    class Greeter:
        def __init__(self, greeting):
            self.greeting = greeting
            self.count = 0

        def __call__(self, name):
            self.count += 1
            return f"{self.greeting}, {name}!"

        def stats(self):
            return self.count

    handle = serve.run(Greeter.bind("Hello"))
    assert rt.get(handle.remote("TPU"), timeout=60) == "Hello, TPU!"
    assert rt.get(handle.options(method_name="stats").remote(), timeout=60) >= 1


def test_multiple_replicas_balance(serve_session):
    @serve.deployment(num_replicas=2)
    class Worker:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self):
            return self.pid

    handle = serve.run(Worker.bind(), name="workers")
    pids = {rt.get(handle.remote(), timeout=60) for _ in range(12)}
    assert len(pids) == 2  # both replicas served


def test_composition(serve_session):
    """Model composition via handles (reference: DeploymentHandle
    composition, serve/handle.py)."""

    @serve.deployment
    class Preprocess:
        def __call__(self, x):
            return x * 2

    @serve.deployment
    class Pipeline:
        def __init__(self, pre_app_name):
            from ray_tpu.serve import get_app_handle

            self.pre = get_app_handle(pre_app_name)

        def __call__(self, x):
            doubled = rt.get(self.pre.remote(x), timeout=30)
            return doubled + 1

    serve.run(Preprocess.bind(), name="pre")
    handle = serve.run(Pipeline.bind("pre"), name="pipe")
    assert rt.get(handle.remote(5), timeout=60) == 11


def test_status_and_delete(serve_session):
    @serve.deployment
    def f():
        return 1

    serve.run(f.bind(), name="app1")
    st = serve.status()
    assert "app1" in st
    assert st["app1"]["running_replicas"] == 1
    serve.delete("app1")
    st = serve.status()
    assert "app1" not in st


def test_redeploy_replaces(serve_session):
    @serve.deployment
    def v1():
        return "v1"

    @serve.deployment
    def v2():
        return "v2"

    h = serve.run(v1.bind(), name="app")
    assert rt.get(h.remote(), timeout=60) == "v1"
    h2 = serve.run(v2.bind(), name="app")
    time.sleep(0.2)
    assert rt.get(h2.remote(), timeout=60) == "v2"


def test_http_proxy(serve_session):
    @serve.deployment
    def adder(a, b):
        return a + b

    serve.run(adder.bind(), name="adder")
    addr = serve.start_http_proxy(port=18123)

    import json
    import urllib.request

    req = urllib.request.Request(
        addr + "/adder",
        data=json.dumps({"a": 2, "b": 3}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        body = json.loads(resp.read())
    assert body == {"result": 5}

    # Health endpoint
    with urllib.request.urlopen(addr + "/-/healthz", timeout=30) as resp:
        assert json.loads(resp.read())["status"] == "ok"


def test_serve_timeout_knobs_registered_and_env_overridable(monkeypatch):
    """The serve data/control-plane timeouts ride the RT_* config registry
    (reference: RAY_CONFIG env-overridable entries, ray_config_def.h)."""
    import ray_tpu._private.config as config_mod

    for name, default in (
        ("serve_rpc_timeout_s", 60.0),
        ("serve_ready_timeout_s", 30.0),
        ("serve_deploy_timeout_s", 300.0),
        ("serve_result_timeout_s", 120.0),
        ("serve_admin_timeout_s", 60.0),
        ("serve_probe_timeout_s", 5.0),
        ("serve_health_wait_s", 10.0),
        ("object_directory_rpc_timeout_s", 30.0),
    ):
        assert getattr(config_mod.Config(), name) == default
    monkeypatch.setenv("RT_SERVE_RPC_TIMEOUT_S", "7.5")
    assert config_mod.Config().serve_rpc_timeout_s == 7.5


def test_bind_composition_injects_handles(serve_session):
    """The reference composition idiom: nested .bind() applications
    deploy automatically and arrive as DeploymentHandles
    (serve.run(Pipeline.bind(Preprocess.bind())))."""

    @serve.deployment
    class Embed:
        def __call__(self, x):
            return x * 10

    @serve.deployment
    class Rank:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class Pipeline:
        def __init__(self, embed, rank):
            self.embed = embed  # DeploymentHandles, injected
            self.rank = rank

        def __call__(self, x):
            e = self.embed.remote(x).result(timeout=30)
            return self.rank.remote(e).result(timeout=30)

    handle = serve.run(
        Pipeline.bind(Embed.bind(), Rank.bind()), name="pipe2"
    )
    assert rt.get(handle.remote(4), timeout=60) == 41
    # The nested apps are live, individually addressable deployments.
    st = serve.status()
    assert "Embed" in st and "Rank" in st


def test_bind_composition_nested_containers(serve_session):
    """Bound apps inside lists/dicts resolve to handles too (the
    reference's DAG scanner traverses containers)."""

    @serve.deployment
    class M1:
        def __call__(self, x):
            return x + 1

    @serve.deployment
    class M2:
        def __call__(self, x):
            return x + 2

    @serve.deployment
    class Ensemble:
        def __init__(self, models):
            self.models = models

        def __call__(self, x):
            return sum(
                m.remote(x).result(timeout=30) for m in self.models
            )

    handle = serve.run(Ensemble.bind([M1.bind(), M2.bind()]), name="ens")
    assert rt.get(handle.remote(10), timeout=60) == 23  # 11 + 12


def test_redeploy_with_array_init_args(serve_session):
    """Redeploying an app bound with numpy args must not crash the
    user_config-comparison path (regression: ambiguous array ==)."""
    import numpy as np

    @serve.deployment
    class Weighted:
        def __init__(self, w):
            self.w = w

        def __call__(self, x):
            return float((self.w * x).sum())

    serve.run(Weighted.bind(np.ones(4)), name="warr")
    h = serve.run(Weighted.bind(np.ones(4) * 2), name="warr")
    assert rt.get(h.remote(3), timeout=60) == 24.0


def test_duplicate_bind_names_uniquified(serve_session):
    """Two bound instances of the same deployment class in one graph
    must become two deployments (the reference's DAG builder appends
    _1/_2 on name collisions) — not the second silently replacing the
    first so both handles route to one instance."""

    @serve.deployment
    class Scale:
        def __init__(self, w):
            self.w = w

        def __call__(self, x):
            return x * self.w

    @serve.deployment
    class Ensemble:
        def __init__(self, models):
            self.models = models

        def __call__(self, x):
            return [m.remote(x).result(timeout=30) for m in self.models]

    handle = serve.run(
        Ensemble.bind([Scale.bind(3), Scale.bind(5)]), name="ens_dup"
    )
    assert rt.get(handle.remote(2), timeout=60) == [6, 10]
    st = serve.status()
    assert "Scale" in st and "Scale_1" in st


def test_noop_redeploy_keeps_replicas(serve_session):
    """Redeploying with nothing changed must not restart healthy
    replicas (reference: same-version redeploys are no-ops)."""

    @serve.deployment
    class P:
        def __init__(self):
            import os

            self.pid = os.getpid()

        def __call__(self):
            return self.pid

    h = serve.run(P.bind(), name="noop")
    pid1 = rt.get(h.remote(), timeout=60)
    h2 = serve.run(P.bind(), name="noop")
    pid2 = rt.get(h2.remote(), timeout=60)
    assert pid1 == pid2


def test_buried_application_raises(serve_session):
    """An Application hidden where resolution cannot inject a handle
    (an object attribute) fails fast with a clear error instead of
    shipping a raw graph node to the replica."""

    @serve.deployment
    class Inner:
        def __call__(self, x):
            return x

    class Holder:
        def __init__(self, app):
            self.app = app

    @serve.deployment
    class Outer:
        def __init__(self, holder):
            self.holder = holder

    with pytest.raises(Exception, match="Application"):
        serve.run(Outer.bind(Holder(Inner.bind())), name="buried")


def test_shared_application_object_deploys_once(serve_session):
    """The same bound Application OBJECT used twice in a graph is one
    shared deployment (a diamond dependency), not two copies — only
    distinct .bind() calls get uniquified."""

    @serve.deployment
    class Counter:
        def __init__(self):
            self.n = 0

        def __call__(self):
            self.n += 1
            return self.n

    @serve.deployment
    class Pair:
        def __init__(self, models):
            self.models = models

        def __call__(self):
            return [m.remote().result(timeout=30) for m in self.models]

    shared = Counter.bind()
    handle = serve.run(Pair.bind([shared, shared]), name="pair_shared")
    # Both handles hit the SAME replica: counts are 1 then 2.
    assert rt.get(handle.remote(), timeout=60) == [1, 2]
    st = serve.status()
    assert "Counter" in st and "Counter_1" not in st
