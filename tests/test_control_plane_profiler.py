"""Control-plane profiler tests: sampled lifecycle spans, GCS RPC
accounting, scheduler queue instrumentation, batched trace flush.

The contract under test (ISSUE 6): sampled tasks carry a `sampled` bit
that every hop honors (client serialize/submit-buffer, raylet queue/
dispatch, worker fetch/deserialize/exec/store); the stitched per-phase
breakdown sums to ≈ the submit→complete wall; the GCS counts every RPC
per method on both sides; sampling off emits nothing and costs ~nothing.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.util import lifecycle, profiling, tracing


def _wait_for(fn, timeout=30.0, poll=0.25):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(poll)
    raise TimeoutError("condition not met")


@pytest.fixture(autouse=True)
def _profiler_off_after():
    yield
    lifecycle.set_sample_rate(0.0)
    tracing.disable()


def _state_client():
    from ray_tpu.util.state.api import StateApiClient

    return StateApiClient()


def _lifecycle_events():
    c = _state_client()
    try:
        return [e for e in c.task_events(warn=False)
                if e.get("type") == "LIFECYCLE_SPAN"]
    finally:
        c.close()


# -- stitcher / aggregator units (no runtime needed) ---------------------

def test_stitch_joins_hops_and_aggregate_shapes():
    tid = b"\x01" * 16
    events = [
        lifecycle.event(tid, "f()", b"", b"n1", "client",
                        {"serialize": [10.0, 0.001],
                         "submit_buffer": [10.001, 0.002]}, e2e_s=0.010),
        lifecycle.event(tid, "", b"", b"n1", "raylet",
                        {"queue_wait": [10.003, 0.003],
                         "dispatch": [10.006, 0.001]}),
        lifecycle.event(tid, "", b"", b"n1", "worker",
                        {"exec": [10.007, 0.002]}),
    ]
    recs = lifecycle.stitch(events)
    assert list(recs) == [tid.hex()]
    rec = recs[tid.hex()]
    assert set(rec["hops"]) == {"client", "raylet", "worker"}
    assert rec["name"] == "f()"
    assert abs(sum(rec["phases"].values()) - 0.009) < 1e-9
    assert abs(lifecycle.coverage(rec) - 0.9) < 1e-9
    agg = lifecycle.aggregate(recs)
    for phase in ("serialize", "queue_wait", "exec", "e2e", "coverage"):
        assert agg[phase]["count"] == 1
    assert agg["exec"]["p50_us"] == pytest.approx(2000.0)


def test_sample_rate_clamps_and_gates():
    lifecycle.set_sample_rate(2.0)
    assert lifecycle.get_sample_rate() == 1.0
    assert lifecycle.enabled and lifecycle.sample()
    lifecycle.set_sample_rate(0.0)
    assert not lifecycle.enabled


# -- end-to-end sampling --------------------------------------------------

def test_rate_zero_emits_no_lifecycle_events(rt_start):
    assert not lifecycle.enabled  # default off

    @rt.remote
    def f(x):
        return x + 1

    assert rt.get([f.remote(i) for i in range(20)], timeout=120) == list(
        range(1, 21)
    )
    profiling.flush()
    time.sleep(1.2)  # let worker/raylet event buffers drain
    assert _lifecycle_events() == []


def test_phases_cover_e2e_wall(rt_start):
    @rt.remote
    def work(x):
        time.sleep(0.02)
        return x

    # Warm the worker pool unsampled so sampled tasks measure a steady
    # state dispatch, not a worker cold start.
    rt.get([work.remote(i) for i in range(4)], timeout=120)

    lifecycle.set_sample_rate(1.0)
    # Serial round-trips: burst submissions complete batch-granular (an
    # early task's e2e spans its successors' exec), so the coverage
    # contract holds per round-trip, matching how bench_scale measures.
    for i in range(6):
        assert rt.get(work.remote(i), timeout=120) == i
    lifecycle.set_sample_rate(0.0)
    profiling.flush()

    def stitched():
        recs = lifecycle.stitch(_lifecycle_events())
        full = {
            k: r for k, r in recs.items()
            if r["e2e_s"] and "worker" in r["hops"] and "exec" in r["phases"]
        }
        return full or None

    recs = _wait_for(stitched)
    rec = next(iter(recs.values()))
    assert "client" in rec["hops"]
    assert rec["phases"]["exec"] >= 0.019
    cov = lifecycle.coverage(rec)
    # Leaf phases explain most of the wall and never (meaningfully)
    # exceed it — the phase marks are disjoint intervals inside e2e.
    assert 0.5 < cov < 1.25, (cov, rec)


def test_actor_calls_carry_the_sampled_bit(rt_start):
    @rt.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            time.sleep(0.01)
            return self.n

    a = Counter.remote()
    rt.get(a.bump.remote(), timeout=120)  # unsampled warmup
    lifecycle.set_sample_rate(1.0)
    assert rt.get(a.bump.remote(), timeout=120) == 2
    lifecycle.set_sample_rate(0.0)
    profiling.flush()

    def actor_span():
        for k, r in lifecycle.stitch(_lifecycle_events()).items():
            if r["name"] == "bump()" and "exec" in r["phases"]:
                return r
        return None

    rec = _wait_for(actor_span)
    assert rec["phases"]["exec"] >= 0.009
    assert "worker" in rec["hops"] and "client" in rec["hops"]


def test_sampled_bit_propagates_across_two_nodes(rt_cluster):
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = rt_cluster
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    @rt.remote
    def where():
        import os

        return os.environ["RT_NODE_ID"]

    def on(node):
        return where.options(scheduling_strategy=(
            NodeAffinitySchedulingStrategy(node_id=node.node_id.binary())
        ))

    # Warm both nodes unsampled, then pin sampled tasks to each node.
    rt.get([on(n1).remote(), on(n2).remote()], timeout=120)
    lifecycle.set_sample_rate(1.0)
    nodes = {rt.get(on(n).remote(), timeout=120) for n in (n1, n2)}
    lifecycle.set_sample_rate(0.0)
    assert nodes == {n1.node_id.hex(), n2.node_id.hex()}
    profiling.flush()

    def worker_hop_nodes():
        seen = set()
        for ev in _lifecycle_events():
            if (ev.get("extra") or {}).get("hop") == "worker":
                seen.add(bytes(ev["node_id"]))
        return seen if len(seen) >= 2 else None

    # Worker-hop spans arrive from BOTH nodes: the bit rode the spec
    # across the wire and remote workers stamped their phases.
    assert len(_wait_for(worker_hop_nodes)) >= 2


def test_profile_config_flips_sampling_at_runtime(rt_start):
    assert lifecycle.get_sample_rate() == 0.0
    c = _state_client()
    try:
        r = c.call("set_profile_config", {"task_trace_sample": 0.5})
        assert r["profile_config"]["task_trace_sample"] == 0.5
        # The GCS publishes to every subscribed client (this driver
        # included) — no reconnect, no env var.
        _wait_for(lambda: lifecycle.get_sample_rate() == 0.5, timeout=10)
        c.call("set_profile_config", {"task_trace_sample": 0.0})
        _wait_for(lambda: lifecycle.get_sample_rate() == 0.0, timeout=10)
    finally:
        c.close()


# -- GCS RPC accounting ---------------------------------------------------

def test_gcs_rpc_counters_move_on_actor_create(rt_start):
    from ray_tpu._private import worker as worker_mod

    c = _state_client()
    try:
        before = dict(c.call("gcs_stats").get("rpc_counts") or {})

        @rt.remote
        class A:
            def ping(self):
                return "pong"

        a = A.remote()
        assert rt.get(a.ping.remote(), timeout=120) == "pong"

        stats = c.call("gcs_stats")
        after = stats.get("rpc_counts") or {}
        assert after.get("register_actor", 0) > before.get(
            "register_actor", 0
        )
        # Server-side latency histogram: every counted method has a
        # consistent bucket sum.
        lat = stats.get("rpc_latency") or {}
        assert "register_actor" in lat
        st = lat["register_actor"]
        assert st["count"] >= 1
        assert sum(st["buckets"]) == st["count"]
        assert st["sum_s"] >= 0.0 and st["max_s"] >= 0.0
        assert len(st["buckets"]) == len(
            stats["rpc_latency_boundaries"]
        ) + 1
    finally:
        c.close()

    # Client-side chokepoint accounting on the driver's own GCS calls.
    client = worker_mod.get_client()
    assert client.gcs_rpc_counts.get("register_actor", 0) >= 1
    assert client.gcs_rpc_time_s.get("register_actor", 0.0) >= 0.0


def test_metrics_snapshot_exports_rpc_and_scheduler_series(rt_start):
    @rt.remote
    def f():
        return 1

    assert rt.get([f.remote() for _ in range(8)], timeout=120) == [1] * 8

    c = _state_client()
    try:
        def series():
            names = {m["name"] for m in
                     c.call("metrics_snapshot")["metrics"]}
            want = {"gcs_rpc_calls_total", "gcs_rpc_server_seconds",
                    "rt_raylet_dispatch_passes_total"}
            return want <= names and names
        names = _wait_for(series)
        snapshot = c.call("metrics_snapshot")["metrics"]
    finally:
        c.close()
    rpc = next(m for m in snapshot if m["name"] == "gcs_rpc_calls_total")
    assert rpc["type"] == "counter"
    assert any(val > 0 for _tags, val in rpc["series"])
    hist = next(m for m in snapshot if m["name"] == "gcs_rpc_server_seconds")
    assert hist["type"] == "histogram"
    _tags, payload = hist["series"][0]
    assert payload["count"] == sum(payload["buckets"])


# -- task-event pagination ------------------------------------------------

def test_list_task_events_paginates_without_truncation(rt_start):
    from ray_tpu.util.state.api import fetch_task_events

    c = _state_client()
    try:
        total0 = c.call("list_task_events",
                        {"offset": 0, "limit": 1})["total"]
        events = [
            {"task_id": i.to_bytes(4, "big"), "name": f"ev{i}",
             "job_id": b"", "node_id": b"t", "type": "NORMAL_TASK",
             "state": "FINISHED", "ts": float(i)}
            for i in range(250)
        ]
        c.call("add_task_events", {"events": events})
        r = c.call("list_task_events", {"offset": 0, "limit": 100})
        assert r["total"] >= total0 + 250
        assert len(r["events"]) == 100
        assert r["dropped"] == 0
        # Offset pages tile the ring exactly, no overlap and no holes.
        fetched = fetch_task_events(c.call, page=64, warn=False)
        assert len(fetched) >= r["total"]
        names = [e["name"] for e in fetched if str(e.get("name", ""))
                 .startswith("ev")]
        assert names == [f"ev{i}" for i in range(250)]
        # Legacy no-offset call still answers with the tail slice.
        legacy = c.call("list_task_events", {"limit": 10})
        assert len(legacy["events"]) == 10
        assert legacy["events"][-1]["name"] == "ev249"
    finally:
        c.close()


# -- batched trace flush --------------------------------------------------

def test_trace_spans_batch_into_few_rpcs(rt_start):
    """50 spans inside one flush window ride ~1 add_task_events RPC
    (the old per-span force-flush cost 50)."""
    c = _state_client()
    try:
        profiling.flush()  # drain anything pending before measuring
        time.sleep(0.1)
        before = (c.call("gcs_stats").get("rpc_counts") or {}).get(
            "add_task_events", 0
        )
        tracing.enable()
        for i in range(50):
            with tracing.span(f"s{i}"):
                pass
        tracing.disable()
        # Wait out the bounded-delay window (default 0.25s) plus slack.
        time.sleep(1.0)
        after = (c.call("gcs_stats").get("rpc_counts") or {}).get(
            "add_task_events", 0
        )
    finally:
        c.close()
    delta = after - before
    assert 1 <= delta <= 3, delta
    ev = _state_client()
    try:
        names = {e.get("name") for e in ev.task_events(warn=False)}
    finally:
        ev.close()
    assert {"s0", "s49"} <= names


# -- serve request span tree ----------------------------------------------

def test_serve_request_joins_span_tree(rt_start):
    from ray_tpu import serve

    @serve.deployment
    class Echo:
        def __call__(self, x):
            return {"echo": x}

    try:
        handle = serve.run(Echo.bind())
        assert rt.get(handle.remote("warm"), timeout=60) == {"echo": "warm"}

        tracing.enable()
        with tracing.span("serve-request"):
            ctx = tracing.current()
            assert rt.get(handle.remote("hi"), timeout=60) == {"echo": "hi"}
        tracing.disable()
        profiling.flush()

        def tree():
            spans = tracing.get_trace(ctx["trace_id"])
            by_name = {s["name"]: s for s in spans}
            serve_spans = [s for n, s in by_name.items()
                           if n.startswith("serve.Echo.")]
            if "serve-request" in by_name and serve_spans:
                return by_name, serve_spans
            return None

        by_name, serve_spans = _wait_for(tree)
        # The replica's execution span hangs off the caller's request
        # span: handle.remote() injected the active context and the
        # replica activated it.
        assert serve_spans[0]["parent_id"] == \
            by_name["serve-request"]["span_id"]
        assert serve_spans[0]["kind"] == "task"
    finally:
        serve.shutdown()
