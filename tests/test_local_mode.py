"""Local (in-process) mode tests — reference: ray.init(local_mode=True)."""

import pytest

from ray_tpu.exceptions import TaskError


def test_local_task(rt_local):
    rt = rt_local

    @rt.remote
    def mul(a, b):
        return a * b

    assert rt.get(mul.remote(6, 7)) == 42


def test_local_actor(rt_local):
    rt = rt_local

    @rt.remote
    class Acc:
        def __init__(self):
            self.n = 0

        def add(self, x):
            self.n += x
            return self.n

    a = Acc.remote()
    a.add.remote(1)
    assert rt.get(a.add.remote(2)) == 3


def test_local_error(rt_local):
    rt = rt_local

    @rt.remote
    def bad():
        raise KeyError("nope")

    with pytest.raises(TaskError):
        rt.get(bad.remote())


def test_local_put_get(rt_local):
    rt = rt_local
    ref = rt.put([1, 2, 3])
    assert rt.get(ref) == [1, 2, 3]


def test_local_dynamic_generator(rt_local):
    """num_returns='dynamic' works in local mode: iteration yields item
    refs (regression: returned a bare ObjectRef, dropping later items)."""
    rt = rt_local

    @rt.remote(num_returns="dynamic")
    def gen(n):
        for i in range(n):
            yield i * 10

    out = [rt.get(ref) for ref in gen.remote(3)]
    assert out == [0, 10, 20]

    @rt.remote(num_returns="dynamic")
    def boom():
        raise ValueError("nope")
        yield  # pragma: no cover — makes it a generator

    import pytest as _pytest

    # Real-path semantics: the error raises FROM ITERATION after any
    # produced items (here: none).
    with _pytest.raises(Exception, match="nope"):
        list(boom.remote())

    @rt.remote(num_returns="dynamic")
    def partial():
        yield 1
        raise ValueError("late")

    gen = partial.remote()
    assert rt.get(next(gen)) == 1
    with _pytest.raises(Exception, match="late"):
        next(gen)
