"""State API, timeline, CLI, and job submission tests.

Reference analogs: python/ray/tests/test_state_api*.py,
dashboard/modules/job/tests, and the state CLI (util/state/state_cli.py).
"""

import json
import subprocess
import sys
import time

import pytest

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod


def _gcs_address():
    node = worker_mod._global_node
    return node.gcs_address


def _wait_for(fn, timeout=10.0, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(poll)
    raise TimeoutError("condition not met")


def test_state_api_lists(rt_start):
    from ray_tpu.util import state as state_api

    @rt.remote
    def add(a, b):
        return a + b

    @rt.remote
    class Holder:
        def get(self):
            return 1

    rt.get([add.remote(i, i) for i in range(3)])
    h = Holder.remote()
    assert rt.get(h.get.remote()) == 1
    import numpy as np

    # Hold the ref: owner-side reference GC frees dropped objects now.
    big_ref = rt.put(np.ones(300_000))  # big enough for the shared store
    assert big_ref is not None

    nodes = state_api.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"
    assert nodes[0]["resources_total"]["CPU"] == 4

    # Task events flush on the heartbeat (0.5s period).
    tasks = _wait_for(
        lambda: [
            t
            for t in state_api.list_tasks()
            if t["name"].endswith("add") and t.get("state") == "FINISHED"
        ]
    )
    assert all(t["type"] == "NORMAL_TASK" for t in tasks)

    actor_tasks = _wait_for(
        lambda: [t for t in state_api.list_tasks() if t["name"] == "get"]
    )
    assert actor_tasks[0]["type"] == "ACTOR_TASK"

    actors = state_api.list_actors()
    assert len(actors) == 1 and actors[0]["class_name"] == "Holder"

    objs = state_api.list_objects()
    assert any(o["size"] > 1_000_000 for o in objs)

    summary = state_api.summarize_tasks()
    add_key = next(k for k in summary if k.endswith("add"))
    assert summary[add_key]["FINISHED"] == 3

    workers = state_api.list_workers()
    assert len(workers) >= 1

    trace = state_api.get_timeline()
    ev = next(ev for ev in trace if ev["name"].endswith("add"))
    assert ev["ph"] == "X" and ev["dur"] >= 0


def test_failed_task_event(rt_start):
    from ray_tpu.util.state import list_tasks

    @rt.remote(max_retries=0)
    def broken():
        raise RuntimeError("nope")

    with pytest.raises(rt.exceptions.TaskError):
        rt.get(broken.remote())
    tasks = _wait_for(
        lambda: [
            t
            for t in list_tasks()
            if t["name"].endswith("broken") and t.get("state") == "FAILED"
        ]
    )
    assert tasks


def test_job_submission_lifecycle(rt_start):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient(_gcs_address())
    try:
        sid = client.submit_job(
            entrypoint=f"{sys.executable} -c \"print('hello from job')\""
        )
        state = client.wait_until_finished(sid, timeout=60)
        assert state == "SUCCEEDED"
        assert "hello from job" in client.get_job_logs(sid)
        info = client.get_job_info(sid)
        assert info["entrypoint"].endswith('"print(\'hello from job\')"')
        assert any(j.get("submission_id") == sid for j in client.list_jobs())
    finally:
        client.close()


def test_job_stop(rt_start):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient(_gcs_address())
    try:
        sid = client.submit_job(
            entrypoint=f"{sys.executable} -c \"import time; time.sleep(60)\""
        )
        _wait_for(lambda: client.get_job_status(sid) == "RUNNING", timeout=30)
        assert client.stop_job(sid)
        state = client.wait_until_finished(sid, timeout=30)
        assert state == "STOPPED"
    finally:
        client.close()


def test_job_failure_reported(rt_start):
    from ray_tpu.job import JobSubmissionClient

    client = JobSubmissionClient(_gcs_address())
    try:
        sid = client.submit_job(
            entrypoint=f"{sys.executable} -c \"raise SystemExit(3)\""
        )
        assert client.wait_until_finished(sid, timeout=60) == "FAILED"
    finally:
        client.close()


def test_cli_status_list_timeline(rt_start, tmp_path):
    @rt.remote
    def noop():
        return 0

    rt.get(noop.remote())
    time.sleep(1.2)  # let events flush

    addr = _gcs_address()
    env = {"PYTHONPATH": ":".join(sys.path)}
    import os

    env.update(os.environ)

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "status", "--address", addr],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert "nodes alive" in out.stdout and "CPU" in out.stdout

    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "list", "nodes", "--address", addr],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    assert json.loads(out.stdout)[0]["state"] == "ALIVE"

    tl = tmp_path / "trace.json"
    out = subprocess.run(
        [sys.executable, "-m", "ray_tpu", "timeline", "-o", str(tl),
         "--address", addr],
        capture_output=True, text=True, env=env, timeout=60,
    )
    assert out.returncode == 0, out.stderr
    trace = json.loads(tl.read_text())
    assert any(ev["name"].endswith("noop") for ev in trace)

def test_user_profiling_spans_in_timeline(rt_start):
    """rt.util.profiling.profile spans appear in the chrome-trace timeline
    (reference: ray.profiling.profile, _private/profiling.py:84)."""
    import time as _time

    from ray_tpu.util import profiling
    from ray_tpu.util import state as state_api

    @rt.remote
    def work():
        from ray_tpu.util import profiling as prof

        with prof.profile("inner-phase"):
            _time.sleep(0.05)
        prof.flush()
        return 1

    with profiling.profile("driver-phase", extra={"k": "v"}):
        assert rt.get(work.remote(), timeout=60) == 1
    profiling.flush()

    deadline = _time.monotonic() + 15
    names = set()
    while _time.monotonic() < deadline:
        trace = state_api.get_timeline()
        names = {e["name"] for e in trace if e["cat"] == "user_span"}
        if {"driver-phase", "inner-phase"} <= names:
            break
        _time.sleep(0.3)
    assert {"driver-phase", "inner-phase"} <= names, names


def test_worker_stacks(rt_start):
    """`rt stack` backend: live thread stacks from every worker
    (reference: on-demand py-spy dumps via the reporter agent)."""
    import time as _time

    from ray_tpu.util.state import get_worker_stacks

    @rt.remote
    class Sleeper:
        def busy(self):
            import time

            time.sleep(5)
            return 1

    s = Sleeper.remote()
    ref = s.busy.remote()  # in flight while we sample
    _time.sleep(0.5)
    stacks = get_worker_stacks()
    workers = [w for w in stacks if "threads" in w]
    assert workers, stacks
    blob = "\n".join(
        t["stack"] for w in workers for t in w["threads"]
    )
    # The sleeping actor method's frame is visible in some worker.
    assert "busy" in blob
    assert all("pid" in w for w in workers)
    rt.get(ref, timeout=120)


def test_list_and_get_logs(rt_start):
    """Per-node log listing + tail through the state API (reference:
    `ray logs` via the per-node log agents)."""
    import os
    import tempfile

    from ray_tpu.util.state import get_log, list_logs

    logdir = os.path.join(tempfile.gettempdir(), "ray_tpu", "logs")
    os.makedirs(logdir, exist_ok=True)
    marker = os.path.join(logdir, "rt-logs-test.log")
    with open(marker, "w") as f:
        f.write("alpha\n" * 100 + "OMEGA-LINE\n")
    try:
        entries = list_logs()
        names = {e.get("name") for e in entries}
        assert "rt-logs-test.log" in names
        tail = get_log("rt-logs-test.log", tail_bytes=32)
        assert tail.endswith("OMEGA-LINE\n")
        assert len(tail) <= 32
        import pytest as _p

        with _p.raises(FileNotFoundError):
            get_log("no-such-file.log")
        with _p.raises(FileNotFoundError):
            get_log("../../../etc/passwd")  # path traversal sanitized
    finally:
        os.remove(marker)
