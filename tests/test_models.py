"""Model tests: forward/loss/grad on CPU, sharded execution on the 8-dev
virtual mesh (dp/fsdp/tp and ring-attention sp)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from ray_tpu.models import (
    TransformerConfig,
    configs,
    forward,
    init_params,
    loss_fn,
    param_logical_axes,
)
from ray_tpu.models.mlp import init_mlp, mlp_classifier_loss, mlp_forward
from ray_tpu.parallel import MeshConfig, build_mesh, shard_params

pytestmark = pytest.mark.slow  # jax-compile-heavy compute-path tier


@pytest.fixture(scope="module")
def tiny_setup():
    cfg = configs.tiny
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0, cfg.vocab_size)
    return cfg, params, tokens


def test_forward_shapes(tiny_setup):
    cfg, params, tokens = tiny_setup
    logits, aux = forward(params, tokens, cfg)
    assert logits.shape == (2, 33, cfg.vocab_size)
    assert bool(jnp.isfinite(logits).all())


def test_loss_and_grad_finite(tiny_setup):
    cfg, params, tokens = tiny_setup
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert bool(jnp.isfinite(loss))
    flat = jax.tree.leaves(grads)
    assert all(bool(jnp.isfinite(g).all()) for g in flat)
    assert float(loss) > 0


def test_gqa_forward():
    cfg = configs.tiny_gqa
    params = init_params(jax.random.PRNGKey(2), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(3), (1, 16), 0, cfg.vocab_size)
    logits, _ = forward(params, tokens, cfg)
    assert logits.shape == (1, 16, cfg.vocab_size)


def test_moe_forward_and_grad():
    cfg = configs.tiny_moe
    params = init_params(jax.random.PRNGKey(4), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(5), (2, 17), 0, cfg.vocab_size)
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert bool(jnp.isfinite(loss))
    # Router must receive gradient signal.
    assert float(jnp.abs(grads["layers"]["router"]).sum()) > 0


def test_causality(tiny_setup):
    """Changing a future token must not change past logits."""
    cfg, params, tokens = tiny_setup
    logits1, _ = forward(params, tokens, cfg)
    perturbed = tokens.at[:, -1].set((tokens[:, -1] + 1) % cfg.vocab_size)
    logits2, _ = forward(params, perturbed, cfg)
    np.testing.assert_allclose(
        np.asarray(logits1[:, :-1]), np.asarray(logits2[:, :-1]), atol=1e-5
    )


def test_sharded_dp_tp_matches_single(tiny_setup):
    cfg, params, _ = tiny_setup
    tokens = jax.random.randint(jax.random.PRNGKey(11), (8, 33), 0, cfg.vocab_size)
    expected, _ = forward(params, tokens, cfg)

    mesh = build_mesh(MeshConfig(fsdp=4, tp=2))
    axes = param_logical_axes(cfg)
    sharded = shard_params(params, axes, mesh)
    tokens_sharded = jax.device_put(
        tokens, NamedSharding(mesh, P(("dp", "fsdp"), None))
    )

    @jax.jit
    def run(p, t):
        return forward(p, t, cfg)[0]

    got = run(sharded, tokens_sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-3, atol=2e-3)


def test_ring_attention_model_matches_flash():
    from dataclasses import replace

    cfg = replace(configs.tiny, attn_impl="ring", max_seq=256)
    params = init_params(jax.random.PRNGKey(6), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(7), (2, 64), 0, cfg.vocab_size)

    expected, _ = forward(params, tokens, replace(cfg, attn_impl="flash"))

    mesh = build_mesh(MeshConfig(sp=8))
    axes = param_logical_axes(cfg)
    sharded = shard_params(params, axes, mesh)
    tokens_sharded = jax.device_put(tokens, NamedSharding(mesh, P(None, "sp")))

    @jax.jit
    def run(p, t):
        return forward(p, t, cfg, mesh=mesh)[0]

    got = run(sharded, tokens_sharded)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=3e-3, atol=3e-3)


def test_named_configs():
    assert configs.get_config("llama2-7b").d_model == 4096
    assert configs.get_config("llama2-70b").n_kv_heads == 8
    assert configs.get_config("mixtral-8x7b").num_experts == 8
    with pytest.raises(KeyError):
        configs.get_config("nope")


def test_mlp_classifier():
    params = init_mlp(jax.random.PRNGKey(8), [4, 32, 3])
    x = jax.random.normal(jax.random.PRNGKey(9), (16, 4))
    y = jax.random.randint(jax.random.PRNGKey(10), (16,), 0, 3)
    (loss, metrics), grads = jax.value_and_grad(
        mlp_classifier_loss, has_aux=True
    )(params, {"x": x, "y": y})
    assert bool(jnp.isfinite(loss))
    assert 0.0 <= float(metrics["accuracy"]) <= 1.0


def test_gemma_family_trains_and_ties_embeddings():
    """Gemma-style knobs (GeGLU, MQA, tied embeddings, embedding scaling,
    final logit softcap) train end-to-end; tying removes lm_head from the
    param tree; softcap bounds the logits."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs, forward, init_params, loss_fn

    cfg = configs.get_config("tiny_gemma")
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert "lm_head" not in params  # tied
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)
    logits, _ = forward(params, tokens, cfg)
    assert logits.shape == (2, 33, cfg.vocab_size)
    # Softcap: |logits| strictly below the cap.
    assert float(jnp.abs(logits).max()) < cfg.final_logit_softcap
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert np.isfinite(float(loss))
    # Tied embedding receives gradient from BOTH ends of the model.
    assert float(jnp.abs(grads["embed"]).sum()) > 0


def test_gemma_generation_parity():
    """KV-cache generation matches the full forward argmax for the gemma
    config (exercises tied lm_head + softcap + GeGLU in the decode path)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs, forward, init_params
    from ray_tpu.models.generate import generate

    cfg = configs.get_config("tiny_gemma")
    params = init_params(jax.random.PRNGKey(2), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 5), 0,
                                cfg.vocab_size)
    out = generate(params, prompt, cfg, max_new_tokens=6)
    # Reference: greedy next-token from the full forward, step by step.
    seq = prompt
    for _ in range(6):
        logits, _ = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out),
                                  np.asarray(seq[:, 5:]))


def test_qwen_family_qk_norm_and_wide_heads():
    """Qwen3-style knobs: per-head-dim QK-norm params exist, custom
    head_dim wider than d_model/n_heads shapes the projections, and the
    model trains end-to-end with finite grads including the norms."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs, forward, init_params, loss_fn

    cfg = configs.get_config("tiny_qwen")
    assert cfg.head_dim == 32 and cfg.d_model // cfg.n_heads == 16
    params = init_params(jax.random.PRNGKey(0), cfg)
    assert params["layers"]["q_norm"].shape == (cfg.n_layers, 32)
    assert params["layers"]["k_norm"].shape == (cfg.n_layers, 32)
    assert params["layers"]["wq"].shape == (
        cfg.n_layers, cfg.d_model, cfg.n_heads * 32
    )
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 17), 0,
                                cfg.vocab_size)
    logits, _ = forward(params, tokens, cfg)
    assert logits.shape == (2, 17, cfg.vocab_size)
    loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert np.isfinite(float(loss))
    # The norm scales receive gradient (they're on the training path).
    assert float(jnp.abs(grads["layers"]["q_norm"]).sum()) > 0
    assert float(jnp.abs(grads["layers"]["k_norm"]).sum()) > 0
    # Flipping the norm scales changes the output (really applied).
    params2 = jax.tree.map(lambda x: x, params)
    params2["layers"]["q_norm"] = params2["layers"]["q_norm"] * 2.0
    logits2, _ = forward(params2, tokens, cfg)
    assert not np.allclose(np.asarray(logits), np.asarray(logits2))


def test_qwen_generation_parity():
    """KV-cache decode matches full-forward argmax under qk_norm +
    custom head_dim (the decode path applies the same norms)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from ray_tpu.models import configs, forward, init_params
    from ray_tpu.models.generate import generate

    cfg = configs.get_config("tiny_qwen")
    params = init_params(jax.random.PRNGKey(2), cfg)
    prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 4), 0,
                                cfg.vocab_size)
    out = generate(params, prompt, cfg, max_new_tokens=5)
    seq = prompt
    for _ in range(5):
        logits, _ = forward(params, seq, cfg)
        nxt = jnp.argmax(logits[:, -1], axis=-1)[:, None]
        seq = jnp.concatenate([seq, nxt.astype(seq.dtype)], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(seq[:, 4:]))


def test_resnet_shapes_and_jit():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.conv import (
        ResNetConfig, init_resnet, resnet_forward, resnet_loss,
        resnet_param_logical_axes,
    )

    cfg = ResNetConfig(num_classes=10, stage_sizes=(1, 1, 1), width=8)
    params = init_resnet(jax.random.PRNGKey(0), cfg)
    x = jnp.zeros((2, 32, 32, 3))
    logits = jax.jit(lambda p, x: resnet_forward(p, x, cfg))(params, x)
    assert logits.shape == (2, 10)
    loss, metrics = resnet_loss(params, {"x": x, "y": jnp.array([0, 1])}, cfg)
    assert jnp.isfinite(loss)
    # The logical-axes tree must mirror the params tree exactly (the
    # contract shard_params relies on).
    axes = resnet_param_logical_axes(cfg)
    s_p = jax.tree_util.tree_structure(jax.tree.map(lambda _: 0, params))
    s_a = jax.tree_util.tree_structure(
        jax.tree.map(lambda _: 0, axes,
                     is_leaf=lambda v: isinstance(v, tuple))
    )
    assert s_p == s_a


def test_resnet_dp_tp_sharded_step():
    """ResNet under a dp x tp mesh: conv out-channels shard on tp, the
    batch on dp, via the transformer's logical-axis rules."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from ray_tpu.models.conv import (
        ResNetConfig, init_resnet, resnet_loss, resnet_param_logical_axes,
    )
    from ray_tpu.parallel import MeshConfig, build_mesh, shard_params

    devices = jax.devices()[:4]
    if len(devices) < 4:
        import pytest

        pytest.skip("needs 4 virtual devices")
    mesh = build_mesh(MeshConfig(dp=2, tp=2), devices)
    cfg = ResNetConfig(num_classes=4, stage_sizes=(1,), width=8)
    params = shard_params(
        init_resnet(jax.random.PRNGKey(0), cfg),
        resnet_param_logical_axes(cfg), mesh,
    )
    x = jax.device_put(
        jnp.zeros((4, 16, 16, 3)), NamedSharding(mesh, P("dp"))
    )
    y = jax.device_put(
        jnp.zeros((4,), dtype=jnp.int32), NamedSharding(mesh, P("dp"))
    )

    @jax.jit
    def step(p, x, y):
        (loss, _), grads = jax.value_and_grad(resnet_loss, has_aux=True)(
            p, {"x": x, "y": y}, cfg
        )
        return loss, grads

    loss, grads = step(params, x, y)
    assert bool(jnp.isfinite(jax.device_get(loss)))


def test_cnn_torso_filters():
    import jax
    import jax.numpy as jnp

    from ray_tpu.models.conv import (
        ATARI_FILTERS, cnn_torso_forward, init_cnn_torso,
    )

    p = init_cnn_torso(jax.random.PRNGKey(0), (84, 84, 4), ATARI_FILTERS,
                       out_dim=256)
    f = jax.jit(
        lambda p, x: cnn_torso_forward(p, x, ATARI_FILTERS)
    )(p, jnp.zeros((2, 84, 84, 4)))
    assert f.shape == (2, 256)
