"""Pipelined device feed: parallel multi-ref get, background prefetch,
and feed-stall observability (data/feed.py + CoreClient.get/prefetch).

The chaos-marked tests model slow cross-node transfer deterministically
(chaos.delay_object_pulls delays the raylet's wait_object_local handler)
so parallelism is visible as wall-clock without real network.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu as rt
import ray_tpu.data as rtd
from ray_tpu.data.feed import FeedStats, _DevicePrefetcher


# -- _DevicePrefetcher unit behavior (no runtime needed) -----------------

def test_producer_exception_surfaces_at_consumer():
    def src():
        yield 1
        yield 2
        raise ValueError("boom in producer")

    pf = _DevicePrefetcher(src, depth=2)
    assert next(pf) == 1
    assert next(pf) == 2
    with pytest.raises(ValueError, match="boom in producer"):
        next(pf)
    # A consumer that keeps iterating after the error must not hang.
    with pytest.raises(StopIteration):
        next(pf)
    pf._thread.join(timeout=2.0)
    assert not pf._thread.is_alive()


def test_stop_joins_thread_and_gc_cleans_up():
    def src():
        for i in range(10_000):
            yield i

    pf = _DevicePrefetcher(src, depth=2)
    assert next(pf) == 0
    thread = pf._thread
    pf.stop()
    assert not thread.is_alive()
    pf.stop()  # idempotent
    with pytest.raises(StopIteration):
        next(pf)

    # GC path: dropping the last reference mid-stream must also end the
    # producer thread (weakref.finalize wired to the same shutdown).
    pf2 = _DevicePrefetcher(src, depth=2)
    assert next(pf2) == 0
    thread2 = pf2._thread
    del pf2
    gc.collect()
    thread2.join(timeout=2.0)
    assert not thread2.is_alive()


def test_prefetch_depth_respected_under_slow_consumer():
    produced = []

    def src():
        for i in range(100):
            produced.append(i)
            yield i

    pf = _DevicePrefetcher(src, depth=3)
    try:
        deadline = time.monotonic() + 2.0
        while len(produced) < 4 and time.monotonic() < deadline:
            time.sleep(0.01)
        time.sleep(0.2)  # consumer stalled: producer must park at the bound
        # depth ready in the queue + one blocked in put() = depth + 1.
        assert 3 <= len(produced) <= 4, produced
    finally:
        pf.stop()


def test_transform_runs_producer_side_and_stats_account():
    stats = FeedStats()
    consumer_thread_items = []

    def src():
        for i in range(5):
            yield i

    pf = _DevicePrefetcher(src, depth=2, transform=lambda x: x * 10,
                           stats=stats)
    consumer_thread_items.extend(pf)
    assert consumer_thread_items == [0, 10, 20, 30, 40]
    snap = stats.snapshot()
    assert snap["batches"] == 5
    assert snap["h2d_s"] >= 0.0
    assert "feed: 5 batches" in stats.render()


def test_depth_must_be_positive():
    with pytest.raises(ValueError):
        _DevicePrefetcher(lambda: iter([]), depth=0)


# -- Dataset wiring ------------------------------------------------------

def test_pipelined_batches_byte_identical_to_serial(rt_start):
    ds = rtd.range(100).map(lambda r: {"id": r["id"], "x": float(r["id"])})
    ds = ds.repartition(5)
    serial = list(ds.iter_batches(batch_size=16, prefetch_batches=0))
    pipelined = list(ds.iter_batches(batch_size=16, prefetch_batches=3))
    assert len(serial) == len(pipelined) == 7
    for a, b in zip(serial, pipelined):
        assert set(a) == set(b)
        for k in a:
            np.testing.assert_array_equal(a[k], b[k])


def test_iter_jax_batches_pipelined_default_and_stats(rt_start):
    import jax

    ds = rtd.from_numpy({"x": np.arange(64, dtype=np.float32)})
    batches = list(ds.iter_jax_batches(batch_size=16))
    assert [len(b["x"]) for b in batches] == [16, 16, 16, 16]
    assert isinstance(batches[0]["x"], jax.Array)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["x"]) for b in batches]),
        np.arange(64, dtype=np.float32),
    )
    snap = ds._last_feed_stats.snapshot()
    assert snap["batches"] == 4
    assert "feed: 4 batches" in ds.stats()


def test_local_shuffle_seeded_deterministic(rt_start):
    ds = rtd.range(60).repartition(3)

    def run():
        return [
            int(i)
            for b in ds.iter_batches(batch_size=10,
                                     local_shuffle_buffer_size=20,
                                     local_shuffle_seed=7)
            for i in b["id"]
        ]

    a, b = run(), run()
    assert a == b  # seeded determinism across runs
    assert sorted(a) == list(range(60))  # a permutation...
    assert a != list(range(60))          # ...that actually shuffled


def test_local_shuffle_one_permutation_per_refill(rt_start, monkeypatch):
    import ray_tpu.data.dataset as dsmod

    calls = []
    real_random = dsmod._random.Random

    class CountingRandom(real_random):
        def shuffle(self, x):
            calls.append(len(x))
            super().shuffle(x)

    monkeypatch.setattr(dsmod._random, "Random", CountingRandom)
    ds = rtd.range(120).repartition(2)
    out = list(ds.iter_batches(batch_size=10,
                               local_shuffle_buffer_size=60,
                               local_shuffle_seed=0))
    assert sum(len(b["id"]) for b in out) == 120
    # One shuffle per buffer refill (2 blocks) plus one tail drain — not
    # one per batch (12 would mean the O(buffer)-per-batch cost is back).
    assert 2 <= len(calls) <= 4, calls


# -- prefetch API --------------------------------------------------------

def test_prefetch_skips_local_objects(rt_start):
    ref = rt.put(np.arange(1000))
    assert rt.prefetch([ref]) == 0
    assert rt.prefetch(ref) == 0  # single-ref form


def test_prefetch_noop_in_local_mode(rt_local):
    ref = rt.put(123)
    assert rt.prefetch([ref]) == 0
    assert rt.get(ref) == 123


# -- multi-ref get parallelism (chaos-delayed remote pulls) --------------

def _remote_refs(cluster, n, delay_tag="feed"):
    """n store-kind (>100KB, non-inline) objects living on a non-driver
    node, so a driver get must pull them over the node boundary."""
    @rt.remote(resources={delay_tag: 1})
    def big(i):
        return np.full(64_000, i, dtype=np.float32)  # ~256KB

    refs = [big.remote(i) for i in range(n)]
    ready, _ = rt.wait(refs, num_returns=n, timeout=60)  # wait never pulls
    assert len(ready) == n
    return refs


@pytest.mark.chaos
def test_multi_ref_get_resolves_in_one_probe_round(rt_cluster):
    from ray_tpu._private import chaos

    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, resources={"feed": 8})
    cluster.connect()
    chaos.enable()
    try:
        refs = _remote_refs(cluster, 4)
        per_pull = 0.4
        chaos.delay_object_pulls(per_pull, count=100)
        t0 = time.monotonic()
        vals = rt.get(refs, timeout=30)
        wall = time.monotonic() - t0
        for i, v in enumerate(vals):
            assert v[0] == np.float32(i) and len(v) == 64_000
        # Serial pulls would stack 4 x 0.4s of injected transfer delay;
        # one concurrent probe round pays it once (plus slack for the
        # actual transfers).
        assert wall < 4 * per_pull * 0.75, f"pulls did not overlap: {wall:.2f}s"
    finally:
        chaos.clear()
        chaos.disable()


@pytest.mark.chaos
def test_prefetch_overlaps_transfer_and_get_joins(rt_cluster):
    from ray_tpu._private import chaos

    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2, resources={"feed": 8})
    cluster.connect()
    chaos.enable()
    try:
        refs = _remote_refs(cluster, 3)
        chaos.delay_object_pulls(0.3, count=100)
        started = rt.prefetch(refs)
        assert started == 3
        time.sleep(1.2)  # background pulls (concurrent 0.3s delays) finish
        t0 = time.monotonic()
        vals = rt.get(refs, timeout=30)
        wall = time.monotonic() - t0
        assert [v[0] for v in vals] == [np.float32(i) for i in range(3)]
        # The transfer already happened in the background: this get is a
        # local store read, not a 0.3s-delayed pull.
        assert wall < 0.25, f"get did not join the finished prefetch: {wall:.2f}s"
        # Re-prefetching now-local refs is a no-op.
        assert rt.prefetch(refs) == 0
    finally:
        chaos.clear()
        chaos.disable()
