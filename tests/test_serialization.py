import numpy as np

from ray_tpu._private import serialization as ser


def test_roundtrip_basic():
    value = {"a": 1, "b": [1, 2, 3], "c": "hello", "d": (4, 5)}
    assert ser.deserialize_from_bytes(ser.serialize_to_bytes(value)) == value


def test_roundtrip_numpy_zero_copy():
    arr = np.random.rand(1000, 100)
    data = ser.serialize_to_bytes({"x": arr})
    out = ser.deserialize_from_bytes(data)["x"]
    assert np.array_equal(out, arr)


def test_small_arrays_inline():
    arr = np.arange(10)
    so = ser.serialize(arr)
    assert len(so.buffers) == 0  # tiny buffers ride inline


def test_large_arrays_out_of_band():
    arr = np.zeros(100_000)
    so = ser.serialize(arr)
    assert len(so.buffers) == 1


def test_closure_roundtrip():
    x = 42

    def f(y):
        return x + y

    g = ser.deserialize_from_bytes(ser.serialize_to_bytes(f))
    assert g(1) == 43
