"""LLM serving showcase: the generation stack behind a Serve deployment —
batched prefill+decode, per-model multiplexing, streaming tokens.

This is the TPU serving story end to end: serve.batch coalesces
concurrent prompts into one batched generate() call (one set of MXU
passes), multiplexing keeps several checkpoints LRU-resident per replica,
and token streaming rides the generator protocol.
"""

import threading
import time
from dataclasses import replace

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def rt_serve():
    rt.init(num_cpus=4)
    yield
    serve.shutdown()
    rt.shutdown()


@pytest.mark.slow
def test_batched_llm_generation(rt_serve):
    @serve.deployment(max_ongoing_requests=8)
    class LLM:
        def __init__(self):
            import jax

            from ray_tpu.models import configs, init_params

            self.cfg = replace(configs.tiny, dtype=np.float32)
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.75)
        def generate_batch(self, prompts):
            import jax.numpy as jnp

            from ray_tpu.models import generate

            # Same-length prompts stack into ONE batched generate call.
            batch = jnp.asarray(np.stack(prompts), dtype=jnp.int32)
            out = generate(self.params, batch, self.cfg, max_new_tokens=4)
            return [np.asarray(row).tolist() for row in out]

        def __call__(self, prompt):
            return self.generate_batch(np.asarray(prompt, dtype=np.int32))

    handle = serve.run(LLM.bind(), name="llm")
    prompts = [[1 + i, 7, 42, 3] for i in range(8)]
    results = [None] * 8

    def call(i):
        results[i] = rt.get(handle.remote(prompts[i]), timeout=120)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert all(r is not None and len(r) == 4 for r in results)

    # Same prompt => same greedy tokens regardless of batch composition.
    again = rt.get(handle.remote(prompts[0]), timeout=120)
    assert again == results[0]

    # Batching actually coalesced concurrent prompts.
    handle._refresh(force=True)
    replica = handle._shared["replicas"][0]
    stats = rt.get(replica.stats.remote(), timeout=30)
    assert max(stats["batch_sizes"]["generate_batch"]) > 1


@pytest.mark.slow
def test_streaming_token_generation(rt_serve):
    @serve.deployment
    class StreamLLM:
        def __init__(self):
            import jax

            from ray_tpu.models import configs, init_params

            self.cfg = replace(configs.tiny, dtype=np.float32)
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        def __call__(self, prompt, n=5):
            import jax.numpy as jnp

            from ray_tpu.models.generate import (
                decode_step, init_kv_cache, prefill,
            )

            tokens = jnp.asarray([prompt], dtype=jnp.int32)
            cache = init_kv_cache(self.cfg, 1, tokens.shape[1] + n)
            logits, cache = prefill(self.params, tokens, cache, self.cfg)
            for _ in range(n):
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                yield int(nxt[0])
                logits, cache = decode_step(self.params, nxt, cache, self.cfg)

    handle = serve.run(StreamLLM.bind(), name="sllm")
    toks = list(handle.options(stream=True).remote([5, 9, 2], n=5))
    assert len(toks) == 5 and all(isinstance(t, int) for t in toks)

    # The stream matches batch generation of the same prompt (greedy).
    from ray_tpu.models import configs, generate, init_params
    import jax
    import jax.numpy as jnp

    cfg = replace(configs.tiny, dtype=np.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref = generate(
        params, jnp.asarray([[5, 9, 2]], dtype=jnp.int32), cfg,
        max_new_tokens=5,
    )
    assert toks == np.asarray(ref[0]).tolist()


def _tiny_model():
    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, dtype=np.float32)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def test_continuous_batching_greedy_parity():
    """Engine decode == generate() greedy decode for concurrent
    mixed-length prompts (per-slot lengths do not perturb the math)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=3, max_len=64)
    try:
        prompts = [[1, 2, 3], [5, 6, 7, 8, 9], [4], [9, 9, 2, 1]]
        refs = [
            np.asarray(
                generate(params, jnp.asarray([p], dtype=jnp.int32), cfg,
                         max_new_tokens=5)
            )[0].tolist()
            for p in prompts
        ]
        handles = [eng.submit(p, max_new_tokens=5) for p in prompts]
        outs = [h.result(timeout=180) for h in handles]
        assert outs == refs
    finally:
        eng.shutdown()


def test_continuous_batching_joins_mid_decode():
    """A request arriving while another decodes is admitted at a step
    boundary (admitted_at_step > 0) — the capability the static batcher
    lacks — and both decode correctly."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=4, max_len=128)
    try:
        first = eng.submit([3, 7, 11, 2], max_new_tokens=40)
        # Wait until the first request is visibly mid-decode.
        deadline = time.monotonic() + 60
        while eng.stats()["steps"] < 3:
            assert time.monotonic() < deadline, "engine never stepped"
            time.sleep(0.01)
        second = eng.submit([8, 1], max_new_tokens=5)
        out2 = second.result(timeout=180)
        out1 = first.result(timeout=180)
        assert second.admitted_at_step >= 3, (
            "second request did not join a running decode loop"
        )
        ref1 = np.asarray(
            generate(params, jnp.asarray([[3, 7, 11, 2]], dtype=jnp.int32),
                     cfg, max_new_tokens=40)
        )[0].tolist()
        ref2 = np.asarray(
            generate(params, jnp.asarray([[8, 1]], dtype=jnp.int32), cfg,
                     max_new_tokens=5)
        )[0].tolist()
        assert out1 == ref1 and out2 == ref2
    finally:
        eng.shutdown()


@pytest.mark.slow
def test_continuous_batching_throughput_vs_static():
    """At mixed arrivals, the continuous engine must clear >=2x the
    tokens/s of one-request-at-a-time static decoding (BENCH north-star
    configs[4]: 'more than parity' vs serve/batching.py)."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    n_req, n_tok = 8, 16
    prompts = [[1 + i, 5, 9] for i in range(n_req)]

    # Static batch=1 baseline: requests served back to back.
    t0 = time.perf_counter()
    for p in prompts:
        np.asarray(generate(params, jnp.asarray([p], dtype=jnp.int32), cfg,
                            max_new_tokens=n_tok))
    static_s = time.perf_counter() - t0

    eng = ContinuousBatchingEngine(params, cfg, num_slots=4, max_len=64)
    try:
        eng.submit(prompts[0], max_new_tokens=n_tok).result(timeout=180)
        t0 = time.perf_counter()
        handles = []
        for i, p in enumerate(prompts):
            handles.append(eng.submit(p, max_new_tokens=n_tok))
            time.sleep(0.002 * i)  # staggered (Poisson-ish) arrivals
        for h in handles:
            h.result(timeout=300)
        cont_s = time.perf_counter() - t0
    finally:
        eng.shutdown()
    speedup = static_s / cont_s
    assert speedup >= 2.0, (
        f"continuous batching speedup {speedup:.2f}x < 2x "
        f"(static={static_s:.2f}s continuous={cont_s:.2f}s)"
    )


def test_llm_deployment_serving(rt_serve):
    """llm_deployment end to end through serve: blocking generate and
    token streaming against the continuous-batching replica."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import llm_deployment

    app = llm_deployment(_tiny_model, num_slots=4, max_len=64,
                         default_max_new_tokens=6)
    handle = serve.run(app, name="cllm")
    params, cfg = _tiny_model()
    prompt = [2, 4, 6]
    ref = np.asarray(
        generate(params, jnp.asarray([prompt], dtype=jnp.int32), cfg,
                 max_new_tokens=6)
    )[0].tolist()
    out = rt.get(handle.remote(prompt), timeout=180)
    assert out == ref
    toks = list(
        handle.options(stream=True, method_name="stream").remote(prompt)
    )
    assert toks == ref


def test_continuous_batching_mixed_sampling():
    """Per-request sampling params: a sampled (temperature/top_k)
    request shares the decode batch with a greedy one WITHOUT
    perturbing the greedy request's exact output."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=3, max_len=64)
    try:
        greedy = eng.submit([3, 7, 11, 2], max_new_tokens=6)
        sampled = eng.submit([5, 1], max_new_tokens=6,
                             temperature=0.9, top_k=20, top_p=0.95)
        g = greedy.result(timeout=180)
        s = sampled.result(timeout=180)
        ref = np.asarray(
            generate(params, jnp.asarray([[3, 7, 11, 2]], dtype=jnp.int32),
                     cfg, max_new_tokens=6)
        )[0].tolist()
        assert g == ref
        assert len(s) == 6
        assert all(0 <= t < cfg.vocab_size for t in s)
    finally:
        eng.shutdown()
    with pytest.raises(ValueError):
        eng.submit([1], top_k=10_000)  # beyond MAX_TOP_K


def test_continuous_batching_steady_state_zero_host_traffic():
    """PERF CONTRACT for the device-resident hot loop: once all slots
    are admitted and decoding (mixed greedy + sampled), a >=32-step
    window must see ZERO recompilations and ZERO host->device
    sampling-param uploads. Any per-step jnp.asarray of temps/top_k/
    top_p/active, or a shape/dtype flip that retraces a jitted step,
    reintroduces the per-step tunnel RTTs this engine was rebuilt to
    eliminate (ISSUE r6 tentpole; BENCH_INFER r5 showed a ~20x
    engine-vs-raw throughput hole from exactly this traffic)."""
    import time

    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=4, max_len=256)
    try:
        handles = [
            eng.submit([3, 7, 11, 2], max_new_tokens=160),
            eng.submit([5, 1, 8], max_new_tokens=160),
            eng.submit([2, 9], max_new_tokens=160,
                       temperature=0.7, top_k=16),
            eng.submit([4, 4, 6, 1, 3], max_new_tokens=160,
                       temperature=1.1, top_p=0.9),
        ]
        deadline = time.monotonic() + 180
        # Steady state: every request admitted, prefills drained.
        while time.monotonic() < deadline:
            s0 = eng.stats()
            if s0["active"] == 4 and s0["prefilling"] == 0:
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"never reached steady state: {eng.stats()}")
        # Let the loop take two more steps before opening the window:
        # the LAST admission's param upload lands at the next snapshot
        # after stats() can already report active==4.
        settle = s0["steps"] + 2
        while time.monotonic() < deadline:
            s0 = eng.stats()
            if s0["steps"] >= settle:
                break
            time.sleep(0.005)
        while time.monotonic() < deadline:
            s1 = eng.stats()
            if s1["steps"] - s0["steps"] >= 32:
                break
            time.sleep(0.01)
        assert s1["steps"] - s0["steps"] >= 32, (
            f"window too short: {s1['steps'] - s0['steps']} steps"
        )
        assert s1["active"] == 4, "a request finished inside the window"
        assert s1["compiles"] == s0["compiles"], (
            f"recompiled mid-decode: {s0['compiles']} -> {s1['compiles']}"
        )
        assert s1["param_uploads"] == s0["param_uploads"], (
            "sampling params re-uploaded during steady-state decode: "
            f"{s0['param_uploads']} -> {s1['param_uploads']}"
        )
        assert s1["recompiles_post_warm"] == 0
        # Warmup is the ONLY compile site: admission of real traffic
        # (greedy AND sampled, prefill, pick) hits warmed programs.
        assert s1["compiles"] == s1["warm_compiles"]
        for h in handles:
            out = h.result(timeout=180)
            assert len(out) == 160
    finally:
        eng.shutdown()


def test_continuous_batching_step_timing_breakdown():
    """stats()['timing'] decomposes engine steps into dispatch/fetch/
    host wall-time; totals are cumulative (probes delta two snapshots)
    and consistent with the averages."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=64)
    try:
        eng.submit([3, 7, 11], max_new_tokens=12).result(timeout=180)
        t = eng.stats()["timing"]
        assert t["steps_timed"] >= 12
        for part in ("dispatch", "fetch", "host"):
            total = t[f"{part}_ms_total"]
            avg = t[f"{part}_ms_avg"]
            assert total >= 0.0
            assert avg == pytest.approx(total / t["steps_timed"])
    finally:
        eng.shutdown()


def test_continuous_batching_tp_sharded():
    """The engine over a tp=8 mesh (KV heads sharded, params via
    shard_params) decodes bit-identically to the single-device engine —
    the pod-serving layout with collectives inside the compiled step."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import configs, init_params, param_logical_axes
    from ray_tpu.parallel import MeshConfig, build_mesh, shard_params
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    devices = jax.devices()[:8]
    if len(devices) < 8:
        pytest.skip("needs 8 virtual devices")
    cfg = replace(configs.tiny, d_model=64, d_ff=128, vocab_size=128,
                  n_layers=2, n_heads=8, n_kv_heads=8, max_seq=64,
                  remat=False, dtype=np.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)

    base_eng = ContinuousBatchingEngine(params, cfg, num_slots=2,
                                        max_len=48)
    try:
        base = base_eng.submit([3, 7, 5], max_new_tokens=6).result(
            timeout=180
        )
    finally:
        base_eng.shutdown()

    mesh = build_mesh(MeshConfig(tp=8), devices)
    sharded = shard_params(params, param_logical_axes(cfg), mesh)
    tp_eng = ContinuousBatchingEngine(sharded, cfg, num_slots=2,
                                      max_len=48, mesh=mesh)
    try:
        tp = tp_eng.submit([3, 7, 5], max_new_tokens=6).result(timeout=180)
    finally:
        tp_eng.shutdown()
    assert tp == base


def test_llm_deployment_tp_via_loader(rt_serve):
    """Tensor-parallel serving through serve.run: the loader builds the
    mesh and shards params inside the replica (a Mesh cannot cross the
    actor boundary) and returns (params, cfg, mesh)."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import llm_deployment

    def loader():
        import jax

        from ray_tpu.models import configs, init_params, param_logical_axes
        from ray_tpu.parallel import MeshConfig, build_mesh, shard_params

        cfg = replace(configs.tiny, d_model=64, d_ff=128, vocab_size=128,
                      n_layers=2, n_heads=8, n_kv_heads=8, max_seq=64,
                      remat=False, dtype=np.float32)
        params = init_params(jax.random.PRNGKey(0), cfg)
        mesh = build_mesh(MeshConfig(tp=8), jax.devices()[:8])
        return shard_params(params, param_logical_axes(cfg), mesh), cfg, mesh

    app = llm_deployment(loader, num_slots=2, max_len=48,
                         default_max_new_tokens=5)
    handle = serve.run(app, name="tpllm")
    out = rt.get(handle.remote([3, 7, 5]), timeout=180)

    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, d_model=64, d_ff=128, vocab_size=128,
                  n_layers=2, n_heads=8, n_kv_heads=8, max_seq=64,
                  remat=False, dtype=np.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref = np.asarray(
        generate(params, jnp.asarray([[3, 7, 5]], dtype=jnp.int32), cfg,
                 max_new_tokens=5)
    )[0].tolist()
    assert out == ref


def test_chunked_prefill_parity_and_interleaving():
    """A multi-chunk prompt decodes bit-identically to generate(), and
    a short request arriving during the long prompt's prefill is served
    WITHOUT waiting for it (chunks interleave with decode steps)."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    long_prompt = [(7 * i) % 250 + 1 for i in range(30)]  # 4 chunks @ 8
    eng = ContinuousBatchingEngine(params, cfg, num_slots=3, max_len=96,
                                   prefill_chunk=8)
    try:
        long_h = eng.submit(long_prompt, max_new_tokens=6)
        short_h = eng.submit([5, 9], max_new_tokens=4)
        short = short_h.result(timeout=180)
        long_out = long_h.result(timeout=180)
        ref_long = np.asarray(
            generate(params, jnp.asarray([long_prompt], dtype=jnp.int32),
                     cfg, max_new_tokens=6)
        )[0].tolist()
        ref_short = np.asarray(
            generate(params, jnp.asarray([[5, 9]], dtype=jnp.int32), cfg,
                     max_new_tokens=4)
        )[0].tolist()
        assert long_out == ref_long
        assert short == ref_short
        # The short request's single chunk completed while the long
        # prompt was still chunking — STRICTLY earlier admission is the
        # interleaving property (whole-prompt prefill would admit both
        # in the same iteration).
        assert short_h.admitted_at_step < long_h.admitted_at_step
    finally:
        eng.shutdown()


def test_chunked_prefill_non_multiple_max_len():
    """Regression: a final chunk whose padding runs past the cache end
    must DROP the overflow rows, not clamp the write start over earlier
    chunks (dynamic_update_slice clamping corrupted the cache when
    max_len was not a multiple of prefill_chunk)."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    prompt = [(3 * i) % 250 + 1 for i in range(35)]
    eng = ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=40,
                                   prefill_chunk=16)  # 40 % 16 != 0
    try:
        out = eng.submit(prompt, max_new_tokens=4).result(timeout=180)
    finally:
        eng.shutdown()
    ref = np.asarray(
        generate(params, jnp.asarray([prompt], dtype=jnp.int32), cfg,
                 max_new_tokens=4)
    )[0].tolist()
    assert out == ref


def test_engine_recovers_after_decode_failure():
    """A decode-step failure fails the in-flight handles with the error
    and the engine keeps serving: the donated cache buffers rebuild
    (mesh placement included) and later requests succeed."""
    import jax.numpy as jnp

    from ray_tpu.models import generate
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=48)
    try:
        boom = RuntimeError("injected decode failure")
        real = eng._decode_greedy
        calls = {"n": 0}

        def flaky(*args, **kwargs):
            calls["n"] += 1
            if calls["n"] == 1:
                raise boom
            return real(*args, **kwargs)

        eng._decode_greedy = flaky
        h = eng.submit([3, 1, 4], max_new_tokens=6)
        with pytest.raises(RuntimeError, match="injected"):
            h.result(timeout=120)
        # The engine recovered: a fresh request decodes correctly.
        out = eng.submit([3, 1, 4], max_new_tokens=6).result(timeout=180)
        ref = np.asarray(
            generate(params, jnp.asarray([[3, 1, 4]], dtype=jnp.int32),
                     cfg, max_new_tokens=6)
        )[0].tolist()
        assert out == ref
    finally:
        eng.shutdown()


@pytest.mark.slow
def test_llm_replica_killed_and_replaced(rt_serve):
    """Fault tolerance for the continuous-batching serving path: kill
    the LLM replica actor; the controller's reconcile replaces it (a
    fresh engine boots in the new actor) and later requests succeed."""
    import time as _time

    from ray_tpu.serve.controller import CONTROLLER_NAME
    from ray_tpu.serve.llm import llm_deployment

    app = llm_deployment(_tiny_model, num_slots=2, max_len=48,
                         default_max_new_tokens=4)
    handle = serve.run(app, name="killable")
    first = rt.get(handle.remote([1, 2, 3]), timeout=180)
    assert len(first) == 4

    ctrl = rt.get_actor(CONTROLLER_NAME)
    (replica,) = rt.get(
        ctrl.get_replicas.remote("killable"), timeout=60
    )["replicas"]
    rt.kill(replica)

    deadline = _time.monotonic() + 120
    out = None
    while _time.monotonic() < deadline:
        try:
            out = rt.get(handle.remote([1, 2, 3]), timeout=60)
            break
        except Exception:  # noqa: BLE001 — replica still rebooting
            _time.sleep(0.5)
    assert out == first, (
        "replacement replica never served (or served differently)"
    )
