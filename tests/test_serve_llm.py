"""LLM serving showcase: the generation stack behind a Serve deployment —
batched prefill+decode, per-model multiplexing, streaming tokens.

This is the TPU serving story end to end: serve.batch coalesces
concurrent prompts into one batched generate() call (one set of MXU
passes), multiplexing keeps several checkpoints LRU-resident per replica,
and token streaming rides the generator protocol.
"""

import threading
import time
from dataclasses import replace

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def rt_serve():
    rt.init(num_cpus=4)
    yield
    serve.shutdown()
    rt.shutdown()


@pytest.mark.slow
def test_batched_llm_generation(rt_serve):
    @serve.deployment(max_ongoing_requests=8)
    class LLM:
        def __init__(self):
            import jax

            from ray_tpu.models import configs, init_params

            self.cfg = replace(configs.tiny, dtype=np.float32)
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.75)
        def generate_batch(self, prompts):
            import jax.numpy as jnp

            from ray_tpu.models import generate

            # Same-length prompts stack into ONE batched generate call.
            batch = jnp.asarray(np.stack(prompts), dtype=jnp.int32)
            out = generate(self.params, batch, self.cfg, max_new_tokens=4)
            return [np.asarray(row).tolist() for row in out]

        def __call__(self, prompt):
            return self.generate_batch(np.asarray(prompt, dtype=np.int32))

    handle = serve.run(LLM.bind(), name="llm")
    prompts = [[1 + i, 7, 42, 3] for i in range(8)]
    results = [None] * 8

    def call(i):
        results[i] = rt.get(handle.remote(prompts[i]), timeout=120)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=180)
    assert all(r is not None and len(r) == 4 for r in results)

    # Same prompt => same greedy tokens regardless of batch composition.
    again = rt.get(handle.remote(prompts[0]), timeout=120)
    assert again == results[0]

    # Batching actually coalesced concurrent prompts.
    handle._refresh(force=True)
    replica = handle._shared["replicas"][0]
    stats = rt.get(replica.stats.remote(), timeout=30)
    assert max(stats["batch_sizes"]["generate_batch"]) > 1


@pytest.mark.slow
def test_streaming_token_generation(rt_serve):
    @serve.deployment
    class StreamLLM:
        def __init__(self):
            import jax

            from ray_tpu.models import configs, init_params

            self.cfg = replace(configs.tiny, dtype=np.float32)
            self.params = init_params(jax.random.PRNGKey(0), self.cfg)

        def __call__(self, prompt, n=5):
            import jax.numpy as jnp

            from ray_tpu.models.generate import (
                decode_step, init_kv_cache, prefill,
            )

            tokens = jnp.asarray([prompt], dtype=jnp.int32)
            cache = init_kv_cache(self.cfg, 1, tokens.shape[1] + n)
            logits, cache = prefill(self.params, tokens, cache, self.cfg)
            for _ in range(n):
                nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                yield int(nxt[0])
                logits, cache = decode_step(self.params, nxt, cache, self.cfg)

    handle = serve.run(StreamLLM.bind(), name="sllm")
    toks = list(handle.options(stream=True).remote([5, 9, 2], n=5))
    assert len(toks) == 5 and all(isinstance(t, int) for t in toks)

    # The stream matches batch generation of the same prompt (greedy).
    from ray_tpu.models import configs, generate, init_params
    import jax
    import jax.numpy as jnp

    cfg = replace(configs.tiny, dtype=np.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    ref = generate(
        params, jnp.asarray([[5, 9, 2]], dtype=jnp.int32), cfg,
        max_new_tokens=5,
    )
    assert toks == np.asarray(ref[0]).tolist()
