"""Loadgen harness tests: trace determinism / byte-identical replay,
open- vs closed-loop runner semantics (stub call_fn — no cluster),
client<->server reconciliation math, the gap gate, and schedule-
anchored chaos replay. The cluster-backed end of the same machinery is
exercised by bench_serve_macro.py.
"""

import threading
import time

import pytest

from ray_tpu.loadgen import (
    GAP_FRACTION_LIMIT,
    LengthMix,
    RateCurve,
    StampCard,
    TenantBlend,
    TraceSpec,
    apply_chaos_schedule,
    closed_loop_think_times,
    default_blend,
    open_loop_arrivals,
    reconcile,
    run_trace,
)
from ray_tpu.loadgen import trace as trace_mod


def _spec(**kw):
    kw.setdefault("seed", 42)
    kw.setdefault("duration_s", 10.0)
    kw.setdefault("curve", RateCurve(
        base_qps=20.0, ramp_to_qps=60.0, ramp_s=6.0,
        diurnal_amplitude=0.3, diurnal_period_s=20.0,
        flash=[(4.0, 1.5, 3.0)]))
    return TraceSpec(**kw)


# ---------------------------------------------------------------------------
# trace determinism / byte-identical replay
# ---------------------------------------------------------------------------


class TestTraceDeterminism:
    def test_same_seed_identical_bytes(self):
        h1, r1 = trace_mod.generate(_spec())
        h2, r2 = trace_mod.generate(_spec())
        assert trace_mod.dumps(h1, r1) == trace_mod.dumps(h2, r2)

    def test_different_seed_differs(self):
        h1, r1 = trace_mod.generate(_spec(seed=1))
        h2, r2 = trace_mod.generate(_spec(seed=2))
        assert trace_mod.dumps(h1, r1) != trace_mod.dumps(h2, r2)

    def test_replay_from_own_header_is_byte_identical(self, tmp_path):
        spec = _spec(chaos=[
            {"kind": "kill_replica", "t": 3.0, "kwargs": {"app": "A"}},
            {"kind": "drop_controller", "t": 5.0,
             "kwargs": {"restart": True}},
        ])
        header, records = trace_mod.generate(spec)
        path = str(tmp_path / "t.jsonl")
        trace_mod.write(path, header, records)
        with open(path, "rb") as f:
            on_disk = f.read()
        assert trace_mod.regenerate_bytes(path) == on_disk

    def test_header_roundtrips_through_spec(self):
        spec = _spec(kind="closed", num_requests=17, mean_think_s=0.2,
                     concurrency=4)
        assert TraceSpec.from_header(spec.header()).header() == \
            spec.header()

    def test_pareto_trace_deterministic_and_distinct(self):
        hp1, rp1 = trace_mod.generate(_spec(process="pareto"))
        hp2, rp2 = trace_mod.generate(_spec(process="pareto"))
        assert trace_mod.dumps(hp1, rp1) == trace_mod.dumps(hp2, rp2)
        _, rpois = trace_mod.generate(_spec(process="poisson"))
        assert [r["t"] for r in rp1] != [r["t"] for r in rpois]

    def test_shapes_independent_of_arrival_process(self):
        # Same seed, different arrival process: the request SHAPES
        # (tenant, lengths) must not reshuffle — the shape rng is
        # salted independently of the arrival rng.
        _, ra = trace_mod.generate(_spec(process="poisson"))
        _, rb = trace_mod.generate(_spec(process="pareto"))
        n = min(len(ra), len(rb))
        keep = ("tenant", "prompt_tokens", "max_tokens")
        assert [{k: r[k] for k in keep} for r in ra[:n]] == \
            [{k: r[k] for k in keep} for r in rb[:n]]

    def test_closed_loop_records_carry_think_times(self):
        spec = _spec(kind="closed", num_requests=25, mean_think_s=0.1)
        _, records = trace_mod.generate(spec)
        assert len(records) == 25
        assert [r["t"] for r in records] == \
            closed_loop_think_times(25, 42, 0.1)

    def test_unknown_schema_rejected(self, tmp_path):
        path = str(tmp_path / "bad.jsonl")
        with open(path, "w") as f:
            f.write('{"schema":99}\n')
        with pytest.raises(ValueError, match="schema"):
            trace_mod.read(path)


# ---------------------------------------------------------------------------
# arrival processes
# ---------------------------------------------------------------------------


class TestArrivals:
    def test_open_loop_offsets_sorted_in_range(self):
        for process in ("poisson", "pareto"):
            ts = open_loop_arrivals(RateCurve(30.0), 5.0, seed=3,
                                    process=process)
            assert ts == sorted(ts)
            assert all(0.0 <= t < 5.0 for t in ts)

    def test_poisson_tracks_rate(self):
        ts = open_loop_arrivals(RateCurve(50.0), 10.0, seed=1)
        assert 350 <= len(ts) <= 650  # ~500 expected

    def test_flash_crowd_concentrates_arrivals(self):
        curve = RateCurve(10.0, flash=[(2.0, 1.0, 5.0)])
        ts = open_loop_arrivals(curve, 4.0, seed=7)
        in_flash = sum(1 for t in ts if 2.0 <= t < 3.0)
        before = sum(1 for t in ts if 0.0 <= t < 1.0)
        assert in_flash > 2 * before

    def test_pareto_is_burstier_than_poisson(self):
        # Same mean load; the Pareto renewal process should show a
        # heavier-tailed gap distribution (larger max inter-arrival).
        pois = open_loop_arrivals(RateCurve(20.0), 20.0, seed=5)
        par = open_loop_arrivals(RateCurve(20.0), 20.0, seed=5,
                                 process="pareto")
        gap = lambda ts: max(  # noqa: E731
            b - a for a, b in zip(ts, ts[1:]))
        assert gap(par) > gap(pois)

    def test_bad_process_and_alpha_rejected(self):
        with pytest.raises(ValueError, match="arrival process"):
            open_loop_arrivals(RateCurve(1.0), 1.0, 0, process="uniform")
        with pytest.raises(ValueError, match="pareto_alpha"):
            open_loop_arrivals(RateCurve(1.0), 1.0, 0, process="pareto",
                               pareto_alpha=1.0)

    def test_think_times(self):
        assert closed_loop_think_times(4, 1, 0.0) == [0.0] * 4
        a = closed_loop_think_times(10, 1, 0.5)
        assert a == closed_loop_think_times(10, 1, 0.5)
        assert all(t > 0 for t in a)


# ---------------------------------------------------------------------------
# runner semantics (stub call_fn, no cluster)
# ---------------------------------------------------------------------------


class _ConcurrencyProbe:
    """A call_fn that services requests with a fixed sleep and records
    the peak number of in-flight calls."""

    def __init__(self, service_s: float):
        self.service_s = service_s
        self.cur = 0
        self.peak = 0
        self.lock = threading.Lock()

    def __call__(self, request, card):
        with self.lock:
            self.cur += 1
            self.peak = max(self.peak, self.cur)
        time.sleep(self.service_s)
        with self.lock:
            self.cur -= 1
        card.first_byte_p = time.perf_counter()
        card.done_p = time.perf_counter()
        card.chunks = 1
        return card


class TestRunnerSemantics:
    def test_open_loop_does_not_wait_for_completions(self):
        # 10 arrivals in a burst, each taking 0.3s: an open-loop driver
        # must overlap them (exogenous arrivals), not serialize.
        records = [{"i": i, "t": 0.01 * i, "tenant": "t"}
                   for i in range(10)]
        header = {"kind": "open", "duration_s": 0.1}
        probe = _ConcurrencyProbe(0.3)
        t0 = time.perf_counter()
        result = run_trace(header, records, probe, workers=16,
                           emit_metrics=False)
        wall = time.perf_counter() - t0
        assert probe.peak >= 5
        assert wall < 10 * 0.3  # far below the serialized time
        assert result.summary()["ok"] == 10

    def test_open_loop_respects_schedule(self):
        records = [{"i": i, "t": 0.25 * i, "tenant": "t"}
                   for i in range(4)]
        header = {"kind": "open", "duration_s": 1.0}
        sends = {}

        def call(request, card):
            sends[request["i"]] = time.perf_counter()
            card.first_byte_p = card.done_p = time.perf_counter()
            return card

        t0 = time.perf_counter()
        run_trace(header, records, call, workers=4, emit_metrics=False)
        for i in range(4):
            offset = sends[i] - t0
            assert offset == pytest.approx(0.25 * i, abs=0.2)

    def test_closed_loop_bounds_concurrency(self):
        records = [{"i": i, "t": 0.0, "tenant": "t"} for i in range(12)]
        header = {"kind": "closed", "duration_s": 0.0, "concurrency": 3}
        probe = _ConcurrencyProbe(0.05)
        result = run_trace(header, records, probe, emit_metrics=False)
        assert probe.peak <= 3
        assert result.summary()["ok"] == 12

    def test_call_fn_exception_lands_on_card(self):
        records = [{"i": i, "t": 0.0, "tenant": "t"} for i in range(3)]
        header = {"kind": "closed", "duration_s": 0.0, "concurrency": 1}

        def boom(request, card):
            raise RuntimeError("injected")

        result = run_trace(header, records, boom, emit_metrics=False)
        assert result.summary()["errors"] == 3
        assert all("RuntimeError" in c.error for c in result.cards)


# ---------------------------------------------------------------------------
# reconciliation
# ---------------------------------------------------------------------------


def _card(idx, rid, e2e_s, tenant="t", ttfb_s=0.01, error=None):
    c = StampCard(idx, tenant)
    c.rid = rid
    c.send_p = 100.0
    if error is None:
        c.first_byte_p = 100.0 + ttfb_s
        c.done_p = 100.0 + e2e_s
    else:
        c.error = error
    return c


def _server(rid, phases, ttft_s=0.01):
    return {"rid": rid, "tenant": "t", "method": "__call__",
            "ts": 0.0, "phases": dict(phases),
            "e2e_s": sum(phases.values()), "ttft_s": ttft_s,
            "tpot_s": 0.0, "tokens_in": 1, "tokens_out": 1}


class TestReconcile:
    def test_gap_is_exactly_e2e_minus_phase_sum(self):
        cards = [_card(0, "r0", 1.0)]
        server = [_server("r0", {"handle_queue": 0.125, "dispatch": 0.125,
                                 "exec": 0.5})]
        report = reconcile(cards, server)
        row = report["requests"][0]
        assert row["server_attributed_s"] == 0.75
        assert row["gap_s"] == 0.25
        assert row["gap_fraction"] == 0.25
        assert report["summary"]["matched"] == 1

    def test_negative_gap_clamped_to_zero(self):
        # Server attributes MORE than the client saw (sub-ms clock
        # disagreement): the gap must clamp at zero, not go negative.
        cards = [_card(0, "r0", 0.5)]
        server = [_server("r0", {"exec": 0.6})]
        row = reconcile(cards, server)["requests"][0]
        assert row["gap_s"] == 0.0
        assert row["gap_fraction"] == 0.0

    def test_gate_passes_on_well_attributed_run(self):
        cards, server = [], []
        for i in range(50):
            e2e = 0.2 + 0.001 * i
            cards.append(_card(i, f"r{i}", e2e))
            server.append(_server(f"r{i}", {"exec": e2e * 0.99}))
        s = reconcile(cards, server)["summary"]
        assert s["matched"] == 50
        assert s["gap_fraction"]["p99"] <= GAP_FRACTION_LIMIT
        assert s["gate_pass"] is True

    def test_gate_trips_on_injected_unattributed_stall(self):
        # 50 clean requests plus a handful whose client e2e carries a
        # 500ms stall the server never attributed — the p99 gate must
        # catch them.
        cards, server = [], []
        for i in range(50):
            cards.append(_card(i, f"r{i}", 0.2))
            server.append(_server(f"r{i}", {"exec": 0.199}))
        for i in range(50, 55):
            cards.append(_card(i, f"r{i}", 0.7))  # 0.5s stall
            server.append(_server(f"r{i}", {"exec": 0.2}))
        s = reconcile(cards, server)["summary"]
        assert s["gap_fraction"]["p99"] > GAP_FRACTION_LIMIT
        assert s["gate_pass"] is False

    def test_unmatched_and_errors_counted_not_hidden(self):
        cards = [
            _card(0, "r0", 0.2),
            _card(1, "gone", 0.2),       # replica died with its ring
            _card(2, "", 0.0, error="ServeOverloadedError: shed"),
        ]
        server = [_server("r0", {"exec": 0.199})]
        s = reconcile(cards, server)["summary"]
        assert s["matched"] == 1
        assert s["unmatched"] == 1
        assert s["errors"] == 1

    def test_no_matches_is_a_failure_not_a_vacuous_pass(self):
        s = reconcile([_card(0, "x", 0.1)], [])["summary"]
        assert s["gate_pass"] is False


# ---------------------------------------------------------------------------
# schedule-anchored chaos replay
# ---------------------------------------------------------------------------


class TestChaosSchedule:
    def test_apply_requires_known_kinds(self):
        from ray_tpu._private import chaos

        chaos.enable()
        try:
            with pytest.raises(ValueError, match="unknown chaos kind"):
                apply_chaos_schedule(
                    {"chaos": [{"kind": "meteor", "t": 1.0}]})
        finally:
            chaos.disable()

    def test_scheduled_fault_fires_at_anchor_offset(self):
        from ray_tpu._private import chaos

        chaos.enable()
        try:
            apply_chaos_schedule({"chaos": [
                {"kind": "kill_replica", "t": 0.05,
                 "kwargs": {"app": "NoSuchApp"}},
            ]})
            faults = chaos.scheduled_faults()
            assert len(faults) == 1 and not faults[0]["fired"]
            chaos.anchor_schedule()
            deadline = time.time() + 2.0
            while time.time() < deadline:
                faults = chaos.scheduled_faults()
                if faults[0]["fired"]:
                    break
                time.sleep(0.02)
            assert faults[0]["fired"]
            # No cluster here: the executor errored, and the schedule
            # recorded it instead of crashing the scheduler thread.
            assert str(faults[0]["result"]).startswith("error")
        finally:
            chaos.disable()

    def test_clear_cancels_pending_faults(self):
        from ray_tpu._private import chaos

        chaos.enable()
        try:
            apply_chaos_schedule({"chaos": [
                {"kind": "drop_controller", "t": 30.0,
                 "kwargs": {"restart": True}},
            ]})
            assert len(chaos.scheduled_faults()) == 1
            chaos.clear()
            assert chaos.scheduled_faults() == []
        finally:
            chaos.disable()


# ---------------------------------------------------------------------------
# workload shapes
# ---------------------------------------------------------------------------


class TestWorkload:
    def test_blend_draw_respects_bounds(self):
        import random

        blend = default_blend()
        rng = random.Random(0)
        for _ in range(500):
            r = blend.draw(rng)
            assert r["tenant"] in ("interactive", "batch")
            assert r["prompt_tokens"] >= 1
            assert r["max_tokens"] >= 1

    def test_length_mix_tail_bucket(self):
        import random

        mix = LengthMix(median=10, sigma=0.1, lo=1, hi=2000,
                        tail_p=1.0, tail_lo=1000, tail_hi=2000)
        rng = random.Random(0)
        assert all(1000 <= mix.draw(rng) <= 2000 for _ in range(50))

    def test_rate_curve_peak_catches_flash_edges(self):
        curve = RateCurve(10.0, flash=[(1.05, 0.02, 10.0)])
        assert curve.peak(5.0) == pytest.approx(100.0)

    def test_blend_needs_a_tenant(self):
        with pytest.raises(ValueError):
            TenantBlend([])
