"""Multi-node scheduling, placement groups, and fault-tolerance tests.

Modeled on the reference's python/ray/tests/test_scheduling*.py,
test_placement_group*.py, and the Cluster harness usage
(cluster_utils.py:108).
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.util.placement_group import placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import (
    NodeAffinitySchedulingStrategy,
    PlacementGroupSchedulingStrategy,
)


def test_two_node_scheduling(rt_cluster):
    cluster = rt_cluster
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    @rt.remote
    def where():
        import os
        import time as _t

        _t.sleep(2)  # hold the slot so later tasks must spill
        return os.environ["RT_NODE_ID"]

    # Saturate: 2-CPU tasks on 2-CPU nodes; overlap forces spillover.
    refs = [where.options(num_cpus=2).remote() for _ in range(4)]
    nodes = set(rt.get(refs, timeout=120))
    assert len(nodes) == 2  # spilled over to the second node


def test_node_affinity(rt_cluster):
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    @rt.remote
    def where():
        import os

        return os.environ["RT_NODE_ID"]

    strategy = NodeAffinitySchedulingStrategy(node_id=n2.node_id.binary())
    got = rt.get(where.options(scheduling_strategy=strategy).remote())
    assert got == n2.node_id.hex()


def test_custom_resources(rt_cluster):
    cluster = rt_cluster
    cluster.add_node(num_cpus=1)
    special = cluster.add_node(num_cpus=1, resources={"special": 2})
    cluster.connect()

    @rt.remote(resources={"special": 1})
    def on_special():
        import os

        return os.environ["RT_NODE_ID"]

    assert rt.get(on_special.remote()) == special.node_id.hex()


def test_placement_group_strict_spread(rt_cluster):
    cluster = rt_cluster
    for _ in range(3):
        cluster.add_node(num_cpus=2)
    cluster.connect()

    pg = placement_group([{"CPU": 1}] * 3, strategy="STRICT_SPREAD")
    assert pg.ready(timeout=10)
    nodes = pg.bundle_node_ids()
    assert len(set(nodes)) == 3

    @rt.remote
    def where():
        import os

        return os.environ["RT_NODE_ID"]

    refs = [
        where.options(
            scheduling_strategy=PlacementGroupSchedulingStrategy(
                placement_group=pg, placement_group_bundle_index=i
            )
        ).remote()
        for i in range(3)
    ]
    got = rt.get(refs)
    assert [bytes.fromhex(g) for g in got] == nodes
    remove_placement_group(pg)


def test_placement_group_strict_pack(rt_cluster):
    cluster = rt_cluster
    cluster.add_node(num_cpus=4)
    cluster.add_node(num_cpus=4)
    cluster.connect()

    pg = placement_group([{"CPU": 1}, {"CPU": 1}], strategy="STRICT_PACK")
    assert pg.ready(timeout=10)
    nodes = pg.bundle_node_ids()
    assert len(set(nodes)) == 1


def test_placement_group_infeasible(rt_cluster):
    cluster = rt_cluster
    cluster.add_node(num_cpus=1)
    cluster.connect()

    pg = placement_group([{"CPU": 16}], strategy="PACK")
    assert not pg.ready(timeout=1.5)


def test_tpu_gang_resources(rt_cluster):
    """TPU pod topology: head resource + per-host pod-name resource
    (reference pattern: _private/accelerators/tpu.py:335)."""
    cluster = rt_cluster
    pod = "my-tpu-pod"
    # 2-host v5e slice: worker 0 advertises the head resource.
    cluster.add_node(
        num_cpus=1,
        resources={"TPU": 8, pod: 1, "TPU-v5litepod-16-head": 1},
    )
    cluster.add_node(num_cpus=1, resources={"TPU": 8, pod: 1})
    cluster.connect()

    @rt.remote(resources={"TPU-v5litepod-16-head": 1}, num_cpus=0)
    def on_head():
        import os

        return os.environ["RT_NODE_ID"]

    @rt.remote(num_cpus=0)
    def on_pod_host():
        import os

        return os.environ["RT_NODE_ID"]

    head_node = rt.get(on_head.remote())
    # Fan out one whole-host task per pod worker via the pod-name resource.
    refs = [
        on_pod_host.options(resources={pod: 1, "TPU": 8}).remote()
        for _ in range(2)
    ]
    hosts = set(rt.get(refs))
    assert len(hosts) == 2
    assert head_node in hosts


def test_object_transfer_between_nodes(rt_cluster):
    cluster = rt_cluster
    n1 = cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    import numpy as np

    @rt.remote
    def produce():
        return np.ones(500_000)  # ~4MB -> goes to the shared store

    @rt.remote
    def consume(arr):
        return float(arr.sum())

    strategy1 = NodeAffinitySchedulingStrategy(node_id=n1.node_id.binary())
    strategy2 = NodeAffinitySchedulingStrategy(node_id=n2.node_id.binary())
    ref = produce.options(scheduling_strategy=strategy1).remote()
    out = rt.get(consume.options(scheduling_strategy=strategy2).remote(ref))
    assert out == 500_000.0


def test_actor_restart_after_kill(rt_cluster):
    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    cluster.connect()

    @rt.remote(max_restarts=1, max_task_retries=2)
    class Phoenix:
        def __init__(self):
            self.calls = 0

        def call(self):
            self.calls += 1
            return self.calls

        def die(self):
            import os

            os._exit(1)

    p = Phoenix.remote()
    assert rt.get(p.call.remote()) == 1
    # A poison call must not be retried onto the restarted actor
    # (at-least-once retries would replay the kill).
    p.die.options(max_task_retries=0).remote()
    time.sleep(1.0)
    # Restarted actor: state reset, calls start over.
    assert rt.get(p.call.remote(), timeout=30) == 1


def test_gang_tasks_spread_not_pipelined(rt_cluster):
    """Two concurrent node-saturating tasks ({pod:1, TPU:8}) must run on
    TWO hosts: the direct transport may not queue a resource-bearing task
    behind a running one on a held worker while the raylet could spill it
    to idle capacity (lease depth is CPU-only; reference keeps leases 1:1
    with running tasks, direct_task_transport.cc)."""
    pod = "tpu-pod-spread"
    for _ in range(2):
        rt_cluster.add_node(
            num_cpus=2, resources={"TPU": 8, pod: 1}
        )
    rt_cluster.connect()

    @rt.remote
    def hold_and_report():
        import time as _t

        _t.sleep(1.0)  # force overlap: the first holds its lease
        return rt.get_runtime_context().node_id

    refs = [
        hold_and_report.options(resources={pod: 1, "TPU": 8}).remote()
        for _ in range(2)
    ]
    hosts = set(rt.get(refs, timeout=120))
    assert len(hosts) == 2, f"gang tasks serialized on one host: {hosts}"


@pytest.mark.slow
def test_graceful_node_drain(rt_cluster):
    """rt drain semantics (reference: `ray drain-node`): cordon a node ->
    new work avoids it while running work finishes -> once idle it is
    removed from the cluster."""
    import time as _t

    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    @rt.remote
    def where(sleep_s=0.0):
        import os
        import time as _tt

        _tt.sleep(sleep_s)
        return os.environ["RT_NODE_ID"]

    # Place one long task on n2 by affinity, then cordon n2 mid-flight.
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    n2_id = n2.node_id.binary()
    busy = where.options(
        scheduling_strategy=NodeAffinitySchedulingStrategy(node_id=n2_id),
    ).remote(4.0)
    _t.sleep(0.5)

    from ray_tpu.util.state import drain_node

    hexid = n2_id.hex()
    # Kick off the drain in a thread: it must wait for `busy` to finish.
    import threading

    result = {}

    def run_drain():
        result["r"] = drain_node(hexid, timeout=60, poll_s=0.3)

    th = threading.Thread(target=run_drain)
    th.start()

    _t.sleep(1.0)  # cordon has propagated via heartbeat by now
    # New tasks land on the OTHER node even though n2 has free CPU.
    spots = set(rt.get([where.remote() for _ in range(6)], timeout=60))
    assert hexid not in spots, "cordoned node still received work"
    # The long task is still running on n2 (drain waits).
    assert th.is_alive()

    assert rt.get(busy, timeout=60) == hexid  # ran to completion
    th.join(timeout=60)
    assert result["r"].get("ok"), result["r"]

    # Node removed from the cluster view.
    from ray_tpu.util.state import list_nodes

    states = {n["node_id"]: n["state"] for n in list_nodes()}
    assert states.get(hexid) == "DEAD"
    # The survivors still run work.
    assert rt.get(where.remote(), timeout=60) != hexid


@pytest.mark.slow
def test_drain_guards(rt_cluster):
    """Drain edge semantics: the head node refuses to drain; hard
    node-affinity work aimed at a draining node fails fast instead of
    landing on it; --undo mid-drain aborts the removal."""
    import time as _t

    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    from ray_tpu.util.state import StateApiClient, drain_node

    head_id = cluster.head.node_id.binary().hex()
    r = drain_node(head_id, timeout=5)
    assert not r.get("ok") and "head" in r.get("error", "")

    # Cordon n2 (no removal yet), wait for its raylet to learn of it.
    c = StateApiClient()
    n2_id = n2.node_id.binary()
    assert c.call("cordon_node", {"node_id": n2_id}).get("ok")
    _t.sleep(1.2)

    @rt.remote
    def where():
        import os

        return os.environ["RT_NODE_ID"]

    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    with pytest.raises(Exception, match="draining"):
        rt.get(
            where.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=n2_id
                ),
            ).remote(),
            timeout=30,
        )

    # Lift the cordon: affinity works again (drain aborted cleanly).
    assert c.call("cordon_node", {"node_id": n2_id, "undo": True}).get("ok")
    _t.sleep(1.2)
    out = rt.get(
        where.options(
            scheduling_strategy=NodeAffinitySchedulingStrategy(
                node_id=n2_id
            ),
        ).remote(),
        timeout=30,
    )
    assert out == n2_id.hex()
    c.close()


@pytest.mark.slow
def test_drain_revokes_direct_leases(rt_cluster):
    """A driver colocated with a cordoned node must stop streaming
    direct-transport tasks to it: the lease path bypasses h_submit's
    drain spill, so the raylet refuses NEW leases while draining and
    revokes the ones already granted (owners return them and fall back
    to the submit path, which spills remote)."""
    import time as _t

    cluster = rt_cluster
    cluster.add_node(num_cpus=2)
    n2 = cluster.add_node(num_cpus=2)
    cluster.connect()

    from ray_tpu._private.ids import JobID
    from ray_tpu._private.worker import CoreClient

    # Second driver attached to n2 — the colocated-driver scenario.
    client2 = CoreClient(
        cluster.io.loop,
        ("127.0.0.1", cluster.gcs_port),
        ("127.0.0.1", n2.port),
        n2.store_name,
        n2.node_id.binary(),
        JobID.from_random(),
        mode="driver",
    )
    client2.connect()
    try:
        def node_of():
            import os

            return os.environ["RT_NODE_ID"]

        def run_one(timeout=30):
            [ref] = client2.submit_task(node_of, (), {})
            return client2.get([ref], timeout=timeout)[0]

        hexid = n2.node_id.binary().hex()
        # Warm the direct-lease path on the local (n2) raylet.
        pre = {run_one() for _ in range(8)}
        assert hexid in pre, "expected the colocated lease path on n2"

        from ray_tpu.util.state import StateApiClient

        c = StateApiClient()
        assert c.call(
            "cordon_node", {"node_id": n2.node_id.binary()}
        ).get("ok")
        _t.sleep(1.5)  # cordon propagates via the resource sync

        # The warm lease must be revoked: post-cordon tasks land on the
        # other nodes even though n2 has free CPU and held a lease.
        post = {run_one() for _ in range(8)}
        assert hexid not in post, "cordoned node still served leased tasks"
    finally:
        client2.disconnect()
