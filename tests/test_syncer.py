"""Resource delta-sync protocol tests (ray_syncer analog).

Reference model: common/ray_syncer — versioned per-node resource views
with delta updates and gap recovery, replacing full-state broadcast.
"""

import time

import pytest

import ray_tpu as rt


def _gcs_call(method, payload):
    client = rt._worker.get_client()
    return client._run(client._gcs_call(method, payload))


def test_delta_protocol_full_delta_gap(rt_start):
    """Drive the GCS-side protocol directly with a synthetic node:
    full baseline -> delta applies -> version gap demands a full view."""
    node_id = b"\x42" * 16
    _gcs_call("register_node", {
        "node_id": node_id, "address": "127.0.0.1", "port": 1,
        "object_store_name": None, "resources": {"CPU": 4.0, "TPU": 8.0},
        "labels": {}, "is_head": False,
    })
    # 1. Full view establishes the baseline.
    r = _gcs_call("resource_update", {
        "node_id": node_id, "version": 1,
        "available": {"CPU": 4.0, "TPU": 8.0},
    })
    assert r["ok"] and not r.get("need_full")
    # 2. Delta: CPU drops, TPU entry removed.
    r = _gcs_call("resource_update", {
        "node_id": node_id, "version": 2,
        "delta": {"CPU": 1.5}, "removed": ["TPU"],
    })
    assert r["ok"]
    nodes = {n["node_id"]: n for n in _gcs_call("get_nodes", {})["nodes"]}
    avail = nodes[node_id]["resources_available"]
    assert avail == {"CPU": 1.5}
    # 3. Version gap (skipped 3): the GCS must refuse and ask for a full
    # view rather than apply a delta against unknown intermediate state.
    r = _gcs_call("resource_update", {
        "node_id": node_id, "version": 4, "delta": {"CPU": 4.0},
    })
    assert r.get("need_full") and not r["ok"]
    # The stale view is untouched.
    nodes = {n["node_id"]: n for n in _gcs_call("get_nodes", {})["nodes"]}
    assert nodes[node_id]["resources_available"] == {"CPU": 1.5}
    # 4. Recovery: a full view under the next version re-bases.
    r = _gcs_call("resource_update", {
        "node_id": node_id, "version": 5, "available": {"CPU": 4.0},
    })
    assert r["ok"]
    nodes = {n["node_id"]: n for n in _gcs_call("get_nodes", {})["nodes"]}
    assert nodes[node_id]["resources_available"] == {"CPU": 4.0}


def test_unknown_node_demands_full(rt_start):
    r = _gcs_call("resource_update", {
        "node_id": b"\x99" * 16, "version": 7, "delta": {"CPU": 1.0},
    })
    assert r.get("need_full")


def test_live_raylet_syncs_deltas_end_to_end(rt_start):
    """The real heartbeat path: occupancy changes propagate to the GCS
    view through the delta protocol while a task holds resources."""
    from ray_tpu.util.state import list_nodes

    @rt.remote
    def hold():
        import time as _t

        _t.sleep(2.0)
        return 1

    ref = hold.options(num_cpus=3).remote()
    deadline = time.monotonic() + 10
    saw_drop = False
    while time.monotonic() < deadline:
        [node] = [n for n in list_nodes() if n["state"] == "ALIVE"]
        if node["resources_available"].get("CPU") == 1.0:
            saw_drop = True
            break
        time.sleep(0.2)
    assert saw_drop, "GCS never observed the CPU drop via delta sync"
    assert rt.get(ref, timeout=120) == 1
    deadline = time.monotonic() + 10
    while time.monotonic() < deadline:
        [node] = [n for n in list_nodes() if n["state"] == "ALIVE"]
        if node["resources_available"].get("CPU") == 4.0:
            return
        time.sleep(0.2)
    raise AssertionError("GCS never observed the CPU release")
