"""TPU accelerator manager tests (no hardware required).

Modeled on the reference's python/ray/tests/accelerators/test_tpu.py:
detection, pod topology, gang resources, and visible-chips isolation are
all driven by patched env.
"""

import pytest

from ray_tpu._private.accelerators import TPUAcceleratorManager, get_accelerator_manager
from ray_tpu._private.node import resolve_resources


@pytest.fixture
def tpu_host_env(monkeypatch):
    monkeypatch.setenv("RT_TPU_CHIPS", "8")
    monkeypatch.setenv("TPU_ACCELERATOR_TYPE", "v5litepod-16")
    monkeypatch.setenv("TPU_NAME", "slice-abc")
    monkeypatch.setenv("TPU_WORKER_ID", "0")
    monkeypatch.setenv("TPU_WORKER_HOSTNAMES", "h0,h1")
    yield


def test_registry():
    assert get_accelerator_manager("TPU") is TPUAcceleratorManager


def test_detection(tpu_host_env):
    assert TPUAcceleratorManager.get_current_node_num_accelerators() == 8
    assert TPUAcceleratorManager.get_current_node_accelerator_type() == "TPU-V5LITEPOD"
    assert TPUAcceleratorManager.get_current_node_tpu_pod_type() == "v5litepod-16"


def test_pod_topology(tpu_host_env):
    assert TPUAcceleratorManager.get_current_node_tpu_name() == "slice-abc"
    assert TPUAcceleratorManager.get_current_node_tpu_worker_id() == 0
    assert TPUAcceleratorManager.get_num_workers_in_current_tpu_pod() == 2


def test_gang_resources_worker0(tpu_host_env):
    extra = TPUAcceleratorManager.get_current_node_additional_resources()
    assert extra == {"slice-abc": 1.0, "TPU-v5litepod-16-head": 1.0}


def test_gang_resources_worker1(tpu_host_env, monkeypatch):
    monkeypatch.setenv("TPU_WORKER_ID", "1")
    extra = TPUAcceleratorManager.get_current_node_additional_resources()
    assert extra == {"slice-abc": 1.0}  # no head resource off worker 0


def test_resolve_resources_includes_tpu(tpu_host_env):
    res = resolve_resources(num_cpus=4)
    assert res["CPU"] == 4.0
    assert res["TPU"] == 8.0
    assert res["TPU-V5LITEPOD"] == 1.0
    assert res["slice-abc"] == 1.0
    assert res["TPU-v5litepod-16-head"] == 1.0


def test_chip_quantity_validation():
    ok, _ = TPUAcceleratorManager.validate_resource_request_quantity(4)
    assert ok
    bad, msg = TPUAcceleratorManager.validate_resource_request_quantity(3)
    assert not bad and "chips" in msg


def test_visible_chips_isolation(tpu_host_env, monkeypatch):
    monkeypatch.delenv("TPU_VISIBLE_CHIPS", raising=False)
    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(["0", "1"])
    assert TPUAcceleratorManager.get_current_process_visible_accelerator_ids() == [
        "0",
        "1",
    ]


def test_all_chips_passthrough(tpu_host_env, monkeypatch):
    """Whole-host lease: taking all chips unsets TPU_VISIBLE_CHIPS so libtpu
    owns the host (reference tpu.py:158 'not set when task takes all 4')."""
    monkeypatch.setenv("TPU_VISIBLE_CHIPS", "0")
    TPUAcceleratorManager.set_current_process_visible_accelerator_ids(
        [str(i) for i in range(8)]
    )
    assert TPUAcceleratorManager.get_current_process_visible_accelerator_ids() is None


def test_pod_helpers(tpu_host_env):
    from ray_tpu._private.accelerators.tpu import (
        get_current_pod_name,
        get_current_pod_worker_count,
    )

    assert get_current_pod_name() == "slice-abc"
    assert get_current_pod_worker_count() == 2
