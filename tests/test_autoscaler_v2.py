"""Autoscaler v2 (instance manager) tests.

Reference model: autoscaler/v2 unit tests — the instance lifecycle state
machine, the demand scheduler, and reconciliation against a mock cloud
provider, all without a live cluster.
"""

import pytest

from ray_tpu.autoscaler import v2
from ray_tpu.autoscaler.node_provider import NodeProvider
from ray_tpu.autoscaler.v2 import InstanceManager, Reconciler, Scheduler


class MockProvider(NodeProvider):
    """In-memory cloud: created nodes appear in non_terminated_nodes
    after `delay_ticks` calls (0 = immediately)."""

    def __init__(self, fail_types=()):
        self._nodes = {}
        self._counter = 0
        self.fail_types = set(fail_types)
        self.terminated = []

    def create_node(self, node_type, node_config, count):
        if node_type in self.fail_types:
            raise RuntimeError("cloud quota exceeded")
        out = []
        for _ in range(count):
            self._counter += 1
            cid = f"i-{self._counter:04d}"
            self._nodes[cid] = node_type
            out.append(cid)
        return out

    def terminate_node(self, provider_node_id):
        self._nodes.pop(provider_node_id, None)
        self.terminated.append(provider_node_id)

    def non_terminated_nodes(self):
        return list(self._nodes)

    def node_tags(self, provider_node_id):
        return {"rt-node-type": self._nodes.get(provider_node_id, "")}


def test_instance_lifecycle_legal_transitions():
    im = InstanceManager()
    inst = im.create("cpu")
    assert inst.status == v2.QUEUED
    im.set_status(inst.instance_id, v2.REQUESTED)
    im.set_status(inst.instance_id, v2.ALLOCATED)
    im.set_status(inst.instance_id, v2.RAY_RUNNING)
    with pytest.raises(ValueError):  # RAY_RUNNING -> ALLOCATED is illegal
        im.set_status(inst.instance_id, v2.ALLOCATED)
    im.set_status(inst.instance_id, v2.TERMINATING)
    im.set_status(inst.instance_id, v2.TERMINATED)
    with pytest.raises(ValueError):  # terminal state
        im.set_status(inst.instance_id, v2.QUEUED)
    assert [s for s, _ in inst.status_history] == [
        "QUEUED", "REQUESTED", "ALLOCATED", "RAY_RUNNING",
        "TERMINATING", "TERMINATED",
    ]


def test_scheduler_binpacks_and_respects_limits():
    sched = Scheduler({
        "cpu": {"resources": {"CPU": 4}, "max_workers": 2},
        "v5e": {"resources": {"TPU": 4}, "slice_hosts": 4, "max_workers": 1},
    })
    # 6 CPU bundles of 2 -> 12 CPU -> 3 cpu nodes, capped at 2.
    launches = sched.desired_launches(
        [{"CPU": 2.0}] * 6, free_per_node=[], active_counts={}
    )
    assert launches["cpu"] == 2
    # TPU demand launches one slice UNIT (4 hosts handled by the caller).
    launches = sched.desired_launches(
        [{"TPU": 4.0}], free_per_node=[], active_counts={}
    )
    assert launches == {"v5e": 1}
    # Existing free capacity absorbs demand: nothing to launch.
    launches = sched.desired_launches(
        [{"CPU": 2.0}], free_per_node=[{"CPU": 4.0}], active_counts={"cpu": 1}
    )
    assert launches == {}


def test_scheduler_min_workers_floor():
    sched = Scheduler({"cpu": {"resources": {"CPU": 4}, "min_workers": 2,
                               "max_workers": 5}})
    launches = sched.desired_launches([], [], {})
    assert launches == {"cpu": 2}
    launches = sched.desired_launches([], [], {"cpu": 2})
    assert launches == {}


def _mk_reconciler(provider, node_types, ray_state, demands,
                   idle_timeout_s=60.0):
    im = InstanceManager()
    rec = Reconciler(
        im, provider, node_types,
        ray_state_fn=lambda: ray_state,
        demands_fn=lambda: demands,
        idle_timeout_s=idle_timeout_s,
    )
    return im, rec


def test_reconciler_full_lifecycle():
    provider = MockProvider()
    ray_state = {}
    demands = [{"CPU": 2.0}]
    im, rec = _mk_reconciler(
        provider, {"cpu": {"resources": {"CPU": 4}, "max_workers": 4}},
        ray_state, demands, idle_timeout_s=0.0,
    )
    rec.step()  # demand -> QUEUED -> REQUESTED (cloud create issued)
    [inst] = im.instances((v2.REQUESTED,))
    assert inst.cloud_id in provider.non_terminated_nodes()

    rec.step()  # cloud lists it -> ALLOCATED
    assert im.get(inst.instance_id).status == v2.ALLOCATED

    # Raylet registers, busy: RAY_RUNNING and stays.
    ray_state[inst.cloud_id] = {"alive": True, "idle_s": 0.0,
                                "free": {"CPU": 2.0}}
    demands.clear()
    rec.step()
    assert im.get(inst.instance_id).status == v2.RAY_RUNNING

    # Node goes idle past the (zero) timeout -> terminated, slice-atomic
    # path for a single host is the host itself.
    ray_state[inst.cloud_id] = {"alive": True, "idle_s": 10.0,
                                "free": {"CPU": 4.0}}
    rec.step()
    assert im.get(inst.instance_id).status == v2.TERMINATING
    rec.step()  # provider no longer lists it
    assert im.get(inst.instance_id).status == v2.TERMINATED
    assert provider.terminated == [inst.cloud_id]


def test_reconciler_slice_atomic_scale_down():
    provider = MockProvider()
    ray_state = {}
    demands = [{"TPU": 4.0}]
    im, rec = _mk_reconciler(
        provider,
        {"v5e": {"resources": {"TPU": 4}, "slice_hosts": 2, "max_workers": 2}},
        ray_state, demands, idle_timeout_s=0.0,
    )
    rec.step()
    insts = im.instances((v2.REQUESTED,))
    assert len(insts) == 2  # one slice unit = 2 hosts
    assert len({i.slice_group for i in insts}) == 1
    demands.clear()
    # Both register; only ONE is idle -> slice must survive.
    ray_state[insts[0].cloud_id] = {"alive": True, "idle_s": 10.0, "free": {}}
    ray_state[insts[1].cloud_id] = {"alive": True, "idle_s": 0.0, "free": {}}
    rec.step()
    rec.step()
    assert all(
        im.get(i.instance_id).status == v2.RAY_RUNNING for i in insts
    )
    # Both idle -> the whole slice goes together.
    ray_state[insts[1].cloud_id]["idle_s"] = 10.0
    rec.step()
    assert sorted(provider.terminated) == sorted(
        i.cloud_id for i in insts
    )


def test_reconciler_retries_failed_allocation():
    provider = MockProvider(fail_types={"cpu"})
    im, rec = _mk_reconciler(
        provider, {"cpu": {"resources": {"CPU": 4}, "min_workers": 1,
                           "max_workers": 2}},
        {}, [],
    )
    rec.step()  # create_node raises -> instance stays QUEUED
    assert len(im.instances((v2.QUEUED,))) == 1
    rec.step()  # retried every tick; still failing, still exactly one
    assert len(im.instances((v2.QUEUED,))) == 1
    provider.fail_types.clear()
    rec.step()  # cloud recovered
    assert len(im.instances((v2.REQUESTED,))) == 1
    assert rec.report()["cpu"][v2.REQUESTED] == 1


def test_v2_reconciler_against_live_cluster():
    """End-to-end v2: infeasible task demand reaches the GCS, the
    reconciler launches a fake node through the full instance lifecycle
    (QUEUED->...->RAY_RUNNING), the task completes, and idle timeout
    walks the instance to TERMINATED."""
    import time

    import ray_tpu as rt
    from ray_tpu.autoscaler import FakeMultiNodeProvider
    from ray_tpu.autoscaler.v2 import GcsRayState, gcs_demands
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.connect()
    try:
        provider = FakeMultiNodeProvider(
            cluster.io, "127.0.0.1", cluster.gcs_port
        )
        client = rt._worker.get_client()

        def gcs_call(method, payload):
            return client._run(client._gcs_call(method, payload))

        im = InstanceManager()
        rec = Reconciler(
            im, provider,
            {"worker": {"resources": {"CPU": 2}, "max_workers": 2}},
            ray_state_fn=GcsRayState(provider, gcs_call),
            demands_fn=gcs_demands(gcs_call),
            idle_timeout_s=1.5,
        )

        @rt.remote(num_cpus=2)
        def heavy():
            time.sleep(0.3)
            return 7

        ref = heavy.remote()  # infeasible on the 1-CPU head
        time.sleep(1.2)       # demand rides the heartbeat to the GCS

        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            rec.step()
            done, _ = rt.wait([ref], timeout=0.3)
            if done:
                break
            time.sleep(0.2)
        assert rt.get(ref, timeout=60) == 7
        assert any(
            i.status == v2.RAY_RUNNING for i in im.instances()
        ), rec.report()

        # With the task done, the node idles past the timeout -> gone.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            rec.step()
            insts = im.instances()
            if insts and all(i.status == v2.TERMINATED for i in insts):
                break
            time.sleep(0.3)
        assert all(i.status == v2.TERMINATED for i in im.instances()), (
            rec.report()
        )
    finally:
        cluster.shutdown()


@pytest.mark.slow
def test_process_provider_monitor_e2e():
    """VERDICT r3 item 9: a fake provider launching REAL raylet
    subprocesses, driven by the background Monitor loop (no manual
    stepping): infeasible demand -> scale-up -> process node joins ->
    task schedules -> idle scale-down terminates the process (reference:
    autoscaler/_private/fake_multi_node/)."""
    import time

    import ray_tpu as rt
    from ray_tpu.autoscaler import Monitor, ProcessNodeProvider
    from ray_tpu.autoscaler.v2 import GcsRayState, gcs_demands
    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=1)
    cluster.connect()
    provider = None
    monitor = None
    try:
        provider = ProcessNodeProvider("127.0.0.1", cluster.gcs_port)
        client = rt._worker.get_client()

        def gcs_call(method, payload):
            return client._run(client._gcs_call(method, payload))

        im = InstanceManager()
        rec = Reconciler(
            im, provider,
            {"worker": {"resources": {"CPU": 2}, "max_workers": 2}},
            ray_state_fn=GcsRayState(provider, gcs_call),
            demands_fn=gcs_demands(gcs_call),
            idle_timeout_s=2.0,
        )
        monitor = Monitor(rec, interval_s=0.5).start()

        @rt.remote(num_cpus=2)
        def heavy():
            time.sleep(0.3)
            return 11

        ref = heavy.remote()  # infeasible on the 1-CPU head
        # The monitor must scale up on its own and the task must land on
        # the subprocess node.
        assert rt.get(ref, timeout=90) == 11
        assert any(i.status == v2.RAY_RUNNING for i in im.instances()), (
            rec.report()
        )
        live_pids = provider.non_terminated_nodes()
        assert live_pids, "expected a live subprocess node"

        # Idle past the timeout: the monitor terminates the process node.
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            insts = im.instances()
            if insts and all(i.status == v2.TERMINATED for i in insts) and (
                not provider.non_terminated_nodes()
            ):
                break
            time.sleep(0.4)
        assert all(i.status == v2.TERMINATED for i in im.instances()), (
            rec.report()
        )
        assert not provider.non_terminated_nodes()
    finally:
        if monitor is not None:
            monitor.stop()
        if provider is not None:
            provider.shutdown()
        cluster.shutdown()
