"""Cluster black box: HLC correctness, journal ring accounting, bundle
assembly, and failure-triggered postmortem capture end to end.

The unit half exercises util/journal.py in-process: hybrid logical
clocks stay monotone when the host clock steps backwards, cross-process
send happens-before receive in stamp order despite skew, the ring drops
(and counts) overflow instead of growing, and a dumped bundle merges
into one causally-ordered timeline with a nameable culprit chain. The
e2e half runs the real runtime: chaos.kill_replica under in-flight
serve traffic must produce an automatic postmortem bundle whose merged
events reconstruct the injection -> replacement chain across processes,
and chaos.postmortem() must force a bundle on demand. A subprocess test
pins the profiling atexit drain (buffered LIFECYCLE_SPANs flush on
interpreter exit even with the batch timer still armed).
"""

import json
import os
import subprocess
import sys
import time

import pytest

from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.util import journal
from ray_tpu.util.journal import HLC


@pytest.fixture
def cfg_override():
    """Mutate the config singleton for this (test) process; restore on
    exit. Worker processes are unaffected — driver/GCS-side knobs only."""
    cfg = get_config()
    saved = {}

    def override(**kw):
        for k, v in kw.items():
            if k not in saved:
                saved[k] = getattr(cfg, k)
            setattr(cfg, k, v)

    yield override
    for k, v in saved.items():
        setattr(cfg, k, v)


@pytest.fixture
def serve_session(rt_start):
    from ray_tpu import serve

    yield rt_start
    serve.shutdown()


# -- hybrid logical clock -------------------------------------------------

def test_hlc_monotone_under_clock_regression(monkeypatch):
    """An NTP step / VM migration walks the wall clock BACKWARDS; stamps
    must still be strictly increasing (lc bumps instead of pt reversing)."""
    walls = [1000.0, 999.0, 998.5, 1005.0]

    def fake_time():
        return walls.pop(0) if walls else 1005.0

    monkeypatch.setattr(journal.time, "time", fake_time)
    clk = HLC()
    stamps = [clk.tick() for _ in range(4)]
    assert stamps == sorted(stamps)
    assert len(set(stamps)) == 4  # strictly increasing, no duplicates
    # The regression ticks reuse the frozen pt and count up the lc.
    assert stamps[1][0] == stamps[0][0] and stamps[1][1] == stamps[0][1] + 1
    assert stamps[2][1] == stamps[1][1] + 1
    # Once the wall catches up, pt advances and lc resets.
    assert stamps[3][0] > stamps[0][0] and stamps[3][1] == 0


def test_hlc_skewed_cross_process_ordering(monkeypatch):
    """Sender's wall clock is 1000s AHEAD of the receiver's. update()
    must still order send < receive < every later receiver stamp."""
    now = {"wall": 2000.0}
    monkeypatch.setattr(journal.time, "time", lambda: now["wall"])
    sender = HLC()
    sent = sender.tick()

    now["wall"] = 1000.0  # receiver is far behind
    receiver = HLC()
    received = receiver.update(sent)
    assert received > sent  # send happens-before receive in stamp order
    later = receiver.tick()
    assert later > received  # local progress stays after the merge
    # pt was adopted from the sender; the receiver's lagging wall clock
    # never issues a stamp that sorts before the message it saw.
    assert later[0] == sent[0]


def test_wire_stamp_observe_roundtrip(cfg_override):
    """wire_stamp/observe_wire thread the module clock through frames:
    after observing a remote stamp from the near future, the next local
    event sorts after it. Malformed/absent stamps are ignored."""
    cfg_override(journal_enabled=True)
    s = journal.wire_stamp()
    assert s is not None and len(s) == 2
    remote = [s[0] + 1_500_000, 7]  # 1.5s ahead of us
    journal.observe_wire(remote)
    journal.emit("test.after_observe")
    last = journal.snapshot()[-1]
    assert last["kind"] == "test.after_observe"
    assert tuple(last["hlc"]) > (remote[0], remote[1])
    # Garbage on the wire must never raise or move the clock backwards.
    journal.observe_wire(None)
    journal.observe_wire({"not": "a stamp"})
    journal.observe_wire([-5])
    assert journal.wire_stamp() > last["hlc"]


def test_wire_stamp_disabled_returns_none(cfg_override):
    cfg_override(journal_enabled=False)
    assert journal.wire_stamp() is None
    before = journal.counts()
    journal.emit("test.disabled")  # swallowed, not buffered
    assert journal.counts() == before


# -- ring accounting ------------------------------------------------------

def test_ring_overflow_drops_and_counts(cfg_override):
    cfg_override(journal_ring=16)
    ev0, drop0 = journal.counts()
    for i in range(40):
        journal.emit("test.fill", i=i)
    ev1, drop1 = journal.counts()
    assert ev1 - ev0 == 40
    assert drop1 - drop0 >= 24  # everything past the ring was dropped
    tail = [e for e in journal.snapshot() if e["kind"] == "test.fill"]
    assert len(tail) <= 16
    assert tail[-1]["i"] == 39  # ring keeps the NEWEST events


def test_emit_never_raises_on_weird_fields(cfg_override):
    cfg_override(journal_ring=64)
    journal.emit("test.weird", obj=object(), blob=b"\xff", none=None)
    e = journal.snapshot()[-1]
    assert e["kind"] == "test.weird"
    # dump() must serialize it anyway (default=str).
    assert json.dumps(e, default=str)


# -- bundle assembly ------------------------------------------------------

def test_dump_and_load_bundle_merges_across_processes(tmp_path, cfg_override):
    """Two per-process files (one real dump, one hand-written 'remote'
    file) merge into a single HLC-ordered timeline with per-file metas."""
    cfg_override(journal_ring=256, journal_window_s=60.0)
    journal.emit("test.local_a")
    a = journal.snapshot()[-1]["hlc"]
    # A remote process stamps an event just after ours, sends it to us;
    # observing the stamp forces our NEXT event after it (HLC contract).
    mid = [a[0], a[1] + 1]
    journal.observe_wire(mid)
    journal.emit("test.local_b")
    b = journal.snapshot()[-1]["hlc"]
    assert tuple(b) > tuple(mid)
    bundle = str(tmp_path / "pm-test")
    path = journal.dump(bundle, trigger={"trigger_id": "t1", "reason": "unit"})
    assert path and os.path.exists(path)

    remote = [
        {"hlc": mid, "ts": time.time(), "kind": "test.remote_mid",
         "proc": "replica:Echo", "pid": 99999},
        {"hlc": [b[0], b[1] + 1], "ts": time.time(), "kind": "test.remote_late",
         "proc": "replica:Echo", "pid": 99999},
    ]
    with open(os.path.join(bundle, "replica_Echo-99999.jsonl"), "w") as f:
        f.write(json.dumps({"kind": "journal.meta", "proc": "replica:Echo",
                            "pid": 99999, "ts": time.time(),
                            "hlc": remote[-1]["hlc"], "events": 2,
                            "trigger": {}}) + "\n")
        for e in remote:
            f.write(json.dumps(e) + "\n")

    events, metas = journal.load_bundle(bundle)
    assert len(metas) == 2
    assert {m["proc"] for m in metas} == {journal.process_label(), "replica:Echo"}
    kinds = [e["kind"] for e in events]
    ia, imid = kinds.index("test.local_a"), kinds.index("test.remote_mid")
    ib, ilate = kinds.index("test.local_b"), kinds.index("test.remote_late")
    assert ia < imid < ib < ilate  # interleaved by (pt, lc), not by file
    assert events == journal.merge_events(events)
    text = journal.render_timeline(events, limit=10)
    assert "test.remote_mid" in text and "replica:Echo" in text


def test_causal_chain_names_culprits_and_stops_at_client_error():
    mk = lambda pt, kind, **kw: dict({"hlc": [pt, 0], "ts": pt / 1e6,
                                      "kind": kind, "proc": "p", "pid": 1}, **kw)
    events = [
        mk(1, "serve.request", rid="r0"),  # pre-fault noise: not a link
        mk(2, "gcs.actor", state="ALIVE", actor_id="aa"),  # churn: skipped
        mk(3, "chaos.kill_replica", app="Echo", index=0),
        mk(4, "gcs.actor", state="DEAD", actor_id="aa"),
        mk(5, "serve.controller", action="replace_dead", app="Echo"),
        mk(6, "serve.redispatch", rid="r1"),
        mk(7, "serve.redispatch", rid="r2"),  # duplicate link: collapsed
        mk(8, "client.error", rid="r1", error="TaskError"),
        mk(9, "serve.shed", rid="r3"),  # after the client effect: excluded
    ]
    chain = journal.causal_chain(events)
    assert [e["kind"] for e in chain] == [
        "chaos.kill_replica", "gcs.actor", "serve.controller",
        "serve.redispatch", "client.error",
    ]
    assert journal.causal_chain([mk(1, "serve.request")]) == []  # no seed


# -- failure-triggered capture, end to end --------------------------------

def _get_postmortems(rt):
    from ray_tpu._private import worker as worker_mod

    client = worker_mod.get_client()
    resp = client._run(client._gcs_call("get_postmortems", {}))
    return resp.get("postmortems", [])


def _wait_bundle_settled(bundle, timeout_s=10.0, settle_s=1.0):
    """Per-process dumps arrive asynchronously; wait until the file
    count has been stable for settle_s (or the timeout lapses)."""
    deadline = time.monotonic() + timeout_s
    last_n, last_change = -1, time.monotonic()
    while time.monotonic() < deadline:
        try:
            n = len([f for f in os.listdir(bundle) if f.endswith(".jsonl")])
        except OSError:
            n = 0
        if n != last_n:
            last_n, last_change = n, time.monotonic()
        elif n > 0 and time.monotonic() - last_change >= settle_s:
            break
        time.sleep(0.2)
    return last_n


def test_chaos_postmortem_forced_capture(rt_start, tmp_path, monkeypatch,
                                         cfg_override):
    """chaos.postmortem() forces a bundle through the GCS even inside
    the cooldown window; the driver's ring lands in it with the trigger
    recorded in the meta header."""
    monkeypatch.setenv("RT_CHAOS", "1")
    cfg_override(journal_dir=str(tmp_path))
    journal.emit("test.before_forced_dump", probe=1)
    bundle = chaos.postmortem("unit-forced")
    assert bundle.startswith(str(tmp_path))
    assert _wait_bundle_settled(bundle) >= 1
    events, metas = journal.load_bundle(bundle)
    assert any(m["trigger"].get("reason") == "unit-forced" for m in metas)
    assert any(e["kind"] == "test.before_forced_dump" for e in events)
    pms = _get_postmortems(rt_start)
    assert any(p["bundle"] == bundle and p["source"] == "chaos" for p in pms)


def test_kill_replica_autocaptures_causal_postmortem(serve_session, tmp_path,
                                                     monkeypatch, cfg_override):
    """The acceptance path in miniature: kill one of two replicas under
    in-flight traffic. WITHOUT any manual step, the controller's
    replace_dead observer must trigger a cluster dump, and the merged
    bundle must reconstruct injection -> replacement causally, with
    events from more than one process."""
    from ray_tpu import serve

    monkeypatch.setenv("RT_CHAOS", "1")
    # cooldown=0 (a GCS-side knob; the GCS runs in this process): the
    # controller's replica_dead trigger mints its own bundle even though
    # the handle's breaker-open observer fires first.
    cfg_override(journal_dir=str(tmp_path), journal_cooldown_s=0.0)
    t0 = time.time()

    @serve.deployment(num_replicas=2)
    class Echo:
        def __call__(self, x):
            time.sleep(0.4)
            return x * 2

    h = serve.run(Echo.bind())
    rs = [h.remote(i) for i in range(6)]
    time.sleep(0.15)  # let dispatches land on both replicas
    chaos.kill_replica("Echo", 0)
    assert sorted(r.result(timeout=90) for r in rs) == [0, 2, 4, 6, 8, 10]

    bundle = None
    deadline = time.monotonic() + 30.0
    while time.monotonic() < deadline and bundle is None:
        for p in _get_postmortems(serve_session):
            if p["ts"] >= t0 and p["reason"].startswith("replica_dead"):
                bundle = p["bundle"]
                break
        time.sleep(0.5)
    assert bundle, "replica death produced no automatic postmortem"
    assert _wait_bundle_settled(bundle) >= 1

    events, metas = journal.load_bundle(bundle)
    procs = {(m["proc"], m["pid"]) for m in metas}
    assert len(procs) >= 2, f"bundle only covers {procs}"
    kinds = {e["kind"] for e in events}
    assert "chaos.kill_replica" in kinds  # the driver's injection record
    assert any(e["kind"] == "serve.controller" and
               e.get("action") == "replace_dead" for e in events)
    chain = journal.causal_chain(events)
    assert chain and chain[0]["kind"].startswith("chaos.")
    assert len(chain) >= 2  # injection plus at least one downstream link
    # The injection sorts before the replacement it caused — across
    # processes, on HLC order alone.
    i_kill = next(i for i, e in enumerate(events)
                  if e["kind"] == "chaos.kill_replica")
    i_replace = next(i for i, e in enumerate(events)
                     if e["kind"] == "serve.controller"
                     and e.get("action") == "replace_dead")
    assert i_kill < i_replace


# -- profiling atexit drain (regression) ----------------------------------

_ATEXIT_SCRIPT = """
import os
os.environ["JAX_PLATFORMS"] = "cpu"
from ray_tpu._private import worker as worker_mod

class FakeClient:
    def _gcs_call(self, method, payload):
        return (method, payload)
    def _run(self, rpc, timeout=None):
        method, payload = rpc
        print("ATEXIT_FLUSH %s %d" % (method, len(payload["events"])), flush=True)

worker_mod.get_client = lambda: FakeClient()
from ray_tpu.util import profiling
# Long delay: the batch timer must NOT be what saves these events.
profiling.buffer_events([{"event_type": "span", "name": "late"},
                         {"event_type": "span", "name": "later"}],
                        flush_delay_s=3600.0)
print("BUFFERED", flush=True)
"""


def test_profiling_buffer_drains_at_exit():
    """Spans buffered moments before interpreter exit still reach the
    GCS: the atexit hook force-flushes past the armed batch timer."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    out = subprocess.run(
        [sys.executable, "-c", _ATEXIT_SCRIPT],
        capture_output=True, text=True, timeout=120, env=env,
    )
    assert out.returncode == 0, out.stderr
    lines = out.stdout.splitlines()
    assert "BUFFERED" in lines
    assert "ATEXIT_FLUSH add_task_events 2" in lines
    # ...and strictly AFTER the script body finished: it is the exit
    # hook, not an eager per-event RPC.
    assert lines.index("BUFFERED") < lines.index("ATEXIT_FLUSH add_task_events 2")


def test_emit_envelope_fields_cannot_collide(cfg_override):
    """A payload field named like an envelope key ("kind", "ts", ...)
    must neither raise at call time nor clobber the event's own stamp —
    the chaos scheduler once lost an injection to exactly this."""
    cfg_override(journal_ring=64)
    journal.emit("test.envelope", kind="kill_replica", ts=0, pid=-1)
    e = journal.snapshot()[-1]
    assert e["kind"] == "test.envelope"
    assert e["pid"] == os.getpid() and e["ts"] > 0
    assert e["f_kind"] == "kill_replica"


def test_causal_chain_injection_outranks_ambient_seeds():
    """Teardown noise from an unrelated app (worker deaths) inside the
    capture window must not steal the seed from an explicit injection."""
    mk = lambda pt, kind, **kw: dict({"hlc": [pt, 0], "ts": pt / 1e6,
                                      "kind": kind, "proc": "p", "pid": 1}, **kw)
    events = [
        mk(1, "raylet.worker_dead", pid_dead=123),  # old app's teardown
        mk(2, "gcs.actor", state="DEAD", actor_id="old"),
        mk(3, "chaos.kill_replica", app="Echo", index=0),
        mk(4, "gcs.actor", state="DEAD", actor_id="victim"),
        mk(5, "serve.controller", action="replace_dead", app="Echo"),
    ]
    chain = journal.causal_chain(events)
    assert chain[0]["kind"] == "chaos.kill_replica"
    assert [e["kind"] for e in chain] == [
        "chaos.kill_replica", "gcs.actor", "serve.controller"]
    assert chain[1]["actor_id"] == "victim"  # not the stale teardown death
    # Without an injection the earliest typed infrastructure seed wins.
    chain2 = journal.causal_chain([e for e in events
                                   if not e["kind"].startswith("chaos.")])
    assert chain2[0]["kind"] == "raylet.worker_dead"
