"""Tune tests: random/grid search, ASHA early stopping, best-result
selection, experiment snapshots (reference: python/ray/tune/tests)."""

import json
import os
import time

import pytest

import ray_tpu as rt
from ray_tpu import tune
from ray_tpu.tune import ASHAScheduler, TuneConfig, Tuner

pytestmark = pytest.mark.usefixtures("rt_start")


def _objective(config):
    # Quadratic bowl: best at x=3.
    loss = (config["x"] - 3.0) ** 2
    tune.report({"loss": loss, "x": config["x"]})


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_grid_search_finds_best(tmp_path):
    from ray_tpu.train.config import RunConfig

    tuner = Tuner(
        _objective,
        param_space={"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="grid", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 4
    best = grid.get_best_result()
    assert best.metrics["x"] == 3.0
    # Experiment state snapshot written.
    state_file = os.path.join(str(tmp_path), "grid", "experiment_state.json")
    assert os.path.exists(state_file)
    assert len(json.load(open(state_file))) == 4


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_random_search_samples(tmp_path):
    from ray_tpu.train.config import RunConfig

    tuner = Tuner(
        _objective,
        param_space={"x": tune.uniform(-1.0, 1.0)},
        tune_config=TuneConfig(metric="loss", mode="min", num_samples=5, seed=7),
        run_config=RunConfig(name="rand", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 5
    xs = [r.metrics["x"] for r in grid]
    assert all(-1.0 <= x <= 1.0 for x in xs)
    assert len(set(xs)) > 1  # actually sampled


def test_tpe_searcher_beats_random_on_quadratic():
    """Pure-searcher test (no cluster): after warmup, TPE concentrates
    samples near the optimum of a quadratic, beating uniform sampling on
    the same budget."""
    import random as _r

    from ray_tpu.tune import TPESearcher

    def run_searcher(searcher):
        best = float("inf")
        for i in range(48):
            tid = f"t{i}"
            cfg = searcher.suggest(tid)
            if cfg is None:
                break
            loss = (cfg["x"] - 3.0) ** 2 + (cfg["lr"] - 0.01) ** 2
            searcher.on_trial_complete(tid, {"loss": loss})
            best = min(best, loss)
        return best

    space = {"x": tune.uniform(-10.0, 10.0), "lr": tune.loguniform(1e-5, 1.0)}
    tpe_best = run_searcher(TPESearcher(
        space, metric="loss", mode="min", num_samples=48, n_startup=8, seed=0
    ))
    rng = _r.Random(0)
    rand_best = min(
        (rng.uniform(-10, 10) - 3.0) ** 2 for _ in range(48)
    )
    # TPE should land close to the optimum; random over [-10,10] rarely
    # gets within 0.05 of x=3 in 48 draws.
    assert tpe_best < 1.0, f"TPE best {tpe_best}"
    assert tpe_best <= rand_best * 1.5 + 1e-6, (tpe_best, rand_best)


def test_tpe_categorical_concentrates():
    from ray_tpu.tune import TPESearcher

    space = {"opt": tune.choice(["bad1", "bad2", "good", "bad3"])}
    searcher = TPESearcher(
        space, metric="loss", mode="min", num_samples=64, n_startup=12, seed=1
    )
    picks = []
    for i in range(64):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        if cfg is None:
            break
        picks.append(cfg["opt"])
        searcher.on_trial_complete(
            tid, {"loss": 0.0 if cfg["opt"] == "good" else 1.0}
        )
    late = picks[-24:]
    assert late.count("good") > len(late) * 0.5, late


def test_asha_brackets_ladders():
    from ray_tpu.tune import ASHAScheduler

    s = ASHAScheduler(metric="m", mode="max", grace_period=1,
                      reduction_factor=4, max_t=64, brackets=3)
    assert s.bracket_rungs == [[1, 4, 16], [4, 16], [16]]
    # Trials round-robin across brackets; rung stats are per-bracket.
    for i, expect in enumerate([0, 1, 2, 0, 1]):
        assert s._bracket(f"t{i}") == expect
    # A bad trial in bracket 2 survives t=4 (bracket 2's first rung is 16).
    assert s.on_result("t2", {"m": 0.0, "training_iteration": 4}) == "CONTINUE"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_concurrency_limiter_with_tuner(tmp_path):
    """The limiter defers (PAUSED) at its cap instead of permanently
    exhausting the tuner's launch loop."""
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune import BasicVariantGenerator, ConcurrencyLimiter

    searcher = ConcurrencyLimiter(
        BasicVariantGenerator({"x": tune.grid_search([0.0, 1.0, 3.0, 5.0])}),
        max_concurrent=2,
    )
    grid = Tuner(
        _objective,
        tune_config=TuneConfig(metric="loss", mode="min", search_alg=searcher),
        run_config=RunConfig(name="limited", storage_path=str(tmp_path)),
    ).fit()
    # All four grid points ran despite the cap of 2 in flight.
    assert len(grid) == 4
    assert grid.get_best_result().metrics["x"] == 3.0


def _iterative(config):
    # Good configs (high "quality") improve faster.
    for i in range(1, 17):
        tune.report({"score": config["quality"] * i, "training_iteration": i})
        time.sleep(0.05)


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_asha_stops_bad_trials(tmp_path):
    from ray_tpu.train.config import RunConfig

    tuner = Tuner(
        _iterative,
        # Good trials first: ASHA is asynchronous, so a rung's cutoff only
        # exists once earlier trials recorded scores there; later bad
        # trials are then culled against it.
        param_space={"quality": tune.grid_search([1.0, 0.9, 0.2, 0.1])},
        tune_config=TuneConfig(
            metric="score",
            mode="max",
            scheduler=ASHAScheduler(
                metric="score", mode="max", grace_period=2,
                reduction_factor=2, max_t=16,
            ),
            max_concurrent_trials=4,
        ),
        run_config=RunConfig(name="asha", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.metrics["score"] >= 0.9 * 16 * 0.9  # a good trial won
    # At least one bad trial was stopped early.
    iters = [len(r.metrics_history) for r in grid]
    assert min(iters) < 16


def _failing(config):
    if config["x"] == 1:
        raise ValueError("boom")
    tune.report({"loss": 0.0})


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_trial_errors_surface(tmp_path):
    from ray_tpu.train.config import RunConfig

    tuner = Tuner(
        _failing,
        param_space={"x": tune.grid_search([0, 1])},
        tune_config=TuneConfig(metric="loss", mode="min"),
        run_config=RunConfig(name="err", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid.errors) == 1
    assert "boom" in str(grid.errors[0])
    assert grid.get_best_result().metrics["loss"] == 0.0


def _trainer_objective(tmp_path):
    """Tuning a JaxTrainer end to end (Train-on-Tune integration)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    def loop(config):
        from ray_tpu import train

        train.report({"final": config["lr"] * 10})

    return JaxTrainer(
        loop,
        train_loop_config={"lr": 0.0},
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(name="inner", storage_path=str(tmp_path)),
    )


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_tune_over_trainer(tmp_path):
    from ray_tpu.train.config import RunConfig

    trainer = _trainer_objective(tmp_path)
    tuner = Tuner(
        trainer,
        param_space={"lr": tune.grid_search([0.1, 0.3])},
        tune_config=TuneConfig(metric="final", mode="max",
                               max_concurrent_trials=1),
        run_config=RunConfig(name="outer", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert len(grid) == 2
    assert abs(grid.get_best_result().metrics["final"] - 3.0) < 1e-6

def _pbt_trainable(config):
    """Score grows by `lr` each step; progress carries via checkpoints so
    an exploited trial inherits its donor's accumulated score."""
    import json as _json
    import os as _os
    import tempfile

    from ray_tpu.train.checkpoint import Checkpoint

    ckpt = tune.get_checkpoint()
    step, score = 0, 0.0
    if ckpt is not None:
        with open(_os.path.join(ckpt.path, "state.json")) as f:
            st = _json.load(f)
        step, score = st["step"], st["score"]
    for _ in range(40):
        step += 1
        score += config["lr"]
        d = tempfile.mkdtemp()
        with open(_os.path.join(d, "state.json"), "w") as f:
            _json.dump({"step": step, "score": score}, f)
        tune.report(
            {"score": score, "lr": config["lr"]},
            checkpoint=Checkpoint.from_directory(d),
        )
        time.sleep(0.1)


@pytest.mark.slow
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_pbt_exploits_and_improves(tmp_path):
    from ray_tpu.train.config import RunConfig
    from ray_tpu.tune import PopulationBasedTraining

    pbt = PopulationBasedTraining(
        metric="score",
        mode="max",
        perturbation_interval=3,
        hyperparam_mutations={"lr": [0.1, 1.0, 10.0]},
        quantile_fraction=0.25,
        resample_probability=0.0,  # deterministic neighbor moves
        seed=0,
    )
    tuner = Tuner(
        _pbt_trainable,
        param_space={"lr": tune.grid_search([0.1, 0.1, 10.0, 10.0])},
        tune_config=TuneConfig(
            metric="score", mode="max", scheduler=pbt,
            max_concurrent_trials=4,
        ),
        run_config=RunConfig(name="pbt", storage_path=str(tmp_path)),
    )
    grid = tuner.fit()
    assert pbt.num_exploits >= 1, "PBT never exploited"
    # Exploited low-lr trials inherit donor progress + a mutated config,
    # so every trial must finish far above the pure lr=0.1 ceiling (3.0).
    finals = sorted(r.metrics["score"] for r in grid)
    assert finals[0] > 4.0, f"bottom trial never improved: {finals}"

def _crashy_trainable(config):
    """Checkpoints progress; crashes the whole worker process at step 3 on
    the first life (the checkpoint lets a restarted trial resume)."""
    import json as _json
    import os as _os
    import tempfile

    from ray_tpu.train.checkpoint import Checkpoint

    step = 0
    ckpt = tune.get_checkpoint()
    if ckpt is not None:
        with open(_os.path.join(ckpt.path, "s.json")) as f:
            step = _json.load(f)["step"]
    first_life = ckpt is None
    for step in range(step, 8):
        d = tempfile.mkdtemp()
        with open(_os.path.join(d, "s.json"), "w") as f:
            _json.dump({"step": step}, f)
        tune.report({"step": step}, checkpoint=Checkpoint.from_directory(d))
        time.sleep(0.05)
        if first_life and step == 3:
            _os._exit(1)  # hard crash: the actor process dies


@pytest.mark.parametrize("rt_start", [{"num_cpus": 2}], indirect=True)
def test_trial_crash_restarts_from_checkpoint(tmp_path):
    from ray_tpu.train.config import FailureConfig, RunConfig

    tuner = Tuner(
        _crashy_trainable,
        param_space={"x": tune.grid_search([1])},
        tune_config=TuneConfig(metric="step", mode="max"),
        run_config=RunConfig(
            name="crashy", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=2),
        ),
    )
    grid = tuner.fit()
    best = grid.get_best_result()
    assert best.error is None
    # The trial finished all 8 steps across two lives, resuming >= step 3.
    assert best.metrics["step"] == 7


@pytest.mark.parametrize("rt_start", [{"num_cpus": 2}], indirect=True)
def test_trial_crash_exhausts_budget(tmp_path):
    from ray_tpu.train.config import FailureConfig, RunConfig

    def always_crash(config):
        import os as _os

        tune.report({"step": 0})
        time.sleep(0.1)
        _os._exit(1)

    tuner = Tuner(
        always_crash,
        param_space={"x": tune.grid_search([1])},
        tune_config=TuneConfig(metric="step", mode="max"),
        run_config=RunConfig(
            name="crashy2", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=1),
        ),
    )
    grid = tuner.fit()
    assert grid.errors, "exhausted failure budget must surface an error"


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_tuner_restore_resumes_unfinished_trials(tmp_path):
    """Tuner.restore: finished trials keep their recorded results (their
    functions never re-run); interrupted ones resume from their recorded
    checkpoint instead of step 0 (reference: Tuner.restore)."""
    import json as _json
    import os

    from ray_tpu.train.checkpoint import Checkpoint
    from ray_tpu.train.config import RunConfig

    marker = str(tmp_path / "runs.jsonl")

    def objective(config):
        import json
        import os
        import tempfile

        from ray_tpu import tune as tmod

        start = 0
        ckpt = tmod.get_checkpoint()
        if ckpt:
            start = json.load(open(os.path.join(ckpt.path, "s.json")))["step"] + 1
        with open(config["marker"], "a") as f:
            f.write(json.dumps({"x": config["x"], "start": start}) + "\n")
        if config["x"] == 99 and start == 0:
            # The "interrupted" trial: checkpoint step 3, then die.
            d = tempfile.mkdtemp()
            json.dump({"step": 3}, open(os.path.join(d, "s.json"), "w"))
            from ray_tpu.train.checkpoint import Checkpoint as C

            tmod.report({"score": 0.0}, checkpoint=C.from_directory(d))
            raise RuntimeError("simulated interruption")
        tmod.report({"score": float(config["x"] + start)})

    exp_dir = str(tmp_path)
    run_config = RunConfig(name="resume-exp", storage_path=exp_dir)
    grid = Tuner(
        objective,
        param_space={"x": tune.grid_search([1, 2, 99]),
                     "marker": marker},
        tune_config=TuneConfig(metric="score", mode="max"),
        run_config=run_config,
    ).fit()
    # x=99 failed; the others finished.
    assert len(grid.errors) == 1

    restored = Tuner.restore(
        os.path.join(exp_dir, "resume-exp"), objective,
        tune_config=TuneConfig(metric="score", mode="max"),
    ).fit()
    assert len(restored) == 3 and not restored.errors
    # The resumed trial continued from its checkpoint (start=4 => 103).
    assert restored.get_best_result().metrics["score"] == 103.0
    runs = [_json.loads(l) for l in open(marker)]
    # Finished trials (x=1,2) ran exactly once — never re-executed.
    assert sum(1 for r in runs if r["x"] == 1) == 1
    assert sum(1 for r in runs if r["x"] == 2) == 1
    # The interrupted trial ran twice: fresh, then from step 4.
    assert [r["start"] for r in runs if r["x"] == 99] == [0, 4]


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_with_parameters_ships_large_objects(tmp_path):
    """tune.with_parameters: the object goes to the store once; every
    trial receives it as a kwarg, not through config serialization."""
    import numpy as np

    from ray_tpu.train.config import RunConfig

    data = np.arange(10_000, dtype=np.float64)

    def objective(config, data):
        tune.report({"total": float(data.sum()) + config["x"]})

    grid = Tuner(
        tune.with_parameters(objective, data=data),
        param_space={"x": tune.grid_search([1.0, 2.0])},
        tune_config=TuneConfig(metric="total", mode="max"),
        run_config=RunConfig(name="params", storage_path=str(tmp_path)),
    ).fit()
    want = float(data.sum())
    assert sorted(r.metrics["total"] for r in grid) == [want + 1.0, want + 2.0]


def test_external_searcher_adapter():
    """Any ask/tell optimizer plugs in behind the Searcher seam (VERDICT
    r3 missing #6; reference: tune/search/ integration adapters)."""
    from ray_tpu.tune import ExternalSearcher

    class FakeOptimizerLib:
        """Stands in for optuna/hyperopt: ask/tell protocol, minimizes."""

        def __init__(self):
            self.next_token = 0
            self.told = {}

        def ask(self):
            self.next_token += 1
            # Sweep x deterministically so the test can assert the data
            # flow, not the optimizer quality.
            return self.next_token, {"x": float(self.next_token)}

        def tell(self, token, score):
            self.told[token] = score

    lib = FakeOptimizerLib()
    s = ExternalSearcher(lib, metric="loss", mode="max", num_samples=3)
    cfgs = [s.suggest(f"t{i}") for i in range(4)]
    assert [c["x"] for c in cfgs[:3]] == [1.0, 2.0, 3.0]
    assert cfgs[3] is None  # num_samples budget
    s.on_trial_complete("t0", {"loss": 5.0})
    s.on_trial_complete("t2", {"loss": 7.0})
    # mode=max: the external lib sees negated (minimization) scores.
    assert lib.told == {1: -5.0, 3: -7.0}

    with pytest.raises(TypeError):
        ExternalSearcher(object(), metric="loss")


def test_bohb_searcher_models_intermediate_rungs():
    """BOHB: the TPE model fits on the highest fidelity rung with enough
    points (reference: tune/search/bohb)."""
    from ray_tpu.tune import BOHBSearcher

    space = {"x": tune.uniform(-10.0, 10.0)}
    s = BOHBSearcher(space, metric="loss", mode="min",
                     num_samples=64, n_startup=6, seed=0)
    # Feed intermediate results at two fidelities over an evenly spread
    # population: the low rung is misleading (prefers x=-9), the high
    # rung is the true quadratic around x=3 — the model must fit the
    # HIGH rung.
    for i in range(13):
        tid = f"t{i}"
        x = -9.0 + 1.5 * i
        cfg = s.suggest(tid)
        s._configs[tid] = {"x": x}  # crafted population
        s.on_trial_result(tid, {"loss": x + 100.0,  # misleading rung
                                "training_iteration": 1})
        s.on_trial_result(tid, {"loss": (x - 3.0) ** 2,
                                "training_iteration": 4})
        s.on_trial_complete(tid, {"loss": (x - 3.0) ** 2})
    # Model must now be fit on rung 4 (12 >= n_startup).
    assert s._observations and all(
        score >= 0 for _, score in s._observations
    ), "model should hold rung-4 (quadratic) observations"
    xs = [s.suggest(f"m{i}")["x"] for i in range(12)]
    # Guided samples concentrate near x=3, not near x=10 (which the
    # misleading low rung would prefer).
    assert sum(1 for x in xs if abs(x - 3.0) < 4.0) >= 7, xs


def test_pb2_gp_guided_explore():
    """PB2: explore() proposes from a GP-UCB over observed improvement
    instead of random 0.8x/1.2x (reference: tune/schedulers/pb2.py)."""
    from ray_tpu.tune import PB2

    sched = PB2(
        metric="score", mode="max",
        perturbation_interval=1,
        hyperparam_bounds={"lr": (0.0, 1.0)},
        seed=0,
    )
    # Simulate a population where lr near 0.7 improves fastest.
    import random as _r

    rng = _r.Random(0)
    for t in range(8):
        tid = f"t{t}"
        lr = rng.random()
        sched.on_trial_add(tid, {"lr": lr})
        score = 0.0
        for it in range(4):
            score += 1.0 - (lr - 0.7) ** 2  # improvement peaks at 0.7
            sched.on_result(tid, {"score": score})
    # GP has data; explore must propose inside bounds, guided.
    proposals = [sched._explore({"lr": 0.1})["lr"] for _ in range(8)]
    assert all(0.0 <= p <= 1.0 for p in proposals)
    # The acquisition should concentrate proposals toward the
    # high-improvement region rather than uniformly.
    assert sum(1 for p in proposals if p > 0.4) >= 5, proposals


def test_with_resources_overrides_trial_resources(rt_start):
    """tune.with_resources pins per-trial resources on the trainable,
    winning over TuneConfig.trial_resources (reference precedence)."""
    from ray_tpu import tune
    from ray_tpu.tune import TuneConfig, Tuner

    def train_fn(config):
        tune.report({"score": config["x"] * 2})

    wrapped = tune.with_resources(train_fn, {"CPU": 2})
    assert wrapped._tune_resources == {"CPU": 2}
    tuner = Tuner(
        wrapped,
        param_space={"x": tune.grid_search([1, 2])},
        tune_config=TuneConfig(trial_resources={"CPU": 0.5}),
    )
    grid = tuner.fit()
    scores = sorted(r.metrics["score"] for r in grid)
    assert scores == [2, 4]


def test_with_resources_propagates_through_as_trainable():
    """Trainer objects keep their pinned resources through as_trainable
    (regression: the closure dropped _tune_resources)."""
    from ray_tpu import tune
    from ray_tpu.train.trainer import BaseTrainer

    t = tune.with_resources(BaseTrainer(), {"CPU": 3})
    assert t.as_trainable()._tune_resources == {"CPU": 3}
