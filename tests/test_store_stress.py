"""Multi-process stress test of the C++ shared-memory object store.

The store is a process-shared robust-mutex allocator with LRU eviction
(native/object_store.cc) — exactly the code that needs concurrent
create/seal/get/release/delete hammering from MULTIPLE PROCESSES, not the
single-process happy path (VERDICT r1 weak #7; reference analog: the
plasma test tree, object_manager/plasma/test/).

Run against the ASAN build with:
    make -C ray_tpu/native asan
    RT_STORE_LIB=$PWD/ray_tpu/native/libray_tpu_store_asan.so \\
        LD_PRELOAD=$(gcc -print-file-name=libasan.so) \\
        python -m pytest tests/test_store_stress.py -q
"""

import os
import subprocess
import sys
import textwrap

import pytest

pytestmark = pytest.mark.slow  # chaos/e2e tier — fast runs skip

from ray_tpu._private.ids import ObjectID
from ray_tpu._private.object_store import ObjectStore

_WORKER = textwrap.dedent(
    """
    import os, random, sys, hashlib
    sys.path.insert(0, {repo!r})
    from ray_tpu._private.ids import ObjectID
    from ray_tpu._private.object_store import ObjectStore
    from ray_tpu.exceptions import ObjectStoreFullError

    store_name, seed, n_ops = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    rng = random.Random(seed)
    store = ObjectStore(store_name)
    mine = []  # (oid, payload_checksum, size)
    ok_reads = creates = deletes = full = 0
    for op in range(n_ops):
        r = rng.random()
        if r < 0.45 or not mine:
            # create + seal an object of random size
            oid = ObjectID.from_random()
            size = rng.randrange(64, 256 * 1024)
            payload = bytes([op % 256]) * size
            try:
                buf = store.create(oid, size)
            except ObjectStoreFullError:
                full += 1
                # delete something of ours to make progress
                if mine:
                    oid2, _, _ = mine.pop(rng.randrange(len(mine)))
                    store.delete(oid2)
                continue
            buf[:] = payload
            store.seal(oid)
            store.release(oid)
            mine.append((oid, payload[:16], size))
            creates += 1
        elif r < 0.85:
            # read-verify one of ours (it may have been LRU-evicted)
            oid, head, size = mine[rng.randrange(len(mine))]
            view = store.get(oid)
            if view is not None:
                assert len(view) == size, (len(view), size)
                assert bytes(view[:16]) == head, "payload corrupted"
                del view
                store.release(oid)
                ok_reads += 1
        else:
            idx = rng.randrange(len(mine))
            oid, _, _ = mine.pop(idx)
            store.delete(oid)
            deletes += 1
    store.close(unmap=True)
    print(f"creates={{creates}} reads={{ok_reads}} deletes={{deletes}} full={{full}}")
    """
).format(repo=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def test_multiprocess_create_get_delete_stress(tmp_path):
    name = f"/rt_stress_{os.getpid()}"
    store = ObjectStore(name, create=True, size=32 * 1024 * 1024)
    try:
        script = tmp_path / "stress_worker.py"
        script.write_text(_WORKER)
        procs = [
            subprocess.Popen(
                [sys.executable, str(script), name, str(seed), "400"],
                stdout=subprocess.PIPE,
                stderr=subprocess.PIPE,
                env={**os.environ},
            )
            for seed in range(4)
        ]
        for p in procs:
            out, err = p.communicate(timeout=300)
            assert p.returncode == 0, (
                f"stress worker died rc={p.returncode}\n"
                f"stdout: {out.decode()}\nstderr: {err.decode()[-2000:]}"
            )
            assert b"creates=" in out
        stats = store.stats()
        assert stats["num_objects"] >= 0  # header still consistent
    finally:
        store.destroy()


def test_stress_under_asan_if_available(tmp_path):
    """Build + run one stress worker against the ASAN store, if gcc+asan
    exist in the image (sanitizer story for the shm allocator)."""
    native = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "ray_tpu", "native",
    )
    r = subprocess.run(
        ["make", "-s", "-C", native, "asan"], capture_output=True
    )
    if r.returncode != 0:
        pytest.skip(f"no ASAN toolchain: {r.stderr.decode()[-200:]}")
    asan_lib = os.path.join(native, "libray_tpu_store_asan.so")
    # find libasan for LD_PRELOAD (the host python isn't instrumented)
    p = subprocess.run(
        ["gcc", "-print-file-name=libasan.so"], capture_output=True, text=True
    )
    libasan = p.stdout.strip()
    if not libasan or not os.path.exists(libasan):
        pytest.skip("libasan.so not found")

    name = f"/rt_asan_{os.getpid()}"
    env = {
        **os.environ,
        "RT_STORE_LIB": asan_lib,
        "LD_PRELOAD": libasan,
        "ASAN_OPTIONS": "detect_leaks=0,abort_on_error=1",
    }
    script = tmp_path / "stress_worker.py"
    script.write_text(_WORKER)
    boot = tmp_path / "boot.py"
    boot.write_text(
        _WORKER.replace(
            'store = ObjectStore(store_name)',
            'store = ObjectStore(store_name, create=True, '
            'size=16 * 1024 * 1024)',
        )
    )
    p = subprocess.run(
        [sys.executable, str(boot), name, "1", "600"],
        capture_output=True, timeout=300, env=env,
    )
    shm = f"/dev/shm/{name.lstrip('/')}"
    if os.path.exists(shm):
        os.unlink(shm)
    assert p.returncode == 0, (
        f"ASAN stress failed rc={p.returncode}\n"
        f"stderr: {p.stderr.decode()[-3000:]}"
    )
    assert b"AddressSanitizer" not in p.stderr


def test_multithreaded_store_under_tsan_if_available():
    """8 threads hammer create/seal/get/release/delete on one store under
    ThreadSanitizer (SURVEY §4: the reference's race-detection story is
    TSAN builds over the C++ tests). Skips where the toolchain lacks
    -fsanitize=thread."""
    import subprocess

    native = os.path.join(
        os.path.dirname(os.path.dirname(__file__)), "ray_tpu", "native"
    )
    probe = subprocess.run(
        ["g++", "-fsanitize=thread", "-x", "c++", "-", "-o", "/dev/null"],
        input=b"int main(){return 0;}", capture_output=True,
    )
    if probe.returncode != 0:
        pytest.skip("toolchain lacks -fsanitize=thread")
    out = subprocess.run(
        ["make", "-s", "-C", native, "tsan_test"],
        capture_output=True, text=True, timeout=600,
    )
    assert out.returncode == 0, (out.stdout[-2000:], out.stderr[-2000:])
    assert "STORE THREAD TESTS OK" in out.stdout
    assert "WARNING: ThreadSanitizer" not in out.stderr
