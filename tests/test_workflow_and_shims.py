"""Workflow durability + multiprocessing/joblib/iter shim tests.

Reference analogs: python/ray/workflow/tests, util/multiprocessing tests,
util/joblib tests, util/iter tests.
"""

import pytest

import ray_tpu as rt
from ray_tpu.dag import InputNode


calls = {"n": 0}


def test_workflow_run_and_resume(rt_start, tmp_path):
    from ray_tpu import workflow

    storage = str(tmp_path / "wf")

    @rt.remote
    def ingest(x):
        return list(range(x))

    @rt.remote
    def total(xs):
        return sum(xs)

    @rt.remote
    def must_fail_once(t, flag_path=str(tmp_path / "flag")):
        import os

        if not os.path.exists(flag_path):
            open(flag_path, "w").close()
            raise RuntimeError("transient")
        return t * 10

    with InputNode() as inp:
        dag = must_fail_once.bind(total.bind(ingest.bind(inp)))

    # First run: the last step fails once, then retries succeed.
    out = workflow.run(dag, 5, workflow_id="wf-1", storage=storage)
    assert out == 100  # sum(range(5)) * 10

    assert workflow.get_status("wf-1", storage=storage) == "SUCCEEDED"
    assert workflow.get_output("wf-1", storage=storage) == 100
    # Resume of a finished workflow returns the stored output.
    assert workflow.resume("wf-1", storage=storage) == 100
    assert any(w["workflow_id"] == "wf-1" for w in workflow.list_all(storage))
    workflow.delete("wf-1", storage=storage)
    assert workflow.get_status("wf-1", storage=storage) is None


def test_workflow_resume_skips_completed_steps(rt_start, tmp_path):
    from ray_tpu import workflow

    storage = str(tmp_path / "wf2")
    marker = tmp_path / "count"
    marker.write_text("0")

    @rt.remote
    def counted(x, path=str(marker)):
        n = int(open(path).read()) + 1
        open(path, "w").write(str(n))
        return x + 1

    @rt.remote
    def boom(x, arm_path=str(tmp_path / "armed")):
        import os

        if os.path.exists(arm_path):
            return x * 2
        raise RuntimeError("not armed yet")

    with InputNode() as inp:
        dag = boom.bind(counted.bind(inp))

    with pytest.raises(workflow.WorkflowError):
        workflow.run(dag, 1, workflow_id="wf-2", storage=storage,
                     max_step_retries=0)
    assert workflow.get_status("wf-2", storage=storage) == "FAILED"
    assert marker.read_text() == "1"  # first step ran once and checkpointed

    (tmp_path / "armed").write_text("")  # arm the second step
    out = workflow.resume("wf-2", storage=storage, max_step_retries=0)
    assert out == 4
    # The checkpointed first step did NOT re-run.
    assert marker.read_text() == "1"


def _sq(x):
    return x * x


def test_multiprocessing_pool(rt_start):
    from ray_tpu.util.multiprocessing import Pool

    with Pool(processes=2) as pool:
        assert pool.map(_sq, range(10)) == [x * x for x in range(10)]
        assert pool.apply(_sq, (7,)) == 49
        ar = pool.apply_async(_sq, (8,))
        assert ar.get(timeout=30) == 64
        assert pool.starmap(pow, [(2, 3), (3, 2)]) == [8, 9]
        assert sorted(pool.imap_unordered(_sq, [1, 2, 3])) == [1, 4, 9]


def test_joblib_backend(rt_start):
    import joblib

    from ray_tpu.util.joblib import register_rt

    register_rt()
    with joblib.parallel_backend("rt"):
        out = joblib.Parallel(n_jobs=2)(
            joblib.delayed(_sq)(i) for i in range(8)
        )
    assert out == [i * i for i in range(8)]


def test_parallel_iterator(rt_start):
    from ray_tpu.util import iter as rt_iter

    it = rt_iter.from_range(10, num_shards=3)
    out = it.for_each(lambda x: x * 2).filter(lambda x: x % 4 == 0).gather_sync()
    assert sorted(out) == [0, 4, 8, 12, 16]

def test_workflow_events_signal_and_resume(rt_start, tmp_path):
    """workflow.event blocks until workflow.signal delivers a payload; the
    payload checkpoints, so a resume does not re-wait (reference: workflow
    events / wait_for_event)."""
    import threading
    import time

    from ray_tpu import workflow

    @rt.remote
    def combine(a, b):
        return {"approved": a, "value": b}

    ev = workflow.event("approval")
    dag = combine.bind(ev, 42)

    wf_id = "wf-events-1"
    out = {}

    def run():
        out["result"] = workflow.run(
            dag, workflow_id=wf_id, storage=str(tmp_path)
        )

    t = threading.Thread(target=run, daemon=True)
    t.start()
    # The workflow must be WAITING on the event, not finished.
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        if workflow.get_status(wf_id, storage=str(tmp_path)) == "WAITING":
            break
        time.sleep(0.1)
    assert t.is_alive(), "workflow finished without the event"

    workflow.signal(wf_id, "approval", {"by": "alice"}, storage=str(tmp_path))
    t.join(timeout=60)
    assert out["result"] == {"approved": {"by": "alice"}, "value": 42}

    # Signal-before-run also works (durable delivery).
    wf2 = "wf-events-2"
    workflow.signal(wf2, "approval", "pre", storage=str(tmp_path))
    res = workflow.run(
        combine.bind(workflow.event("approval"), 1),
        workflow_id=wf2, storage=str(tmp_path),
    )
    assert res == {"approved": "pre", "value": 1}


def test_workflow_event_timeout(rt_start, tmp_path):
    from ray_tpu import workflow

    @rt.remote
    def use(x):
        return x

    with pytest.raises(workflow.WorkflowError, match="timed out"):
        workflow.run(
            use.bind(workflow.event("never", timeout_s=0.5)),
            workflow_id="wf-timeout", storage=str(tmp_path),
        )


def test_workflow_run_async_and_waiting_output(rt_start, tmp_path):
    """run_async returns immediately; get_output(wait=...) blocks for the
    background run, including across the events/signal path."""
    from ray_tpu import workflow

    @rt.remote
    def slow_double(x):
        import time as _t

        _t.sleep(0.4)
        return x * 2

    wid = workflow.run_async(
        slow_double.bind(21), workflow_id="async-wf", storage=str(tmp_path)
    )
    assert wid == "async-wf"
    # Not done yet (the step sleeps); non-waiting read raises.
    import pytest as _pytest

    with _pytest.raises(workflow.WorkflowError):
        workflow.get_output(wid, storage=str(tmp_path))
    assert workflow.get_output(wid, storage=str(tmp_path), wait=30) == 42
    assert workflow.get_status(wid, storage=str(tmp_path)) == "SUCCEEDED"


def test_util_debug_log_gates():
    """ray.util.debug surface: log_once / log_every_n_seconds /
    reset_log_once / disable_log_once_globally."""
    from ray_tpu.util import debug

    key = "t-debug-gate"
    debug.reset_log_once(key)
    assert debug.log_once(key)
    assert not debug.log_once(key)
    debug.reset_log_once(key)
    assert debug.log_once(key)

    pkey = "t-debug-periodic"
    debug.reset_log_once(pkey)
    assert debug.log_every_n_seconds(pkey, 60.0)
    assert not debug.log_every_n_seconds(pkey, 60.0)
    assert debug.log_every_n_seconds(pkey, 0.0)

    debug.disable_log_once_globally()
    try:
        assert not debug.log_once("t-debug-disabled")
    finally:
        debug.enable_periodic_logging()


def test_inspect_serializability_blames_nested_member():
    """inspect_serializability pinpoints the unpicklable leaf (reference:
    ray.util.inspect_serializability, util/check_serialize.py)."""
    import threading

    from ray_tpu.util import inspect_serializability

    lines = []
    ok, failures = inspect_serializability(
        {"fine": 1, "bad": threading.Lock()}, name="payload",
        _print=lines.append,
    )
    assert not ok
    assert any("bad" in f for f in failures)

    lock = threading.Lock()

    def closure_fn():
        return lock

    ok2, failures2 = inspect_serializability(
        closure_fn, name="closure_fn", _print=lines.append
    )
    assert not ok2
    assert any("closure" in f for f in failures2)

    ok3, failures3 = inspect_serializability(
        lambda: 42, name="clean", _print=lines.append
    )
    assert ok3 and not failures3
