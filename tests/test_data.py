"""Dataset tests (reference model: python/ray/data/tests)."""

import os

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rtd

pytestmark = pytest.mark.usefixtures("rt_start")


def test_range_count_take():
    ds = rtd.range(100)
    assert ds.count() == 100
    assert ds.take(3) == [{"id": 0}, {"id": 1}, {"id": 2}]


def test_map_and_filter():
    ds = rtd.range(20).map(lambda r: {"id": r["id"], "sq": r["id"] ** 2})
    ds = ds.filter(lambda r: r["sq"] % 2 == 0)
    rows = ds.take_all()
    assert all(r["sq"] == r["id"] ** 2 for r in rows)
    assert all(r["sq"] % 2 == 0 for r in rows)
    assert len(rows) == 10


def test_map_batches_numpy():
    ds = rtd.range(32).map_batches(
        lambda batch: {"id": batch["id"], "double": batch["id"] * 2}
    )
    rows = sorted(ds.take_all(), key=lambda r: r["id"])
    assert rows[5] == {"id": 5, "double": 10}


def test_flat_map():
    ds = rtd.from_items([{"n": 2}, {"n": 3}]).flat_map(
        lambda r: [{"v": r["n"]} for _ in range(r["n"])]
    )
    assert ds.count() == 5


def test_repartition_and_num_blocks():
    ds = rtd.range(100).repartition(10)
    assert ds.materialize().num_blocks() == 10
    assert ds.count() == 100


def test_random_shuffle_preserves_rows():
    ds = rtd.range(50).random_shuffle(seed=42)
    ids = sorted(r["id"] for r in ds.take_all())
    assert ids == list(range(50))
    # Actually shuffled
    assert [r["id"] for r in rtd.range(50).random_shuffle(seed=42).take_all()] != list(range(50))


def test_sort():
    ds = rtd.from_items([{"x": 3}, {"x": 1}, {"x": 2}]).sort("x")
    assert [r["x"] for r in ds.take_all()] == [1, 2, 3]
    ds = rtd.from_items([{"x": 3}, {"x": 1}, {"x": 2}]).sort("x", descending=True)
    assert [r["x"] for r in ds.take_all()] == [3, 2, 1]


def test_aggregations():
    ds = rtd.from_items([{"v": float(i)} for i in range(10)])
    assert ds.sum("v") == 45.0
    assert ds.mean("v") == 4.5
    assert ds.min("v") == 0.0
    assert ds.max("v") == 9.0


def test_groupby():
    ds = rtd.from_items(
        [{"k": i % 3, "v": i} for i in range(9)]
    )
    counts = sorted(ds.groupby("k").count().take_all(), key=lambda r: r["k"])
    assert counts == [
        {"k": 0, "count()": 3},
        {"k": 1, "count()": 3},
        {"k": 2, "count()": 3},
    ]
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums[0] == 0 + 3 + 6


def test_groupby_string_keys_cross_process():
    """String keys must hash to the same shuffle partition in every worker
    process — builtin hash() is salted per process (PYTHONHASHSEED), so a
    salted hash silently duplicates groups across reduce partitions."""
    ds = rtd.from_items(
        [{"k": f"key-{i % 4}", "v": i} for i in range(32)]
    ).repartition(8)
    counts = {r["k"]: r["count()"] for r in ds.groupby("k").count().take_all()}
    assert counts == {f"key-{i}": 8 for i in range(4)}
    sums = {r["k"]: r["sum(v)"] for r in ds.groupby("k").sum("v").take_all()}
    assert sums["key-0"] == sum(i for i in range(32) if i % 4 == 0)


def test_std_and_generic_aggregate():
    vals = [float(i) for i in range(20)]
    ds = rtd.from_items([{"v": v} for v in vals]).repartition(4)
    np.testing.assert_allclose(ds.std("v"), np.std(vals, ddof=1), rtol=1e-9)
    out = ds.aggregate(rtd.Count(), rtd.Sum("v"), rtd.Mean("v"), rtd.Std("v"))
    assert out["count()"] == 20
    assert out["sum(v)"] == sum(vals)
    np.testing.assert_allclose(out["mean(v)"], np.mean(vals))
    np.testing.assert_allclose(out["std(v)"], np.std(vals, ddof=1), rtol=1e-9)


def test_unique():
    ds = rtd.from_items([{"k": i % 4} for i in range(40)]).repartition(5)
    assert ds.unique("k") == [0, 1, 2, 3]


def test_groupby_distributed_aggregates():
    ds = rtd.from_items(
        [{"k": f"g{i % 3}", "v": float(i)} for i in range(12)]
    ).repartition(4)
    rows = {r["k"]: r for r in ds.groupby("k").mean("v").take_all()}
    # g0: 0,3,6,9 -> 4.5; g1: 1,4,7,10 -> 5.5; g2: 2,5,8,11 -> 6.5
    assert rows["g0"]["mean(v)"] == 4.5
    assert rows["g1"]["mean(v)"] == 5.5
    assert rows["g2"]["mean(v)"] == 6.5
    stds = {r["k"]: r["std(v)"] for r in ds.groupby("k").std("v").take_all()}
    np.testing.assert_allclose(
        stds["g0"], np.std([0.0, 3.0, 6.0, 9.0], ddof=1), rtol=1e-9
    )


def test_groupby_map_groups():
    ds = rtd.from_items([{"k": i % 2, "v": i} for i in range(8)])
    out = ds.groupby("k").map_groups(
        lambda rows: {"k": rows[0]["k"], "span": max(r["v"] for r in rows)
                      - min(r["v"] for r in rows)}
    ).take_all()
    assert {r["k"]: r["span"] for r in out} == {0: 6, 1: 6}


def test_local_shuffle_and_prefetch_iter():
    ds = rtd.range(40).repartition(4)
    batches = list(ds.iter_batches(
        batch_size=10, prefetch_blocks=2,
        local_shuffle_buffer_size=20, local_shuffle_seed=0,
    ))
    ids = [int(i) for b in batches for i in b["id"]]
    assert sorted(ids) == list(range(40))  # a permutation...
    assert ids != list(range(40))          # ...that actually shuffled


def test_iter_jax_batches_device_arrays():
    import jax

    ds = rtd.from_numpy({"x": np.arange(12, dtype=np.float32)})
    batches = list(ds.iter_jax_batches(batch_size=5))
    assert [len(b["x"]) for b in batches] == [5, 5, 2]
    assert isinstance(batches[0]["x"], jax.Array)
    np.testing.assert_array_equal(
        np.concatenate([np.asarray(b["x"]) for b in batches]),
        np.arange(12, dtype=np.float32),
    )


def test_dataset_stats():
    ds = rtd.range(20).map(lambda r: {"id": r["id"] * 2}).repartition(2)
    ds.count()
    s = ds.stats()
    assert "map" in s and "repartition" in s, s


def test_iter_batches_rebatching():
    ds = rtd.range(25).repartition(4)
    batches = list(ds.iter_batches(batch_size=10))
    sizes = [len(b["id"]) for b in batches]
    assert sizes == [10, 10, 5]
    all_ids = sorted(int(i) for b in batches for i in b["id"])
    assert all_ids == list(range(25))


def test_split_for_training():
    shards = rtd.range(30).split(3)
    assert len(shards) == 3
    counts = [s.count() for s in shards]
    assert sum(counts) == 30
    assert all(c == 10 for c in counts)


def test_from_numpy_roundtrip():
    ds = rtd.from_numpy({"x": np.arange(10), "y": np.arange(10) * 2})
    batch = next(ds.iter_batches(batch_size=10))
    assert list(batch["y"]) == [i * 2 for i in range(10)]


def test_read_parquet(tmp_path):
    import pyarrow as pa
    import pyarrow.parquet as pq

    table = pa.table({"a": list(range(20)), "b": [str(i) for i in range(20)]})
    path = os.path.join(str(tmp_path), "t.parquet")
    pq.write_table(table, path)
    ds = rtd.read_parquet(path)
    assert ds.count() == 20
    assert ds.sum("a") == sum(range(20))


def test_read_csv(tmp_path):
    path = os.path.join(str(tmp_path), "t.csv")
    with open(path, "w") as f:
        f.write("a,b\n1,x\n2,y\n3,z\n")
    ds = rtd.read_csv(path)
    assert ds.count() == 3
    assert ds.sum("a") == 6


def test_union_and_limit():
    a = rtd.range(5)
    b = rtd.range(5).map(lambda r: {"id": r["id"] + 5})
    u = a.union(b)
    assert u.count() == 10
    assert u.limit(3).count() == 3


def test_dataset_with_trainer(tmp_path):
    """Dataset shards feed JaxTrainer workers (train ingest path)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig

    ds = rtd.range(20)

    def loop(config):
        from ray_tpu import train

        shard = train.get_dataset_shard("train")
        total = sum(r["id"] for r in shard.iter_rows())
        train.report({"total": total, "rank": train.get_world_rank()})

    trainer = JaxTrainer(
        loop,
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(name="ds", storage_path=str(tmp_path)),
        datasets={"train": ds},
    )
    result = trainer.fit()
    assert result.error is None

def test_write_and_read_roundtrip(rt_start, tmp_path):
    import ray_tpu.data as rtd

    ds = rtd.from_items(
        [{"i": i, "x": float(i) * 0.5} for i in range(40)], parallelism=4
    )
    files = ds.write_parquet(str(tmp_path / "pq"))
    assert len(files) == 4
    back = rtd.read_parquet(str(tmp_path / "pq"))
    assert sorted(r["i"] for r in back.take_all()) == list(range(40))

    csvs = ds.write_csv(str(tmp_path / "csv"))
    assert csvs and all(f.endswith(".csv") for f in csvs)
    jls = ds.write_json(str(tmp_path / "jl"))
    assert jls and all(f.endswith(".jsonl") for f in jls)


def test_read_text(rt_start, tmp_path):
    import ray_tpu.data as rtd

    p = tmp_path / "lines.txt"
    p.write_text("alpha\nbeta\ngamma\n")
    ds = rtd.read_text(str(p))
    assert [r["text"] for r in ds.take_all()] == ["alpha", "beta", "gamma"]

def test_column_ops_and_sampling(rt_start):
    import ray_tpu.data as rtd

    ds = rtd.from_items([{"a": i, "b": i * 2} for i in range(50)],
                        parallelism=4)
    out = (
        ds.add_column("c", lambda r: r["a"] + r["b"])
        .drop_columns(["b"])
        .select_columns(["c"])
        .take(3)
    )
    assert out == [{"c": 0}, {"c": 3}, {"c": 6}]

    sampled = ds.random_sample(0.5, seed=1).count()
    assert 5 <= sampled <= 45

    zipped = rtd.from_items([{"x": i} for i in range(5)]).zip(
        rtd.from_items([{"y": i * 10} for i in range(5)])
    )
    assert zipped.take(2) == [{"x": 0, "y": 0}, {"x": 1, "y": 10}]


def test_iter_torch_batches():
    import torch

    ds = rtd.from_numpy({"x": np.arange(10, dtype=np.float32),
                         "y": np.arange(10)})
    batches = list(ds.iter_torch_batches(
        batch_size=4, dtypes={"x": torch.float64}
    ))
    assert [len(b["x"]) for b in batches] == [4, 4, 2]
    assert isinstance(batches[0]["x"], torch.Tensor)
    assert batches[0]["x"].dtype == torch.float64
    np.testing.assert_array_equal(
        torch.cat([b["y"] for b in batches]).numpy(), np.arange(10)
    )


def test_pandas_arrow_interop(rt_start):
    import pandas as pd
    import pyarrow as pa

    df = pd.DataFrame({"a": [1, 2, 3, 4], "b": ["x", "y", "z", "w"]})
    ds = rtd.from_pandas(df, parallelism=2)
    assert ds.count() == 4
    back = ds.sort("a").to_pandas()
    assert list(back["a"]) == [1, 2, 3, 4]
    assert list(back.columns) == ["a", "b"]

    t = pa.Table.from_pydict({"v": [10, 20, 30]})
    ds2 = rtd.from_arrow(t)
    assert ds2.count() == 3
    out = ds2.map(lambda r: {"v": r["v"] + 1}).to_arrow()
    assert sorted(out.column("v").to_pylist()) == [11, 21, 31]

    # limit guard on to_pandas
    big = rtd.range(100)
    assert len(big.to_pandas(limit=7)) == 7


def test_cloud_shaped_io_through_fake_fs(rt_start, tmp_path):
    """read_/write_ with s3://-shaped URIs over an injected local
    filesystem (the pyarrow.fs layer cloud IO rides; reference:
    data/datasource file IO with filesystem=)."""
    import pyarrow.fs as pafs

    fake_s3 = pafs.SubTreeFileSystem(str(tmp_path), pafs.LocalFileSystem())
    ds = rtd.from_items([{"i": i, "x": i * 0.5} for i in range(20)],
                        parallelism=4)
    files = ds.write_parquet("s3://bucket/out", filesystem=fake_s3)
    assert len(files) == 4 and all(f.startswith("s3://bucket/out/part-")
                                   for f in files)
    back = rtd.read_parquet("s3://bucket/out", filesystem=fake_s3)
    assert sorted(r["i"] for r in back.take_all()) == list(range(20))

    jl = ds.write_json("s3://bucket/jl", filesystem=fake_s3)
    assert jl and all(f.endswith(".jsonl") for f in jl)
    back2 = rtd.read_json("s3://bucket/jl", filesystem=fake_s3)
    assert back2.count() == 20

    # file:// URIs resolve with no injection at all.
    local = ds.write_csv("file://" + str(tmp_path / "csvs"))
    assert local
    back3 = rtd.read_csv(str(tmp_path / "csvs"))
    assert back3.count() == 20


def test_read_images_decodes_and_resizes(rt_start, tmp_path):
    """read_images decodes in the read tasks: {"path", "image"} HWC uint8
    rows, with resize + mode conversion (reference: read_images)."""
    from PIL import Image

    from ray_tpu import data as rt_data

    for i, color in enumerate([(255, 0, 0), (0, 255, 0), (0, 0, 255)]):
        Image.new("RGB", (8, 6), color).save(tmp_path / f"im{i}.png")
    (tmp_path / "notes.txt").write_text("not an image")

    ds = rt_data.read_images(str(tmp_path), size=(4, 4), mode="RGB")
    rows = sorted(ds.take_all(), key=lambda r: r["path"])
    assert len(rows) == 3  # the .txt is filtered out
    for r, color in zip(rows, [(255, 0, 0), (0, 255, 0), (0, 0, 255)]):
        img = np.asarray(r["image"])
        assert img.shape == (4, 4, 3) and img.dtype == np.uint8
        assert tuple(img[0, 0]) == color


def test_read_numpy_roundtrip(rt_start, tmp_path):
    from ray_tpu import data as rt_data

    a = np.arange(12, dtype=np.float32).reshape(3, 4)
    b = np.ones((2, 2), dtype=np.int64)
    np.save(tmp_path / "a.npy", a)
    np.save(tmp_path / "b.npy", b)

    ds = rt_data.read_numpy(str(tmp_path))
    rows = {r["path"].split("/")[-1]: r["data"] for r in ds.take_all()}
    assert np.array_equal(np.asarray(rows["a.npy"]), a)
    assert np.array_equal(np.asarray(rows["b.npy"]), b)


def test_read_images_default_mode_uniform_hwc(rt_start, tmp_path):
    """Mixed source modes (palette GIF + grayscale + RGB) all come back
    (H, W, 3) uint8 under the default mode="RGB"."""
    from PIL import Image

    from ray_tpu import data as rt_data

    Image.new("RGB", (5, 5), (9, 9, 9)).save(tmp_path / "rgb.png")
    Image.new("L", (5, 5), 100).save(tmp_path / "gray.png")
    Image.new("P", (5, 5)).save(tmp_path / "pal.gif")
    rows = rt_data.read_images(str(tmp_path)).take_all()
    assert len(rows) == 3
    for r in rows:
        img = np.asarray(r["image"])
        assert img.shape == (5, 5, 3) and img.dtype == np.uint8


def test_train_test_split(rt_start):
    """(train, test) split from block refs with boundary slicing and
    optional shuffle (reference: Dataset.train_test_split)."""
    from ray_tpu import data as rt_data

    ds = rt_data.range(100)
    train, test = ds.train_test_split(0.2)
    assert train.count() == 80 and test.count() == 20
    # Unshuffled: order preserved, test takes the tail.
    assert [r["id"] for r in test.take_all()] == list(range(80, 100))
    # Shuffled split covers all rows exactly once.
    train_s, test_s = ds.train_test_split(30, shuffle=True, seed=1)
    ids = [r["id"] for r in train_s.take_all()] + [
        r["id"] for r in test_s.take_all()
    ]
    assert sorted(ids) == list(range(100))
    assert test_s.count() == 30
    import pytest as _pytest

    with _pytest.raises(ValueError):
        ds.train_test_split(1.5)


def test_iter_batches_zero_copy_views(rt_start):
    """The numpy batching path must not copy host->host: every batch
    fully inside one block is a VIEW over the block's arrow buffers as
    restored (zero-copy) from the shared-memory store (SURVEY §7
    "Plasma<->HBM boundary")."""
    import numpy as np

    arr = np.arange(64, dtype=np.float32)
    ds = rtd.from_numpy({"x": arr}, parallelism=2)  # 2 blocks of 32
    # Materialize the store-resident blocks the iterator will read.
    block_cols = [
        rt.get(ref).column("x").to_numpy() for ref in ds._executed_refs()
    ]
    batches = list(ds.iter_batches(batch_size=8, batch_format="numpy"))
    assert sum(len(b["x"]) for b in batches) == 64
    for b in batches:
        col = b["x"]
        assert not col.flags.owndata, "batch column was copied"
        assert any(np.shares_memory(col, blk) for blk in block_cols), (
            "batch column does not alias the store-resident block"
        )


def test_iter_batches_boundary_straddle_and_remainder(rt_start):
    """Batches straddling block boundaries still come out correct (the
    one place the zero-copy path pays a concatenate)."""
    import numpy as np

    arr = np.arange(50, dtype=np.int64)
    ds = rtd.from_numpy({"x": arr}, parallelism=3)  # ragged blocks
    batches = list(ds.iter_batches(batch_size=12, batch_format="numpy"))
    got = np.concatenate([b["x"] for b in batches])
    np.testing.assert_array_equal(np.sort(got), arr)
    assert [len(b["x"]) for b in batches][-1] == 50 % 12 or 50 % 12 == 0


def test_iter_jax_batches_feeds_jitted_consumer(rt_start):
    """Data -> device feed end to end: one copy host->HBM, zero
    host->host, consumed by a jitted reducer."""
    import jax
    import numpy as np

    arr = np.arange(96, dtype=np.float32)
    ds = rtd.from_numpy({"x": arr}, parallelism=2)

    @jax.jit
    def consume(batch):
        return batch["x"].sum()

    total = 0.0
    for batch in ds.iter_jax_batches(batch_size=16):
        total += float(consume(batch))
    assert total == float(arr.sum())
