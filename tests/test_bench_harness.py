"""Bench supervisor harness tests (no TPU, no jax): JSON-line parsing
and the cached live-TPU artifact gate (bench.py phases)."""

import json
import time

import bench


def test_last_json_line_parses_tail():
    text = "noise\n{broken\n" + json.dumps({"a": 1}) + "\n[bench] done\n"
    assert bench._last_json_line(text) == {"a": 1}
    assert bench._last_json_line("no json here") is None


def _write_live(tmp_path, device="TPU_0(process=0)", age_s=60.0,
                measured_at=None):
    p = tmp_path / "BENCH_TPU_LIVE.json"
    stamp = measured_at or time.strftime(
        "%Y-%m-%dT%H:%M:%SZ", time.gmtime(time.time() - age_s)
    )
    p.write_text(json.dumps({
        "metric": "llama2(0.8B) train-step tokens/s/chip",
        "value": 12345.0,
        "vs_baseline": 1.5,
        "device": device,
        "measured_at": stamp,
    }))
    return str(p)


def test_live_artifact_fresh_tpu_is_labeled_cached(tmp_path):
    path = _write_live(tmp_path, age_s=3600)
    live = bench.load_live_artifact(path, max_age=14 * 3600)
    assert live is not None
    assert live["cached"] is True
    assert "tpu_live.py" in live["cache_note"]
    assert live["value"] == 12345.0


def test_live_artifact_stale_is_rejected(tmp_path):
    """An artifact older than the round window (e.g. committed last
    round) must never be replayed as this round's number."""
    path = _write_live(tmp_path, age_s=20 * 3600)
    assert bench.load_live_artifact(path, max_age=14 * 3600) is None
    # Future timestamps (clock skew) are rejected too.
    path = _write_live(tmp_path, age_s=-3600)
    assert bench.load_live_artifact(path, max_age=14 * 3600) is None


def test_live_artifact_non_tpu_is_rejected(tmp_path):
    path = _write_live(tmp_path, device="TFRT_CPU_0")
    assert bench.load_live_artifact(path) is None


def test_live_artifact_garbage_is_rejected(tmp_path):
    p = tmp_path / "BENCH_TPU_LIVE.json"
    p.write_text("{not json")
    assert bench.load_live_artifact(str(p)) is None
    p.write_text(json.dumps({"device": "TPU_0"}))  # no timestamp
    assert bench.load_live_artifact(str(p)) is None
    assert bench.load_live_artifact(str(tmp_path / "missing.json")) is None


def test_doc_claims_match_artifacts():
    """Every perf number quoted in README/COMPONENTS must match its
    committed JSON artifact (the doc/artifact drift the round-3 and
    round-4 verdicts both flagged). tools/check_claims.py owns the
    claim registry; this keeps the suite red on stale numbers."""
    import os
    import sys

    tools = os.path.join(os.path.dirname(__file__), "..", "tools")
    sys.path.insert(0, tools)
    try:
        from check_claims import check_all
    finally:
        sys.path.remove(tools)
    problems = check_all()
    assert not problems, "stale doc claims:\n" + "\n".join(problems)
