"""Owner-side reference GC: dropping the last ObjectRef frees cluster copies.

Reference analog: the ReferenceCounter-driven plasma free
(core_worker/reference_count.h:61) — when an owned object's ref count hits
zero the owner deletes the primary copy instead of letting it rot until
eviction/spilling.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod


@pytest.fixture
def rt_start():
    rt.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)
    yield
    rt.shutdown()


def _wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return False


def test_del_put_ref_frees_store(rt_start):
    client = worker_mod.get_client()
    ref = rt.put(np.ones(1_000_000))  # 8 MB
    oid = ref.id.binary()
    assert client.store.contains_raw(oid)
    del ref
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid)), (
        "store copy not freed after the last ref died"
    )


def test_del_task_return_frees_store(rt_start):
    client = worker_mod.get_client()

    @rt.remote
    def produce():
        return np.ones(1_000_000)

    ref = produce.remote()
    rt.get(ref)  # materialize in the store
    oid = ref.id.binary()
    assert client.store.contains_raw(oid)
    del ref
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid))


def test_repeated_big_puts_never_fill_store(rt_start):
    """The bench_core regression: 20 x 64MB puts through a 256MB store must
    recycle freed space, not spill or die with ObjectStoreFullError."""
    for i in range(20):
        ref = rt.put(np.full(8_000_000, i, dtype=np.float64))  # 64 MB
        out = rt.get(ref)
        assert out[0] == i
        del out, ref
        gc.collect()


def test_borrowed_arg_not_freed_under_running_task(rt_start):
    """Dropping the driver's ref right after submit must not free the
    argument out from under the running task."""

    @rt.remote
    def consume(arr):
        time.sleep(1.0)  # outlive the driver-side del + flush debounce
        return float(arr.sum())

    ref = rt.put(np.ones(1_000_000))
    out_ref = consume.remote(ref)
    del ref
    gc.collect()
    assert rt.get(out_ref, timeout=60) == 1_000_000.0


def test_freed_object_get_fails(rt_start):
    client = worker_mod.get_client()
    ref = rt.put(np.ones(100_000))
    oid = ref.id.binary()
    # A true borrower copy: NOT the owner's instance from known_refs.
    borrowed = worker_mod.ObjectRef(worker_mod.ObjectID(oid))
    del ref
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid))
    client._in_store.discard(oid)  # the borrower resolves via the cluster
    with pytest.raises((rt.exceptions.ObjectLostError,
                        rt.exceptions.GetTimeoutError)):
        rt.get(borrowed, timeout=5)


# ---------------------------------------------------------------------------
# Nested references (reference_count.h:61 — refs serialized inside
# arguments/returns are promoted to the store and tracked like plasma
# promotions in the reference).
# ---------------------------------------------------------------------------


def test_nested_ref_in_list_arg(rt_start):
    """A ref inside a container arg is promoted; the task resolves it."""

    @rt.remote
    def read_nested(pair):
        tag, inner = pair
        return tag + float(rt.get(inner).sum())

    inner = rt.put(np.ones(1000))
    assert rt.get(read_nested.remote([1.0, inner]), timeout=30) == 1001.0


def test_nested_ref_in_kwarg_dict(rt_start):
    @rt.remote
    def read_cfg(cfg=None):
        return float(rt.get(cfg["data"]).sum())

    inner = rt.put(np.full(10, 2.0))
    assert rt.get(read_cfg.remote(cfg={"data": inner}), timeout=30) == 20.0


def test_ref_returned_inside_container(rt_start):
    """A task returns a container holding a ref it created; the caller
    (now a borrower of a worker-owned object) can resolve it."""

    @rt.remote
    def produce_wrapped():
        return {"inner": rt.put(np.full(100, 7.0))}

    out = rt.get(produce_wrapped.remote(), timeout=30)
    assert float(rt.get(out["inner"], timeout=30).sum()) == 700.0


def test_task_returns_plain_ref(rt_start):
    @rt.remote
    def produce_ref():
        return [rt.put(b"payload")]

    (inner,) = rt.get(produce_ref.remote(), timeout=30)
    assert rt.get(inner, timeout=30) == b"payload"


# ---------------------------------------------------------------------------
# Borrower chains
# ---------------------------------------------------------------------------


def test_borrower_hands_ref_to_second_borrower(rt_start):
    """A -> B chain: the first borrower submits a task with the borrowed
    ref; each hop pins the arg for its own execution."""

    @rt.remote
    def second(arr):
        return float(arr.sum())

    @rt.remote
    def first(arr):
        # arr arrived resolved; re-share it onward as a fresh object.
        return rt.get(second.remote(arr), timeout=30)

    ref = rt.put(np.ones(5000))
    out_ref = first.remote(ref)
    del ref  # driver's handle dies while the chain runs
    gc.collect()
    assert rt.get(out_ref, timeout=60) == 5000.0


def test_borrowed_ref_forwarded_unresolved(rt_start):
    """The borrower forwards the REF (not the value) to a second task."""

    @rt.remote
    def reader(wrapped):
        return float(rt.get(wrapped["r"], timeout=30).sum())

    @rt.remote
    def forwarder(wrapped):
        return rt.get(reader.remote(wrapped), timeout=30)

    inner = rt.put(np.full(100, 3.0))
    out = forwarder.remote({"r": inner})
    res = rt.get(out, timeout=60)
    assert res == 300.0


def test_same_ref_to_two_concurrent_tasks(rt_start):
    @rt.remote
    def consume(arr):
        time.sleep(0.3)
        return float(arr.sum())

    ref = rt.put(np.ones(2000))
    a = consume.remote(ref)
    b = consume.remote(ref)
    del ref
    gc.collect()
    assert rt.get(a, timeout=60) == 2000.0
    assert rt.get(b, timeout=60) == 2000.0


def test_actor_borrows_arg_during_call(rt_start):
    @rt.remote
    class Reader:
        def read(self, arr):
            time.sleep(0.5)
            return float(arr.sum())

    r = Reader.remote()
    ref = rt.put(np.ones(3000))
    out = r.read.remote(ref)
    del ref
    gc.collect()
    assert rt.get(out, timeout=60) == 3000.0


# ---------------------------------------------------------------------------
# Owner death while a borrower holds a handle
# ---------------------------------------------------------------------------


def test_store_copy_survives_owner_actor_kill(rt_start):
    """The primary copy lives in the node's shared store, not the owner
    process: killing the owning actor must not invalidate a copy a
    borrower already holds a handle to (availability under owner death;
    reference: OBJECT_UNRECONSTRUCTABLE only once copies are gone)."""

    @rt.remote
    class Owner:
        def make(self):
            return rt.put(np.full(100, 9.0))

    o = Owner.remote()
    inner = rt.get(o.make.remote(), timeout=30)
    assert float(rt.get(inner, timeout=30).sum()) == 900.0
    rt.kill(o)
    time.sleep(0.5)
    # Borrowed handle still resolves from the store copy.
    assert float(rt.get(inner, timeout=30).sum()) == 900.0


# ---------------------------------------------------------------------------
# Lineage reconstruction
# ---------------------------------------------------------------------------


def test_lineage_reexecutes_lost_task_result(rt_start):
    """All copies of a task return are lost -> the owner re-executes the
    creating task from lineage (task_manager.cc lineage reconstruction)."""
    client = worker_mod.get_client()

    @rt.remote
    def produce():
        return np.full(50_000, 4.0)

    ref = produce.remote()
    rt.get(ref, timeout=30)
    oid = ref.id.binary()
    assert client.store.contains_raw(oid)
    # Simulate losing every copy: drop it from the store + local caches.
    client.store.delete(worker_mod.ObjectID(oid))
    client._in_store.discard(oid)
    client.memory_store.pop(oid, None)
    out = rt.get(ref, timeout=60)
    assert float(out.sum()) == 200_000.0


def test_lineage_reexec_with_ref_arg(rt_start):
    """Reconstruction of a task whose argument is itself a ref."""
    client = worker_mod.get_client()

    @rt.remote
    def double(arr):
        return arr * 2.0

    base = rt.put(np.full(20_000, 3.0))
    ref = double.remote(base)
    rt.get(ref, timeout=30)
    oid = ref.id.binary()
    client.store.delete(worker_mod.ObjectID(oid))
    client._in_store.discard(oid)
    client.memory_store.pop(oid, None)
    out = rt.get(ref, timeout=60)
    assert float(out.sum()) == 120_000.0
    del base


# ---------------------------------------------------------------------------
# Counts under retries
# ---------------------------------------------------------------------------


def test_borrow_survives_worker_crash_retry(rt_start):
    """First attempt SIGKILLs its worker; the retry still finds the
    borrowed argument alive even though the driver dropped its handle."""

    @rt.remote(max_retries=2)
    def crash_once(arr, marker):
        import os

        key = b"crashed:" + marker
        client = worker_mod.get_client()
        if client.kv_get(key) is None:
            client.kv_put(key, b"1")
            os.kill(os.getpid(), 9)
        return float(arr.sum())

    ref = rt.put(np.ones(1000))
    out = crash_once.remote(ref, b"t1")
    del ref
    gc.collect()
    assert rt.get(out, timeout=90) == 1000.0


def test_retry_failure_releases_borrow_pins(rt_start):
    """After an exhausted-retries failure the argument is freed once the
    driver handle dies too (no leaked pins)."""
    client = worker_mod.get_client()

    @rt.remote(max_retries=0)
    def boom(arr):
        raise ValueError("no")

    ref = rt.put(np.ones(500_000))
    oid = ref.id.binary()
    out = boom.remote(ref)
    with pytest.raises(rt.exceptions.TaskError):
        rt.get(out, timeout=30)
    del ref, out
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid)), (
        "failed-task argument pin leaked"
    )


# ---------------------------------------------------------------------------
# Bulk / idempotence
# ---------------------------------------------------------------------------


def test_many_refs_all_freed(rt_start):
    client = worker_mod.get_client()
    oids = []
    refs = []
    for i in range(50):
        r = rt.put(np.full(20_000, float(i)))
        oids.append(r.id.binary())
        refs.append(r)
        del r  # the loop variable must not keep the last object alive
    assert all(client.store.contains_raw(o) for o in oids)
    refs.clear()
    gc.collect()
    assert _wait_for(
        lambda: not any(client.store.contains_raw(o) for o in oids), 20.0
    ), "bulk ref drop left store copies behind"


def test_borrowed_copy_does_not_double_free(rt_start):
    """Deleting a borrower's handle must not free the owner's object."""
    client = worker_mod.get_client()
    ref = rt.put(np.ones(200_000))
    oid = ref.id.binary()
    borrowed = worker_mod.ObjectRef(worker_mod.ObjectID(oid))
    del borrowed
    gc.collect()
    time.sleep(0.5)
    assert client.store.contains_raw(oid), (
        "borrower's del freed the owner's object"
    )
    assert float(rt.get(ref, timeout=10).sum()) == 200_000.0


def test_wait_does_not_leak_pins(rt_start):
    client = worker_mod.get_client()

    @rt.remote
    def produce():
        return np.ones(200_000)

    refs = [produce.remote() for _ in range(4)]
    done, pending = rt.wait(refs, num_returns=4, timeout=60)
    assert len(done) == 4 and not pending
    oids = [r.id.binary() for r in refs]
    refs.clear()
    done.clear()
    gc.collect()
    assert _wait_for(
        lambda: not any(client.store.contains_raw(o) for o in oids), 20.0
    )


def test_get_mixed_inline_and_store(rt_start):
    @rt.remote
    def small():
        return 7  # inline return

    @rt.remote
    def big():
        return np.ones(500_000)  # store return

    s, b = rt.get([small.remote(), big.remote()], timeout=60)
    assert s == 7 and float(b.sum()) == 500_000.0


def test_ref_in_closure_of_second_task(rt_start):
    ref = rt.put(np.full(100, 5.0))

    @rt.remote
    def via_closure():
        return float(rt.get(ref, timeout=30).sum())

    out = rt.get(via_closure.remote(), timeout=60)
    assert out == 500.0


def test_nested_ref_pinned_when_driver_drops_handle(rt_start):
    """A ref nested INSIDE a container argument is borrow-pinned like a
    top-level dep: the driver dropping its handle right after submit must
    not free the object under the running task (reference_count.h nested
    ref tracking)."""

    @rt.remote
    def read_nested(wrapped):
        time.sleep(0.8)  # outlive the driver-side del + free debounce
        return float(rt.get(wrapped["data"], timeout=30).sum())

    inner = rt.put(np.ones(300_000))
    out = read_nested.remote({"data": inner})
    del inner
    gc.collect()
    assert rt.get(out, timeout=60) == 300_000.0


def test_actor_ctor_nested_ref_pinned(rt_start):
    """Constructor args with nested refs are pinned until the actor is
    live: the driver dropping its handle right after Actor.remote() must
    not free the arg before __init__ resolves it."""

    @rt.remote
    class Holder:
        def __init__(self, wrapped):
            self.total = float(rt.get(wrapped["data"], timeout=30).sum())

        def total_(self):
            return self.total

    inner = rt.put(np.ones(200_000))
    h = Holder.remote({"data": inner})
    del inner
    gc.collect()
    assert rt.get(h.total_.remote(), timeout=60) == 200_000.0


def test_restartable_actor_ctor_args_survive_restart(rt_start):
    """A restartable actor's ctor args stay pinned past first ALIVE: the
    GCS replays create_spec on restart, and the replayed __init__ must
    still resolve nested refs the driver dropped long ago."""
    import os

    @rt.remote
    class Phoenix:
        def __init__(self, wrapped):
            self.total = float(rt.get(wrapped["data"], timeout=30).sum())

        def total_(self):
            return self.total

        def die(self):
            os._exit(1)

    inner = rt.put(np.full(150_000, 2.0))
    p = Phoenix.options(max_restarts=1).remote({"data": inner})
    assert rt.get(p.total_.remote(), timeout=60) == 300_000.0
    del inner
    gc.collect()
    time.sleep(0.5)  # free debounce window: pins must hold the object
    try:
        rt.get(p.die.remote(), timeout=30)
    except Exception:
        pass
    # The restarted __init__ replays the create_spec and re-reads the arg.
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        try:
            assert rt.get(p.total_.remote(), timeout=30) == 300_000.0
            break
        except Exception:
            time.sleep(0.5)
    else:
        import pytest as _pytest

        _pytest.fail("restarted actor could not re-resolve its ctor arg")
