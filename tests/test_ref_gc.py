"""Owner-side reference GC: dropping the last ObjectRef frees cluster copies.

Reference analog: the ReferenceCounter-driven plasma free
(core_worker/reference_count.h:61) — when an owned object's ref count hits
zero the owner deletes the primary copy instead of letting it rot until
eviction/spilling.
"""

import gc
import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod


@pytest.fixture
def rt_start():
    rt.init(num_cpus=2, object_store_memory=256 * 1024 * 1024)
    yield
    rt.shutdown()


def _wait_for(cond, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(0.1)
    return False


def test_del_put_ref_frees_store(rt_start):
    client = worker_mod.get_client()
    ref = rt.put(np.ones(1_000_000))  # 8 MB
    oid = ref.id.binary()
    assert client.store.contains_raw(oid)
    del ref
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid)), (
        "store copy not freed after the last ref died"
    )


def test_del_task_return_frees_store(rt_start):
    client = worker_mod.get_client()

    @rt.remote
    def produce():
        return np.ones(1_000_000)

    ref = produce.remote()
    rt.get(ref)  # materialize in the store
    oid = ref.id.binary()
    assert client.store.contains_raw(oid)
    del ref
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid))


def test_repeated_big_puts_never_fill_store(rt_start):
    """The bench_core regression: 20 x 64MB puts through a 256MB store must
    recycle freed space, not spill or die with ObjectStoreFullError."""
    for i in range(20):
        ref = rt.put(np.full(8_000_000, i, dtype=np.float64))  # 64 MB
        out = rt.get(ref)
        assert out[0] == i
        del out, ref
        gc.collect()


def test_borrowed_arg_not_freed_under_running_task(rt_start):
    """Dropping the driver's ref right after submit must not free the
    argument out from under the running task."""

    @rt.remote
    def consume(arr):
        time.sleep(1.0)  # outlive the driver-side del + flush debounce
        return float(arr.sum())

    ref = rt.put(np.ones(1_000_000))
    out_ref = consume.remote(ref)
    del ref
    gc.collect()
    assert rt.get(out_ref, timeout=60) == 1_000_000.0


def test_freed_object_get_fails(rt_start):
    client = worker_mod.get_client()
    ref = rt.put(np.ones(100_000))
    oid = ref.id.binary()
    # A true borrower copy: NOT the owner's instance from known_refs.
    borrowed = worker_mod.ObjectRef(worker_mod.ObjectID(oid))
    del ref
    gc.collect()
    assert _wait_for(lambda: not client.store.contains_raw(oid))
    client._in_store.discard(oid)  # the borrower resolves via the cluster
    with pytest.raises((rt.exceptions.ObjectLostError,
                        rt.exceptions.GetTimeoutError)):
        rt.get(borrowed, timeout=5)
