"""Gang fault tolerance tests: elastic restart, epoch fencing, hang-proof
DCN collectives, proactive drain migration.

Modeled on the reference's train fault-tolerance suites
(python/ray/train/tests/test_backend.py worker-failure cases +
test_tune_torch_get_device_gpu restart paths), using the shared
fault-injection API in ray_tpu._private.chaos instead of hand-rolled kill
threads. Everything is deterministic: faults fire at caller-chosen steps
via chaos.once() markers, never on timers.
"""

import socket
import struct
import time

import pytest

from ray_tpu.exceptions import CollectiveTimeoutError
from ray_tpu.train import (
    CheckpointConfig,
    FailureConfig,
    JaxConfig,
    JaxTrainer,
    RunConfig,
    ScalingConfig,
)


# -- tentpole acceptance: rank death mid-training --------------------------
def _die_once_loop(config):
    import os
    import time

    from ray_tpu import train
    from ray_tpu._private import chaos
    from ray_tpu.train import Checkpoint

    with open(os.path.join(config["dir"], "attempts.log"), "a") as f:
        f.write(f"rank{train.get_world_rank()}\n")
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start = ckpt.to_dict()["step"] + 1
    for step in range(start, config["steps"]):
        if train.get_world_rank() == 0:
            train.report({"step": step},
                         checkpoint=Checkpoint.from_dict({"step": step}))
        else:
            train.report({"step": step})
        # Give the driver's 50ms poll loop time to drain the report (and
        # register the checkpoint) before anything can kill this rank.
        time.sleep(0.12)
        if (train.get_world_rank() == 0 and step == config["die_at"]
                and chaos.once(config["dir"], "rank0_death")):
            chaos.enable()
            chaos.die()  # SIGKILL-style: no cleanup, no goodbye


@pytest.mark.chaos
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_rank_death_resumes_from_checkpoint(rt_start, tmp_path):
    """A rank hard-killed mid-training is detected, the gang restarts,
    and training resumes from the newest checkpoint — not from scratch."""
    trainer = JaxTrainer(
        _die_once_loop,
        train_loop_config={"dir": str(tmp_path), "steps": 6, "die_at": 3},
        jax_config=JaxConfig(dp_sync="none"),
        scaling_config=ScalingConfig(num_workers=2),
        run_config=RunConfig(
            name="ft", storage_path=str(tmp_path),
            checkpoint_config=CheckpointConfig(num_to_keep=2),
            failure_config=FailureConfig(max_failures=2, backoff_s=0.05,
                                         backoff_max_s=0.2),
        ),
    )
    result = trainer.fit()
    assert result.error is None
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 5
    # Resumed from the checkpoint, not from zero: step 0 ran exactly once.
    assert steps.count(0) == 1, steps
    # The gang actually restarted: both ranks started twice.
    attempts = (tmp_path / "attempts.log").read_text().splitlines()
    assert sorted(attempts) == ["rank0", "rank0", "rank1", "rank1"], attempts


# -- epoch fencing ---------------------------------------------------------
class FakeKV:
    """The kv_put/kv_get/kv_del slice of the core client, in-memory."""

    def __init__(self):
        self._d = {}

    def kv_put(self, key, value, ns=""):
        self._d[(ns, key)] = value

    def kv_get(self, key, ns=""):
        return self._d.get((ns, key))

    def kv_del(self, key, ns=""):
        self._d.pop((ns, key), None)


def test_gang_epoch_rejects_stale_rank():
    """A zombie rank from a torn-down attempt can neither find the new
    ring in the KV (epoch-stamped rendezvous keys) nor pass the
    identification handshake (epoch-stamped ident frame)."""
    from ray_tpu.util.collective.dcn_group import _IDENT, _LEN, DcnGroup

    kv = FakeKV()
    fresh = DcnGroup(kv, 2, 0, "fence", timeout=0.5, epoch=1)
    stale = DcnGroup(kv, 2, 1, "fence", timeout=0.3, epoch=0)
    try:
        # Rendezvous fence: the stale rank looks up epoch-0 keys that the
        # epoch-1 gang never wrote.
        with pytest.raises(TimeoutError):
            stale._peer_out(0)

        # Handshake fence: even told the new address out-of-band, the
        # stale epoch in the ident frame gets the socket closed.
        s = socket.create_connection(tuple(fresh.addr), timeout=2)
        ident = _IDENT.pack(1, 0, 0, 0)  # rank 1, stale epoch 0, null HLC
        s.sendall(_LEN.pack(len(ident)) + ident)
        with pytest.raises(CollectiveTimeoutError):
            fresh._peer_in(1)
        s.close()

        # Control: the correct epoch is accepted.
        s2 = socket.create_connection(tuple(fresh.addr), timeout=2)
        ident = _IDENT.pack(1, 1, 0, 0)
        s2.sendall(_LEN.pack(len(ident)) + ident)
        assert fresh._peer_in(1) is not None
        s2.close()
    finally:
        fresh.destroy()
        stale.destroy()


# -- hang-proof collectives ------------------------------------------------
def test_dcn_recv_timeout_raises_instead_of_hanging():
    """A peer that connects and then goes silent (preempted host) trips
    the per-op socket deadline as a typed CollectiveTimeoutError rather
    than blocking the surviving rank forever."""
    from ray_tpu.util.collective.dcn_group import DcnGroup

    kv = FakeKV()
    g0 = DcnGroup(kv, 2, 0, "hang", timeout=2.0, epoch=0, op_timeout=0.5)
    g1 = DcnGroup(kv, 2, 1, "hang", timeout=2.0, epoch=0, op_timeout=0.5)
    try:
        g1._peer_out(0)  # connect + identify, then never send anything
        t0 = time.monotonic()
        with pytest.raises(CollectiveTimeoutError) as exc:
            g0.recv(1)
        elapsed = time.monotonic() - t0
        assert 0.3 <= elapsed < 5.0, elapsed
        assert exc.value.peer_rank == 1
        assert exc.value.group_name == "hang"
    finally:
        g0.destroy()
        g1.destroy()


# -- proactive drain migration ---------------------------------------------
def _drain_aware_loop(config):
    import os
    import time

    from ray_tpu import train
    from ray_tpu.train import Checkpoint

    with open(os.path.join(config["dir"], "attempts.log"), "a") as f:
        f.write("start\n")
    start = 0
    ckpt = train.get_checkpoint()
    if ckpt is not None:
        start = ckpt.to_dict()["step"] + 1
    for step in range(start, config["steps"]):
        train.report({"step": step},
                     checkpoint=Checkpoint.from_dict({"step": step}))
        if train.should_stop():
            return  # checkpointed above; migrate with zero lost work
        time.sleep(0.12)


@pytest.mark.chaos
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_drain_triggers_proactive_checkpoint_and_restart(rt_start, tmp_path):
    """A drain notice makes the trainer request a checkpoint-and-stop,
    then restart the gang — moving BEFORE preemption kills the host."""
    from ray_tpu._private import chaos

    chaos.enable()
    try:
        chaos.inject_drain([0])
        trainer = JaxTrainer(
            _drain_aware_loop,
            train_loop_config={"dir": str(tmp_path), "steps": 6},
            scaling_config=ScalingConfig(num_workers=1),
            run_config=RunConfig(
                name="drain", storage_path=str(tmp_path),
                failure_config=FailureConfig(max_failures=1, backoff_s=0.05,
                                             backoff_max_s=0.2),
            ),
        )
        result = trainer.fit()
    finally:
        chaos.disable()
    assert result.error is None
    steps = [m["step"] for m in result.metrics_history]
    assert steps[-1] == 5
    assert steps.count(0) == 1, steps  # resumed, not restarted from zero
    # The drain really interrupted attempt 1: the loop started twice.
    attempts = (tmp_path / "attempts.log").read_text().splitlines()
    assert len(attempts) == 2, attempts


# -- fail-fast + metrics preservation --------------------------------------
def _report_then_fail_loop(config):
    from ray_tpu import train

    train.report({"step": 0, "loss": 1.0})
    train.report({"step": 1, "loss": 0.5})
    raise RuntimeError("unrecoverable user error")


@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_max_failures_zero_fails_fast_with_metrics(rt_start, tmp_path):
    """max_failures=0 surfaces the first failure without restarting, and
    the Result still carries everything reported before the failure
    (previously it returned Result(metrics={}))."""
    from ray_tpu.train import TrainingFailedError

    trainer = JaxTrainer(
        _report_then_fail_loop,
        scaling_config=ScalingConfig(num_workers=1),
        run_config=RunConfig(
            name="fff", storage_path=str(tmp_path),
            failure_config=FailureConfig(max_failures=0),
        ),
    )
    result = trainer.fit()
    assert isinstance(result.error, TrainingFailedError)
    assert result.error.failed_ranks == [0]
    assert "unrecoverable user error" in str(result.error)
    assert result.metrics == {"step": 1, "loss": 0.5}
    assert [m["step"] for m in result.metrics_history] == [0, 1]
    # Only one attempt ran: no restart consumed the failure budget.
    attempts = (tmp_path / "fff").exists()
    assert attempts


def test_failure_config_backoff_schedule():
    fc = FailureConfig(backoff_s=0.5, backoff_max_s=4.0)
    assert [fc.backoff_for_attempt(a) for a in range(5)] == \
        [0.5, 1.0, 2.0, 4.0, 4.0]
    assert FailureConfig(backoff_s=0).backoff_for_attempt(3) == 0.0
