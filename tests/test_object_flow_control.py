"""Object-manager flow control (VERDICT r2 item 7).

Reference analogs: PullManager's prioritized memory-quota admission
(object_manager/pull_manager.h:52) and PushManager's in-flight chunk
throttling (push_manager.h:30).
"""

import asyncio

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu._private.raylet import _PullByteBudget
from ray_tpu.cluster_utils import Cluster


def _run(coro):
    loop = asyncio.new_event_loop()
    try:
        return loop.run_until_complete(coro)
    finally:
        loop.close()


def test_budget_admits_until_full_then_blocks():
    async def body():
        b = _PullByteBudget(100)
        await b.acquire(60)
        await b.acquire(40)  # exactly full
        waiter = asyncio.ensure_future(b.acquire(10))
        await asyncio.sleep(0.01)
        assert not waiter.done(), "over-budget pull was admitted"
        b.release(60)
        await asyncio.wait_for(waiter, 1)
        assert b.in_use == 50

    _run(body())


def test_budget_oversized_object_proceeds_alone():
    async def body():
        b = _PullByteBudget(100)
        await b.acquire(1000)  # bigger than the whole budget: runs alone
        waiter = asyncio.ensure_future(b.acquire(10))
        await asyncio.sleep(0.01)
        assert not waiter.done()
        b.release(1000)
        await asyncio.wait_for(waiter, 1)

    _run(body())


def test_budget_wakes_smallest_first():
    async def body():
        b = _PullByteBudget(100)
        await b.acquire(100)
        big = asyncio.ensure_future(b.acquire(90))
        await asyncio.sleep(0)  # enqueue in submission order
        small = asyncio.ensure_future(b.acquire(10))
        await asyncio.sleep(0.01)
        b.release(100)
        await asyncio.sleep(0.01)
        # The small pull is admitted ahead of the earlier-queued big one
        # while both fit... only 10+90=100 fits too; smallest went first.
        assert small.done(), "small pull starved behind big one"
        await asyncio.wait_for(big, 1)

    _run(body())


def test_budget_release_wakes_multiple():
    async def body():
        b = _PullByteBudget(100)
        await b.acquire(100)
        waiters = [asyncio.ensure_future(b.acquire(25)) for _ in range(4)]
        await asyncio.sleep(0.01)
        assert not any(w.done() for w in waiters)
        b.release(100)
        await asyncio.wait_for(asyncio.gather(*waiters), 1)
        assert b.in_use == 100

    _run(body())


def test_cross_node_broadcast_under_flow_control():
    """A ~48MB object broadcast to two other nodes: chunked pulls ride
    the byte budget + push chunk caps and arrive intact."""
    cluster = Cluster()
    n1 = cluster.add_node(num_cpus=1, object_store_memory=256 << 20)
    cluster.add_node(num_cpus=1, object_store_memory=256 << 20)
    cluster.add_node(num_cpus=1, object_store_memory=256 << 20)
    cluster.connect()
    try:
        from ray_tpu.util.scheduling_strategies import (
            NodeAffinitySchedulingStrategy,
        )

        blob = np.arange(6_000_000, dtype=np.float64)  # 48 MB
        ref = rt.put(blob)

        @rt.remote
        def checksum(x):
            return float(x.sum())

        expected = float(blob.sum())
        nodes = [n.node_id.binary() for n in cluster.raylets[1:]]
        outs = rt.get(
            [
                checksum.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=nid
                    )
                ).remote(ref)
                for nid in nodes
            ],
            timeout=300,
        )
        assert outs == [expected, expected]
    finally:
        cluster.shutdown()


def test_broadcast_chain_tcp_path(monkeypatch):
    """Multi-consumer broadcast over the TCP pull path (same-host shm
    shortcut disabled): every consumer sees exact bytes while pullers may
    chain off in-progress partial copies (VERDICT r3 item 7; reference:
    object_manager.cc:339 any-holder pulls)."""
    import ray_tpu._private.config as config_mod
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    monkeypatch.setenv("RT_SAME_HOST_SHM_TRANSFER", "0")
    config_mod._config = None
    cluster = Cluster()
    cluster.add_node(num_cpus=1, object_store_memory=256 * 1024 * 1024)
    for _ in range(3):
        cluster.add_node(num_cpus=1, object_store_memory=256 * 1024 * 1024)
    cluster.connect()
    try:
        rng = np.random.default_rng(7)
        payload = rng.standard_normal(4_000_000)  # 32MB
        ref = rt.put(payload)

        @rt.remote
        def digest(x):
            return float(x.sum()), x.nbytes

        outs = rt.get(
            [
                digest.options(
                    scheduling_strategy=NodeAffinitySchedulingStrategy(
                        node_id=r.node_id.binary()
                    )
                ).remote(ref)
                for r in cluster.raylets[1:]
            ],
            timeout=300,
        )
        want = float(payload.sum())
        for s, nbytes in outs:
            assert nbytes == payload.nbytes
            assert abs(s - want) < 1e-6
    finally:
        cluster.shutdown()
        config_mod._config = None


def test_broadcast_same_host_shm_path():
    """Same-machine peers move objects by direct store-to-store memcpy;
    bytes must be exact and the location directory updated."""
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy,
    )

    cluster = Cluster()
    cluster.add_node(num_cpus=1, object_store_memory=256 * 1024 * 1024)
    cluster.add_node(num_cpus=1, object_store_memory=256 * 1024 * 1024)
    cluster.connect()
    try:
        rng = np.random.default_rng(11)
        payload = rng.standard_normal(2_000_000)
        ref = rt.put(payload)

        @rt.remote
        def digest(x):
            return float(x.sum())

        out = rt.get(
            digest.options(
                scheduling_strategy=NodeAffinitySchedulingStrategy(
                    node_id=cluster.raylets[1].node_id.binary()
                )
            ).remote(ref),
            timeout=120,
        )
        assert abs(out - float(payload.sum())) < 1e-6
        # The peer's copy is registered: a second consumer on that node
        # reads locally.
        from ray_tpu._private import worker as worker_mod

        client = worker_mod.get_client()
        locs = client._run(client.gcs.call(
            "object_location_get", {"object_id": ref.id.binary()}
        ))
        assert len(locs["nodes"]) == 2
    finally:
        cluster.shutdown()
