"""Store-independent ray_tpu.data unit tests.

test_data.py / test_data_connectors.py pin the rt_start fixture module-
wide (they exercise the distributed path through the shared-memory
store). The codec and batching logic below has no runtime dependency at
all — these tests run even where libray_tpu_store.so cannot load, so
the pure-Python contracts stay covered on every box.
"""

import numpy as np
import pytest


def test_encode_example_accepts_numpy_scalars_and_arrays():
    """map() outputs on the list-of-rows block path carry np.int64 /
    np.float32 / np.ndarray values straight into the TFRecord sink;
    encode_example must normalize them to the Python equivalents the
    Arrow path gets from to_pylist (connectors.py encode_example) —
    and produce the IDENTICAL wire bytes."""
    from ray_tpu.data.connectors import decode_example, encode_example

    plain = {
        "label": 7,
        "score": 0.25,
        "ids": [1, 2, 300000],
        "weights": [0.5, 1.5],
        "name": b"cat",
    }
    numpyed = {
        "label": np.int64(7),
        "score": np.float32(0.25),
        "ids": np.array([1, 2, 300000], dtype=np.int64),
        "weights": [np.float32(0.5), np.float64(1.5)],
        "name": np.bytes_(b"cat"),
    }
    assert encode_example(numpyed) == encode_example(plain)
    decoded = decode_example(encode_example(numpyed))
    assert decoded["label"] == [7]
    assert decoded["ids"] == [1, 2, 300000]
    assert decoded["name"] == [b"cat"]
    np.testing.assert_allclose(decoded["weights"], [0.5, 1.5], rtol=1e-6)
    # np.bool_ rides the int64 branch like Python bool.
    assert decode_example(encode_example({"flag": np.bool_(True)}))[
        "flag"
    ] == [1]
    # Unsupported dtypes still fail loudly, post-normalization.
    with pytest.raises(TypeError):
        encode_example({"bad": object()})


def test_iter_numpy_batches_schema_mismatch_is_diagnosed():
    """A batch straddling blocks with DIFFERENT column sets must fail
    with a ValueError naming both schemas, not a bare KeyError from the
    carry-merge concatenate (dataset.py _iter_numpy_batches). Blocks
    are injected directly so the straddle is guaranteed: batch_size 8
    over two 5-row blocks forces a carry across the boundary."""
    import pyarrow as pa

    from ray_tpu.data.dataset import Dataset

    blocks = [
        pa.table({"x": list(range(5))}),
        pa.table({"y": list(range(5))}),
    ]
    ds = Dataset.__new__(Dataset)
    ds._iter_blocks = lambda prefetch_blocks=0: iter(blocks)
    with pytest.raises(ValueError, match="schema mismatch across blocks"):
        list(ds._iter_numpy_batches(batch_size=8, prefetch_blocks=0))
    # Same column sets, same straddle: concatenates fine.
    ok = [
        pa.table({"x": list(range(5))}),
        pa.table({"x": list(range(5, 10))}),
    ]
    ds._iter_blocks = lambda prefetch_blocks=0: iter(ok)
    batches = list(ds._iter_numpy_batches(batch_size=8, prefetch_blocks=0))
    assert [len(b["x"]) for b in batches] == [8, 2]
    assert list(batches[0]["x"]) == list(range(8))
