"""Kernel tests: pallas kernels run in interpret mode on CPU; fallbacks
checked against straightforward references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (
    apply_rope,
    flash_attention,
    rmsnorm,
    rope_frequencies,
    softmax_cross_entropy,
)
from ray_tpu.parallel.ring_attention import reference_attention

pytestmark = pytest.mark.slow  # jax-compile-heavy compute-path tier


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_xla_fallback(causal):
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (2, 64, 4, 16)) for kk in jax.random.split(key, 3)
    )
    got = flash_attention(q, k, v, causal=causal, use_pallas=False)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_pallas_interpret(causal):
    key = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(kk, (1, 128, 2, 32)) for kk in jax.random.split(key, 3)
    )
    got = flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32, interpret=True,
        use_pallas=True,
    )
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_pallas_grads(causal):
    """The round-1 bench died on a missing Pallas VJP — this pins grad
    parity of the Pallas backward (interpret mode) against the XLA path so
    the TPU training path can never silently lose its backward again."""
    key = jax.random.PRNGKey(12)
    ks = jax.random.split(key, 3)
    q = jax.random.normal(ks[0], (2, 96, 2, 32))
    k = jax.random.normal(ks[1], (2, 96, 2, 32))
    v = jax.random.normal(ks[2], (2, 96, 2, 32))

    def loss_pallas(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                              interpret=True, use_pallas=True)
        return (out ** 2).sum()

    def loss_xla(q, k, v):
        return (flash_attention(q, k, v, causal=causal, use_pallas=False) ** 2).sum()

    lp, gp = jax.value_and_grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    lx, gx = jax.value_and_grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(lp), float(lx), rtol=2e-4)
    for a, b, name in zip(gp, gx, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-3, err_msg=f"d{name}")


@pytest.mark.parametrize("causal,lq,lk", [(True, 80, 80), (False, 80, 112),
                                          (False, 96, 80)])
def test_flash_attention_pallas_nondivisible_blocks(causal, lq, lk):
    """Sequence lengths not divisible by the block sizes: the kernels pad
    to the block grid and mask beyond the true lengths (review finding:
    interior pl.ds clamping double-counted edge rows)."""
    ks = jax.random.split(jax.random.PRNGKey(20), 3)
    q = jax.random.normal(ks[0], (1, lq, 2, 32))
    k = jax.random.normal(ks[1], (1, lk, 2, 32))
    v = jax.random.normal(ks[2], (1, lk, 2, 32))

    def loss_pallas(q, k, v):
        out = flash_attention(q, k, v, causal=causal, block_q=32, block_k=32,
                              interpret=True, use_pallas=True)
        return (out ** 2).sum()

    def loss_xla(q, k, v):
        return (flash_attention(q, k, v, causal=causal, use_pallas=False) ** 2).sum()

    lp, gp = jax.value_and_grad(loss_pallas, argnums=(0, 1, 2))(q, k, v)
    lx, gx = jax.value_and_grad(loss_xla, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(float(lp), float(lx), rtol=2e-4)
    for a, b, name in zip(gp, gx, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-3, err_msg=f"d{name}")


def test_flash_attention_pallas_grads_uneven_kv():
    """Cross-attention shape (Lk != Lq) through the Pallas backward."""
    q = jax.random.normal(jax.random.PRNGKey(13), (1, 64, 2, 16))
    k = jax.random.normal(jax.random.PRNGKey(14), (1, 128, 2, 16))
    v = jax.random.normal(jax.random.PRNGKey(15), (1, 128, 2, 16))

    def loss(impl):
        def f(q, k, v):
            out = flash_attention(q, k, v, causal=False, block_q=32,
                                  block_k=32, **impl)
            return (out ** 2).sum()
        return f

    gp = jax.grad(loss({"interpret": True, "use_pallas": True}),
                  argnums=(0, 1, 2))(q, k, v)
    gx = jax.grad(loss({"use_pallas": False}), argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gp, gx):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=2e-3,
                                   atol=2e-3)


def test_flash_attention_gqa():
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (1, 32, 8, 16))
    k = jax.random.normal(jax.random.PRNGKey(3), (1, 32, 2, 16))
    v = jax.random.normal(jax.random.PRNGKey(4), (1, 32, 2, 16))
    got = flash_attention(q, k, v, use_pallas=False)
    assert got.shape == (1, 32, 8, 16)


def test_rmsnorm_matches_reference_and_grads():
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 32, 64))
    w = jax.random.normal(jax.random.PRNGKey(6), (64,)) * 0.1 + 1.0

    got = rmsnorm(x, w, use_pallas=False)
    var = (x.astype(jnp.float32) ** 2).mean(-1, keepdims=True)
    expected = x / jnp.sqrt(var + 1e-6) * w
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)

    # Grad parity with autodiff of the reference.
    def loss_custom(x, w):
        return (rmsnorm(x, w, use_pallas=False) ** 2).sum()

    def loss_ref(x, w):
        var = (x.astype(jnp.float32) ** 2).mean(-1, keepdims=True)
        return ((x / jnp.sqrt(var + 1e-6) * w) ** 2).sum()

    gx1, gw1 = jax.grad(loss_custom, argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), rtol=1e-4, atol=1e-4)


def test_rmsnorm_pallas_interpret():
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 64))
    w = jnp.ones((64,))
    got = rmsnorm(x, w, interpret=True, use_pallas=True)
    expected = rmsnorm(x, w, use_pallas=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("rows", [132, 128])
def test_rmsnorm_pallas_grads(rows):
    """Grad parity of the Pallas rmsnorm backward kernel vs the XLA path
    (the flagship model now uses the Pallas path on TPU). rows=132 with
    block_rows=64 leaves a partial tail block — dw must not sum padding."""
    from ray_tpu.ops.rmsnorm import _rmsnorm_pallas

    x = jax.random.normal(jax.random.PRNGKey(16), (4, rows // 4, 64))
    w = jax.random.normal(jax.random.PRNGKey(17), (64,)) * 0.1 + 1.0

    def loss_pallas(x, w):
        return (_rmsnorm_pallas(x, w, 1e-6, block_rows=64,
                                interpret=True) ** 2).sum()

    def loss_xla(x, w):
        return (rmsnorm(x, w, use_pallas=False) ** 2).sum()

    gp = jax.grad(loss_pallas, argnums=(0, 1))(x, w)
    gx = jax.grad(loss_xla, argnums=(0, 1))(x, w)
    for a, b, name in zip(gp, gx, ["dx", "dw"]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3,
                                   atol=1e-3, err_msg=name)


def test_model_grads_through_pallas_interpret():
    """End-to-end: the flagship forward+backward with the Pallas kernels
    forced on (interpret mode) — the exact path bench.py takes on TPU."""
    from dataclasses import replace
    from unittest import mock

    from ray_tpu.models import configs, init_params, loss_fn
    import ray_tpu.models.transformer as tf_mod

    cfg = replace(configs.tiny, d_model=32, d_ff=64, vocab_size=64,
                  n_layers=2, n_heads=2, n_kv_heads=2, max_seq=64,
                  remat=True)
    params = init_params(jax.random.PRNGKey(18), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(19), (2, 33), 0,
                                cfg.vocab_size)

    def fa_forced(q, k, v, **kw):
        kw.update(interpret=True, use_pallas=True)
        return flash_attention(q, k, v, **kw)

    def rn_forced(x, w, eps=1e-6, **kw):
        return rmsnorm(x, w, eps, interpret=True, use_pallas=True)

    with mock.patch.object(tf_mod, "flash_attention", fa_forced), \
         mock.patch.object(tf_mod, "rmsnorm", rn_forced):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    assert jnp.isfinite(loss)
    assert all(jnp.all(jnp.isfinite(g)) for g in jax.tree.leaves(grads))


def test_rope_rotation_preserves_norm():
    cos, sin = rope_frequencies(32, 128)
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 16, 4, 32))
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )


def test_rope_with_positions():
    cos, sin = rope_frequencies(16, 64)
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 8, 2, 16))
    pos = jnp.arange(8)[None, :] + 10
    out_shifted = apply_rope(x, cos, sin, positions=pos)
    assert out_shifted.shape == x.shape
    # Shifted positions differ from default positions.
    out_default = apply_rope(x, cos, sin)
    assert not np.allclose(np.asarray(out_shifted), np.asarray(out_default))


def test_cross_entropy_matches_reference():
    logits = jax.random.normal(jax.random.PRNGKey(10), (4, 100))
    labels = jnp.array([3, 50, 99, 0])
    got = softmax_cross_entropy(logits, labels)
    expected = -jax.nn.log_softmax(logits)[jnp.arange(4), labels]
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_cross_entropy_grad():
    logits = jax.random.normal(jax.random.PRNGKey(11), (4, 50))
    labels = jnp.array([1, 2, 3, 4])

    g1 = jax.grad(lambda x: softmax_cross_entropy(x, labels).sum())(logits)
    g2 = jax.grad(
        lambda x: (-jax.nn.log_softmax(x)[jnp.arange(4), labels]).sum()
    )(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4,
                               atol=1e-5)


def test_chunked_lm_head_ce_parity():
    """Chunked lm_head+CE (never materializes full logits) matches the
    fused full-logits loss in value AND gradient."""
    from dataclasses import replace

    import jax
    import numpy as np

    from ray_tpu.models import configs, init_params, loss_fn

    cfg = replace(configs.tiny, max_seq=64, remat=False, dtype=jax.numpy.float32)
    params = init_params(jax.random.PRNGKey(0), cfg)
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 33), 0,
                                cfg.vocab_size)
    l_full, g_full = jax.value_and_grad(loss_fn)(params, tokens, cfg)
    cfg_c = replace(cfg, ce_chunk=8)
    l_chunk, g_chunk = jax.value_and_grad(loss_fn)(params, tokens, cfg_c)
    np.testing.assert_allclose(float(l_full), float(l_chunk), rtol=1e-5)
    for a, b in zip(jax.tree.leaves(g_full), jax.tree.leaves(g_chunk)):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-4, atol=2e-5
        )
