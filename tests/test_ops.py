"""Kernel tests: pallas kernels run in interpret mode on CPU; fallbacks
checked against straightforward references."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.ops import (
    apply_rope,
    flash_attention,
    rmsnorm,
    rope_frequencies,
    softmax_cross_entropy,
)
from ray_tpu.parallel.ring_attention import reference_attention


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_xla_fallback(causal):
    key = jax.random.PRNGKey(0)
    q, k, v = (
        jax.random.normal(kk, (2, 64, 4, 16)) for kk in jax.random.split(key, 3)
    )
    got = flash_attention(q, k, v, causal=causal, use_pallas=False)
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [True, False])
def test_flash_attention_pallas_interpret(causal):
    key = jax.random.PRNGKey(1)
    q, k, v = (
        jax.random.normal(kk, (1, 128, 2, 32)) for kk in jax.random.split(key, 3)
    )
    got = flash_attention(
        q, k, v, causal=causal, block_q=32, block_k=32, interpret=True,
        use_pallas=True,
    )
    expected = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=2e-4, atol=2e-4)


def test_flash_attention_gqa():
    key = jax.random.PRNGKey(2)
    q = jax.random.normal(key, (1, 32, 8, 16))
    k = jax.random.normal(jax.random.PRNGKey(3), (1, 32, 2, 16))
    v = jax.random.normal(jax.random.PRNGKey(4), (1, 32, 2, 16))
    got = flash_attention(q, k, v, use_pallas=False)
    assert got.shape == (1, 32, 8, 16)


def test_rmsnorm_matches_reference_and_grads():
    x = jax.random.normal(jax.random.PRNGKey(5), (4, 32, 64))
    w = jax.random.normal(jax.random.PRNGKey(6), (64,)) * 0.1 + 1.0

    got = rmsnorm(x, w, use_pallas=False)
    var = (x.astype(jnp.float32) ** 2).mean(-1, keepdims=True)
    expected = x / jnp.sqrt(var + 1e-6) * w
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)

    # Grad parity with autodiff of the reference.
    def loss_custom(x, w):
        return (rmsnorm(x, w, use_pallas=False) ** 2).sum()

    def loss_ref(x, w):
        var = (x.astype(jnp.float32) ** 2).mean(-1, keepdims=True)
        return ((x / jnp.sqrt(var + 1e-6) * w) ** 2).sum()

    gx1, gw1 = jax.grad(loss_custom, argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(loss_ref, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2), rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(np.asarray(gw1), np.asarray(gw2), rtol=1e-4, atol=1e-4)


def test_rmsnorm_pallas_interpret():
    x = jax.random.normal(jax.random.PRNGKey(7), (8, 64))
    w = jnp.ones((64,))
    got = rmsnorm(x, w, interpret=True, use_pallas=True)
    expected = rmsnorm(x, w, use_pallas=False)
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-5)


def test_rope_rotation_preserves_norm():
    cos, sin = rope_frequencies(32, 128)
    x = jax.random.normal(jax.random.PRNGKey(8), (2, 16, 4, 32))
    out = apply_rope(x, cos, sin)
    np.testing.assert_allclose(
        np.linalg.norm(np.asarray(out), axis=-1),
        np.linalg.norm(np.asarray(x), axis=-1),
        rtol=1e-5,
    )


def test_rope_with_positions():
    cos, sin = rope_frequencies(16, 64)
    x = jax.random.normal(jax.random.PRNGKey(9), (1, 8, 2, 16))
    pos = jnp.arange(8)[None, :] + 10
    out_shifted = apply_rope(x, cos, sin, positions=pos)
    assert out_shifted.shape == x.shape
    # Shifted positions differ from default positions.
    out_default = apply_rope(x, cos, sin)
    assert not np.allclose(np.asarray(out_shifted), np.asarray(out_default))


def test_cross_entropy_matches_reference():
    logits = jax.random.normal(jax.random.PRNGKey(10), (4, 100))
    labels = jnp.array([3, 50, 99, 0])
    got = softmax_cross_entropy(logits, labels)
    expected = -jax.nn.log_softmax(logits)[jnp.arange(4), labels]
    np.testing.assert_allclose(np.asarray(got), np.asarray(expected),
                               rtol=1e-5, atol=1e-6)


def test_cross_entropy_grad():
    logits = jax.random.normal(jax.random.PRNGKey(11), (4, 50))
    labels = jnp.array([1, 2, 3, 4])

    g1 = jax.grad(lambda x: softmax_cross_entropy(x, labels).sum())(logits)
    g2 = jax.grad(
        lambda x: (-jax.nn.log_softmax(x)[jnp.arange(4), labels]).sum()
    )(logits)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2), rtol=1e-4,
                               atol=1e-5)
