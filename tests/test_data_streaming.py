"""Streaming-executor + distributed-shuffle tests (VERDICT r1 item 7).

Reference analogs: data streaming executor backpressure tests and
push-based shuffle (push_based_shuffle_task_scheduler.py:382).
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rtd
from ray_tpu.cluster_utils import Cluster


def test_out_of_core_pipeline_exceeds_store():
    """A map pipeline whose working set exceeds the object store must
    stream through with bounded in-flight blocks (+ spill/ref-GC)."""
    rt.init(num_cpus=2, object_store_memory=48 * 1024 * 1024)
    try:
        # 16 blocks x ~8MB = 128MB >> 48MB store.
        ds = rtd.from_items(
            [{"i": i} for i in range(16)], parallelism=16
        ).map_batches(
            lambda b: {"x": np.ones((len(b["i"]), 1_000_000))}
        ).map_batches(
            lambda b: {"s": np.asarray([np.asarray(x).sum() for x in b["x"]])}
        )
        out = ds.take_all()
        assert len(out) == 16
        assert all(r["s"] == 1_000_000.0 for r in out)
    finally:
        rt.shutdown()


def test_two_node_distributed_shuffle():
    """random_shuffle moves rows via map/reduce TASKS (driver touches only
    refs); with two nodes the work spreads across both."""
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        ds = rtd.from_items([{"i": i} for i in range(500)], parallelism=8)
        shuffled = ds.random_shuffle(seed=7).materialize()
        vals = [r["i"] for r in shuffled.take_all()]
        assert sorted(vals) == list(range(500))
        assert vals != list(range(500)), "shuffle produced identity order"
        # Determinism with a seed.
        again = [
            r["i"]
            for r in ds.random_shuffle(seed=7).materialize().take_all()
        ]
        assert again == vals
    finally:
        cluster.shutdown()


def test_distributed_sort_range_partitioned():
    rt.init(num_cpus=2)
    try:
        import random

        items = [{"k": random.Random(3).random() * i} for i in range(200)]
        random.Random(5).shuffle(items)
        ds = rtd.from_items(items, parallelism=6).sort("k")
        out = [r["k"] for r in ds.take_all()]
        assert out == sorted(out)
        desc = [
            r["k"]
            for r in rtd.from_items(items, parallelism=6)
            .sort("k", descending=True)
            .take_all()
        ]
        assert desc == sorted(desc, reverse=True)
    finally:
        rt.shutdown()


def test_repartition_distributed():
    rt.init(num_cpus=2)
    try:
        ds = rtd.from_items([{"i": i} for i in range(100)], parallelism=2)
        rp = ds.repartition(8).materialize()
        assert rp.num_blocks() == 8
        assert sorted(r["i"] for r in rp.take_all()) == list(range(100))
    finally:
        rt.shutdown()
