"""Streaming-executor + distributed-shuffle tests (VERDICT r1 item 7).

Reference analogs: data streaming executor backpressure tests and
push-based shuffle (push_based_shuffle_task_scheduler.py:382).
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rtd
from ray_tpu.cluster_utils import Cluster


def test_out_of_core_pipeline_exceeds_store():
    """A map pipeline whose working set exceeds the object store must
    stream through with bounded in-flight blocks (+ spill/ref-GC)."""
    rt.init(num_cpus=2, object_store_memory=48 * 1024 * 1024)
    try:
        # 16 blocks x ~8MB = 128MB >> 48MB store.
        ds = rtd.from_items(
            [{"i": i} for i in range(16)], parallelism=16
        ).map_batches(
            lambda b: {"x": np.ones((len(b["i"]), 1_000_000))}
        ).map_batches(
            lambda b: {"s": np.asarray([np.asarray(x).sum() for x in b["x"]])}
        )
        out = ds.take_all()
        assert len(out) == 16
        assert all(r["s"] == 1_000_000.0 for r in out)
    finally:
        rt.shutdown()


def test_two_node_distributed_shuffle():
    """random_shuffle moves rows via map/reduce TASKS (driver touches only
    refs); with two nodes the work spreads across both."""
    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        ds = rtd.from_items([{"i": i} for i in range(500)], parallelism=8)
        shuffled = ds.random_shuffle(seed=7).materialize()
        vals = [r["i"] for r in shuffled.take_all()]
        assert sorted(vals) == list(range(500))
        assert vals != list(range(500)), "shuffle produced identity order"
        # Determinism with a seed.
        again = [
            r["i"]
            for r in ds.random_shuffle(seed=7).materialize().take_all()
        ]
        assert again == vals
    finally:
        cluster.shutdown()


def test_distributed_sort_range_partitioned():
    rt.init(num_cpus=2)
    try:
        import random

        items = [{"k": random.Random(3).random() * i} for i in range(200)]
        random.Random(5).shuffle(items)
        ds = rtd.from_items(items, parallelism=6).sort("k")
        out = [r["k"] for r in ds.take_all()]
        assert out == sorted(out)
        desc = [
            r["k"]
            for r in rtd.from_items(items, parallelism=6)
            .sort("k", descending=True)
            .take_all()
        ]
        assert desc == sorted(desc, reverse=True)
    finally:
        rt.shutdown()


def _event_log_fn(log_path, stage, delay=0.0):
    """Block fn that appends (stage, idx, start, end) lines to a shared
    file — cross-process evidence of scheduling order."""

    def fn(block, _stage=stage, _p=log_path, _d=delay):
        import os
        import time as _t

        start = _t.monotonic()
        if _d:
            _t.sleep(_d)
        with open(_p, "a") as f:
            f.write(f"{_stage} {start} {_t.monotonic()}\n")
            f.flush()
        return block

    return fn


def test_block_level_pipelining(tmp_path):
    """Stage 2 must start on early blocks while stage 1 is still running
    later blocks — no stage barrier (streaming_executor.py:57)."""
    from ray_tpu.data.executor import MapStage, StreamingExecutor

    rt.init(num_cpus=2)
    try:
        log = str(tmp_path / "events.log")
        refs = [rt.put([{"i": i}]) for i in range(8)]
        ex = StreamingExecutor([
            MapStage(_event_log_fn(log, "s1", delay=0.15), name="s1",
                     max_in_flight=2, resources={"CPU": 0.1}),
            MapStage(_event_log_fn(log, "s2", delay=0.15), name="s2",
                     max_in_flight=2, resources={"CPU": 0.2}),
        ])
        out = ex.execute(refs)
        assert len(out) == 8
        events = []
        with open(log) as f:
            for line in f:
                stage, start, end = line.split()
                events.append((stage, float(start), float(end)))
        s1_ends = sorted(e[2] for e in events if e[0] == "s1")
        s2_starts = sorted(e[1] for e in events if e[0] == "s2")
        assert len(s1_ends) == 8 and len(s2_starts) == 8
        # The first stage-2 task started before the LAST stage-1 finished.
        assert s2_starts[0] < s1_ends[-1], (
            "no overlap between stages — executor is running a barrier"
        )
    finally:
        rt.shutdown()


def test_adjacent_maps_fuse_into_one_task(tmp_path):
    """Chained maps with the same resource shape run as ONE task per
    block (OperatorFusionRule analog)."""
    from ray_tpu.data.executor import MapStage, StreamingExecutor

    rt.init(num_cpus=2)
    try:
        refs = [rt.put([{"i": i}]) for i in range(6)]
        ex = StreamingExecutor([
            MapStage(lambda b: b, name="a"),
            MapStage(lambda b: b, name="b"),
            MapStage(lambda b: b, name="c"),
        ])
        out = ex.execute(refs)
        assert len(out) == 6
        (seg,) = ex.stats
        assert seg["stage"] == "a+b+c"
        assert seg["tasks"] == 6, (
            f"fusion should run 6 tasks (one per block), ran {seg['tasks']}"
        )
    finally:
        rt.shutdown()


class _CountingModel:
    """Stand-in for a compiled TPU model: expensive once-per-actor init."""

    def __init__(self, log_path):
        import os

        with open(log_path, "a") as f:
            f.write(f"init {os.getpid()}\n")
        self.bias = 100.0

    def __call__(self, batch):
        import numpy as np

        return {"y": np.asarray(batch["i"], dtype=float) + self.bias}


def test_actor_pool_map_batches(tmp_path):
    """map_batches(CallableClass, compute=ActorPoolStrategy): state is
    built once per pool actor and reused for every routed block."""
    log = str(tmp_path / "inits.log")
    rt.init(num_cpus=2)
    try:
        ds = rtd.from_items(
            [{"i": i} for i in range(24)], parallelism=8
        ).map_batches(
            _CountingModel,
            compute=rtd.ActorPoolStrategy(size=2),
            fn_constructor_args=(log,),
        )
        out = ds.take_all()
        assert sorted(r["y"] for r in out) == [100.0 + i for i in range(24)]
        with open(log) as f:
            inits = f.readlines()
        assert len(inits) == 2, (
            f"pool of 2 should init exactly twice, saw {len(inits)}"
        )
    finally:
        rt.shutdown()


def test_backpressure_bounds_inflight(tmp_path):
    """No more than the operator window of stage tasks may overlap."""
    from ray_tpu.data.executor import MapStage, StreamingExecutor

    rt.init(num_cpus=4)
    try:
        log = str(tmp_path / "bp.log")
        refs = [rt.put([{"i": i}]) for i in range(10)]
        ex = StreamingExecutor([
            MapStage(_event_log_fn(log, "w", delay=0.1), name="w",
                     max_in_flight=2),
        ])
        ex.execute(refs)
        spans = []
        with open(log) as f:
            for line in f:
                _, start, end = line.split()
                spans.append((float(start), float(end)))
        assert len(spans) == 10
        peak = max(
            sum(1 for s, e in spans if s <= t < e)
            for t, _ in spans
        )
        assert peak <= 2, f"window=2 but {peak} tasks overlapped"
    finally:
        rt.shutdown()


def test_repartition_distributed():
    rt.init(num_cpus=2)
    try:
        ds = rtd.from_items([{"i": i} for i in range(100)], parallelism=2)
        rp = ds.repartition(8).materialize()
        assert rp.num_blocks() == 8
        assert sorted(r["i"] for r in rp.take_all()) == list(range(100))
    finally:
        rt.shutdown()


@pytest.mark.slow
def test_wide_shuffle_bounded_fanin():
    """A 150-block shuffle must not hand any reduce task 150 object args:
    the tree combine bounds fan-in (reference: push-based shuffle merge
    factor) while preserving row multiset and seeded determinism."""
    rt.init(num_cpus=4, object_store_memory=256 * 1024 * 1024)
    try:
        n = 600
        ds = rtd.from_items([{"i": i} for i in range(n)], parallelism=150)
        rp = ds.repartition(4).materialize()
        assert rp.num_blocks() == 4
        assert sorted(r["i"] for r in rp.take_all()) == list(range(n))

        s1 = [r["i"] for r in ds.random_shuffle(seed=11).take_all()]
        s2 = [r["i"] for r in ds.random_shuffle(seed=11).take_all()]
        assert s1 == s2, "seeded wide shuffle must be deterministic"
        assert sorted(s1) == list(range(n))
        assert s1 != list(range(n))
    finally:
        rt.shutdown()


def test_datasource_datasink_plugin(rt_start, tmp_path):
    """Custom Datasource/Datasink on the plugin ABC (VERDICT r3 item 4;
    reference: data/datasource/datasource.py + datasink.py)."""
    from ray_tpu.data import block as B
    from ray_tpu.data.datasource import Datasink, Datasource, ReadTask

    class SquaresSource(Datasource):
        def __init__(self, n):
            self.n = n

        def get_read_tasks(self, parallelism):
            per = (self.n + parallelism - 1) // parallelism
            tasks = []
            for i in range(parallelism):
                lo, hi = i * per, min((i + 1) * per, self.n)
                if lo >= hi:
                    continue
                tasks.append(ReadTask(
                    lambda lo=lo, hi=hi: [B.block_from_rows(
                        [{"i": j, "sq": j * j} for j in range(lo, hi)]
                    )],
                    {"num_rows": hi - lo},
                ))
            return tasks

    class ManifestSink(Datasink):
        def __init__(self, path):
            self.path = str(path)
            self.started = False
            self.completed = None

        def on_write_start(self):
            self.started = True

        def write(self, block, ctx):
            rows = B.block_to_rows(block)
            fp = f"{self.path}/chunk-{ctx['task_index']}.txt"
            with open(fp, "w") as f:
                for r in rows:
                    f.write(f"{r['i']},{r['sq']}\n")
            return {"file": fp, "rows": len(rows)}

        def on_write_complete(self, results):
            self.completed = results

    ds = rtd.read_datasource(SquaresSource(30), parallelism=4)
    assert ds.count() == 30
    assert sorted(r["sq"] for r in ds.take_all())[:4] == [0, 1, 4, 9]

    import os
    os.makedirs(tmp_path / "out", exist_ok=True)
    sink = ManifestSink(tmp_path / "out")
    results = ds.write_datasink(sink)
    assert sum(r["rows"] for r in results) == 30
    # built-in formats ride the same surface
    files = ds.write_parquet(str(tmp_path / "pq"))
    assert files and all(f.endswith(".parquet") for f in files)
    back = rtd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 30


def test_read_binary_files(rt_start, tmp_path):
    for i in range(3):
        (tmp_path / f"blob{i}.bin").write_bytes(bytes([i]) * (10 + i))
    ds = rtd.read_binary_files(str(tmp_path), parallelism=2)
    rows = ds.take_all()
    assert len(rows) == 3
    assert sorted(len(r["bytes"]) for r in rows) == [10, 11, 12]


def test_streaming_split_coverage_and_epochs(rt_start):
    """streaming_split(n, equal=True): the n iterators cover every row
    exactly once per epoch, ROW-EXACTLY equal (boundary blocks sliced),
    and re-execute per epoch (reference: dataset.py:1161)."""
    import threading

    ds = rtd.range(90, parallelism=9).map(lambda r: {"id": r["id"]})
    its = ds.streaming_split(3, equal=True)
    for _epoch in range(2):
        parts = [[] for _ in range(3)]

        def consume(i):
            parts[i] = [r["id"] for r in its[i].iter_rows()]

        ts = [threading.Thread(target=consume, args=(i,)) for i in range(3)]
        [t.start() for t in ts]
        [t.join(timeout=120) for t in ts]
        assert sorted(x for p in parts for x in p) == list(range(90))
        sizes = [len(p) for p in parts]
        assert sizes == [30, 30, 30], sizes  # row-EXACT


def test_streaming_split_equal_slices_uneven_blocks(rt_start):
    """Row-exact equality with adversarial block sizes: 100 rows in
    ragged blocks over 3 splits -> 33/33/33 delivered, 1 remainder row
    dropped (the reference's equal=True contract)."""
    import threading

    # Ragged blocks: sizes 1..13 (sum 91) plus a 9-row block = 100 rows.
    ds = rtd.range(100, parallelism=7)
    its = ds.streaming_split(3, equal=True)
    parts = [[] for _ in range(3)]

    def consume(i):
        parts[i] = [r["id"] for r in its[i].iter_rows()]

    ts = [threading.Thread(target=consume, args=(i,)) for i in range(3)]
    [t.start() for t in ts]
    [t.join(timeout=120) for t in ts]
    sizes = [len(p) for p in parts]
    assert sizes == [33, 33, 33], sizes
    seen = sorted(x for p in parts for x in p)
    assert len(seen) == 99 and len(set(seen)) == 99  # 1 row dropped, no dupes


def test_trainer_streaming_ingestion_multi_epoch(tmp_path):
    """JaxTrainer ingests a Dataset per epoch through DataConfig +
    streaming_split; a non-split dataset broadcasts whole (VERDICT r3
    item 4 acceptance; reference: train/_internal/data_config.py)."""
    from ray_tpu.train import JaxTrainer, RunConfig, ScalingConfig
    from ray_tpu.train.data_config import DataConfig

    rt.init(num_cpus=4)
    try:
        @rt.remote
        class EpochSums:
            def __init__(self):
                self.sums = {}

            def add(self, epoch, rank, s):
                self.sums.setdefault(epoch, {})[rank] = s
                return True

            def get(self):
                return self.sums

        acc = EpochSums.options(name="epoch_sums").remote()
        rt.get(acc.add.remote(-1, -1, 0))  # ensure ready

        train_ds = rtd.range(40, parallelism=8)
        val_ds = rtd.range(5, parallelism=1)

        def loop(config):
            from ray_tpu import train

            acc = rt.get_actor("epoch_sums")
            shard = train.get_dataset_shard("train")
            val = train.get_dataset_shard("val")
            rank = train.get_world_rank()
            for epoch in range(3):
                s = sum(r["id"] for r in shard.iter_rows())
                rt.get(acc.add.remote(epoch, rank, s))
            # broadcast dataset: every worker sees all rows
            assert sorted(r["id"] for r in val.iter_rows()) == list(range(5))
            train.report({"done": True})

        trainer = JaxTrainer(
            loop,
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name="stream", storage_path=str(tmp_path)),
            datasets={"train": train_ds, "val": val_ds},
            dataset_config=DataConfig(datasets_to_split=["train"]),
        )
        result = trainer.fit()
        assert result.error is None
        sums = rt.get(acc.get.remote())
        expected = sum(range(40))
        for epoch in range(3):
            per_rank = sums.get(epoch, {})
            assert len(per_rank) == 2, sums
            assert sum(per_rank.values()) == expected, sums
    finally:
        rt.shutdown()
