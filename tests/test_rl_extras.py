"""Multi-agent, connector, and offline-RL tests.

Reference model: rllib's multi-agent tests (shared and separate policies,
the agent->policy mapping fn), connector unit tests, and the offline/BC
learning tests (SURVEY.md §2.3 RLlib rollout/offline rows) — scaled for a
1-CPU CI box with a fast-learning contextual-bandit multi-agent env.
"""

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.rl import (
    BCConfig,
    ConnectorPipeline,
    ClipReward,
    FlattenObs,
    MultiAgentEnv,
    MultiAgentPPOConfig,
    NormalizeObs,
    RLModuleSpec,
    dataset_to_batch,
    episodes_to_dataset,
)


class MatchContextEnv(MultiAgentEnv):
    """Two-agent contextual bandit: each agent sees a one-hot context and
    earns 1.0 for picking the hot index. Episodes run 8 steps. Learnable
    in a handful of PPO iterations — exercises the multi-agent plumbing,
    not the optimizer."""

    agent_ids = ("a0", "a1")

    def __init__(self, seed=0, horizon=8):
        self.rng = np.random.default_rng(seed)
        self.horizon = horizon
        self.t = 0

    def _obs(self):
        out = {}
        for aid in self.agent_ids:
            ctx = np.zeros(3, dtype=np.float32)
            ctx[self.rng.integers(0, 3)] = 1.0
            out[aid] = ctx
        self._current = out
        return out

    def reset(self):
        self.t = 0
        return self._obs(), {}

    def step(self, action_dict):
        rewards = {
            aid: float(action_dict[aid] == int(np.argmax(self._current[aid])))
            for aid in self.agent_ids
        }
        self.t += 1
        done = self.t >= self.horizon
        obs = self._obs() if not done else self._current
        terms = {aid: done for aid in self.agent_ids}
        terms["__all__"] = done
        truncs = {aid: False for aid in self.agent_ids}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


def _ma_config(policies, mapping_fn, seed=0):
    return (
        MultiAgentPPOConfig()
        .environment(lambda: MatchContextEnv(seed=seed))
        .multi_agent(policies=policies, policy_mapping_fn=mapping_fn)
        .env_runners(num_env_runners=2, rollout_length=64)
        .training(lr=1e-2, num_epochs=4, minibatch_size=64)
    )


@pytest.mark.slow
def test_multi_agent_ppo_separate_policies(rt_start):
    spec = RLModuleSpec(obs_dim=3, num_actions=3)
    algo = _ma_config(
        {"p0": spec, "p1": spec},
        lambda aid: "p0" if aid == "a0" else "p1",
    ).build()
    try:
        first = algo.train()
        last = first
        for _ in range(6):
            last = algo.train()
            # Optimal = 16/episode across both agents (8 steps x 2 agents).
            if last["episode_return_mean"] >= 13.0:
                break
        assert last["episode_return_mean"] > first["episode_return_mean"], (
            f"no improvement: {first['episode_return_mean']} -> "
            f"{last['episode_return_mean']}"
        )
        assert last["episodes_total"] > 0
        # Both policies actually trained.
        assert any(k.startswith("learner/p0/") for k in last)
        assert any(k.startswith("learner/p1/") for k in last)
    finally:
        algo.stop()


@pytest.mark.slow
def test_multi_agent_ppo_shared_policy(rt_start):
    spec = RLModuleSpec(obs_dim=3, num_actions=3)
    algo = _ma_config({"shared": spec}, lambda aid: "shared").build()
    try:
        first = algo.train()
        last = first
        for _ in range(6):
            last = algo.train()
            if last["episode_return_mean"] >= 13.0:
                break
        assert last["episode_return_mean"] > first["episode_return_mean"]
    finally:
        algo.stop()


# -- connectors ------------------------------------------------------------


def test_flatten_and_normalize_connectors():
    pipe = ConnectorPipeline([FlattenObs(), NormalizeObs(clip=5.0)])
    rng = np.random.default_rng(0)
    outs = [pipe(rng.normal(loc=7.0, scale=2.0, size=(2, 3))) for _ in range(200)]
    assert outs[-1].shape == (6,)
    stacked = np.stack(outs[100:])
    # After warmup the running normalization centers the stream.
    assert abs(stacked.mean()) < 0.5
    assert stacked.std() < 2.0
    # State round-trips (the runner-sync path).
    state = pipe.get_state()
    pipe2 = ConnectorPipeline([FlattenObs(), NormalizeObs(clip=5.0)])
    pipe2.set_state(state)
    x = rng.normal(loc=7.0, scale=2.0, size=(2, 3))
    np.testing.assert_allclose(pipe(x), pipe2(x), rtol=1e-5)


def test_clip_reward_connector():
    pipe = ConnectorPipeline([ClipReward(bound=1.0)])
    assert pipe.transform_reward(10.0) == 1.0
    assert pipe.transform_reward(-3.0) == -1.0
    assert pipe.transform_reward(0.5) == 0.5
    # Identity on observations.
    obs = np.array([2.0, -2.0], dtype=np.float32)
    np.testing.assert_array_equal(pipe(obs), obs)


def test_env_runner_applies_connectors(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import DiscretePolicyModule, EnvRunner

    spec = RLModuleSpec(obs_dim=4, num_actions=2)
    runner = EnvRunner.remote(
        lambda: gym.make("CartPole-v1"),
        lambda: DiscretePolicyModule(spec),
        rollout_length=64,
        connectors=ConnectorPipeline([NormalizeObs(clip=3.0)]),
    )
    import jax

    params = DiscretePolicyModule(spec).init(jax.random.PRNGKey(0))
    rt.get(runner.set_weights.remote(params), timeout=120)
    batch = rt.get(runner.sample.remote(), timeout=300)
    # The connector's clip bound proves the transform ran.
    assert np.abs(batch["obs"]).max() <= 3.0
    state = rt.get(runner.get_connector_state.remote(), timeout=120)
    assert state[0]["count"] >= 64


# -- offline / BC ----------------------------------------------------------


def test_episodes_to_dataset_roundtrip(rt_start):
    rollouts = [
        {
            "obs": np.arange(6, dtype=np.float32).reshape(3, 2),
            "actions": np.array([0, 1, 0], dtype=np.int32),
            "rewards": np.array([1.0, 2.0, 3.0], dtype=np.float32),
            "last_value": 0.0,  # non-per-step field: must be dropped
        },
        {
            "obs": np.ones((2, 2), dtype=np.float32),
            "actions": np.array([1, 1], dtype=np.int32),
            "rewards": np.array([4.0, 5.0], dtype=np.float32),
            "last_value": 0.0,
        },
    ]
    ds = episodes_to_dataset(rollouts)
    assert ds.count() == 5
    batch = dataset_to_batch(ds, keys=("obs", "actions", "rewards"))
    assert batch["obs"].shape == (5, 2)
    assert batch["actions"].tolist() == [0, 1, 0, 1, 1]
    assert sorted(batch["rewards"].tolist()) == [1.0, 2.0, 3.0, 4.0, 5.0]


def test_bc_learns_expert_policy(rt_start):
    # Expert data for the contextual bandit: action = argmax(context).
    rng = np.random.default_rng(0)
    obs = np.zeros((512, 3), dtype=np.float32)
    hot = rng.integers(0, 3, size=512)
    obs[np.arange(512), hot] = 1.0
    rollouts = [{
        "obs": obs,
        "actions": hot.astype(np.int32),
    }]
    ds = episodes_to_dataset(rollouts)
    bc = BCConfig().module(obs_dim=3, num_actions=3).training(lr=5e-3).build()
    metrics = bc.train_on_dataset(ds, num_epochs=20)
    assert metrics["accuracy"] > 0.95, metrics
    # Cloned policy reproduces the expert on fresh contexts.
    test_obs = np.eye(3, dtype=np.float32)
    np.testing.assert_array_equal(bc.compute_actions(test_obs), [0, 1, 2])


def test_continuous_module_tanh_gaussian_math():
    """Tanh-Gaussian log-probs integrate sanely: actions stay in the
    scaled range and logp matches a numerical check at low variance."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import ContinuousModuleSpec, ContinuousPolicyModule

    spec = ContinuousModuleSpec(3, 2, action_low=-2.0, action_high=2.0,
                                hidden=(16,))
    m = ContinuousPolicyModule(spec)
    params = m.init(jax.random.PRNGKey(0))
    obs = jnp.zeros((5, 3))
    a, logp = m.sample_with_logp(params, obs, jax.random.PRNGKey(1))
    assert a.shape == (5, 2) and logp.shape == (5,)
    assert bool(jnp.all(jnp.abs(a) <= 1.0))
    scaled, lp2, v = m.sample_action(params, obs, jax.random.PRNGKey(1))
    assert bool(jnp.all(jnp.abs(scaled) <= 2.0))
    q1, q2 = m.q_values(params, obs, a)
    assert q1.shape == (5,) and q2.shape == (5,)
    # Deterministic head is the tanh of the mean.
    det = m.deterministic_action(params, obs)
    assert bool(jnp.all(jnp.abs(det) <= 1.0))


@pytest.mark.slow
def test_sac_pendulum_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import SACConfig

    algo = (
        SACConfig()
        .environment(lambda: gym.make("Pendulum-v1"), obs_dim=3,
                     action_dim=1, action_low=-2.0, action_high=2.0)
        .env_runners(num_env_runners=1, rollout_length=400)
        .training(lr=1e-3, batch_size=128, updates_per_iteration=400,
                  warmup_steps=400, tau=0.01)
        .build()
    )
    try:
        first = algo.train()  # mostly warmup/random
        best = -1e9
        # 24 iterations: learning-threshold tests run under whatever
        # load the rest of the suite left behind; the margin is time,
        # not a looser bar.
        for _ in range(24):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best > -400.0:
                break
        # Random Pendulum policy sits near -1200..-1600; learning must
        # lift the best mean return decisively.
        assert best > -800.0 and best > first["episode_return_mean"] + 200, (
            f"no improvement: first={first['episode_return_mean']:.0f}, "
            f"best={best:.0f}"
        )
    finally:
        algo.stop()


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 2}], indirect=True)
def test_marwil_dataset_backed_training():
    """MARWIL trains from a streaming transition Dataset (VERDICT r3 weak
    #7: offline training beyond BC; reference: rllib/algorithms/marwil/).
    The behavior policy is 50/50, but action 1 earns higher returns —
    advantage weighting must tilt the learned policy toward action 1
    where plain BC stays ~50/50."""
    import numpy as np

    from ray_tpu.rl import BCConfig, MARWILConfig
    from ray_tpu.rl.offline import episodes_to_dataset

    rng = np.random.default_rng(3)
    rollouts = []
    for _ in range(8):
        T = 50
        obs = rng.normal(size=(T, 4)).astype(np.float32)
        actions = rng.integers(0, 2, size=T).astype(np.int32)
        # action 1 pays +1, action 0 pays -1 (plus noise).
        rewards = (2.0 * actions - 1.0 + 0.1 * rng.normal(size=T)).astype(
            np.float32
        )
        dones = np.zeros(T, dtype=np.float32)
        dones[-1] = 1.0
        rollouts.append({
            "obs": obs, "actions": actions, "rewards": rewards,
            "dones": dones,
        })

    ds = episodes_to_dataset(rollouts, gamma=0.9)
    assert ds.count() == 8 * 50
    sample = ds.take(1)[0]
    assert "returns" in sample

    marwil = (
        MARWILConfig()
        .module(obs_dim=4, num_actions=2)
        .training(lr=5e-3, minibatch_size=64, beta=2.0, gamma=0.9)
        .build()
    )
    metrics = marwil.train_on_dataset(ds, num_epochs=4)
    assert np.isfinite(metrics["total_loss"])

    bc = (
        BCConfig().module(obs_dim=4, num_actions=2)
        .training(lr=5e-3, minibatch_size=64).build()
    )
    bc.train_on_dataset(ds, num_epochs=4)

    probe = rng.normal(size=(256, 4)).astype(np.float32)
    marwil_pref = float((marwil.compute_actions(probe) == 1).mean())
    bc_pref = float((bc.compute_actions(probe) == 1).mean())
    # BC imitates the uniform behavior policy; MARWIL upweights the
    # high-advantage action.
    assert marwil_pref > 0.8, marwil_pref
    assert marwil_pref > bc_pref + 0.2, (marwil_pref, bc_pref)


@pytest.mark.slow
def test_td3_pendulum_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import TD3Config

    algo = (
        TD3Config()
        .environment(lambda: gym.make("Pendulum-v1"), obs_dim=3,
                     action_dim=1, action_low=-2.0, action_high=2.0)
        .env_runners(num_env_runners=1, rollout_length=400)
        .training(lr=1e-3, batch_size=128, updates_per_iteration=400,
                  warmup_steps=400, tau=0.01, explore_sigma=0.15)
        .build()
    )
    try:
        first = algo.train()  # mostly warmup/random
        best = -1e9
        # 24 iterations: learning-threshold tests run under whatever
        # load the rest of the suite left behind; the margin is time,
        # not a looser bar.
        for _ in range(24):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best > -400.0:
                break
        # Random Pendulum policy sits near -1200..-1600; learning must
        # lift the best mean return decisively.
        assert best > -800.0 and best > first["episode_return_mean"] + 200, (
            f"no improvement: first={first['episode_return_mean']:.0f}, "
            f"best={best:.0f}"
        )
    finally:
        algo.stop()


def test_ddpg_preset_trains(rt_start):
    """DDPG = TD3 preset (policy_delay=1, no target smoothing): fields,
    build, and one real train iteration."""
    import gymnasium as gym

    from ray_tpu.rl import DDPGConfig, TD3

    cfg = (
        DDPGConfig()
        .environment(lambda: gym.make("Pendulum-v1"), obs_dim=3,
                     action_dim=1, action_low=-2.0, action_high=2.0)
        .env_runners(num_env_runners=1, rollout_length=64)
        .training(batch_size=32, updates_per_iteration=4, warmup_steps=32)
    )
    assert cfg.policy_delay == 1
    assert cfg.target_noise == 0.0
    algo = cfg.build()
    assert isinstance(algo, TD3)
    try:
        r1 = algo.train()  # warmup fill
        r2 = algo.train()  # real updates
        assert r2["training_iteration"] == 2
        assert "learner/q_loss" in r2
        import numpy as np

        assert np.isfinite(r2["learner/q_loss"])
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# Rainbow-style DQN extensions: n-step, PER, dueling, double-Q
# (reference: DQNConfig double_q/dueling/n_step + prioritized replay,
# rllib/algorithms/dqn/)
# ---------------------------------------------------------------------------


def test_n_step_transitions_math():
    """3-step windows: discounted reward sums, episode cuts, gamma**m."""
    from ray_tpu.rl import n_step_transitions

    obs = np.arange(5, dtype=np.float32)[:, None]
    nxt = obs + 1
    batch = {
        "obs": obs,
        "next_obs": nxt,
        "actions": np.zeros(5, dtype=np.int32),
        "rewards": np.array([1, 2, 4, 8, 16], dtype=np.float32),
        # step 2 terminates an episode; steps 3-4 are a fresh episode
        "dones": np.array([0, 0, 1, 0, 0], dtype=np.float32),
    }
    ep_ends = np.array([False, False, True, False, False])
    out = n_step_transitions(batch, ep_ends, n=3, gamma=0.5)
    # t=0: r0 + g*r1 + g^2*r2 = 1 + 1 + 1 = 3, window hits the episode
    # end at step 2 -> done=1, next_obs = nxt[2], discount = 0.5**3
    assert out["rewards"][0] == pytest.approx(3.0)
    assert out["dones"][0] == 1.0
    assert out["next_obs"][0] == pytest.approx(nxt[2])
    assert out["discounts"][0] == pytest.approx(0.125)
    # t=1: r1 + g*r2 = 4, cut by episode end after 2 steps
    assert out["rewards"][1] == pytest.approx(4.0)
    assert out["discounts"][1] == pytest.approx(0.25)
    # t=3: full-length window never crosses into nothing: r3 + g*r4 = 16
    # (window truncated by rollout end after 2 steps, not an episode end)
    assert out["rewards"][3] == pytest.approx(16.0)
    assert out["dones"][3] == 0.0
    assert out["discounts"][3] == pytest.approx(0.25)
    # t=4: single-step tail window
    assert out["rewards"][4] == pytest.approx(16.0)
    assert out["discounts"][4] == pytest.approx(0.5)


def test_prioritized_replay_bias_and_weights():
    from ray_tpu.rl import PrioritizedReplayBuffer

    buf = PrioritizedReplayBuffer(capacity=64, obs_dim=1, seed=0, alpha=1.0)
    buf.add_batch({
        "obs": np.arange(64, dtype=np.float32)[:, None],
        "next_obs": np.zeros((64, 1), dtype=np.float32),
        "actions": np.zeros(64, dtype=np.int32),
        "rewards": np.zeros(64, dtype=np.float32),
        "dones": np.zeros(64, dtype=np.float32),
    })
    # Give one transition 100x the priority of the rest: it should
    # dominate samples, and its IS weight should be the smallest.
    buf.update_priorities(np.arange(64), np.ones(64))
    buf.update_priorities(np.array([7]), np.array([100.0]))
    mb = buf.sample(512, beta=1.0)
    counts = np.bincount(mb["indices"], minlength=64)
    assert counts[7] > 0.4 * 512
    assert mb["weights"].max() == pytest.approx(1.0)
    hot = mb["weights"][mb["indices"] == 7]
    cold = mb["weights"][mb["indices"] != 7]
    assert len(hot) and len(cold)
    assert hot.max() < cold.min()


def test_dueling_module_identifiable_and_samples():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import DuelingQNetworkModule, RLModuleSpec

    mod = DuelingQNetworkModule(RLModuleSpec(obs_dim=3, num_actions=4))
    params = mod.init(jax.random.PRNGKey(0))
    obs = jax.random.normal(jax.random.PRNGKey(1), (5, 3))
    q = mod.forward(params, obs)["q_values"]
    assert q.shape == (5, 4)
    # Identifiability: shifting every advantage by a constant must leave
    # Q unchanged (the mean-advantage subtraction).
    shifted = jax.tree.map(lambda x: x, params)
    shifted["a"][-1]["b"] = shifted["a"][-1]["b"] + 3.7
    q2 = mod.forward(shifted, obs)["q_values"]
    assert jnp.allclose(q, q2, atol=1e-5)
    a = mod.sample_action(params, obs, jax.random.PRNGKey(2), epsilon=0.0)
    assert a.shape == (5,)


@pytest.mark.slow
def test_rainbow_dqn_cartpole_improves(rt_start):
    """All four extensions on together must still learn CartPole."""
    import gymnasium as gym

    from ray_tpu.rl import DQNConfig

    algo = (
        DQNConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=200)
        .training(lr=1e-3, train_batch_size=64, updates_per_iteration=64,
                  learning_starts=400, target_update_freq=2,
                  double_q=True, dueling=True, n_step=3,
                  prioritized_replay=True)
        .exploration(epsilon_start=1.0, epsilon_end=0.05,
                     epsilon_decay_iters=6)
        .build()
    )
    try:
        best = -1.0
        for _ in range(30):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert best >= 75.0, f"rainbow DQN failed to learn: best={best}"
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# CQL: offline conservative Q-learning (reference: rllib/algorithms/cql/)
# ---------------------------------------------------------------------------


def _bandit_transitions(n=2048, seed=0):
    """Offline 1-D contextual bandit: reward 1 - (a - 0.5*s)^2, episodes
    of length one. Uniform behavior policy gives full action coverage,
    so the optimal in-distribution policy is a = 0.5*s."""
    rng = np.random.default_rng(seed)
    s = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    a = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    r = (1.0 - (a[:, 0] - 0.5 * s[:, 0]) ** 2).astype(np.float32)
    return {
        "obs": s,
        "actions": a,
        "rewards": r,
        "next_obs": s,
        "dones": np.ones(n, dtype=np.float32),
    }


@pytest.mark.slow
def test_cql_learns_offline_bandit():
    from ray_tpu.rl import CQLConfig

    algo = (
        CQLConfig()
        .module(obs_dim=1, action_dim=1)
        .training(lr=3e-3, cql_alpha=1.0, minibatch_size=256)
        .build()
    )
    batch = _bandit_transitions()
    obs = np.linspace(-1, 1, 21, dtype=np.float32)[:, None]
    before = np.abs(algo.compute_actions(obs)[:, 0] - 0.5 * obs[:, 0]).mean()
    metrics = algo.train_on_batch(batch, num_epochs=40)
    after = np.abs(algo.compute_actions(obs)[:, 0] - 0.5 * obs[:, 0]).mean()
    assert np.isfinite(metrics["q_loss"])
    assert "cql_loss" in metrics
    assert after < before and after < 0.25, (before, after)


@pytest.mark.slow
def test_cql_penalizes_out_of_distribution_actions():
    """Train on a dataset whose behavior policy only covers a < 0; the
    conservative penalty must keep learned Q for (unseen) a > 0 below
    Q for the covered region even though rewards there would be high."""
    import jax.numpy as jnp

    from ray_tpu.rl import CQLConfig

    rng = np.random.default_rng(1)
    n = 2048
    s = rng.uniform(-1, 1, (n, 1)).astype(np.float32)
    a = rng.uniform(-1, 0.0, (n, 1)).astype(np.float32)  # only a<0 seen
    r = (1.0 + a[:, 0]).astype(np.float32)  # best covered reward at a=0
    batch = {"obs": s, "actions": a, "rewards": r, "next_obs": s,
             "dones": np.ones(n, dtype=np.float32)}
    algo = (
        CQLConfig()
        .module(obs_dim=1, action_dim=1)
        .training(lr=3e-3, cql_alpha=5.0, minibatch_size=256)
        .build()
    )
    algo.train_on_batch(batch, num_epochs=30)
    obs = jnp.zeros((64, 1))
    q_in, _ = algo.module.q_values(
        algo.state["params"], obs, jnp.full((64, 1), -0.1)
    )
    q_ood, _ = algo.module.q_values(
        algo.state["params"], obs, jnp.full((64, 1), 0.9)
    )
    assert float(q_ood.mean()) < float(q_in.mean()) + 0.5


# ---------------------------------------------------------------------------
# A2C preset + C51 distributional DQN
# ---------------------------------------------------------------------------


def test_categorical_projection_math():
    from ray_tpu.rl import categorical_projection

    support = np.linspace(-1.0, 1.0, 5)  # dz = 0.5
    # Terminal transition with reward 0.25: all mass lands split between
    # atoms 2 (0.0) and 3 (0.5) at ratio 0.5/0.5.
    probs = np.full((1, 5), 0.2, dtype=np.float32)
    out = categorical_projection(
        probs, support, np.array([0.25], dtype=np.float32),
        np.array([0.9], dtype=np.float32), np.array([1.0], dtype=np.float32),
    )
    assert out.shape == (1, 5)
    assert out.sum() == pytest.approx(1.0, abs=1e-5)
    assert out[0, 2] == pytest.approx(0.5, abs=1e-5)
    assert out[0, 3] == pytest.approx(0.5, abs=1e-5)
    # Non-terminal identity: reward 0, discount 1 -> distribution unchanged.
    eye = np.zeros((1, 5), dtype=np.float32)
    eye[0, 1] = 1.0
    out2 = categorical_projection(
        eye, support, np.zeros(1, dtype=np.float32),
        np.ones(1, dtype=np.float32), np.zeros(1, dtype=np.float32),
    )
    assert np.allclose(out2, eye, atol=1e-6)
    # Out-of-range targets clip to the support edge.
    out3 = categorical_projection(
        eye, support, np.array([50.0], dtype=np.float32),
        np.ones(1, dtype=np.float32), np.array([1.0], dtype=np.float32),
    )
    assert out3[0, -1] == pytest.approx(1.0, abs=1e-5)


def test_c51_module_expected_values():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import C51QNetworkModule, RLModuleSpec

    mod = C51QNetworkModule(RLModuleSpec(obs_dim=3, num_actions=2),
                            num_atoms=11, v_min=-2.0, v_max=2.0)
    params = mod.init(jax.random.PRNGKey(0))
    obs = jax.random.normal(jax.random.PRNGKey(1), (4, 3))
    out = mod.forward(params, obs)
    assert out["q_logits"].shape == (4, 2, 11)
    assert out["q_probs"].shape == (4, 2, 11)
    assert jnp.allclose(out["q_probs"].sum(-1), 1.0, atol=1e-5)
    expect = (out["q_probs"] * mod.support).sum(-1)
    assert jnp.allclose(out["q_values"], expect, atol=1e-5)
    a = mod.sample_action(params, obs, jax.random.PRNGKey(2), epsilon=0.0)
    assert a.shape == (4,)


@pytest.mark.slow
def test_a2c_cartpole_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import A2CConfig

    algo = (
        A2CConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=256)
        .training(lr=3e-3)
        .build()
    )
    assert algo.config.num_epochs == 1
    try:
        first = algo.train()
        best = 0.0
        # Unclipped on-policy PG is the noisiest learner here (and env
        # resets are unseeded): generous budget, modest bar.
        for _ in range(30):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 60.0:
                break
        assert best > first["episode_return_mean"] or best >= 50.0, (
            f"A2C failed to improve: first={first['episode_return_mean']} "
            f"best={best}"
        )
    finally:
        algo.stop()


@pytest.mark.slow
def test_c51_dqn_cartpole_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import DQNConfig

    algo = (
        DQNConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=200)
        .training(lr=1e-3, train_batch_size=64, updates_per_iteration=64,
                  learning_starts=400, distributional=True, num_atoms=51,
                  v_min=0.0, v_max=100.0, n_step=3)
        .exploration(epsilon_start=1.0, epsilon_end=0.05,
                     epsilon_decay_iters=6)
        .build()
    )
    try:
        best = -1.0
        for _ in range(30):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert best >= 75.0, f"C51 DQN failed to learn: best={best}"
    finally:
        algo.stop()


def test_distributional_plus_dueling_rejected():
    from ray_tpu.rl import DQNConfig

    cfg = (
        DQNConfig()
        .environment(lambda: None, obs_dim=2, num_actions=2)
        .training(distributional=True, dueling=True)
    )
    with pytest.raises(ValueError, match="distributional"):
        cfg.build()


def test_categorical_projection_edge_rounding():
    """Support grids whose dz is inexact must not index past the last
    atom when targets clip to v_max (regression: hi = ceil(b) = N)."""
    from ray_tpu.rl import categorical_projection

    support = np.linspace(42.57, 71.49, 95)
    probs = np.full((4, 95), 1.0 / 95, dtype=np.float32)
    out = categorical_projection(
        probs, support, np.full(4, 1e6, dtype=np.float32),
        np.ones(4, dtype=np.float32), np.zeros(4, dtype=np.float32),
    )
    assert np.allclose(out.sum(-1), 1.0, atol=1e-4)
    assert out[:, -1] == pytest.approx(np.ones(4), abs=1e-4)


def test_distributional_single_atom_rejected():
    from ray_tpu.rl import DQNConfig

    cfg = (
        DQNConfig()
        .environment(lambda: None, obs_dim=2, num_actions=2)
        .training(distributional=True, num_atoms=1)
    )
    with pytest.raises(ValueError, match="num_atoms"):
        cfg.build()


# ---------------------------------------------------------------------------
# APEX-DQN: distributed prioritized replay
# (reference: rllib/algorithms/apex_dqn)
# ---------------------------------------------------------------------------


def test_apex_epsilon_ladder():
    from ray_tpu.rl.algorithms.apex import APEXConfig

    cfg = APEXConfig()
    cfg.num_env_runners = 4
    # Horgan et al. ladder: eps_i = base^(1 + 7i/(N-1)), strictly
    # decreasing from base toward near-greedy.
    from ray_tpu.rl.algorithms.apex import APEX  # noqa: F401 — ladder math
    n = cfg.num_env_runners
    eps = [cfg.apex_eps_base ** (1 + 7 * i / (n - 1)) for i in range(n)]
    assert eps[0] == pytest.approx(0.4)
    assert all(a > b for a, b in zip(eps, eps[1:]))
    assert eps[-1] == pytest.approx(0.4 ** 8)


@pytest.mark.slow
def test_apex_dqn_learns_with_sharded_replay(rt_start):
    """Async collection + 2 replay shard actors + the full DQN update
    math must still learn CartPole, and priorities must land on shards."""
    import gymnasium as gym

    from ray_tpu.rl import APEXConfig

    algo = (
        APEXConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=3, rollout_length=200)
        .training(lr=1e-3, train_batch_size=64, updates_per_iteration=48,
                  learning_starts=400, n_step=3)
        .build()
    )
    assert len(algo.shards) == 2
    try:
        best = -1.0
        for _ in range(40):
            result = algo.train()
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert result["buffer_size"] > 400
        # Shard priorities were refreshed away from uniform init.
        import ray_tpu as rt
        sizes = rt.get([s.size.remote() for s in algo.shards], timeout=60)
        assert all(s > 0 for s in sizes)
        assert best >= 75.0, f"APEX failed to learn: best={best}"
    finally:
        algo.stop()


# ---------------------------------------------------------------------------
# NoisyNet DQN (the last Rainbow component; reference: DQNConfig.noisy)
# ---------------------------------------------------------------------------


def test_noisy_module_math():
    import jax
    import jax.numpy as jnp

    from ray_tpu.rl import NoisyQNetworkModule, RLModuleSpec
    from ray_tpu.rl.core.rl_module import factorized_noise

    mod = NoisyQNetworkModule(RLModuleSpec(obs_dim=3, num_actions=4))
    params = mod.init(jax.random.PRNGKey(0))
    obs = jax.random.normal(jax.random.PRNGKey(1), (6, 3))
    # mu-only forward is deterministic.
    q1 = mod.forward(params, obs)["q_values"]
    q2 = mod.forward(params, obs)["q_values"]
    assert jnp.allclose(q1, q2) and q1.shape == (6, 4)
    # Noise perturbs the outputs; different draws differ.
    n1 = factorized_noise(jax.random.PRNGKey(2), 64, 4)
    n2 = factorized_noise(jax.random.PRNGKey(3), 64, 4)
    qa = mod.forward(params, obs, noise=n1)["q_values"]
    qb = mod.forward(params, obs, noise=n2)["q_values"]
    assert not jnp.allclose(qa, q1)
    assert not jnp.allclose(qa, qb)
    # Sigma receives gradient through the noisy loss path.
    from ray_tpu.rl import noisy_dqn_loss

    batch = {
        "obs": obs,
        "actions": jnp.zeros(6, dtype=jnp.int32),
        "targets": jnp.ones(6),
        "eps_in": n1[0],
        "eps_out": n1[1],
    }
    grads = jax.grad(lambda p: noisy_dqn_loss(p, mod, batch)[0])(params)
    assert float(jnp.abs(grads["sigma_w"]).sum()) > 0
    assert float(jnp.abs(grads["sigma_b"]).sum()) > 0
    # Actions vary across rng draws on the same observation (exploration
    # without epsilon).
    acts = {
        int(mod.sample_action(params, obs[:1], jax.random.PRNGKey(k))[0])
        for k in range(40)
    }
    assert len(acts) > 1


@pytest.mark.slow
def test_noisy_dqn_cartpole_improves(rt_start):
    import gymnasium as gym

    from ray_tpu.rl import DQNConfig

    algo = (
        DQNConfig()
        .environment(lambda: gym.make("CartPole-v1"), obs_dim=4, num_actions=2)
        .env_runners(num_env_runners=2, rollout_length=200)
        .training(lr=1e-3, train_batch_size=64, updates_per_iteration=64,
                  learning_starts=400, noisy=True, n_step=3)
        .build()
    )
    try:
        best = -1.0
        for _ in range(45):
            result = algo.train()
            assert result["epsilon"] == 0.0  # exploration is the noise
            best = max(best, result["episode_return_mean"])
            if best >= 75.0:
                break
        assert best >= 75.0, f"noisy DQN failed to learn: best={best}"
    finally:
        algo.stop()


def test_noisy_multi_learner_split_replicates_noise():
    """_split_batch replicates shared noise vectors instead of slicing
    them (regression: num_learners>1 corrupted eps_in/eps_out)."""
    from ray_tpu.rl.core.learner_group import _split_batch

    batch = {
        "obs": np.zeros((64, 4), dtype=np.float32),
        "actions": np.zeros(64, dtype=np.int32),
        "targets": np.zeros(64, dtype=np.float32),
        # Width chosen == batch size to prove the split is by NAME, not
        # by a length heuristic.
        "eps_in": np.arange(64, dtype=np.float32),
        "eps_out": np.arange(2, dtype=np.float32),
    }
    shards = _split_batch(batch, 2)
    assert len(shards) == 2
    for s in shards:
        assert s["obs"].shape == (32, 4)
        assert np.array_equal(s["eps_in"], batch["eps_in"])
        assert np.array_equal(s["eps_out"], batch["eps_out"])


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
@pytest.mark.slow
def test_r2d2_learns_memory_task():
    """R2D2 (recurrent Q + stored-state sequence replay + burn-in +
    double-Q targets) learns the cue-recall memory task a memoryless
    Q-network cannot represent (reference: rllib/algorithms/r2d2/)."""
    import sys

    sys.path.insert(0, "tests")
    from test_rl import CueRecallEnv

    from ray_tpu.rl import R2D2Config

    algo = (
        R2D2Config(state_dim=16)
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=2, window_length=16)
        .training(lr=2e-3, train_batch_size=16, updates_per_iteration=24,
                  learning_starts=16, burn_in=2, target_update_freq=2)
        .exploration(epsilon_start=1.0, epsilon_end=0.05,
                     epsilon_decay_iters=8)
    ).build()
    try:
        best = 0.0
        for _ in range(25):
            r = algo.train()
            best = max(best, r["episode_return_mean"])
            if best >= 0.9:
                break
        assert best >= 0.9, f"R2D2 failed the memory task: best={best}"
    finally:
        algo.stop()


@pytest.mark.usefixtures("rt_start")
@pytest.mark.parametrize("rt_start", [{"num_cpus": 4}], indirect=True)
def test_r2d2_evaluation_greedy_and_explore():
    """Both eval modes work for the recurrent Q module: greedy threads
    the GRU state through q_values argmax; explore epsilon-greedy
    actually explores (the sampler receives a nonzero epsilon)."""
    import sys

    sys.path.insert(0, "tests")
    from test_rl import CueRecallEnv

    from ray_tpu.rl import R2D2Config

    algo = (
        R2D2Config(state_dim=8)
        .environment(lambda: CueRecallEnv(), obs_dim=3, num_actions=2)
        .env_runners(num_env_runners=1, window_length=8)
        .training(learning_starts=4, updates_per_iteration=1,
                  train_batch_size=4)
        .evaluation(evaluation_interval=1, evaluation_duration=2)
    ).build()
    try:
        r = algo.train()
        assert r["evaluation"]["episodes_this_eval"] == 2
        algo.config.evaluation_explore = True
        ev = algo.evaluate()
        assert ev["episodes_this_eval"] == 2
    finally:
        algo.stop()
