"""Autoscaler tests with the fake (in-process) node provider.

Reference analogs: python/ray/tests/test_autoscaler_fake_multinode.py and
the resource-demand binpacking tests of test_resource_demand_scheduler.py.
"""

import time

import pytest

import ray_tpu as rt
from ray_tpu.autoscaler import FakeMultiNodeProvider, StandardAutoscaler
from ray_tpu.cluster_utils import Cluster


@pytest.fixture
def scaling_cluster():
    cluster = Cluster()
    cluster.add_node(num_cpus=1)  # head
    cluster.connect()
    provider = FakeMultiNodeProvider(cluster.io, "127.0.0.1", cluster.gcs_port)
    yield cluster, provider
    cluster.shutdown()


def _wait(fn, timeout=30.0, poll=0.2):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        out = fn()
        if out:
            return out
        time.sleep(poll)
    raise TimeoutError("condition not met")


def test_scale_up_on_demand(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = StandardAutoscaler(
        {
            "node_types": {
                "worker": {"resources": {"CPU": 2}, "max_workers": 4},
            },
            "idle_timeout_s": 9999,
        },
        provider,
        f"127.0.0.1:{cluster.gcs_port}",
        io=cluster.io,
    )

    @rt.remote(num_cpus=2)
    def heavy():
        time.sleep(0.5)
        return 1

    # Head has 1 CPU: these 2-CPU tasks are infeasible until workers join.
    refs = [heavy.remote() for _ in range(4)]
    time.sleep(1.2)  # demand bundles reach the GCS via heartbeat

    launched = autoscaler.update()
    assert launched.get("worker", 0) >= 1
    assert rt.get(refs, timeout=60) == [1, 1, 1, 1]

    # Second pass with no pending demand launches nothing.
    time.sleep(1.2)
    assert autoscaler.update() == {}
    assert len(provider.non_terminated_nodes()) <= 4


def test_scale_up_respects_max_workers(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = StandardAutoscaler(
        {"node_types": {"worker": {"resources": {"CPU": 2}, "max_workers": 1}},
         "idle_timeout_s": 9999},
        provider,
        f"127.0.0.1:{cluster.gcs_port}",
        io=cluster.io,
    )

    @rt.remote(num_cpus=2)
    def heavy():
        time.sleep(0.2)
        return 1

    refs = [heavy.remote() for _ in range(6)]
    time.sleep(1.2)
    autoscaler.update()
    time.sleep(1.2)
    autoscaler.update()
    assert len(provider.non_terminated_nodes()) == 1
    rt.get(refs, timeout=120)


def test_min_workers_and_idle_scale_down(scaling_cluster):
    cluster, provider = scaling_cluster
    autoscaler = StandardAutoscaler(
        {"node_types": {"worker": {"resources": {"CPU": 2}, "min_workers": 1,
                                    "max_workers": 3}},
         "idle_timeout_s": 0.5},
        provider,
        f"127.0.0.1:{cluster.gcs_port}",
        io=cluster.io,
    )
    # min_workers=1 launches a worker with no demand at all.
    launched = autoscaler.update()
    assert launched.get("worker") == 1

    @rt.remote(num_cpus=2)
    def heavy():
        time.sleep(0.3)
        return 1

    refs = [heavy.remote() for _ in range(4)]
    time.sleep(1.2)
    autoscaler.update()
    n_peak = len(provider.non_terminated_nodes())
    assert n_peak >= 1
    rt.get(refs, timeout=60)

    # After the work drains, idle nodes terminate down to min_workers.
    def scaled_down():
        time.sleep(0.6)
        autoscaler.update()
        return len(provider.non_terminated_nodes()) == 1

    _wait(scaled_down, timeout=30)


def test_tpu_slice_scales_whole_slices(scaling_cluster):
    """A slice node type launches slice_hosts hosts atomically."""
    cluster, provider = scaling_cluster
    autoscaler = StandardAutoscaler(
        {"node_types": {
            "v5e-slice": {"resources": {"TPU": 4, "CPU": 1},
                           "slice_hosts": 4, "max_workers": 2}},
         "idle_timeout_s": 9999},
        provider,
        f"127.0.0.1:{cluster.gcs_port}",
        io=cluster.io,
    )

    @rt.remote(num_tpus=4, num_cpus=0)
    def tpu_task():
        return 1

    ref = tpu_task.remote()
    time.sleep(1.2)
    launched = autoscaler.update()
    # One unmet TPU bundle still scales a whole 4-host slice.
    assert launched.get("v5e-slice") == 4
    assert len(provider.non_terminated_nodes()) == 4
    assert rt.get(ref, timeout=60) == 1

def test_tpu_slice_scale_down_is_atomic(scaling_cluster):
    """Idle slices terminate whole-slice or not at all: if even one host of
    a slice is busy, the autoscaler must not strand a partial slice."""
    cluster, provider = scaling_cluster
    autoscaler = StandardAutoscaler(
        {"node_types": {
            "v5e-slice": {"resources": {"TPU": 4, "CPU": 1},
                           "slice_hosts": 2, "max_workers": 2}},
         "idle_timeout_s": 0.5},
        provider,
        f"127.0.0.1:{cluster.gcs_port}",
        io=cluster.io,
    )

    @rt.remote(num_tpus=4, num_cpus=0)
    def tpu_task(t):
        time.sleep(t)
        return 1

    ref = tpu_task.remote(0.1)
    time.sleep(1.2)
    assert autoscaler.update().get("v5e-slice") == 2
    assert rt.get(ref, timeout=60) == 1

    # Keep ONE host of the slice busy: the whole slice must survive.
    busy = tpu_task.remote(6.0)
    time.sleep(1.2)
    for _ in range(4):
        time.sleep(0.7)
        autoscaler.update()
        assert len(provider.non_terminated_nodes()) == 2, (
            "partial slice terminated while one host was busy"
        )
    assert rt.get(busy, timeout=60) == 1

    # Fully idle: the slice terminates together (0 -> whole slice gone).
    def slice_gone():
        time.sleep(0.7)
        autoscaler.update()
        return len(provider.non_terminated_nodes()) == 0

    _wait(slice_gone, timeout=30)
