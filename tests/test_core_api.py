"""Core task/actor/object API tests against the real multiprocess runtime.

Modeled on the reference's python/ray/tests/test_basic*.py and
test_actor.py coverage.
"""

import time

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu.exceptions import ActorDiedError, GetTimeoutError, TaskError


pytestmark = pytest.mark.usefixtures("rt_start")


def test_put_get_roundtrip():
    ref = rt.put({"a": 1, "arr": np.arange(10)})
    out = rt.get(ref)
    assert out["a"] == 1
    assert np.array_equal(out["arr"], np.arange(10))


def test_simple_task():
    @rt.remote
    def add(a, b):
        return a + b

    assert rt.get(add.remote(1, 2)) == 3


def test_task_with_large_result():
    @rt.remote
    def big():
        return np.ones((1000, 1000))

    out = rt.get(big.remote())
    assert out.shape == (1000, 1000)
    assert out[0, 0] == 1.0


def test_task_chain_ref_args():
    @rt.remote
    def inc(x):
        return x + 1

    ref = inc.remote(0)
    for _ in range(5):
        ref = inc.remote(ref)
    assert rt.get(ref) == 6


def test_task_chain_large_intermediate():
    @rt.remote
    def double(x):
        return x * 2

    ref = double.remote(np.ones(200_000))
    ref = double.remote(ref)
    out = rt.get(ref)
    assert out[0] == 4.0


def test_parallel_tasks():
    @rt.remote
    def sq(i):
        return i * i

    refs = [sq.remote(i) for i in range(20)]
    assert rt.get(refs) == [i * i for i in range(20)]


def test_task_error_propagates():
    @rt.remote
    def boom():
        raise ValueError("bad value")

    with pytest.raises(TaskError) as ei:
        rt.get(boom.remote())
    assert "bad value" in str(ei.value)
    assert ei.value.cause_cls_name == "ValueError"


def test_num_returns():
    @rt.remote(num_returns=3)
    def three():
        return 1, 2, 3

    a, b, c = three.remote()
    assert rt.get([a, b, c]) == [1, 2, 3]


def test_wait():
    @rt.remote
    def fast():
        return "fast"

    @rt.remote
    def slow():
        time.sleep(20)
        return "slow"

    f, s = fast.remote(), slow.remote()
    ready, pending = rt.wait([f, s], num_returns=1, timeout=15)
    assert ready == [f]
    assert pending == [s]


def test_get_timeout():
    @rt.remote
    def sleepy():
        time.sleep(10)

    with pytest.raises(GetTimeoutError):
        rt.get(sleepy.remote(), timeout=0.3)


def test_nested_refs_in_args():
    @rt.remote
    def make():
        return np.arange(1000)

    @rt.remote
    def consume(refs):
        return sum(rt.get(r)[0] for r in refs)

    refs = [make.remote() for _ in range(3)]
    assert rt.get(consume.remote(refs)) == 0


def test_nested_task_submission():
    @rt.remote
    def outer():
        @rt.remote
        def inner(x):
            return x * 10

        return rt.get(inner.remote(4))

    assert rt.get(outer.remote()) == 40


def test_basic_actor():
    @rt.remote
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def inc(self, by=1):
            self.n += by
            return self.n

        def value(self):
            return self.n

    c = Counter.remote(10)
    assert rt.get(c.inc.remote()) == 11
    assert rt.get(c.inc.remote(5)) == 16
    assert rt.get(c.value.remote()) == 16


def test_actor_call_ordering():
    @rt.remote
    class Appender:
        def __init__(self):
            self.items = []

        def add(self, x):
            self.items.append(x)

        def items_list(self):
            return self.items

    a = Appender.remote()
    for i in range(50):
        a.add.remote(i)
    assert rt.get(a.items_list.remote()) == list(range(50))


def test_actor_error_propagates():
    @rt.remote
    class Bad:
        def fail(self):
            raise RuntimeError("actor oops")

    b = Bad.remote()
    with pytest.raises(TaskError) as ei:
        rt.get(b.fail.remote())
    assert "actor oops" in str(ei.value)


def test_named_actor():
    @rt.remote
    class Registry:
        def __init__(self):
            self.d = {}

        def set(self, k, v):
            self.d[k] = v

        def get(self, k):
            return self.d.get(k)

    Registry.options(name="reg").remote()
    h = rt.get_actor("reg")
    rt.get(h.set.remote("k", 42))
    assert rt.get(h.get.remote("k")) == 42


def test_kill_actor():
    @rt.remote
    class Victim:
        def ping(self):
            return "pong"

    v = Victim.remote()
    assert rt.get(v.ping.remote()) == "pong"
    rt.kill(v)
    time.sleep(0.5)
    with pytest.raises((ActorDiedError, Exception)):
        rt.get(v.ping.remote(), timeout=10)


def test_actor_handle_passed_to_task():
    @rt.remote
    class Store:
        def __init__(self):
            self.v = None

        def set(self, v):
            self.v = v

        def get(self):
            return self.v

    @rt.remote
    def writer(handle, value):
        rt.get(handle.set.remote(value))
        return True

    s = Store.remote()
    assert rt.get(writer.remote(s, 123))
    assert rt.get(s.get.remote()) == 123


def test_cluster_resources():
    res = rt.cluster_resources()
    assert res.get("CPU") == 4.0


def test_runtime_context():
    ctx = rt.get_runtime_context()
    assert ctx.worker_mode == "driver"
    assert ctx.node_id is not None


def test_task_inside_actor():
    @rt.remote
    def helper(x):
        return x + 1

    @rt.remote
    class Orchestrator:
        def run(self):
            return rt.get(helper.remote(41))

    o = Orchestrator.remote()
    assert rt.get(o.run.remote()) == 42


def test_dynamic_generator_task_streams_items():
    """num_returns="dynamic": a generator task's item refs become
    consumable WHILE the task is still yielding (reference:
    ObjectRefGenerator / streaming generators)."""
    import numpy as np

    @rt.remote
    def produce(n):
        for i in range(n):
            time.sleep(0.15)
            yield np.full(4, i, dtype=np.float64)

    gen = produce.options(num_returns="dynamic").remote(5)
    assert isinstance(gen, rt.ObjectRefGenerator)
    arrivals = []
    values = []
    for ref in gen:
        arrivals.append(time.monotonic())
        values.append(rt.get(ref, timeout=30))
    assert len(values) == 5
    for i, v in enumerate(values):
        assert v[0] == float(i)
    # Streaming proof: items arrived SPREAD over the generator's ~0.75s
    # of yields, not in one burst at completion (first-to-last arrival
    # spans most of the runtime).
    spread = arrivals[-1] - arrivals[0]
    assert spread > 0.4, f"not streaming: all items within {spread:.2f}s"


def test_dynamic_generator_non_generator_value():
    @rt.remote
    def single():
        return 42

    gen = single.options(num_returns="dynamic").remote()
    vals = [rt.get(r, timeout=30) for r in gen]
    assert vals == [42]


def test_dynamic_generator_error_propagates():
    @rt.remote(max_retries=0)
    def explode():
        yield 1
        raise RuntimeError("mid-stream failure")

    gen = explode.options(num_returns="dynamic").remote()
    with pytest.raises(Exception, match="mid-stream failure"):
        for ref in gen:
            rt.get(ref, timeout=30)


def test_dynamic_generator_actor_method():
    """Generator ACTOR methods stream items too (reference: streaming
    generator actor calls — the Serve token-streaming substrate)."""

    @rt.remote
    class Streamer:
        def tokens(self, n):
            for i in range(n):
                yield f"tok-{i}"

    s = Streamer.remote()
    gen = s.tokens.options(num_returns="dynamic").remote(4)
    assert isinstance(gen, rt.ObjectRefGenerator)
    out = [rt.get(r, timeout=30) for r in gen]
    assert out == ["tok-0", "tok-1", "tok-2", "tok-3"]


@pytest.mark.slow
def test_max_calls_retires_worker(rt_start):
    """@rt.remote(max_calls=N): the worker process exits after N
    executions and the pool replaces it — tasks keep completing on fresh
    pids (reference: remote_function.py max_calls leak mitigation)."""
    import os as _os

    @rt.remote(max_calls=3)
    def pid():
        import os

        return os.getpid()

    # Serialize calls so the per-worker counter is deterministic.
    pids = [rt.get(pid.remote(), timeout=120) for _ in range(9)]
    # Every worker served at most 3 calls.
    from collections import Counter

    counts = Counter(pids)
    assert all(c <= 3 for c in counts.values()), counts
    assert len(counts) >= 3  # at least three generations of workers
    # And an unlimited function on the same cluster is unaffected.
    @rt.remote
    def pid2():
        import os

        return os.getpid()

    pids2 = {rt.get(pid2.remote(), timeout=120) for _ in range(4)}
    assert len(pids2) >= 1
