"""Runtime environment tests.

Reference analogs: python/ray/tests/test_runtime_env*.py (env_vars,
working_dir packaging via the GCS KV, per-env worker isolation).
"""

import os
import sys
import time

import pytest

import ray_tpu as rt
from ray_tpu.runtime_env import RuntimeEnv


def test_runtime_env_validation():
    assert RuntimeEnv(pip=["requests"])["pip"] == ["requests"]
    with pytest.raises(TypeError):
        RuntimeEnv(pip=[1, 2])
    with pytest.raises(ValueError):
        RuntimeEnv(conda="env.yml")
    with pytest.raises(ValueError):
        RuntimeEnv(bogus_field=1)
    with pytest.raises(TypeError):
        RuntimeEnv(env_vars={"A": 1})


def test_env_vars_per_task(rt_start):
    @rt.remote
    def read_env(name):
        return os.environ.get(name)

    assert rt.get(read_env.remote("RT_TEST_FLAG")) is None
    got = rt.get(
        read_env.options(
            runtime_env={"env_vars": {"RT_TEST_FLAG": "on"}}
        ).remote("RT_TEST_FLAG")
    )
    assert got == "on"
    # Plain tasks keep using env-less workers.
    assert rt.get(read_env.remote("RT_TEST_FLAG")) is None


def test_working_dir_ships_code(rt_start, tmp_path):
    pkg = tmp_path / "proj"
    pkg.mkdir()
    (pkg / "shipped_mod.py").write_text("MAGIC = 'shipped-42'\n")
    (pkg / "data.txt").write_text("payload\n")

    @rt.remote(runtime_env={"working_dir": str(pkg)})
    def use_shipped():
        import shipped_mod  # importable because cwd/sys.path include the pkg

        with open("data.txt") as f:
            data = f.read().strip()
        return shipped_mod.MAGIC, data, os.path.basename(os.getcwd()) != "proj"

    magic, data, relocated = rt.get(use_shipped.remote(), timeout=60)
    assert magic == "shipped-42"
    assert data == "payload"


def test_py_modules(rt_start, tmp_path):
    mod_dir = tmp_path / "libs"
    mod_dir.mkdir()
    (mod_dir / "extra_lib.py").write_text("def f():\n    return 99\n")

    @rt.remote(runtime_env={"py_modules": [str(mod_dir)]})
    def use_lib():
        import extra_lib

        return extra_lib.f()

    assert rt.get(use_lib.remote(), timeout=60) == 99


def test_actor_runtime_env(rt_start):
    @rt.remote(runtime_env={"env_vars": {"ACTOR_ENV": "yes"}})
    class EnvActor:
        def read(self):
            return os.environ.get("ACTOR_ENV")

    a = EnvActor.remote()
    assert rt.get(a.read.remote(), timeout=60) == "yes"


def test_job_level_runtime_env(rt_start_env):
    """runtime_env passed to init() applies to all tasks of the job."""

    @rt.remote
    def read():
        return os.environ.get("JOB_WIDE")

    assert rt.get(read.remote(), timeout=60) == "set"


@pytest.fixture
def rt_start_env():
    rt.init(num_cpus=2, runtime_env={"env_vars": {"JOB_WIDE": "set"}})
    yield rt
    rt.shutdown()


def test_bad_runtime_env_fails_fast(rt_start):
    """A broken env must error the task, not crash-loop worker spawns."""

    @rt.remote(max_retries=0)
    def never_runs():
        return 1

    with pytest.raises(rt.exceptions.TaskError):
        rt.get(
            never_runs.options(
                runtime_env={"working_dir": "gcs://_rt_pkg_bogus.zip"}
            ).remote(),
            timeout=60,
        )


def test_new_env_when_pool_is_full(rt_start):
    """A task with a fresh env hash must not starve behind a pool full of
    plain workers (an idle one is replaced)."""
    import ray_tpu._private.config as config_mod

    @rt.remote
    def plain():
        return os.getpid()

    # Fill the pool with plain workers.
    rt.get([plain.remote() for _ in range(4)])

    @rt.remote
    def with_env():
        return os.environ.get("POOLTEST")

    old = config_mod.get_config().max_workers_per_node
    config_mod.get_config().max_workers_per_node = len(
        rt._worker._global_node.raylet.workers
    )
    try:
        got = rt.get(
            with_env.options(
                runtime_env={"env_vars": {"POOLTEST": "yes"}}
            ).remote(),
            timeout=60,
        )
        assert got == "yes"
    finally:
        config_mod.get_config().max_workers_per_node = old


def test_job_env_inherited_by_tasks(rt_start, tmp_path):
    """Tasks spawned by a submitted job's driver see the job working_dir."""
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.job import JobSubmissionClient

    proj = tmp_path / "inheritproj"
    proj.mkdir()
    (proj / "helper_mod.py").write_text("TOKEN = 'inherited-7'\n")
    (proj / "driver.py").write_text(
        "import ray_tpu as rt\n"
        "import os\n"
        "rt.init(address=os.environ['RT_GCS_ADDR'])\n"
        "@rt.remote\n"
        "def task():\n"
        "    import helper_mod\n"
        "    return helper_mod.TOKEN\n"
        "print('task got', rt.get(task.remote(), timeout=60))\n"
        "rt.shutdown()\n"
    )

    client = JobSubmissionClient(worker_mod._global_node.gcs_address)
    try:
        sid = client.submit_job(
            entrypoint=f"{sys.executable} driver.py",
            runtime_env={"working_dir": str(proj)},
        )
        state = client.wait_until_finished(sid, timeout=120)
        logs = client.get_job_logs(sid)
        assert state == "SUCCEEDED", logs
        assert "task got inherited-7" in logs
    finally:
        client.close()


def test_job_submission_working_dir(rt_start, tmp_path):
    from ray_tpu._private import worker as worker_mod
    from ray_tpu.job import JobSubmissionClient

    proj = tmp_path / "jobproj"
    proj.mkdir()
    (proj / "main.py").write_text("print('job saw', open('marker.txt').read().strip())\n")
    (proj / "marker.txt").write_text("m4rk3r\n")

    client = JobSubmissionClient(worker_mod._global_node.gcs_address)
    try:
        sid = client.submit_job(
            entrypoint=f"{sys.executable} main.py",
            runtime_env={"working_dir": str(proj),
                         "env_vars": {"IGNORED": "1"}},
        )
        assert client.wait_until_finished(sid, timeout=60) == "SUCCEEDED"
        assert "job saw m4rk3r" in client.get_job_logs(sid)
    finally:
        client.close()

def test_pip_env_installs_dependency_driver_lacks(rt_start, tmp_path):
    """runtime_env={"pip": [...]} builds a per-env-hash venv and the task
    imports a package the driver process does not have (reference:
    _private/runtime_env/pip.py). Uses a local sdist so the zero-egress
    test image needs no index."""
    pkg = tmp_path / "rt_pip_dep"
    (pkg / "rt_pip_dep").mkdir(parents=True)
    (pkg / "rt_pip_dep" / "__init__.py").write_text("MAGIC = 'from-venv'\n")
    (pkg / "pyproject.toml").write_text(
        '[build-system]\n'
        'requires = ["setuptools"]\n'
        'build-backend = "setuptools.build_meta"\n'
        '[project]\n'
        'name = "rt-pip-dep"\n'
        'version = "0.0.1"\n'
        '[tool.setuptools.packages.find]\n'
        'include = ["rt_pip_dep"]\n'
    )

    with pytest.raises(ImportError):
        import rt_pip_dep  # noqa: F401 — the driver must NOT have it

    @rt.remote(
        runtime_env={
            "pip": ["--no-index", "--no-build-isolation", str(pkg)]
        },
        max_retries=0,
    )
    def use_dep():
        import rt_pip_dep

        return rt_pip_dep.MAGIC

    assert rt.get(use_dep.remote(), timeout=300) == "from-venv"


def test_conda_still_rejected(rt_start):
    with pytest.raises(ValueError, match="conda"):
        RuntimeEnv(conda={"dependencies": ["pip"]})


def test_custom_plugin_propagates_to_workers(rt_start, tmp_path):
    """The RuntimeEnvPlugin seam end-to-end (reference: plugin.py +
    RAY_RUNTIME_ENV_PLUGINS class-path loading): a plugin registered on
    the driver ships its import path with the resolved env; the worker
    imports it from the py_modules package and applies it before user
    code runs."""
    moddir = tmp_path / "plugmod"
    moddir.mkdir()
    (moddir / "__init__.py").write_text("")
    (moddir / "marker.py").write_text(
        "import os\n"
        "from ray_tpu.runtime_env.runtime_env import RuntimeEnvPlugin\n"
        "class MarkerPlugin(RuntimeEnvPlugin):\n"
        "    name = 'marker'\n"
        "    def prepare(self, value, client):\n"
        "        return value.upper()  # driver-side transform\n"
        "    def apply(self, value, client):\n"
        "        os.environ['RT_MARKER'] = value\n"
    )
    import sys

    sys.path.insert(0, str(tmp_path))
    try:
        from plugmod.marker import MarkerPlugin

        from ray_tpu.runtime_env.runtime_env import (
            _plugins,
            register_plugin,
        )

        register_plugin(MarkerPlugin())
        try:

            @rt.remote
            def read_marker():
                import os

                return os.environ.get("RT_MARKER")

            result = rt.get(
                read_marker.options(
                    runtime_env={
                        "py_modules": [str(tmp_path)],
                        "marker": "hello",
                    }
                ).remote(),
                timeout=120,
            )
            # prepare() ran on the driver (upper), apply() in the worker.
            assert result == "HELLO"
        finally:
            _plugins.pop("marker", None)
    finally:
        sys.path.remove(str(tmp_path))
