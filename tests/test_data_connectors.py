"""Connector datasources: SQL (real sqlite3), TFRecords (wire codec),
WebDataset tar shards, Mongo/BigQuery recorded fakes, tensor columns.

Reference model: python/ray/data/tests per-datasource suites; SQL runs
against a REAL DB-API driver (stdlib sqlite3), the cloud-shaped sources
against injected fakes (the GKE-provider recorded-surface pattern).
"""

import os
import struct
import tarfile

import numpy as np
import pytest

import ray_tpu as rt
import ray_tpu.data as rtd

pytestmark = pytest.mark.usefixtures("rt_start")


# ---------------------------------------------------------------------------
# SQL
# ---------------------------------------------------------------------------


def _sqlite_factory(path):
    def factory():
        import sqlite3

        return sqlite3.connect(path)
    return factory


def test_read_sql_roundtrip(tmp_path):
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE users (id INTEGER, name TEXT)")
    conn.executemany(
        "INSERT INTO users VALUES (?, ?)",
        [(i, f"user{i}") for i in range(20)],
    )
    conn.commit()
    conn.close()

    ds = rtd.read_sql("SELECT * FROM users", _sqlite_factory(db))
    rows = sorted(ds.take_all(), key=lambda r: r["id"])
    assert len(rows) == 20
    assert rows[3] == {"id": 3, "name": "user3"}


def test_read_sql_sharded(tmp_path):
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE nums (id INTEGER)")
    conn.executemany("INSERT INTO nums VALUES (?)", [(i,) for i in range(30)])
    conn.commit()
    conn.close()

    ds = rtd.read_sql("SELECT * FROM nums", _sqlite_factory(db),
                      parallelism=3, shard_column="id")
    ids = sorted(r["id"] for r in ds.take_all())
    assert ids == list(range(30))


def test_write_sql_datasink(tmp_path):
    import sqlite3

    from ray_tpu.data.connectors import SQLDatasink

    db = str(tmp_path / "out.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE out (id INTEGER, sq INTEGER)")
    conn.commit()
    conn.close()

    ds = rtd.range(10, parallelism=2).map(
        lambda r: {"id": r["id"], "sq": r["id"] ** 2}
    )
    ds.write_datasink(SQLDatasink("out", _sqlite_factory(db)))
    conn = sqlite3.connect(db)
    rows = sorted(conn.execute("SELECT id, sq FROM out").fetchall())
    conn.close()
    assert rows == [(i, i * i) for i in range(10)]


# ---------------------------------------------------------------------------
# TFRecords
# ---------------------------------------------------------------------------


def test_example_wire_codec_roundtrip():
    from ray_tpu.data.connectors import decode_example, encode_example

    features = {
        "label": 7,
        "name": b"cat",
        "weights": [0.25, 0.5],
        "ids": [1, 2, 300000],
        "neg": -5,
    }
    decoded = decode_example(encode_example(features))
    assert decoded["label"] == [7]
    assert decoded["name"] == [b"cat"]
    assert decoded["ids"] == [1, 2, 300000]
    assert decoded["neg"] == [-5]
    np.testing.assert_allclose(decoded["weights"], [0.25, 0.5], rtol=1e-6)


def test_tfrecords_write_read_roundtrip(tmp_path):
    from ray_tpu.data.connectors import TFRecordDatasink

    out_dir = str(tmp_path / "records")
    ds = rtd.range(12, parallelism=3).map(
        lambda r: {"id": r["id"], "name": f"row{r['id']}"}
    )
    ds.write_datasink(TFRecordDatasink(out_dir))
    assert len(os.listdir(out_dir)) == 3  # one shard per write task

    back = rtd.read_tfrecords(out_dir, parallelism=3)
    rows = sorted(back.take_all(), key=lambda r: r["id"])
    assert [r["id"] for r in rows] == list(range(12))
    assert rows[5]["name"] == b"row5"  # bytes_list: bytes out


def test_tfrecords_crc_layout(tmp_path):
    """The written framing matches the TFRecord spec byte layout
    (u64 len + masked crc32c(len) + data + masked crc32c(data)) — the
    compatibility contract with real TF readers."""
    from ray_tpu.data.connectors import (
        _masked_crc, encode_example, TFRecordDatasink,
    )

    out_dir = str(tmp_path / "r")
    rtd.from_items([{"x": 1}], parallelism=1).write_datasink(
        TFRecordDatasink(out_dir)
    )
    raw = open(os.path.join(out_dir, os.listdir(out_dir)[0]), "rb").read()
    (length,) = struct.unpack_from("<Q", raw, 0)
    (len_crc,) = struct.unpack_from("<I", raw, 8)
    data = raw[12:12 + length]
    (data_crc,) = struct.unpack_from("<I", raw, 12 + length)
    assert len_crc == _masked_crc(raw[:8])
    assert data_crc == _masked_crc(data)
    assert data == encode_example({"x": 1})


# ---------------------------------------------------------------------------
# WebDataset
# ---------------------------------------------------------------------------


def test_read_webdataset(tmp_path):
    from PIL import Image

    shard = tmp_path / "shard-000.tar"
    with tarfile.open(shard, "w") as tar:
        for key in ("a", "b"):
            img_path = tmp_path / f"{key}.png"
            Image.fromarray(
                np.full((4, 4, 3), ord(key), dtype=np.uint8)
            ).save(img_path)
            tar.add(img_path, arcname=f"{key}.png")
            cls_path = tmp_path / f"{key}.cls"
            cls_path.write_text(str(ord(key)))
            tar.add(cls_path, arcname=f"{key}.cls")

    ds = rtd.read_webdataset(str(tmp_path), parallelism=1)
    rows = sorted(ds.take_all(), key=lambda r: r["__key__"])
    assert [r["__key__"] for r in rows] == ["a", "b"]
    assert rows[0]["cls"] == ord("a")
    assert rows[0]["png"].shape == (4, 4, 3)
    assert rows[0]["png"][0, 0, 0] == ord("a")


# ---------------------------------------------------------------------------
# Mongo / BigQuery fakes
# ---------------------------------------------------------------------------


class _FakeMongo:
    """pymongo surface: client[db][coll].find(filter)."""

    def __init__(self, docs):
        self._docs = docs

    def __getitem__(self, db):
        return self

    def find(self, flt):
        return [
            d for d in self._docs
            if all(d.get(k) == v for k, v in flt.items())
        ]


def test_read_mongo_fake():
    docs = [{"_id": i, "v": i * 10} for i in range(6)]
    ds = rtd.read_mongo(
        "db", "coll", lambda: _FakeMongo(docs), filter={"v": 30}
    )
    assert ds.take_all() == [{"_id": 3, "v": 30}]
    ds2 = rtd.read_mongo("db", "coll", lambda: _FakeMongo(docs))
    assert len(ds2.take_all()) == 6


class _FakeBQ:
    def query(self, sql):
        class _Job:
            def result(self):
                return [{"n": i, "sql_len": len(sql)} for i in range(4)]
        return _Job()


def test_read_bigquery_fake():
    ds = rtd.read_bigquery("SELECT 1", _FakeBQ())
    rows = ds.take_all()
    assert len(rows) == 4 and rows[0]["sql_len"] == len("SELECT 1")


# ---------------------------------------------------------------------------
# Tensor extension
# ---------------------------------------------------------------------------


def test_tensor_columns_zero_copy_batches():
    """Multi-dim from_numpy columns become arrow tensor columns, survive
    the store, and batch as zero-copy reshaped views (the image version
    of the Plasma<->HBM boundary)."""
    imgs = np.arange(10 * 4 * 4 * 3, dtype=np.float32).reshape(10, 4, 4, 3)
    labels = np.arange(10, dtype=np.int64)
    ds = rtd.from_numpy({"img": imgs, "y": labels}, parallelism=2)
    batches = list(ds.iter_batches(batch_size=5, batch_format="numpy"))
    got = np.concatenate([b["img"] for b in batches])
    np.testing.assert_array_equal(np.sort(got.ravel()),
                                  np.sort(imgs.ravel()))
    for b in batches:
        assert b["img"].shape[1:] == (4, 4, 3)
        assert not b["img"].flags.owndata  # view over the block buffer


def test_tensor_table_roundtrip_through_store():
    from ray_tpu.data.tensor import table_with_tensors, tensor_to_numpy

    arr = np.random.default_rng(0).normal(size=(6, 2, 3)).astype(np.float32)
    t = table_with_tensors({"x": arr})
    ref = rt.put(t)
    out = rt.get(ref)
    back = tensor_to_numpy(out.column("x"))
    np.testing.assert_array_equal(back, arr)
    assert not back.flags.owndata


def test_read_sql_sharded_null_keys_not_dropped(tmp_path):
    """NULL shard keys land in shard 0 instead of vanishing (COALESCE
    in the shard predicate)."""
    import sqlite3

    db = str(tmp_path / "t.db")
    conn = sqlite3.connect(db)
    conn.execute("CREATE TABLE nums (id INTEGER)")
    conn.executemany("INSERT INTO nums VALUES (?)",
                     [(i,) for i in range(10)] + [(None,)] * 3)
    conn.commit()
    conn.close()
    ds = rtd.read_sql("SELECT * FROM nums", _sqlite_factory(db),
                      parallelism=3, shard_column="id")
    rows = ds.take_all()
    assert len(rows) == 13
    assert sum(1 for r in rows if r["id"] is None) == 3


def test_decode_example_unpacked_int64():
    """Legal unpacked Int64List encoding (one varint field per value,
    proto2-style writers) decodes like the packed form."""
    from ray_tpu.data.connectors import (
        _len_field, _varint, decode_example,
    )

    # Feature { int64_list { value: 5 value: -2 } } with UNPACKED values
    # (field 1, wire type 0, one per value).
    unpacked = (_varint(1 << 3 | 0) + _varint(5)
                + _varint(1 << 3 | 0) + _varint((-2) & (2 ** 64 - 1)))
    feature = _len_field(3, unpacked)
    entry = _len_field(1, b"ids") + _len_field(2, feature)
    example = _len_field(1, _len_field(1, entry))
    assert decode_example(example)["ids"] == [5, -2]
