"""Paged KV engine: block-table allocator, prefix cache, affinity
routing pieces, and the ServeSignals-driven autoscaler.

Covers the PR's acceptance list: bit-exact paged-vs-slotted decode on
mixed-length batches, zero page leak over 1k admit/evict cycles,
prefix-share correctness when the donor's cache entries are evicted
mid-share, typed prompt rejection (+ proxy 413 mapping), chaos KV
hooks, autoscaler hysteresis with a fake clock, and the schema-v2
signals surface (old readers keep working).
"""

import time
from dataclasses import replace

import numpy as np
import pytest

from ray_tpu.serve import paged_kv
from ray_tpu.serve.paged_kv import (
    NULL_PAGE,
    OutOfPages,
    PagePool,
    PrefixCache,
    page_hashes,
    prefix_route_key,
)


def _tiny_model():
    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, dtype=np.float32)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


# -- page pool ------------------------------------------------------------
def test_page_pool_alloc_release_refcount():
    pool = PagePool(9, 16)
    assert pool.usable == 8 and pool.free_pages == 8 and pool.in_use == 0
    pages = pool.alloc(3)
    assert len(pages) == 3 and NULL_PAGE not in pages
    assert pool.in_use == 3 and pool.free_pages == 5
    # A second reference keeps the page allocated past one release.
    pool.ref(pages[:1])
    assert pool.refcount(pages[0]) == 2
    pool.release(pages[:1])
    assert pool.in_use == 3
    pool.release(pages)
    assert pool.in_use == 0 and pool.free_pages == 8
    # Releasing an unallocated page is a bug, not a no-op.
    with pytest.raises(ValueError):
        pool.release(pages[:1])


def test_page_pool_alloc_is_all_or_nothing():
    pool = PagePool(5, 4)  # 4 usable
    pool.alloc(3)
    with pytest.raises(OutOfPages) as ei:
        pool.alloc(2)
    assert ei.value.needed == 2 and ei.value.free == 1
    # The failed alloc must not leak its partial grab.
    assert pool.free_pages == 1


# -- prefix trie ----------------------------------------------------------
def test_page_hashes_chain_and_route_key():
    a = page_hashes(list(range(8)), 4)
    b = page_hashes([0, 1, 2, 3, 9, 9, 9, 9], 4)
    assert len(a) == 2
    assert a[0] == b[0] and a[1] != b[1]  # chain hash: shared first page
    # Only FULL pages hash; the partial tail never enters the trie.
    assert len(page_hashes(list(range(5)), 4)) == 1
    assert prefix_route_key(list(range(5)), 4) == a[0]
    assert prefix_route_key([1, 2], 4) is None


def test_prefix_cache_match_insert_evict():
    pool = PagePool(17, 4)
    cache = PrefixCache(pool)
    keys = page_hashes(list(range(12)), 4)
    pages = pool.alloc(3)
    cache.insert(keys, pages)
    pool.release(pages)  # cache holds its own refs
    assert pool.in_use == 3 and cache.pages_held == 3
    got = cache.match(keys)
    assert got == pages  # one ref per matched page handed to the caller
    pool.release(got)
    part = cache.match(keys[:2] + ["not-a-real-key"])
    assert part == pages[:2]
    pool.release(part)
    assert cache.match(page_hashes(list(range(100, 112)), 4)) == []
    assert keys[0] in cache.roots()
    # LRU eviction and flush both hand pages back to the pool.
    assert cache.evict_pages(1) >= 1
    cache.flush()
    assert cache.pages_held == 0 and pool.in_use == 0


# -- engine: bit-exactness ------------------------------------------------
def test_paged_vs_slotted_bit_exact_mixed_lengths():
    """The paged decode must produce token-for-token identical output to
    the slotted baseline for a concurrent mixed-length batch."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    prompts = [[1, 2, 3], [5, 6, 7, 8, 9, 10, 11], [4],
               [9, 9, 2, 1, 3, 3, 7, 7, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10]]
    outs = {}
    for mode in ("slotted", "paged"):
        eng = ContinuousBatchingEngine(
            params, cfg, num_slots=4, max_len=64, kv_mode=mode,
            page_size=16,
        )
        try:
            handles = [eng.submit(p, max_new_tokens=8) for p in prompts]
            outs[mode] = [h.result(timeout=180) for h in handles]
        finally:
            eng.shutdown()
    assert outs["paged"] == outs["slotted"]


# -- engine: page accounting ----------------------------------------------
def test_zero_page_leak_over_1k_admit_evict_cycles():
    """1000 admissions/evictions leave the pool exactly empty. Prompts
    are shorter than a page, so nothing enters the prefix cache — every
    page cycles through alloc -> release."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=8, max_len=16, kv_mode="paged", page_size=4,
    )
    try:
        done = 0
        while done < 1000:
            wave = [
                eng.submit([1 + (done + i) % 50, 7], max_new_tokens=1)
                for i in range(50)
            ]
            for h in wave:
                assert len(h.result(timeout=180)) == 1
            done += len(wave)
        deadline = time.monotonic() + 30
        while eng.stats()["kv"]["pages_in_use"] != 0:
            assert time.monotonic() < deadline, (
                f"page leak after {done} cycles: "
                f"{eng.stats()['kv']}"
            )
            time.sleep(0.02)
        kv = eng.stats()["kv"]
        assert kv["prefix_cache_pages"] == 0
    finally:
        eng.shutdown()


def test_prefix_cache_skips_prefill_for_shared_prompt():
    """A repeat prompt hits the prefix cache, skips resident prefill
    pages (the skipped-token counter says so) and still decodes the
    same greedy tokens."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=2, max_len=64, kv_mode="paged", page_size=8,
    )
    try:
        prompt = [(3 * i + 1) % 50 for i in range(20)]  # 2 full pages
        cold = eng.submit(prompt, max_new_tokens=6).result(timeout=180)
        # Wait for completion-side bookkeeping (insert happens at
        # prefill end; release at eviction).
        deadline = time.monotonic() + 10
        while eng.stats()["kv"]["prefix_cache_pages"] < 2:
            assert time.monotonic() < deadline
            time.sleep(0.02)
        warm = eng.submit(prompt, max_new_tokens=6).result(timeout=180)
        assert warm == cold
        kv = eng.stats()["kv"]
        assert kv["prefix_hits"] >= 1  # one hit event per request
        assert kv["prefill_tokens_skipped"] >= 8
        assert kv["prefix_hit_rate"] > 0
    finally:
        eng.shutdown()


def test_prefix_share_survives_donor_eviction_mid_share():
    """Flush the prefix cache (chaos hook) while a sharer is actively
    decoding off shared pages: the sharer's own page references keep the
    pages alive, output stays correct, and the pool drains to zero
    afterwards (no double release, no leak)."""
    from ray_tpu._private import chaos
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=2, max_len=96, kv_mode="paged", page_size=8,
    )
    chaos.enable()
    try:
        prompt = [(7 * i + 3) % 50 for i in range(24)]  # 3 full pages
        ref = eng.submit(prompt, max_new_tokens=30).result(timeout=180)
        sharer = eng.submit(prompt, max_new_tokens=30)
        # Let the sharer get mid-decode, then yank the donor pages' cache
        # references out from under it.
        deadline = time.monotonic() + 60
        while eng.stats()["kv"]["prefix_hits"] < 1:
            assert time.monotonic() < deadline, "sharer never hit the cache"
            time.sleep(0.005)
        chaos.flush_prefix_cache()
        out = sharer.result(timeout=180)
        assert out == ref
        deadline = time.monotonic() + 30
        while True:
            kv = eng.stats()["kv"]
            if kv["pages_in_use"] == 0 and kv["prefix_cache_pages"] == 0:
                break
            assert time.monotonic() < deadline, f"pages leaked: {kv}"
            time.sleep(0.02)
    finally:
        chaos.disable()
        eng.shutdown()


def test_chaos_exhaust_kv_pages_blocks_then_releases_admission():
    from ray_tpu._private import chaos
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=2, max_len=32, kv_mode="paged", page_size=8,
    )
    chaos.enable()
    try:
        chaos.exhaust_kv_pages(1.0)
        h = eng.submit([1, 2, 3], max_new_tokens=2)
        deadline = time.monotonic() + 30
        while eng.stats()["kv"]["chaos_held_pages"] == 0:
            assert time.monotonic() < deadline, "chaos never grabbed pages"
            time.sleep(0.02)
        # The request cannot be admitted while chaos holds the pool.
        time.sleep(0.3)
        st = eng.stats()
        assert st["active"] == 0 and st["waiting"] == 1
        chaos.exhaust_kv_pages(0.0)
        assert len(h.result(timeout=180)) == 2
    finally:
        chaos.disable()
        eng.shutdown()


# -- typed prompt rejection ----------------------------------------------
def test_prompt_too_long_is_typed_and_bounded_by_pool():
    from ray_tpu.exceptions import PromptTooLongError
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    # Pool smaller than max_len: 3 usable pages x 8 = 24 positions.
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=1, max_len=64, kv_mode="paged", page_size=8,
        kv_pages=4,
    )
    try:
        with pytest.raises(PromptTooLongError) as ei:
            eng.submit(list(range(1, 40)), max_new_tokens=2)
        err = ei.value
        assert isinstance(err, ValueError)  # historical contract
        assert err.prompt_len == 39 and err.max_prompt_len == 22
        assert "39" in str(err) and "22" in str(err) and "page pool" in str(err)
        # An in-bound prompt still serves.
        assert len(
            eng.submit([5, 6, 7], max_new_tokens=2).result(timeout=180)
        ) == 2
    finally:
        eng.shutdown()


def test_proxy_maps_prompt_too_long_to_413():
    from ray_tpu.exceptions import PromptTooLongError, TaskError
    from ray_tpu.serve.proxy import _classify_error

    err = PromptTooLongError("too long", prompt_len=99, max_prompt_len=10)
    wrapped = TaskError("PromptTooLongError", "traceback...", cause=err)
    assert _classify_error(wrapped) == (413, None, "prompt_too_long")
    # Unpickleable cause: classification falls back to the class name.
    nameonly = TaskError("PromptTooLongError", "traceback...", cause=None)
    assert _classify_error(nameonly)[0] == 413


# -- autoscaler -----------------------------------------------------------
def _sig(ongoing_per_rep, n_reps, waiting=0, ttft_p99_s=None, burn=None):
    sig = {
        "replicas": [{"actor_id": f"r{i}", "ongoing": ongoing_per_rep}
                     for i in range(n_reps)],
        "waiting": waiting,
        "ttft_s": {"p99": ttft_p99_s, "p50": ttft_p99_s, "n": 10},
    }
    if burn is not None:
        sig["tenants"] = {
            "t": {"slo_windows": {"60": {"ttft": {"burn": burn}}}}
        }
    return sig


def test_autoscaler_hysteresis_with_fake_clock():
    from ray_tpu.serve.autoscale import AutoscalerState, decide
    from ray_tpu.serve.deployment import AutoscalingConfig

    acfg = AutoscalingConfig(
        min_replicas=1, max_replicas=3, target_ongoing_requests=2.0,
        upscale_delay_s=5.0, downscale_delay_s=20.0,
    )
    st = AutoscalerState()
    now, target = 1000.0, 1

    # Pressure must HOLD for upscale_delay_s before the target moves.
    assert decide(_sig(5, 1), acfg, st, now, target, 1) == 1
    assert decide(_sig(5, 1), acfg, st, now + 4.9, target, 1) == 1
    target = decide(_sig(5, 1), acfg, st, now + 5.1, target, 1)
    assert target == 2 and "ongoing" in st.last_reason

    # One replica per move: immediately after, the cooldown blocks.
    assert decide(_sig(5, 2), acfg, st, now + 5.2, target, 2) == 2
    target = decide(_sig(5, 2), acfg, st, now + 11.0, target, 2)
    assert target == 3
    # Clamped at max_replicas no matter the pressure.
    assert decide(_sig(50, 3), acfg, st, now + 60.0, target, 3) == 3

    # A blip below the hold threshold resets the timer (no flapping).
    st2 = AutoscalerState()
    decide(_sig(5, 1), acfg, st2, 0.0, 1, 1)
    decide(_sig(1, 1, waiting=0), acfg, st2, 3.0, 1, 1)  # pressure gone
    assert decide(_sig(5, 1), acfg, st2, 6.0, 1, 1) == 1  # hold restarted

    # Downscale needs a LONG quiet period and zero queue.
    now2, target = now + 100.0, 3
    assert decide(_sig(0, 3), acfg, st, now2, target, 3) == 3
    assert decide(_sig(0, 3), acfg, st, now2 + 19.0, target, 3) == 3
    target = decide(_sig(0, 3), acfg, st, now2 + 21.0, target, 3)
    assert target == 2 and st.last_reason.startswith("down")
    # Queued work vetoes downscale even with zero ongoing.
    st3 = AutoscalerState()
    assert decide(_sig(0, 2, waiting=5), acfg, st3, 0.0, 2, 2) == 2
    assert st3.low_since is None

    # Clamped at min_replicas.
    st4 = AutoscalerState()
    decide(_sig(0, 1), acfg, st4, 0.0, 1, 1)
    assert decide(_sig(0, 1), acfg, st4, 100.0, 1, 1) == 1


def test_autoscaler_optin_latency_and_burn_signals():
    from ray_tpu.serve.autoscale import AutoscalerState, decide
    from ray_tpu.serve.deployment import AutoscalingConfig

    acfg = AutoscalingConfig(
        target_ongoing_requests=10.0, upscale_delay_s=1.0,
        downscale_delay_s=1.0, max_replicas=4,
        ttft_p99_high_ms=100.0, burn_rate_high=2.0,
    )
    st = AutoscalerState()
    # TTFT p99 past the bound is upscale pressure on its own.
    sig = _sig(1, 2, ttft_p99_s=0.5)
    decide(sig, acfg, st, 0.0, 2, 2)
    assert decide(sig, acfg, st, 2.0, 2, 2) == 3
    assert "ttft" in st.last_reason
    # Elevated burn blocks downscale even when traffic looks idle.
    st2 = AutoscalerState()
    hot = _sig(0, 2, burn=5.0)
    decide(hot, acfg, st2, 0.0, 2, 2)
    assert decide(hot, acfg, st2, 50.0, 2, 2) == 3  # upscale, not down
    # Defaults (None) disable both signals entirely.
    acfg_off = AutoscalingConfig(target_ongoing_requests=10.0,
                                 upscale_delay_s=1.0)
    st3 = AutoscalerState()
    calm = _sig(1, 2, ttft_p99_s=9.9, burn=99.0)
    decide(calm, acfg_off, st3, 0.0, 2, 2)
    assert decide(calm, acfg_off, st3, 2.0, 2, 2) == 2


# -- signals schema v2 ----------------------------------------------------
def test_signals_schema_v2_and_old_reader_tolerance():
    from ray_tpu.scripts.scripts import _render_serve
    from ray_tpu.serve import observatory
    from ray_tpu.serve.autoscale import extract_load

    assert observatory.SIGNALS_SCHEMA_VERSION == 2

    # A v1-shaped doc (no kv / target_replicas / kv_util) still renders.
    old_doc = {
        "schema": 1, "seq": 3, "ts": time.time(),
        "apps": {"a": {
            "replicas": [{"actor_id": "ab" * 8, "ongoing": 1,
                          "total_served": 5}],
            "qps": 1.0, "waiting": 0,
            "ttft_s": {"p50": 0.01, "p99": 0.02, "n": 4},
            "tpot_s": {"p50": 0.001, "p99": 0.002, "n": 4},
        }},
    }
    out = _render_serve(old_doc)
    assert "app a" in out and "kv:" not in out

    # A v2 doc renders the new kv / replica-target columns.
    new_doc = {
        "schema": 2, "seq": 4, "ts": time.time(),
        "apps": {"a": {
            "replicas": [{"actor_id": "cd" * 8, "ongoing": 2,
                          "total_served": 9, "kv_util": 0.25}],
            "qps": 2.0, "waiting": 1,
            "target_replicas": 2, "running_replicas": 1,
            "kv": {"page_size": 16, "pages_total": 40, "pages_in_use": 10,
                   "util": 0.25, "prefix_hit_rate": 0.5,
                   "prefill_tokens_skipped": 128},
        }},
    }
    out = _render_serve(new_doc)
    assert "replicas=1/2" in out
    assert "kv: pages 10/40 (25%)" in out
    assert "prefix_hit=50%" in out and "kv=25%" in out

    # The decision-side reader tolerates both shapes too.
    assert extract_load(old_doc["apps"]["a"])["ongoing_mean"] == 1.0
    assert extract_load({})["replicas"] == 0


def test_engine_stats_expose_kv_plane_for_signals():
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(
        params, cfg, num_slots=2, max_len=32, kv_mode="paged", page_size=8,
    )
    try:
        eng.submit([(11 * i + 1) % 40 for i in range(16)],
                   max_new_tokens=2).result(timeout=180)
        kv = eng.stats()["kv"]
        assert kv["mode"] == "paged" and kv["page_size"] == 8
        assert kv["pages_total"] == 2 * 4  # slotted-HBM parity
        assert kv["prefix_cache_pages"] == 2  # the prompt's full pages
        assert kv["roots"]  # advertised for affinity routing
        assert 0.0 <= kv["util"] <= 1.0
    finally:
        eng.shutdown()

    slotted = ContinuousBatchingEngine(
        params, cfg, num_slots=2, max_len=32, kv_mode="slotted",
    )
    try:
        assert slotted.stats()["kv"] == {"mode": "slotted", "page_size": 0}
    finally:
        slotted.shutdown()


def test_handle_affinity_prefers_covering_replica():
    """_pick_replica with a route_key must choose the replica whose
    advertised prefix set covers it, not the P2C winner."""
    from ray_tpu.serve.handle import DeploymentHandle

    class _Aid:
        def __init__(self, b):
            self._b = b

        def binary(self):
            return self._b

        def hex(self):
            return self._b.hex()

    class _Rep:
        def __init__(self, b):
            self._actor_id = _Aid(b)

    r1, r2 = _Rep(b"\x01" * 8), _Rep(b"\x02" * 8)
    h = DeploymentHandle("app")
    key = prefix_route_key(list(range(16)), 16)
    s = h._shared
    with s["lock"]:
        s["replicas"] = [r1, r2]
        s["version"] = 1
        s["last_refresh"] = time.monotonic()
        s["prefix"] = {r2._actor_id.hex(): {key}}
        s["page_size"] = 16
        # Bias load AGAINST the covering replica: affinity must still win.
        s["inflight"] = {r2._actor_id.binary(): 5}
    assert h._route_key((list(range(16)),)) == key
    for _ in range(8):
        assert h._pick_replica(route_key=key) is r2
    # No coverage -> falls back to the load-based pick.
    assert h._pick_replica(route_key="unknown") in (r1, r2)
    # Short prompts / no advertised prefixes produce no route key.
    assert h._route_key(([1, 2],)) is None
    with s["lock"]:
        s["prefix"] = {}
    assert h._route_key((list(range(16)),)) is None
