"""Full-stack integration: Data pipeline -> JaxTrainer -> checkpoint ->
Serve with batching over real HTTP.

This is the end-to-end story a user of the reference stitches together
from ray.data + ray.train + ray.serve — here exercised as ONE flow on the
TPU-native stack (on the CPU test mesh).
"""

import json
import time
import urllib.request

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import data as rtd
from ray_tpu import serve
from ray_tpu.train import Checkpoint, JaxTrainer, RunConfig, ScalingConfig

pytestmark = pytest.mark.slow  # chaos/e2e tier — fast runs skip


def _train_loop(config):
    """Fit y = 2x + 1 by gradient descent over a Data shard."""
    import jax
    import jax.numpy as jnp

    from ray_tpu import train

    shard = config["shards"][train.get_world_rank()]
    xs = np.asarray([r["x"] for r in shard.take_all()], dtype=np.float32)
    ys = 2.0 * xs + 1.0

    params = {"w": jnp.zeros(()), "b": jnp.zeros(())}

    def loss_fn(p):
        pred = p["w"] * xs + p["b"]
        return ((pred - ys) ** 2).mean()

    grad = jax.jit(jax.grad(loss_fn))
    for step in range(config["steps"]):
        g = grad(params)
        params = jax.tree.map(lambda p, gg: p - 0.4 * gg, params, g)
        loss = float(loss_fn(params))
        if train.get_world_rank() == 0 and step == config["steps"] - 1:
            ckpt = Checkpoint.from_dict(
                {"w": float(params["w"]), "b": float(params["b"])}
            )
            train.report({"loss": loss}, checkpoint=ckpt)
        else:
            train.report({"loss": loss})


def test_data_train_serve_end_to_end(tmp_path):
    rt.init(num_cpus=4)
    try:
        # 1. Data: build + transform a dataset, split into worker shards.
        ds = rtd.from_items(
            [{"x": float(i)} for i in range(64)], parallelism=4
        ).map(lambda r: {"x": r["x"] / 64.0})
        shards = ds.split(2)

        # 2. Train: 2-worker data-parallel fit, checkpoint the model.
        trainer = JaxTrainer(
            _train_loop,
            train_loop_config={"steps": 300, "shards": shards},
            scaling_config=ScalingConfig(num_workers=2),
            run_config=RunConfig(name="e2e", storage_path=str(tmp_path)),
        )
        result = trainer.fit()
        assert result.error is None
        model = result.checkpoint.to_dict()
        assert abs(model["w"] - 2.0) < 0.3 and abs(model["b"] - 1.0) < 0.3

        # 3. Serve the checkpoint with dynamic batching over real HTTP.
        @serve.deployment(max_ongoing_requests=8)
        class LinearModel:
            def __init__(self, ckpt_dict):
                self.w = ckpt_dict["w"]
                self.b = ckpt_dict["b"]

            @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
            def predict(self, xs):
                return [self.w * x + self.b for x in xs]

            def __call__(self, x):
                return self.predict(x)

        serve.run(LinearModel.bind(model), name="linear")
        addr = serve.start_http_proxy(port=18455)

        from concurrent.futures import ThreadPoolExecutor

        def call(x):
            req = urllib.request.Request(
                f"{addr}/linear",
                data=json.dumps({"x": x}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=60) as resp:
                return json.loads(resp.read())["result"]

        with ThreadPoolExecutor(max_workers=8) as pool:
            preds = list(pool.map(call, [0.0, 0.25, 0.5, 1.0] * 2))
        for x, pred in zip([0.0, 0.25, 0.5, 1.0] * 2, preds):
            assert abs(pred - (model["w"] * x + model["b"])) < 1e-5
    finally:
        serve.shutdown()
        rt.shutdown()
