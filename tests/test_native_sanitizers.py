"""Native-store sanitizer gate (opt-in: `pytest -m sanitizer`).

Runs the 8-thread create/seal/get/release/delete stress harness
(store_thread_test.cc) under ThreadSanitizer and UndefinedBehavior-
Sanitizer via the native Makefile. Any TSan race report or UBSan
diagnostic makes the binary exit non-zero (-fno-sanitize-recover), so a
regression in the store's locking or offset arithmetic fails the test
with the sanitizer report in the assertion message.
"""

import os
import shutil
import subprocess

import pytest

NATIVE = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "ray_tpu", "native")

pytestmark = pytest.mark.sanitizer

needs_toolchain = pytest.mark.skipif(
    shutil.which("g++") is None or shutil.which("make") is None,
    reason="needs g++ and make",
)


def _run(target: str) -> subprocess.CompletedProcess:
    return subprocess.run(
        ["make", target], cwd=NATIVE, capture_output=True, text=True,
        timeout=600,
    )


@needs_toolchain
def test_store_stress_under_tsan():
    r = _run("tsan_test")
    assert r.returncode == 0, f"TSan run failed:\n{r.stdout}\n{r.stderr}"
    assert "STORE THREAD TESTS OK" in r.stdout
    assert "WARNING: ThreadSanitizer" not in r.stdout + r.stderr


@needs_toolchain
def test_store_stress_under_ubsan():
    r = _run("ubsan_test")
    assert r.returncode == 0, f"UBSan run failed:\n{r.stdout}\n{r.stderr}"
    assert "STORE THREAD TESTS OK" in r.stdout
    assert "runtime error" not in r.stdout + r.stderr
