"""Serve request observatory: phase attribution, SLO burn, ServeSignals.

The request-path mirror of test_flight_recorder.py: every request gets a
phase vector that sums to its e2e wall, tenants get SLO burn accounting,
the controller publishes ServeSignals to the GCS KV, and the engine's
HOL watchdog attributes decode stalls to the prefill that caused them.
"""

import time
from dataclasses import replace

import numpy as np
import pytest

import ray_tpu as rt
from ray_tpu import serve
from ray_tpu.serve import observatory
from ray_tpu.serve.deployment import SloConfig


@pytest.fixture
def serve_session(rt_start):
    yield rt_start
    serve.shutdown()


@pytest.fixture
def fresh_observatory():
    observatory.reset_for_tests()
    yield
    observatory.reset_for_tests()


def _tiny_model():
    import jax

    from ray_tpu.models import configs, init_params

    cfg = replace(configs.tiny, dtype=np.float32)
    return init_params(jax.random.PRNGKey(0), cfg), cfg


def _fabricated_request(tenant="t", e2e_parts=(0.001, 0.002), tokens_out=0):
    """Drive one synthetic request through begin/finish with real clocks
    (sleeps are ms-scale; the phase math never sees wall-clock jitter
    because it telescopes over its own stamps)."""
    w = observatory.make_wire_ctx(tenant)
    w["disp_t"] = time.time()
    ctx = observatory.begin(w, "synth", "__call__")
    if tokens_out:
        ctx.mark("engine_enqueue")
        ctx.mark("slot_grant")
        time.sleep(e2e_parts[0])
        ctx.mark("first_token")
        time.sleep(e2e_parts[1])
        ctx.mark("engine_done")
        ctx.tokens_out = tokens_out
    else:
        time.sleep(sum(e2e_parts))
    return observatory.finish(ctx)


# -- phase attribution --------------------------------------------------

def test_engine_phase_vector_sums_to_e2e(fresh_observatory):
    """The tentpole invariant: through a REAL engine (submit -> slot
    grant -> prefill -> decode -> done), the six-phase vector sums to
    the request's e2e wall by construction, and every engine phase is
    attributed (no 'exec' fallback)."""
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    observatory.configure("llm-test", None)
    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=64)
    try:
        w = observatory.make_wire_ctx("acme")
        time.sleep(0.002)
        w["disp_t"] = time.time()
        ctx = observatory.begin(w, "llm-test", "__call__")
        h = eng.submit([3, 7, 11, 2], max_new_tokens=8)
        toks = h.result(timeout=120)
        rec = observatory.finish(ctx)
    finally:
        eng.shutdown()
    assert len(toks) == 8
    phases = rec["phases"]
    for p in ("handle_queue", "dispatch", "engine_admission_wait",
              "prefill", "decode", "stream"):
        assert p in phases, f"missing phase {p}"
    assert "exec" not in phases
    # Telescoping: the sum IS the e2e wall (not approximately).
    assert abs(sum(phases.values()) - rec["e2e_s"]) < 1e-9
    assert rec["e2e_s"] > 0
    assert phases["handle_queue"] >= 0.002
    assert rec["tokens_in"] == 4
    assert rec["tokens_out"] == 8
    # TTFT covers everything before the first token; TPOT the decode rate.
    assert rec["ttft_s"] is not None and rec["ttft_s"] > 0
    assert rec["tpot_s"] is not None and rec["tpot_s"] > 0
    snap = observatory.profiler().snapshot()
    assert snap["app"] == "llm-test"
    assert snap["phase_sum_fraction"] == pytest.approx(1.0)
    assert snap["tenants"]["acme"]["tokens_out"] == 8


def test_non_engine_requests_collapse_to_exec(fresh_observatory):
    """Deployments that never touch the engine get {handle_queue,
    dispatch, exec} — still summing to e2e."""
    observatory.configure("plain", None)
    rec = _fabricated_request(tenant="z", e2e_parts=(0.002, 0.003))
    assert set(rec["phases"]) == {"handle_queue", "dispatch", "exec"}
    assert abs(sum(rec["phases"].values()) - rec["e2e_s"]) < 1e-9
    assert rec["phases"]["exec"] >= 0.004


def test_observatory_disabled_is_inert(fresh_observatory, monkeypatch):
    from ray_tpu._private.config import get_config

    monkeypatch.setattr(get_config(), "serve_observatory", False)
    assert observatory.make_wire_ctx("t") is None
    assert observatory.begin(None, "app") is None
    assert observatory.finish(None) is None


# -- SLO burn-rate math -------------------------------------------------

def test_burn_rate_unit_math():
    # 2 violations / 100 requests at objective 0.99 -> burn 2.0.
    assert observatory.burn_rate(98, 100, 0.99) == pytest.approx(2.0)
    # Clean window burns nothing; empty window burns nothing.
    assert observatory.burn_rate(50, 50, 0.99) == 0.0
    assert observatory.burn_rate(0, 0, 0.99) == 0.0
    # Exactly on budget: 1 violation / 100 at 0.99 -> 1.0.
    assert observatory.burn_rate(99, 100, 0.99) == pytest.approx(1.0)


def test_slo_accounting_on_synthetic_traffic(fresh_observatory):
    """Feed known-good and known-violating requests through the real
    scoring path; the tenant window must count them exactly and the
    burn rate must equal violation_rate / error_budget."""
    observatory.configure(
        "slo-app", SloConfig(e2e_ms=50.0, objective=0.9)
    )
    # 3 fast requests (~2ms each, pass) + 2 slow (~60ms, violate e2e).
    for _ in range(3):
        _fabricated_request(tenant="acme", e2e_parts=(0.001, 0.001))
    for _ in range(2):
        _fabricated_request(tenant="acme", e2e_parts=(0.03, 0.03))
    snap = observatory.profiler().snapshot()
    t = snap["tenants"]["acme"]
    assert t["requests"] == 5
    fast_w = str(snap["slo_windows_s"][0])
    counts = t["slo_windows"][fast_w]["e2e"]
    assert counts["total"] == 5
    assert counts["good"] == 3
    # burn = (2/5) / (1 - 0.9) = 4.0
    assert counts["burn"] == pytest.approx(4.0)
    # TTFT was never declared -> never scored.
    assert "ttft" not in t["slo_windows"][fast_w]


# -- head-of-line watchdog ----------------------------------------------

def test_hol_watchdog_attributes_chaos_prefill(fresh_observatory):
    """Chaos-stretch a prefill pass while another request is decoding:
    the watchdog must record the stall, count the decoding victim, and
    blame the prefilling request by id."""
    from ray_tpu._private import chaos
    from ray_tpu.serve.llm import ContinuousBatchingEngine

    params, cfg = _tiny_model()
    eng = ContinuousBatchingEngine(params, cfg, num_slots=2, max_len=128)
    chaos.enable()
    try:
        long_h = eng.submit([3, 7, 11, 2], max_new_tokens=80)
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            s = eng.stats()
            if s["active"] == 1 and s["prefilling"] == 0:
                break
            time.sleep(0.01)
        else:
            pytest.fail(f"request never reached decode: {eng.stats()}")
        # Inject: the NEXT prefill pass sleeps well past the threshold.
        chaos.delay_prefills(0.2, count=1)
        victim_steps = eng.stats()["steps"]
        blocker = eng.submit([5, 1, 8, 2, 9, 4], max_new_tokens=4)
        blocker.result(timeout=120)
        long_h.result(timeout=120)
        stats = eng.stats()
    finally:
        chaos.disable()
        chaos.clear()
        eng.shutdown()
    hol = stats["hol"]
    assert hol["blocked_slot_seconds"] >= 0.2
    assert hol["events"], "no HOL event recorded"
    ev = hol["events"][0]
    assert ev["prefill_s"] >= 0.2
    assert ev["victims"] == 1
    culprit_ids = [c["request_id"] for c in ev["culprits"]]
    assert blocker.request_id in culprit_ids
    assert stats["steps"] > victim_steps


# -- ServeSignals + CLI over a live cluster -----------------------------

def test_serve_signals_roundtrip_and_cli(serve_session):
    """Two replicas, tenant-tagged traffic, declared SLO: the controller
    must publish a merged ServeSignals doc to the GCS KV that rt serve
    can fetch (pure kv_get) and render."""
    from ray_tpu.scripts.scripts import _fetch_serve_signals, _render_serve

    @serve.deployment(num_replicas=2,
                      slo={"e2e_ms": 30_000.0, "objective": 0.99})
    def echo(x=0):
        return x * 2

    handle = serve.run(echo.bind(), name="echo")
    acme = handle.options(tenant="acme")
    globex = handle.options(tenant="globex")
    for i in range(6):
        assert rt.get(acme.remote(i), timeout=60) == i * 2
    for i in range(3):
        assert rt.get(globex.remote(i), timeout=60) == i * 2

    deadline = time.monotonic() + 30
    doc = None
    while time.monotonic() < deadline:
        doc = _fetch_serve_signals(None)
        app = (doc or {}).get("apps", {}).get("echo")
        if app and app.get("tenants", {}).get("acme", {}).get(
            "requests", 0
        ) >= 6 and app.get("tenants", {}).get("globex"):
            break
        time.sleep(0.5)
    else:
        pytest.fail(f"signals never converged: {doc}")

    app = doc["apps"]["echo"]
    assert doc["schema"] == observatory.SIGNALS_SCHEMA_VERSION
    assert len(app["replicas"]) == 2
    assert app["qps"] > 0
    # Phase vector explains the request wall (>= 95% acceptance gate).
    assert app["phase_sum_fraction"] >= 0.95
    assert app["tenants"]["acme"]["requests"] == 6
    assert app["tenants"]["globex"]["requests"] == 3
    windows = app["tenants"]["acme"]["slo_windows"]
    assert any(
        kinds.get("e2e", {}).get("total", 0) >= 6
        for kinds in windows.values()
    )
    # Nothing violated a 30s e2e budget.
    assert all(
        kinds["e2e"]["burn"] == 0.0
        for kinds in windows.values() if "e2e" in kinds
    )
    assert app["slo"]["e2e"] == 30_000.0

    # A second publish must bump seq (versioned snapshots).
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        doc2 = _fetch_serve_signals(None)
        if doc2 and doc2["seq"] > doc["seq"]:
            break
        time.sleep(0.5)
    else:
        pytest.fail("signals seq never advanced")

    # CLI rendering against the live doc.
    out = _render_serve(doc)
    assert "app echo" in out
    assert "tenant acme" in out
    assert "tenant globex" in out
    assert out.count("replica ") == 2
    assert "burn" in out
    # Empty-state rendering.
    assert "no serve signals" in _render_serve(None)


def test_phase_metrics_flow_through_handle(serve_session):
    """Handle-path wiring: requests dispatched via DeploymentHandle land
    in the replica's observatory ring with caller-side stamps."""
    @serve.deployment
    class Doubler:
        def __call__(self, x):
            return x * 2

    handle = serve.run(Doubler.bind(), name="doubler")
    for i in range(4):
        assert rt.get(handle.remote(i), timeout=60) == i * 2
    handle._refresh(force=True)
    replica = handle._shared["replicas"][0]
    snap = rt.get(replica.observatory_snapshot.remote(), timeout=30)
    assert snap["app"] == "doubler"
    assert snap["requests_total"] == 4
    assert snap["phase_sum_fraction"] >= 0.95
    # Caller-side stamps crossed the wire: handle_queue attributed.
    assert "handle_queue" in snap["phases"]
    assert snap["phases"]["exec"]["count"] == 4
