"""Serve data-plane tests: batching, streaming, multiplexing, routing.

Reference analogs: python/ray/serve/tests/test_batching.py,
test_streaming*.py, test_multiplex.py. Batching is the TPU serving
feature: concurrent requests must coalesce into >1-sized batches at the
replica (one MXU pass instead of N).
"""

import threading
import time

import pytest

import ray_tpu as rt
from ray_tpu import serve


@pytest.fixture
def rt_serve():
    rt.init(num_cpus=4)
    yield
    serve.shutdown()
    rt.shutdown()


def test_batch_coalesces_concurrent_requests(rt_serve):
    @serve.deployment(max_ongoing_requests=16)
    class Model:
        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        def predict(self, items):
            return [x * 2 for x in items]

        def __call__(self, x):
            return self.predict(x)

    handle = serve.run(Model.bind())
    # Fire 16 concurrent requests from threads (the proxy's shape).
    results = [None] * 16
    errs = []

    def call(i):
        try:
            results[i] = rt.get(handle.remote(i), timeout=60)
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    threads = [threading.Thread(target=call, args=(i,)) for i in range(16)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=90)
    assert not errs, errs
    assert results == [i * 2 for i in range(16)]

    # The replica must have actually executed batches with >1 item.
    handle._refresh(force=True)
    replica = handle._shared["replicas"][0]
    stats = rt.get(replica.stats.remote(), timeout=30)
    sizes = stats["batch_sizes"]["predict"]
    assert sum(sizes) == 16
    assert max(sizes) > 1, f"no batching happened: {sizes}"


def test_batch_error_propagates_to_all(rt_serve):
    @serve.deployment(max_ongoing_requests=8)
    class Bad:
        @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.1)
        def predict(self, items):
            raise ValueError("batch exploded")

        def __call__(self, x):
            return self.predict(x)

    handle = serve.run(Bad.bind())
    with pytest.raises(rt.exceptions.TaskError):
        rt.get(handle.remote(1), timeout=60)


def test_streaming_chunks_in_order(rt_serve):
    @serve.deployment
    class Streamer:
        def __call__(self, n):
            for i in range(n):
                time.sleep(0.02)
                yield {"token": i}

    handle = serve.run(Streamer.bind())
    chunks = list(handle.options(stream=True).remote(5))
    assert chunks == [{"token": i} for i in range(5)]


def test_streaming_error_raises(rt_serve):
    @serve.deployment
    class Bad:
        def __call__(self):
            yield 1
            raise RuntimeError("mid-stream failure")

    handle = serve.run(Bad.bind())
    it = handle.options(stream=True).remote()
    assert next(it) == 1
    with pytest.raises(RuntimeError, match="mid-stream failure"):
        list(it)


def test_multiplexed_model_loading_and_lru(rt_serve):
    @serve.deployment
    class MultiModel:
        def __init__(self):
            self.loads = []

        @serve.multiplexed(max_num_models_per_replica=2)
        def get_model(self, model_id):
            self.loads.append(model_id)
            return {"id": model_id}

        def __call__(self):
            mid = serve.get_multiplexed_model_id()
            model = self.get_model(mid)
            return {"served_by": model["id"], "loads": list(self.loads)}

    handle = serve.run(MultiModel.bind())
    # Two models: each loads once, repeat calls hit the cache.
    for _ in range(2):
        out_a = rt.get(
            handle.options(multiplexed_model_id="a").remote(), timeout=60
        )
        out_b = rt.get(
            handle.options(multiplexed_model_id="b").remote(), timeout=60
        )
    assert out_a["served_by"] == "a"
    assert out_b["served_by"] == "b"
    assert out_b["loads"].count("a") == 1
    assert out_b["loads"].count("b") == 1
    # A third model evicts the least-recently-used one.
    rt.get(handle.options(multiplexed_model_id="c").remote(), timeout=60)
    out_a2 = rt.get(
        handle.options(multiplexed_model_id="a").remote(), timeout=60
    )
    assert out_a2["loads"].count("a") == 2  # reloaded after eviction


def test_http_proxy_streaming_sse(rt_serve):
    import json
    import urllib.request

    @serve.deployment
    class Streamer:
        def __call__(self, n=3):
            for i in range(n):
                yield {"i": i}

    serve.run(Streamer.bind(), name="sse")
    addr = serve.start_http_proxy(port=0) if False else serve.start_http_proxy(
        port=18431
    )
    req = urllib.request.Request(
        f"{addr}/sse?stream=1",
        data=json.dumps({"n": 3}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=60) as resp:
        assert resp.headers["Content-Type"].startswith("text/event-stream")
        body = resp.read().decode()
    events = [
        json.loads(line[len("data: "):])
        for line in body.splitlines()
        if line.startswith("data: ")
    ]
    assert events == [{"i": 0}, {"i": 1}, {"i": 2}]


def test_http_proxy_concurrent_requests(rt_serve):
    """The proxy must survive a burst of slow concurrent requests (round-1
    weakness: one blocked threadpool thread per in-flight request)."""
    import json
    import urllib.request
    from concurrent.futures import ThreadPoolExecutor

    @serve.deployment(max_ongoing_requests=32)
    class Slow:
        def __call__(self, x):
            time.sleep(0.3)
            return x

    serve.run(Slow.bind(), name="slow")
    addr = serve.start_http_proxy(port=18432)

    def call(i):
        req = urllib.request.Request(
            f"{addr}/slow",
            data=json.dumps({"x": i}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=90) as resp:
            return json.loads(resp.read())["result"]

    t0 = time.monotonic()
    with ThreadPoolExecutor(max_workers=24) as pool:
        out = list(pool.map(call, range(24)))
    dt = time.monotonic() - t0
    assert sorted(out) == list(range(24))
    # 24 x 0.3s serial would be 7.2s; concurrent execution must beat that.
    assert dt < 6.0, f"no request concurrency: {dt:.1f}s"

def test_run_from_config_declarative_deploy(rt_serve, tmp_path):
    """serve.run_from_config deploys apps by import path with overrides
    (reference: `serve deploy` YAML, serve/scripts.py:256)."""
    import json as _json
    import sys

    mod = tmp_path / "my_serve_app.py"
    mod.write_text(
        "from ray_tpu import serve\n"
        "@serve.deployment\n"
        "class Echo:\n"
        "    def __init__(self, prefix='x'):\n"
        "        self.prefix = prefix\n"
        "    def __call__(self, v):\n"
        "        return f'{self.prefix}:{v}'\n"
        "app = Echo.bind(prefix='cfg')\n"
    )
    sys.path.insert(0, str(tmp_path))
    try:
        cfg = {
            "applications": [
                {
                    "name": "echo",
                    "import_path": "my_serve_app:app",
                    "deployments": [{"name": "Echo", "num_replicas": 2}],
                }
            ]
        }
        cfg_path = tmp_path / "serve.json"
        cfg_path.write_text(_json.dumps(cfg))
        from ray_tpu import serve

        handles = serve.run_from_config(str(cfg_path))
        out = rt.get(handles["echo"].remote("hi"), timeout=60)
        assert out == "cfg:hi"
        st = serve.status()
        assert st["echo"]["target_replicas"] >= 2 or st  # deployed w/ override
    finally:
        sys.path.remove(str(tmp_path))

def test_route_push_invalidation_beats_poll_ttl(rt_serve):
    """Replica-set changes push to handles (LongPollHost analog): with the
    poll TTL suppressed, a scale-up still becomes visible via the push."""
    @serve.deployment(num_replicas=1)
    class App:
        def __call__(self):
            return "ok"

    handle = serve.run(App.bind(), name="pushy")
    assert rt.get(handle.remote(), timeout=60) == "ok"  # subscribe happens

    s = handle._shared
    with s["lock"]:
        # Suppress polling: only a push can zero this back out.
        s["last_refresh"] = time.monotonic() + 10_000
        v0 = s["version"]

    serve.run(App.options(num_replicas=3).bind(), name="pushy")

    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        rt.get(handle.remote(), timeout=60)  # requests drive refresh
        with s["lock"]:
            if s["version"] > v0 and len(s["replicas"]) == 3:
                break
        time.sleep(0.2)
    with s["lock"]:
        assert s["version"] > v0 and len(s["replicas"]) == 3, (
            "push invalidation never refreshed the routing table"
        )

def test_dead_replica_replaced_and_service_heals(rt_serve):
    """SIGKILL a replica's worker process: the controller detects the dead
    replica, replaces it, and the handle routes around it (reference:
    DeploymentState failure recovery, deployment_state.py:1211)."""
    import os
    import signal

    from ray_tpu._private import worker as worker_mod

    @serve.deployment(num_replicas=2)
    class App:
        def __call__(self):
            return os.getpid()

    handle = serve.run(App.bind(), name="healme")
    pids = set()
    for _ in range(8):
        pids.add(rt.get(handle.remote(), timeout=60))
    assert len(pids) >= 1

    # Kill one replica's worker process outright.
    victim_pid = next(iter(pids))
    os.kill(victim_pid, signal.SIGKILL)

    # The service keeps answering (handle may briefly hit the dead replica
    # and retry on the next call), and a replacement replica appears.
    deadline = time.monotonic() + 60
    new_pids = set()
    while time.monotonic() < deadline:
        try:
            new_pids.add(rt.get(handle.remote(), timeout=30))
        except Exception:
            pass  # transient while routing catches up
        if len(new_pids - {victim_pid}) >= 2:
            break
        time.sleep(0.3)
    alive = new_pids - {victim_pid}
    assert len(alive) >= 2, f"replacement replica never served: {new_pids}"


def test_handle_redispatches_to_live_replica(rt_serve):
    """DeploymentResponse.result() re-dispatches a request whose replica
    died before answering (reference: the router's retry-on-replica-
    failure), without the caller seeing ActorDiedError."""
    import os
    import signal

    @serve.deployment(num_replicas=2)
    class App:
        def __call__(self):
            return os.getpid()

    handle = serve.run(App.bind(), name="redispatch")
    pids = set()
    for _ in range(8):
        pids.add(handle.remote().result(timeout=60))
    victim = next(iter(pids))

    # Dispatch a request to the victim by brute force: keep sending until
    # a response's chosen ref targets the (about-to-die) pid... simpler:
    # kill the victim, then immediately fire a burst — power-of-two will
    # route some of the burst at the dead replica before any refresh, and
    # every one of them must still succeed via re-dispatch.
    os.kill(victim, signal.SIGKILL)
    results = [handle.remote() for _ in range(8)]
    got = [r.result(timeout=120) for r in results]
    assert all(isinstance(p, int) for p in got)
    assert victim not in got


def test_cross_language_serve_call(rt_serve):
    """serve.call routes through the normal data plane from a plain
    fn_name task — the path a C++ client uses to hit deployments
    (Submit("ray_tpu.serve:call", [app, payload]))."""
    import os as _os

    @serve.deployment(num_replicas=1)
    class Echo:
        def __call__(self, payload):
            return {"echo": payload, "pid": _os.getpid()}

        def shout(self, payload):
            return str(payload).upper()

    serve.run(Echo.bind(), name="xlangserve")
    # Direct driver-side use.
    out = serve.call("xlangserve", "hello")
    assert out["echo"] == "hello"
    # The foreign-client path: a worker executes the fn_name task.
    client = rt._worker.get_client()
    spec = {
        "task_id": _os.urandom(16),
        "job_id": client.job_id.binary(),
        "name": "ray_tpu.serve:call",
        "fn_name": "ray_tpu.serve:call",
        "plain_args": ["xlangserve", "from-cpp"],
        "deps": [],
        "num_returns": 1,
        "resources": {"CPU": 1.0},
        "retriable": False,
    }
    result = client._run(client.raylet.call("submit_task", spec, timeout=120))
    assert result["status"] == "ok", result
    from ray_tpu._private import serialization as ser

    value = ser.deserialize_from_bytes(result["returns"][0]["data"])
    assert value["echo"] == "from-cpp"
    assert value["pid"] != _os.getpid()  # served by a replica process


def test_proxy_per_node_and_binary_ingress():
    """EveryNode proxy mode: the controller's ProxyStateManager keeps one
    proxy per ALIVE node (proxy_state.py analog); requests enter through
    BOTH nodes' HTTP proxies and through the binary msgpack ingress."""
    import asyncio
    import json
    import urllib.request

    from ray_tpu.cluster_utils import Cluster

    cluster = Cluster()
    cluster.add_node(num_cpus=2)
    cluster.add_node(num_cpus=2)
    cluster.connect()
    try:
        @serve.deployment(num_replicas=2)
        def double(x=0):
            return x * 2

        serve.run(double.bind(), name="dbl")
        addrs = serve.start(proxy_location="EveryNode")
        assert len(addrs) == 2, f"expected a proxy per node, got {addrs}"

        # HTTP through each node's proxy.
        for entry in addrs.values():
            req = urllib.request.Request(
                entry["http"] + "/dbl",
                data=json.dumps({"x": 21}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=30) as resp:
                assert json.loads(resp.read())["result"] == 42

        # Binary framed ingress on the first proxy.
        from ray_tpu._private.protocol import connect

        host, port = next(iter(addrs.values()))["binary"]

        async def bin_call():
            conn = await connect(host, port)
            out = await conn.call(
                "serve_call", {"app": "dbl", "kwargs": {"x": 10}},
                timeout=30,
            )
            await conn.close()
            return out

        loop = asyncio.new_event_loop()
        try:
            out = loop.run_until_complete(bin_call())
        finally:
            loop.close()
        assert out == {"result": 20}
    finally:
        serve.shutdown()
        cluster.shutdown()


def test_autoscaling_reacts_to_replica_queue_depth(rt_serve):
    """Replica-reported queue lengths (controller polls replica.queue_len)
    drive scale-up under sustained load and scale-down when idle
    (reference: autoscaling_policy.py from replica queue metrics)."""

    @serve.deployment(
        num_replicas=1,
        max_ongoing_requests=4,
        autoscaling_config=serve.AutoscalingConfig(
            min_replicas=1,
            max_replicas=3,
            target_ongoing_requests=1,
            upscale_delay_s=0.1,
            downscale_delay_s=1.0,
        ),
    )
    class Slowish:
        def __call__(self, x=0):
            time.sleep(0.3)
            return x

    serve.run(Slowish.bind(), name="auto")
    handle = serve.get_app_handle("auto")

    stop = threading.Event()

    def pump():
        while not stop.is_set():
            try:
                handle.remote(1).result(timeout=30)
            except Exception:
                pass

    threads = [threading.Thread(target=pump, daemon=True) for _ in range(6)]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 40
        scaled = False
        while time.monotonic() < deadline:
            n = len(rt.get(
                serve.get_or_create_controller().get_replicas.remote("auto"),
                timeout=10,
            )["replicas"])
            if n >= 2:
                scaled = True
                break
            time.sleep(0.5)
        assert scaled, "queue depth never triggered a scale-up"
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=5)
    # Idle -> back toward min_replicas.
    deadline = time.monotonic() + 40
    while time.monotonic() < deadline:
        n = len(rt.get(
            serve.get_or_create_controller().get_replicas.remote("auto"),
            timeout=10,
        )["replicas"])
        if n == 1:
            return
        time.sleep(0.5)
    pytest.fail("idle deployment did not scale back down")


def test_user_config_redeploy_reconfigures_in_place(rt_serve):
    """A redeploy that changes ONLY user_config reconfigure()s the live
    replicas instead of restarting them: same pids keep serving, with
    the new config applied (reference: lightweight config updates,
    deployment_state.py user_config-only versions)."""
    import os

    @serve.deployment(num_replicas=2, user_config={"factor": 2})
    class Scaler:
        def __init__(self):
            self.factor = 1
            self.pid = os.getpid()

        def reconfigure(self, cfg):
            self.factor = cfg["factor"]

        def __call__(self, x):
            return {"y": x * self.factor, "pid": self.pid}

    handle = serve.run(Scaler.bind(), name="scaler")
    outs = [handle.remote(5).result(timeout=60) for _ in range(6)]
    assert all(o["y"] == 10 for o in outs)
    pids_before = {o["pid"] for o in outs}

    # Redeploy with ONLY user_config changed.
    serve.run(Scaler.options(user_config={"factor": 7}).bind(),
              name="scaler")
    deadline = __import__("time").monotonic() + 30
    outs2 = []
    while __import__("time").monotonic() < deadline:
        outs2 = [handle.remote(5).result(timeout=60) for _ in range(6)]
        if all(o["y"] == 35 for o in outs2):
            break
    assert all(o["y"] == 35 for o in outs2), outs2
    # Same replica processes — no restart happened.
    assert {o["pid"] for o in outs2} <= pids_before

    # A redeploy changing num_replicas DOES replace/reconcile normally.
    serve.run(
        Scaler.options(num_replicas=1, user_config={"factor": 7}).bind(),
        name="scaler",
    )
    out3 = handle.remote(3).result(timeout=60)
    assert out3["y"] == 21
