"""Zygote fork-server tests: generational rotation and death notices.

The rotation defends against Linux rmap (anon_vma) chain growth: page
faults in the Nth COW-faulted sibling of one parent slow superlinearly,
so the manager re-execs a fresh zygote every `zygote_respawn_after`
forks (reference counterpart: the worker pool's process lifecycle,
src/ray/raylet/worker_pool.cc — the reference pays a full interpreter
boot per worker instead, so never hits the sibling regime).
"""

import os
import time

import pytest


def _wait_pid(zp, timeout=30.0):
    deadline = time.monotonic() + timeout
    while zp.pid is None and zp.returncode is None:
        if time.monotonic() > deadline:
            raise TimeoutError("zygote spawn never assigned a pid")
        time.sleep(0.01)
    return zp.pid


def _parent_of(pid):
    raw = open(f"/proc/{pid}/stat").read()
    return int(raw.rsplit(") ", 1)[1].split()[1])


@pytest.fixture
def low_limit(monkeypatch):
    monkeypatch.setenv("RT_ZYGOTE_RESPAWN_AFTER", "10")
    from ray_tpu._private import config

    config._config = None
    yield
    config._config = None


def test_zygote_rotates_after_limit(low_limit):
    from ray_tpu._private.zygote_client import ZygoteManager

    mgr = ZygoteManager()
    try:
        parents = set()
        procs = []
        for _ in range(30):
            zp = mgr.spawn({
                "PATH": os.environ.get("PATH", ""),
                "PYTHONPATH": os.path.dirname(os.path.dirname(
                    os.path.abspath(__file__))),
                # No RT_WORKER_ID: worker_main exits immediately with a
                # KeyError — the child's fate doesn't matter here, only
                # which zygote forked it.
            })
            assert zp is not None
            pid = _wait_pid(zp)
            if pid is not None:
                try:
                    parents.add(_parent_of(pid))
                except (FileNotFoundError, ProcessLookupError):
                    pass  # already exited and reaped
            procs.append(zp)
        # 30 spawns at limit 10 -> at least 3 generations served.
        assert len(parents) >= 3, parents
    finally:
        mgr.stop()


def test_zygote_death_notices_cross_generations(low_limit):
    from ray_tpu._private.zygote_client import ZygoteManager

    mgr = ZygoteManager()
    try:
        procs = []
        for _ in range(25):
            zp = mgr.spawn({"PATH": os.environ.get("PATH", ""),
                            "PYTHONPATH": "/"})
            assert zp is not None
            _wait_pid(zp)
            procs.append(zp)
        # Children die fast (missing RT_WORKER_ID); every handle must
        # still learn its fate — including ones whose zygote generation
        # was retired after they were forked.
        deadline = time.monotonic() + 60
        for zp in procs:
            while zp.poll() is None:
                assert time.monotonic() < deadline, "death notice lost"
                time.sleep(0.02)
    finally:
        mgr.stop()


def test_retired_generation_closes_after_children_exit(low_limit):
    from ray_tpu._private.zygote_client import ZygoteManager

    mgr = ZygoteManager()
    try:
        procs = []
        for _ in range(25):
            zp = mgr.spawn({"PATH": os.environ.get("PATH", ""),
                            "PYTHONPATH": "/"})
            assert zp is not None
            procs.append(zp)
        for zp in procs:
            while zp.poll() is None:
                time.sleep(0.02)
        # All children dead -> retired generations should drain away.
        deadline = time.monotonic() + 30
        while mgr._old and time.monotonic() < deadline:
            time.sleep(0.05)
        assert not mgr._old, "retired zygotes lingered after last child"
    finally:
        mgr.stop()


def test_stop_is_not_counted_as_a_zygote_death():
    """stop() marks every generation retiring BEFORE closing it, so the
    reader threads' EOFs read as intentional shutdown — NOT unexpected
    deaths. Without that ordering, 3 stop/start cycles (common across
    rt.init/shutdown in one process, since the manager is process-
    shared) hit the _deaths >= 3 breaker and permanently push every
    spawn onto the slow Popen path."""
    from ray_tpu._private.zygote_client import ZygoteManager

    mgr = ZygoteManager()
    try:
        for _ in range(4):  # one past the 3-death disable threshold
            assert mgr.start()
            proc = mgr.proc
            mgr.stop()
            # The reader thread sees EOF once the zygote exits; give it
            # a beat to run its accounting before the next cycle.
            deadline = time.monotonic() + 15
            while proc.poll() is None:
                assert time.monotonic() < deadline, "zygote never exited"
                time.sleep(0.02)
            time.sleep(0.1)
        assert mgr._deaths == 0
        # The breaker never tripped: the manager still serves forks.
        zp = mgr.spawn({"PATH": os.environ.get("PATH", ""),
                        "PYTHONPATH": "/"})
        assert zp is not None
    finally:
        mgr.stop()
