"""Fault-tolerance probes: failure detection, gang rebuild, timeout trip.

Measures the three latencies the gang fault-tolerance path promises
(MIGRATION.md "Fault tolerance" quotes these; tools/check_claims.py pins
the quotes to BENCH_FT.json):

  * kill-to-detection: a rank hard-killed mid-training -> the trainer's
    poll raises a classified TrainingFailedError. Bounded by the 50ms
    poll cadence plus actor-death propagation, NOT by rt.get timeouts.
  * gang rebuild: executor.restart() wall time — kill survivors, release
    the placement group, re-reserve, respawn workers at the next epoch.
  * collective timeout trip: a DCN peer that connects then goes silent
    trips CollectiveTimeoutError one op_timeout after the recv starts.

Run: python bench_ft.py [--quick]
CPU-gang numbers on the dev image; TPU pods add scheduler/preemption
latency on top but the detection/rebuild machinery is identical.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np


def _median(f, n: int):
    vals = [f() for _ in range(n)]
    return float(np.median(vals))


def probe_detection_and_rebuild(results, rounds: int):
    import ray_tpu as rt
    from ray_tpu._private import chaos
    from ray_tpu.train.backend import JaxConfig
    from ray_tpu.train.backend_executor import (
        BackendExecutor,
        TrainingFailedError,
    )
    from ray_tpu.train.config import ScalingConfig

    rt.init(num_cpus=4)
    chaos.enable()

    def idle_loop():
        import time as _t

        from ray_tpu import train

        while not train.should_stop():
            _t.sleep(0.02)

    executor = BackendExecutor(
        JaxConfig(dp_sync="none"), ScalingConfig(num_workers=2)
    )
    executor.start()
    detect_ms, rebuild_s = [], []
    try:
        for _ in range(rounds):
            executor.start_training(idle_loop, {}, None, "/tmp/bench_ft")
            executor.poll()  # workers up and answering
            chaos.kill_rank(executor.worker_group, 1)
            t0 = time.monotonic()
            while True:
                try:
                    executor.poll()
                    time.sleep(0.05)
                except TrainingFailedError as e:
                    assert e.failed_ranks == [1]
                    detect_ms.append((time.monotonic() - t0) * 1e3)
                    break
            t0 = time.monotonic()
            executor.restart()
            # restart() returns once actors are submitted (creation is
            # pipelined); "rebuilt" means every rank answers a probe.
            while executor.ping(timeout=10):
                time.sleep(0.01)
            rebuild_s.append(time.monotonic() - t0)
    finally:
        executor.shutdown()
        chaos.disable()
        rt.shutdown()

    for entry in (
        {"metric": "kill-to-detection (2 CPU workers)",
         "detect_ms": round(float(np.median(detect_ms)), 1)},
        {"metric": "gang rebuild at next epoch (2 CPU workers)",
         "rebuild_s": round(float(np.median(rebuild_s)), 2)},
    ):
        print(json.dumps(entry))
        results.append(entry)


def probe_collective_timeout(results, rounds: int):
    from ray_tpu.util.collective.dcn_group import DcnGroup

    class _KV:
        def __init__(self):
            self._d = {}

        def kv_put(self, k, v, ns=""):
            self._d[(ns, k)] = v

        def kv_get(self, k, ns=""):
            return self._d.get((ns, k))

        def kv_del(self, k, ns=""):
            self._d.pop((ns, k), None)

    op_timeout = 0.5

    def trip_once():
        kv = _KV()
        g0 = DcnGroup(kv, 2, 0, "bench", timeout=5, op_timeout=op_timeout)  # rtlint: disable=RT005 — one-shot group built to trip the op timeout; never rebuilt, epoch fence moot
        g1 = DcnGroup(kv, 2, 1, "bench", timeout=5, op_timeout=op_timeout)  # rtlint: disable=RT005 — one-shot group, see above
        try:
            g1._peer_out(0)  # connect + identify, then go silent
            t0 = time.monotonic()
            try:
                g0.recv(1)
            except Exception:
                return time.monotonic() - t0
            raise AssertionError("silent peer did not trip the deadline")
        finally:
            g0.destroy()
            g1.destroy()

    entry = {
        "metric": "dcn collective timeout trip",
        "op_timeout_s": op_timeout,
        "trip_s": round(_median(trip_once, rounds), 3),
    }
    print(json.dumps(entry))
    results.append(entry)


def main():
    quick = "--quick" in sys.argv
    rounds = 1 if quick else 3
    results = []
    probe_detection_and_rebuild(results, rounds)
    probe_collective_timeout(results, rounds)
    if not quick:
        with open("BENCH_FT.json", "w") as f:
            json.dump(results, f, indent=1)


if __name__ == "__main__":
    main()
