"""Flagship benchmark: Llama-family training-step throughput per chip.

Prints ONE JSON line on stdout:
    {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

Architecture (hardened after two rounds of red gates):
  * The parent process imports NO jax.  It spawns a worker subprocess
    (``bench.py --worker tpu``) and supervises it with per-stage
    watchdog timeouts, so a hung TPU tunnel (``jax.devices()`` blocking
    forever in backend init) is killed and retried, never inherited.
  * The worker prints staged progress to stderr (``::stage backend_init``,
    ``compile``, ``run``, ``step i/N``) so a hang is diagnosable from the
    driver log, and the final JSON line to stdout.
  * On persistent TPU failure the parent falls back to a CPU worker so
    the script still emits a valid, parseable JSON line (with a
    ``tpu_error`` field recording why the real measurement was skipped)
    and exits 0.  Only if even the CPU worker dies does it emit a JSON
    error line and exit 1 — never a bare stack trace.
  * A persistent XLA compilation cache (``.cache/jax`` in the repo) keeps
    repeat runs well under the ~3-minute time-to-first-number target.

On the real TPU chip this measures the full jit-compiled training step
(forward + backward + AdamW update, bf16 params/activations, remat) on a
~0.8B-parameter Llama-2-shaped model — sized so params + Adam state +
grads fit one 16GB v5e chip. `vs_baseline` is measured MFU divided by
0.40, the typical MFU of the reference's A100 TorchTrainer+NCCL stack on
Llama-2 (BASELINE.md north star: match TorchTrainer+NCCL tokens/sec/chip);
>1.0 means this stack extracts more of its chip than the baseline stack
extracts of its A100.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

# Peak dense bf16 TFLOP/s per chip by TPU generation.
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5lite": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}
BASELINE_MFU = 0.40  # typical A100 TorchTrainer+NCCL MFU on Llama-2

# ---------------------------------------------------------------------------
# Parent-side supervision knobs (env-overridable for tests / slow tunnels).
# ---------------------------------------------------------------------------
STAGE_TIMEOUTS = {
    "spawn": float(os.environ.get("RT_BENCH_T_SPAWN", 90)),
    "backend_init": float(os.environ.get("RT_BENCH_T_BACKEND", 120)),
    "setup": float(os.environ.get("RT_BENCH_T_SETUP", 150)),
    "compile": float(os.environ.get("RT_BENCH_T_COMPILE", 420)),
    "run": float(os.environ.get("RT_BENCH_T_RUN", 240)),
}
TPU_ATTEMPTS = int(os.environ.get("RT_BENCH_TPU_ATTEMPTS", 3))
TPU_DEADLINE = float(os.environ.get("RT_BENCH_TPU_DEADLINE", 900))
RETRY_BACKOFF = float(os.environ.get("RT_BENCH_RETRY_BACKOFF", 5))
# Cheap tunnel probes (subprocess `jax.devices()` with a timeout) run on a
# backoff loop for up to this long before we burn full worker attempts —
# the tunnel is frequently dead for long stretches and a probe costs 75s
# worst-case vs 2min+ for a full worker spawn.
PROBE_DEADLINE = float(os.environ.get("RT_BENCH_PROBE_DEADLINE", 1200))
PROBE_TIMEOUT = float(os.environ.get("RT_BENCH_PROBE_TIMEOUT", 75))
LIVE_ARTIFACT = os.path.join(
    os.path.dirname(os.path.abspath(__file__)), "BENCH_TPU_LIVE.json"
)
# A cached live artifact older than this is from a previous round — never
# emit it as this round's number.
LIVE_MAX_AGE = float(os.environ.get("RT_BENCH_LIVE_MAX_AGE", 14 * 3600))


def _log(msg: str) -> None:
    print(f"[bench] {time.strftime('%H:%M:%S')} {msg}", file=sys.stderr, flush=True)


class _Watchdog:
    """Tracks the worker's current stage + last-output time."""

    def __init__(self) -> None:
        self.lock = threading.Lock()
        self.stage = "spawn"
        self.last = time.monotonic()

    def touch(self, line: str) -> None:
        with self.lock:
            self.last = time.monotonic()
            if line.startswith("::stage "):
                self.stage = line.split(None, 1)[1].strip()

    def expired(self) -> "tuple[bool, str, float]":
        with self.lock:
            limit = STAGE_TIMEOUTS.get(self.stage, 300.0)
            idle = time.monotonic() - self.last
            return idle > limit, self.stage, idle


def _run_worker(platform: str) -> "tuple[int, str, str]":
    """Spawn one worker; returns (rc, stdout, reason). rc -9 == watchdog kill."""
    env = dict(os.environ)
    if platform == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        # sitecustomize registers the (possibly hung) remote-TPU backend at
        # interpreter startup when this is set; clear it for the CPU child.
        env["PALLAS_AXON_POOL_IPS"] = ""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", platform]
    proc = subprocess.Popen(
        cmd, stdout=subprocess.PIPE, stderr=subprocess.PIPE, env=env, text=True
    )
    dog = _Watchdog()
    out_buf: list[str] = []

    def read_stdout() -> None:
        for line in proc.stdout:  # type: ignore[union-attr]
            out_buf.append(line)
            dog.touch("")

    def read_stderr() -> None:
        for line in proc.stderr:  # type: ignore[union-attr]
            dog.touch(line)
            sys.stderr.write(line)
            sys.stderr.flush()

    t_out = threading.Thread(target=read_stdout, daemon=True)
    t_err = threading.Thread(target=read_stderr, daemon=True)
    t_out.start()
    t_err.start()

    reason = ""
    while True:
        rc = proc.poll()
        if rc is not None:
            break
        expired, stage, idle = dog.expired()
        if expired:
            reason = f"watchdog: no progress for {idle:.0f}s in stage '{stage}'"
            _log(f"killing worker — {reason}")
            proc.kill()
            proc.wait()
            rc = -9
            break
        time.sleep(0.5)
    t_out.join(timeout=5)
    t_err.join(timeout=5)
    return (rc if rc is not None else -9), "".join(out_buf), reason


def _last_json_line(text: str):
    for line in reversed(text.strip().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def load_live_artifact(path: str = None, max_age: float = None,
                       now: float = None):
    """The opportunistically-captured TPU result (tools/tpu_live.py), IF
    it is fresh (this round) and really a TPU measurement — labeled as
    cached. None otherwise."""
    path = path or LIVE_ARTIFACT
    max_age = LIVE_MAX_AGE if max_age is None else max_age
    if not os.path.exists(path):
        return None
    try:
        with open(path) as f:
            live = json.load(f)
    except (OSError, json.JSONDecodeError):
        return None
    if not live.get("measured_at"):
        return None
    try:
        import calendar

        measured = calendar.timegm(
            time.strptime(live["measured_at"], "%Y-%m-%dT%H:%M:%SZ")
        )
    except ValueError:
        return None
    age = (time.time() if now is None else now) - measured
    if not (0 <= age <= max_age):
        return None
    if "tpu" not in str(live.get("device", "")).lower():
        return None
    live["cached"] = True
    live["cache_note"] = (
        "live tunnel dead at bench time; this is a real TPU "
        "measurement captured earlier this round by tools/tpu_live.py "
        f"(measured_at={live.get('measured_at', '?')})"
    )
    return live


def _probe_tunnel() -> bool:
    """Cheap subprocess probe: does `jax.devices()` answer with a TPU?"""
    src = "import jax,sys; sys.stdout.write(jax.devices()[0].platform)"
    try:
        out = subprocess.run(
            [sys.executable, "-c", src],
            capture_output=True,
            text=True,
            timeout=PROBE_TIMEOUT,
        )
    except subprocess.TimeoutExpired:
        return False
    return out.returncode == 0 and "tpu" in out.stdout.lower()


def supervise() -> int:
    t_start = time.monotonic()
    tpu_error = ""
    force_cpu = os.environ.get("JAX_PLATFORMS", "") == "cpu"

    if not force_cpu:
        # Phase 1: cheap probes on a backoff loop until the tunnel answers
        # (or the probe horizon expires). A dead tunnel hangs jax.devices()
        # forever, so full worker attempts against it are pure waste.
        tunnel_up = False
        backoff = 10.0
        n_probe = 0
        while time.monotonic() - t_start < PROBE_DEADLINE:
            n_probe += 1
            _log(f"tunnel probe {n_probe}")
            if _probe_tunnel():
                tunnel_up = True
                _log(f"tunnel alive after {time.monotonic() - t_start:.0f}s")
                break
            if time.monotonic() - t_start + backoff >= PROBE_DEADLINE:
                break
            _log(f"tunnel dead; retrying in {backoff:.0f}s")
            time.sleep(backoff)
            backoff = min(backoff * 1.6, 120.0)
        if not tunnel_up:
            tpu_error = (
                f"tunnel probe horizon {PROBE_DEADLINE:.0f}s exhausted "
                f"({n_probe} probes)"
            )
        else:
            # Phase 2: full supervised worker attempts.
            deadline = time.monotonic() + TPU_DEADLINE
            for attempt in range(1, TPU_ATTEMPTS + 1):
                if time.monotonic() > deadline:
                    tpu_error = f"TPU deadline {TPU_DEADLINE:.0f}s exhausted"
                    break
                _log(f"TPU attempt {attempt}/{TPU_ATTEMPTS}")
                rc, out, reason = _run_worker("tpu")
                result = _last_json_line(out)
                if rc == 0 and result is not None:
                    print(json.dumps(result), flush=True)
                    _log(f"done in {time.monotonic() - t_start:.0f}s")
                    return 0
                tpu_error = reason or f"worker exited rc={rc}"
                _log(f"TPU attempt {attempt} failed: {tpu_error}")
                time.sleep(RETRY_BACKOFF)

    # Phase 3: a TPU measurement captured earlier in the round by
    # tools/tpu_live.py (the tunnel is often alive only in windows). The
    # result is clearly labeled as cached with its capture timestamp.
    if not force_cpu:
        live = load_live_artifact()
        if live is not None:
            if tpu_error:
                live["tpu_error"] = tpu_error
            _log(f"emitting cached live-TPU artifact from {live.get('measured_at')}")
            print(json.dumps(live), flush=True)
            return 0

    _log(f"falling back to CPU worker (tpu_error={tpu_error or 'forced'})")
    rc, out, reason = _run_worker("cpu")
    result = _last_json_line(out)
    if rc == 0 and result is not None:
        if tpu_error:
            result["tpu_error"] = tpu_error
        print(json.dumps(result), flush=True)
        return 0

    print(
        json.dumps(
            {
                "metric": "llama2 train-step tokens/s/chip",
                "value": 0.0,
                "unit": "tokens/s/chip",
                "vs_baseline": 0.0,
                "error": f"tpu: {tpu_error or 'n/a'}; cpu: {reason or f'rc={rc}'}",
            }
        ),
        flush=True,
    )
    return 1


# ---------------------------------------------------------------------------
# Worker: the actual measurement. Runs in a child process the parent can kill.
# ---------------------------------------------------------------------------


def _stage(name: str) -> None:
    print(f"::stage {name}", file=sys.stderr, flush=True)


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return 197.0e12  # assume v5e-class


def worker(platform: str) -> None:
    if platform == "cpu":
        os.environ["JAX_PLATFORMS"] = "cpu"
    _stage("backend_init")
    import jax

    if platform == "cpu":
        # jax may already have been imported (and JAX_PLATFORMS read) by
        # sitecustomize at interpreter startup — env vars alone are too late.
        jax.config.update("jax_platforms", "cpu")
    else:
        # Persistent compile cache keeps repeat TPU runs under the ~3-minute
        # time-to-first-number target. TPU-only: a CPU AOT cache compiled on
        # one host can SIGILL on another (machine-feature mismatch), and CPU
        # compiles are fast anyway.
        cache_dir = os.path.join(
            os.path.dirname(os.path.abspath(__file__)), ".cache", "jax"
        )
        os.makedirs(cache_dir, exist_ok=True)
        try:
            jax.config.update("jax_compilation_cache_dir", cache_dir)
            jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        except Exception:
            pass

    t0 = time.monotonic()
    dev = jax.devices()[0]
    print(
        f"[worker] backend up in {time.monotonic() - t0:.1f}s: "
        f"{dev.platform}/{getattr(dev, 'device_kind', '?')} x{jax.device_count()}",
        file=sys.stderr,
        flush=True,
    )
    on_tpu = dev.platform == "tpu"

    _stage("setup")
    import jax.numpy as jnp  # noqa: F401
    from dataclasses import replace

    import optax

    from ray_tpu.models import configs, init_params, loss_fn

    if on_tpu:
        # ~0.8B params: fits chip HBM with AdamW state + bf16 grads.
        # dots_nobatch remat saves the non-batch matmul outputs — ~12%
        # faster than full recompute and still fits the 16GB chip.
        # batch 8 x seq 1024 (same 8192 tokens/step as 4x2048) measured
        # ~6% higher MFU: attention's quadratic-in-seq work (uncounted by
        # the 6ND convention both stacks are scored with) shrinks while
        # the counted matmul work stays put.
        # attn_block_q=512 (matching bk) measures ~1% over the 256
        # default at seq 1024: one q block per 512 rows halves the
        # grid's q iterations and both blocks still fit scoped VMEM.
        cfg = replace(
            configs.get_config("llama2-1b"),
            n_layers=12,
            max_seq=1024,
            remat=True,
            remat_policy="dots_nobatch",
            attn_block_q=512,
        )
        batch, seq, steps, warmup = 8, 1024, 10, 2
    else:
        cfg = replace(configs.tiny, remat=False)
        batch, seq, steps, warmup = 8, 64, 5, 1

    def _measure(cfg, batch, seq, steps, warmup, tag):
        """One measured training run. Every step consumes a FRESH random
        batch (pre-generated on device) so the final loss evidences a
        working step on unseen data rather than memorization of one
        batch."""
        params = init_params(jax.random.PRNGKey(0), cfg)
        n_params = sum(x.size for x in jax.tree.leaves(params))
        optimizer = optax.adamw(1e-4)
        opt_state = jax.jit(optimizer.init)(params)
        print(f"[worker] {tag}: params built: {n_params:,}",
              file=sys.stderr, flush=True)

        def step(params, opt_state, tokens):
            loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
            updates, opt_state = optimizer.update(grads, opt_state, params)
            params = optax.apply_updates(params, updates)
            return params, opt_state, loss

        jstep = jax.jit(step, donate_argnums=(0, 1))
        all_tokens = [
            jax.random.randint(
                jax.random.fold_in(jax.random.PRNGKey(1), i),
                (batch, seq + 1), 0, cfg.vocab_size,
            )
            for i in range(warmup + steps)
        ]

        _stage("compile")
        t0 = time.monotonic()
        for i in range(warmup):
            params, opt_state, loss = jstep(params, opt_state, all_tokens[i])
            print(f"[worker] {tag}: warmup {i + 1}/{warmup}",
                  file=sys.stderr, flush=True)
        # On remote-tunneled TPU platforms block_until_ready can return
        # before execution finishes; a device_get of the scalar loss is a
        # true sync.
        jax.device_get(loss)
        print(
            f"[worker] {tag}: compile+warmup done in "
            f"{time.monotonic() - t0:.1f}s",
            file=sys.stderr, flush=True,
        )
        t0 = time.perf_counter()
        jax.device_get(loss)
        round_trip = time.perf_counter() - t0

        _stage("run")
        t0 = time.perf_counter()
        for i in range(steps):
            params, opt_state, loss = jstep(
                params, opt_state, all_tokens[warmup + i]
            )
            if (i + 1) % 5 == 0:
                print(f"[worker] {tag}: step {i + 1}/{steps}",
                      file=sys.stderr, flush=True)
        jax.device_get(loss)
        dt = max(time.perf_counter() - t0 - round_trip, 1e-9)

        tokens_per_sec = batch * seq * steps / dt
        # 6ND training FLOPs convention (fwd 2ND + bwd 4ND), ignoring
        # remat recompute — the same convention baseline MFU numbers use.
        mfu = tokens_per_sec * 6.0 * n_params / _peak_flops(dev)
        # 6ND ignores attention's quadratic matmuls, which at long seq
        # are a real double-digit share of the chip's work: QK^T + PV
        # fwd ~= 2*seq_avg*2*d_attn per layer-token, x3 for training.
        d_attn = cfg.n_heads * cfg.head_dim
        attn_flops_per_token = 6.0 * cfg.n_layers * seq * d_attn / 2 * 2
        mfu_attn = (
            tokens_per_sec * (6.0 * n_params + attn_flops_per_token)
            / _peak_flops(dev)
        )
        return {
            "value": round(tokens_per_sec, 1),
            "mfu": round(mfu, 4),
            "mfu_with_attention": round(mfu_attn, 4),
            "batch": batch,
            "seq": seq,
            "params": n_params,
            "loss": float(jax.device_get(loss)),
        }

    result = _measure(cfg, batch, seq, steps, warmup, f"seq{seq}")

    long_context = None
    if on_tpu:
        # Long-context variant AFTER the headline (its failure must
        # never cost the headline number): same 0.8B proxy at seq 4096
        # with the flash-attention kernel in the hot path — the regime
        # ring attention / flash blocks exist for. batch x seq stays
        # 8192 tokens/step.
        try:
            lc_cfg = replace(cfg, max_seq=4096)
            long_context = _measure(lc_cfg, 2, 4096, steps, warmup,
                                    "seq4096")
            print(f"[worker] long-context: {long_context}",
                  file=sys.stderr, flush=True)
        except Exception as e:  # noqa: BLE001 — optional extra point
            long_context = {"error": f"{type(e).__name__}: {e}"}
            print(f"[worker] long-context failed: {e}", file=sys.stderr,
                  flush=True)
    out = {
        "metric": (
            "llama2(0.8B) train-step tokens/s/chip"
            if on_tpu
            else "tiny train-step tokens/s (cpu fallback)"
        ),
        "unit": "tokens/s/chip",
        "vs_baseline": (
            round(result["mfu"] / BASELINE_MFU, 3) if on_tpu else 0.0
        ),
        "device": str(dev),
        **result,
    }
    if long_context is not None:
        out["long_context"] = long_context
    print(json.dumps(out), flush=True)


# ---------------------------------------------------------------------------
# Roll-up: one per-PR trajectory record over every bench artifact.
# ---------------------------------------------------------------------------
# Headline fields, in preference order: the number each bench's gate
# actually reads. A metric entry contributes its first match (or its
# first numeric field as a fallback) so the roll-up stays one line.
_ROLLUP_HEADLINE_KEYS = (
    "overhead_pct", "vs_baseline", "value", "ok", "p99_ms", "p50_ms",
    "e2e_sum_ok", "tokens_per_s", "emit_us", "cost_us_per_step",
)


def rollup() -> int:
    """Aggregate every BENCH_*.json's gate numbers into one trajectory
    record appended to PROGRESS.jsonl (kind="bench_rollup" distinguishes
    it from the driver's wall-clock records)."""
    import glob

    gates = {}
    for path in sorted(glob.glob("BENCH_*.json")):
        name = os.path.splitext(os.path.basename(path))[0]
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            gates[name] = {"error": "unreadable"}
            continue
        entries = doc if isinstance(doc, list) else [doc]
        file_gates = {}
        for e in entries:
            if not isinstance(e, dict):
                continue
            metric = str(e.get("metric") or e.get("name") or "?")
            headline = None
            for k in _ROLLUP_HEADLINE_KEYS:
                if isinstance(e.get(k), (int, float, bool)):
                    headline = {k: e[k]}
                    break
            if headline is None:
                headline = next(
                    ({k: v} for k, v in e.items()
                     if k not in ("ts", "steps", "rounds")
                     and isinstance(v, (int, float))
                     and not isinstance(v, bool)),
                    {},
                )
            file_gates[metric] = headline
        gates[name] = file_gates
    rec = {
        "ts": time.time(),
        "kind": "bench_rollup",
        "files": len(gates),
        "metrics": sum(len(g) for g in gates.values()),
        "gates": gates,
    }
    with open("PROGRESS.jsonl", "a") as f:
        f.write(json.dumps(rec) + "\n")
    print(json.dumps({"kind": "bench_rollup", "files": rec["files"],
                      "metrics": rec["metrics"]}), flush=True)
    return 0


def main() -> int:
    if len(sys.argv) >= 2 and sys.argv[1] == "--rollup":
        return rollup()
    if len(sys.argv) >= 2 and sys.argv[1] == "--worker":
        platform = sys.argv[2] if len(sys.argv) > 2 else "tpu"
        try:
            worker(platform)
            return 0
        except Exception as exc:  # noqa: BLE001 — parent parses this
            print(f"[worker] FAILED: {type(exc).__name__}: {exc}",
                  file=sys.stderr, flush=True)
            return 1
    return supervise()


if __name__ == "__main__":
    sys.exit(main())
