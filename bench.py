"""Flagship benchmark: Llama-family training-step throughput per chip.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": "tokens/s/chip", "vs_baseline": N}

On the real TPU chip this measures the full jit-compiled training step
(forward + backward + AdamW update, bf16 params/activations, remat) on a
~0.8B-parameter Llama-2-shaped model — sized so params + Adam state +
grads fit one 16GB v5e chip. `vs_baseline` is measured MFU divided by
0.40, the typical MFU of the reference's A100 TorchTrainer+NCCL stack on
Llama-2 (BASELINE.md north star: match TorchTrainer+NCCL tokens/sec/chip);
>1.0 means this stack extracts more of its chip than the baseline stack
extracts of its A100.

On CPU (no TPU visible) it falls back to a tiny config so the script still
emits a valid line.
"""

from __future__ import annotations

import json
import time
from dataclasses import replace

import jax
import jax.numpy as jnp

# Peak dense bf16 TFLOP/s per chip by TPU generation.
PEAK_TFLOPS = {
    "v4": 275.0,
    "v5e": 197.0,
    "v5lite": 197.0,
    "v5p": 459.0,
    "v6e": 918.0,
}
BASELINE_MFU = 0.40  # typical A100 TorchTrainer+NCCL MFU on Llama-2


def _peak_flops(device) -> float:
    kind = getattr(device, "device_kind", "").lower().replace(" ", "")
    for key, tf in PEAK_TFLOPS.items():
        if key in kind:
            return tf * 1e12
    return 197.0e12  # assume v5e-class


def count_params(tree) -> int:
    return sum(x.size for x in jax.tree.leaves(tree))


def main():
    import optax

    from ray_tpu.models import configs, init_params, loss_fn, param_logical_axes

    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    if on_tpu:
        # ~0.8B params: fits chip HBM with AdamW state + bf16 grads.
        # dots_nobatch remat saves the non-batch matmul outputs — ~12%
        # faster than full recompute and still fits the 16GB chip.
        # batch 8 x seq 1024 (same 8192 tokens/step as 4x2048) measured
        # ~6% higher MFU: attention's quadratic-in-seq work (uncounted by
        # the 6ND convention both stacks are scored with) shrinks while
        # the counted matmul work stays put.
        # attn_block_q=512 (matching bk) measures ~1% over the 256
        # default at seq 1024: one q block per 512 rows halves the
        # grid's q iterations and both blocks still fit scoped VMEM.
        cfg = replace(
            configs.get_config("llama2-1b"),
            n_layers=12,
            max_seq=1024,
            remat=True,
            remat_policy="dots_nobatch",
            attn_block_q=512,
        )
        batch, seq, steps, warmup = 8, 1024, 10, 2
    else:
        cfg = replace(configs.tiny, remat=False)
        batch, seq, steps, warmup = 8, 64, 5, 1

    params = init_params(jax.random.PRNGKey(0), cfg)
    n_params = count_params(params)
    optimizer = optax.adamw(1e-4)
    opt_state = jax.jit(optimizer.init)(params)

    def step(params, opt_state, tokens):
        loss, grads = jax.value_and_grad(loss_fn)(params, tokens, cfg)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = optax.apply_updates(params, updates)
        return params, opt_state, loss

    jstep = jax.jit(step, donate_argnums=(0, 1))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (batch, seq + 1), 0,
                                cfg.vocab_size)

    for _ in range(warmup):
        params, opt_state, loss = jstep(params, opt_state, tokens)
    # On remote-tunneled TPU platforms block_until_ready can return before
    # execution finishes; a device_get of the scalar loss is a true sync.
    jax.device_get(loss)
    t0 = time.perf_counter()
    jax.device_get(loss)
    round_trip = time.perf_counter() - t0

    t0 = time.perf_counter()
    for _ in range(steps):
        params, opt_state, loss = jstep(params, opt_state, tokens)
    jax.device_get(loss)
    dt = max(time.perf_counter() - t0 - round_trip, 1e-9)

    tokens_per_step = batch * seq
    tokens_per_sec = tokens_per_step * steps / dt
    # 6ND training FLOPs convention (fwd 2ND + bwd 4ND), ignoring remat
    # recompute — the same convention baseline MFU numbers use.
    flops_per_token = 6.0 * n_params
    mfu = tokens_per_sec * flops_per_token / _peak_flops(dev)
    vs_baseline = mfu / BASELINE_MFU if on_tpu else 0.0

    print(
        json.dumps(
            {
                "metric": (
                    "llama2(0.8B) train-step tokens/s/chip"
                    if on_tpu
                    else "tiny train-step tokens/s (cpu fallback)"
                ),
                "value": round(tokens_per_sec, 1),
                "unit": "tokens/s/chip",
                "vs_baseline": round(vs_baseline, 3),
                "mfu": round(mfu, 4),
                "batch": batch,
                "seq": seq,
                "params": n_params,
                "device": str(dev),
                "loss": float(jax.device_get(loss)),
            }
        )
    )


if __name__ == "__main__":
    main()
