"""Training backends: how a worker group becomes a distributed group.

Analog of the reference's Backend/BackendConfig ABC (train/backend.py:15,27
with on_start/on_shutdown/on_training_start hooks) and _TorchBackend
(train/torch/config.py:148, which runs dist.init_process_group on every
worker). The TPU-native backend instead:

  * whole-host workers: each worker owns all local chips
    (TPU_VISIBLE_CHIPS passthrough),
  * multi-host: jax.distributed.initialize with worker 0 as coordinator
    (rendezvous through the GCS KV, the same channel the reference's gloo
    backend uses),
  * gradient allreduce happens INSIDE pjit-compiled programs over ICI —
    the backend only sets the group up; no NCCL-style eager loop.
  * CPU test mode: a "dcn" collective group is created across workers so
    pure-DP training syncs gradients over TCP rings.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Dict, Optional

logger = logging.getLogger("ray_tpu.train.backend")


class Backend:
    def on_start(self, worker_group, backend_config):
        pass

    def on_training_start(self, worker_group, backend_config):
        pass

    def on_shutdown(self, worker_group, backend_config):
        pass

    def on_resize(self, worker_group, backend_config):
        """Rebuild the backend's collective state after an elastic
        resize: the worker group already holds the new ranks/world size
        and a bumped gang epoch."""
        pass


@dataclass
class BackendConfig:
    def backend_cls(self):
        return Backend


@dataclass
class JaxConfig(BackendConfig):
    """Configure the JAX distributed runtime across the worker group.

    distributed=True: call jax.distributed.initialize on every worker
    (multi-host TPU pods). With distributed=False (default for CPU tests
    and single-host), workers run independent jax processes and gradient
    sync uses the eager "dcn" collective group when dp_sync="dcn".
    """

    distributed: bool = False
    dp_sync: str = "dcn"  # "dcn" | "none" (in-program collectives)
    coordinator_port: int = 0

    def backend_cls(self):
        return _JaxBackend


class _JaxBackend(Backend):
    def on_start(self, worker_group, backend_config: JaxConfig):
        n = len(worker_group)
        if backend_config.distributed:
            # Worker 0 is the jax.distributed coordinator; its address is
            # published through the GCS KV (gloo_util.py:271 pattern).
            addrs = worker_group.execute(_get_host_ip)
            port = backend_config.coordinator_port or 47533
            coordinator = f"{addrs[0]}:{port}"
            worker_group.execute_with_rank(
                _jax_distributed_init, coordinator=coordinator, world_size=n
            )
        elif backend_config.dp_sync == "dcn" and n > 1:
            # The gang epoch stamps the rendezvous keys: a zombie rank
            # from a torn-down attempt rendezvouses under the old epoch
            # and can never join (or deadlock) this ring.
            worker_group.execute_with_rank(
                _init_dcn_group, world_size=n,
                epoch=getattr(worker_group, "epoch", 0),
            )

    def on_shutdown(self, worker_group, backend_config: JaxConfig):
        if backend_config.dp_sync == "dcn" and len(worker_group) > 1:
            try:
                worker_group.execute(_destroy_dcn_group)
            except Exception:  # noqa: BLE001 — workers may already be gone
                logger.warning("DCN collective group teardown failed on "
                               "shutdown (workers may already be dead)",
                               exc_info=True)

    def on_resize(self, worker_group, backend_config: JaxConfig):
        """Tear down and rebuild the DCN ring at the new world size.

        The group is destroyed on every surviving rank (tolerant — a
        joiner has nothing to destroy) and re-created under the bumped
        gang epoch, so a departed rank still parked in the old
        rendezvous can never join the new ring. The collective layer's
        topology model re-selects ring/rd/hier per op for the new size.
        jax.distributed has no live-resize path — elastic gangs require
        distributed=False (the eager DCN data plane).
        """
        if backend_config.distributed:
            raise RuntimeError(
                "elastic resize is not supported with "
                "JaxConfig(distributed=True): jax.distributed cannot "
                "re-initialize a live coordinator at a new world size"
            )
        if backend_config.dp_sync != "dcn":
            return
        n = len(worker_group)
        worker_group.execute(_destroy_dcn_group)
        if n > 1:
            worker_group.execute_with_rank(
                _init_dcn_group, world_size=n,
                epoch=getattr(worker_group, "epoch", 0),
            )


def _get_host_ip():
    import socket

    return socket.gethostbyname(socket.gethostname())


def _jax_distributed_init(rank: int, coordinator: str, world_size: int):
    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator,
        num_processes=world_size,
        process_id=rank,
    )
    return True


def _init_dcn_group(rank: int, world_size: int, epoch: int = 0):
    from ray_tpu.util import collective as col

    col.init_collective_group(world_size, rank, backend="dcn",
                              group_name="train_dp", epoch=epoch)
    return True


def _destroy_dcn_group():
    from ray_tpu.util import collective as col

    col.destroy_collective_group("train_dp")
    return True


def allreduce_gradients(grads, group_name: str = "train_dp"):
    """Mean-allreduce a gradient pytree across the training DP group.

    For CPU tests / eager DP mode. On TPU meshes, prefer in-program psum
    via pjit shardings — this helper is the fallback data path.
    """
    import jax
    import numpy as np

    from ray_tpu.util import collective as col

    n = col.get_collective_group_size(group_name)
    leaves, treedef = jax.tree.flatten(grads)
    out = []
    for leaf in leaves:
        reduced = col.allreduce(np.asarray(leaf), group_name)
        out.append(reduced / n)
    return jax.tree.unflatten(treedef, out)
