"""Checkpoints: directory-based with orbax for sharded arrays.

Analog of the reference's Checkpoint (train/_checkpoint.py:55, a directory
plus a pyarrow-fs handle) and CheckpointManager
(train/_internal/checkpoint_manager.py, top-k retention). The TPU twist
(SURVEY.md §5): sharded-array checkpoints are written per-host via orbax
so every host persists only its shards.
"""

from __future__ import annotations

import json
import logging
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional

logger = logging.getLogger("ray_tpu.train.checkpoint")


class Checkpoint:
    """A directory full of checkpoint data (reference: from_directory
    train/_checkpoint.py:178)."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        import cloudpickle

        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle

        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def as_directory(self) -> str:
        return self.path

    # -- sharded pytrees via orbax --------------------------------------

    @classmethod
    def from_pytree(cls, tree: Any, path: str) -> "Checkpoint":
        """Save a (possibly sharded) jax pytree with orbax."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(path, "pytree"), tree, force=True)
        return cls(path)

    def to_pytree(self, template: Any = None) -> Any:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        if template is not None:
            return ckptr.restore(os.path.join(self.path, "pytree"),
                                 item=template)
        return ckptr.restore(os.path.join(self.path, "pytree"))

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-k retention by score (reference:
    train/_internal/checkpoint_manager.py)."""

    def __init__(
        self,
        directory: str,
        num_to_keep: Optional[int] = None,
        score_attribute: Optional[str] = None,
        score_order: str = "max",
        storage=None,
    ):
        self.directory = directory
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        # Optional StorageContext: registered checkpoints also persist to
        # the run's storage_path URI (reference: per-rank upload through
        # StorageContext, train/_internal/storage.py:348).
        self.storage = storage
        self.registered: List[Dict] = []
        os.makedirs(directory, exist_ok=True)
        self._index = 0
        self._uploaded = 0  # sequential storage names: ordering is meaning

    def next_checkpoint_path(self) -> str:
        path = os.path.join(self.directory, f"checkpoint_{self._index:06d}")
        self._index += 1
        return path

    def register(self, checkpoint: Checkpoint, metrics: Dict) -> None:
        entry = {"checkpoint": checkpoint, "metrics": metrics}
        if self.storage is not None:
            # Sequential names: a local checkpoint dir may be a random
            # tempdir (Checkpoint.from_dict), whose basename would make
            # list_checkpoints() ordering — and "latest" selection —
            # arbitrary.
            name = f"checkpoint_{self._uploaded:06d}"
            self._uploaded += 1
            try:
                entry["uri"] = self.storage.persist(checkpoint, name)
                entry["storage_name"] = name
            except Exception as e:  # noqa: BLE001 — storage outage must
                entry["uri_error"] = str(e)  # not kill the training loop
        self.registered.append(entry)
        self._enforce_retention()
        self._write_index()

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self.registered:
            return None
        if self.score_attribute is None:
            return self.registered[-1]["checkpoint"]
        key = lambda e: e["metrics"].get(
            self.score_attribute, float("-inf") if self.score_order == "max" else float("inf")
        )
        best = (max if self.score_order == "max" else min)(self.registered, key=key)
        return best["checkpoint"]

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self.registered[-1]["checkpoint"] if self.registered else None

    def _enforce_retention(self):
        if self.num_to_keep is None or len(self.registered) <= self.num_to_keep:
            return
        if self.score_attribute is not None:
            order = sorted(
                self.registered,
                key=lambda e: e["metrics"].get(self.score_attribute, 0),
                reverse=self.score_order == "max",
            )
        else:
            order = list(reversed(self.registered))  # newest first
        keep = order[: self.num_to_keep]
        drop = [e for e in self.registered if e not in keep]
        for e in drop:
            try:
                shutil.rmtree(e["checkpoint"].path, ignore_errors=True)
            except OSError:
                pass
            # Retention applies to the storage URI too — dropping only
            # the local copy would grow remote storage without bound.
            if self.storage is not None and "storage_name" in e:
                try:
                    self.storage.delete(e["storage_name"])
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    # Silent failure here grows remote storage without
                    # bound, so at least leave a trace.
                    logger.warning("retention could not delete %r from "
                                   "storage", e["storage_name"],
                                   exc_info=True)
            self.registered.remove(e)

    def _write_index(self):
        index = [
            {"path": e["checkpoint"].path, "metrics": _json_safe(e["metrics"]),
             **({"uri": e["uri"]} if "uri" in e else {})}
            for e in self.registered
        ]
        with open(os.path.join(self.directory, "checkpoints.json"), "w") as f:
            json.dump(index, f, indent=2)


def _json_safe(d: Dict) -> Dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = str(v)
    return out


class ShardRemapPlan:
    """Deterministic old_world → new_world re-shard assignment.

    Every pytree leaf is flattened to 1-D and cut into `world` contiguous
    slices with np.array_split boundaries (the first ``size % world``
    ranks get one extra element), so the slice a rank owns is a pure
    function of (leaf size, world, rank). A plan between two world sizes
    is then a bijection on element positions by construction: each new
    rank's slice is assembled from the (at most two, for any divisor or
    non-divisor pair) old slices it overlaps, and reassembling all new
    slices yields the original tree bit-for-bit.

    Elastic resize executes this plan through the object store: each old
    rank publishes its slices once, each new rank fetches only
    ``sources_for(new_rank)`` — no full gather, no disk round trip.
    """

    def __init__(self, old_world: int, new_world: int, leaf_sizes: List[int],
                 leaf_dtypes: Optional[List] = None):
        if old_world < 1 or new_world < 1:
            raise ValueError("world sizes must be >= 1")
        self.old_world = old_world
        self.new_world = new_world
        self.leaf_sizes = [int(s) for s in leaf_sizes]
        # Per-leaf dtypes keep empty slices typed (a rank whose cut of a
        # scalar leaf is empty has no source shard to infer from).
        self.leaf_dtypes = leaf_dtypes

    @staticmethod
    def bounds(size: int, world: int) -> List[tuple]:
        """(start, stop) of each rank's slice of a flat leaf of `size`."""
        base, extra = divmod(size, world)
        out, start = [], 0
        for r in range(world):
            stop = start + base + (1 if r < extra else 0)
            out.append((start, stop))
            start = stop
        return out

    @classmethod
    def for_tree(cls, tree: Any, old_world: int,
                 new_world: int) -> "ShardRemapPlan":
        import jax
        import numpy as np

        leaves = [np.asarray(l) for l in jax.tree_util.tree_leaves(tree)]
        return cls(old_world, new_world, [int(l.size) for l in leaves],
                   [l.dtype for l in leaves])

    def segments_for(self, new_rank: int) -> List[tuple]:
        """(leaf, old_rank, src_lo, src_hi, dst_lo) segments that build
        `new_rank`'s slice of every leaf. src offsets are relative to the
        old rank's slice start; dst to the new rank's."""
        segs = []
        for leaf, size in enumerate(self.leaf_sizes):
            old_b = self.bounds(size, self.old_world)
            ds, de = self.bounds(size, self.new_world)[new_rank]
            for old_rank, (os_, oe) in enumerate(old_b):
                lo, hi = max(ds, os_), min(de, oe)
                if lo >= hi:
                    continue
                segs.append((leaf, old_rank, lo - os_, hi - os_, lo - ds))
        return segs

    def sources_for(self, new_rank: int) -> List[int]:
        """Old ranks whose slices `new_rank` needs (sorted, deduped)."""
        return sorted({s[1] for s in self.segments_for(new_rank)})

    def remap(self, new_rank: int, old_shards: Dict[int, List]) -> List:
        """Assemble `new_rank`'s per-leaf slices from old ranks' slices.

        old_shards maps old_rank → per-leaf 1-D arrays (only the ranks in
        sources_for(new_rank) need be present).
        """
        import numpy as np

        out = []
        for leaf, size in enumerate(self.leaf_sizes):
            ds, de = self.bounds(size, self.new_world)[new_rank]
            buf = None
            for l, old_rank, src_lo, src_hi, dst_lo in self.segments_for(new_rank):
                if l != leaf:
                    continue
                src = np.asarray(old_shards[old_rank][leaf])
                if buf is None:
                    buf = np.empty(de - ds, dtype=src.dtype)
                buf[dst_lo:dst_lo + (src_hi - src_lo)] = src[src_lo:src_hi]
            if buf is None:
                dt = (self.leaf_dtypes[leaf]
                      if self.leaf_dtypes is not None else np.float32)
                buf = np.empty(de - ds, dtype=dt)
            out.append(buf)
        return out


class ShardedState:
    """One rank's slice of a sharded pytree (ZeRO-style optimizer state).

    Holds the full tree's structure + leaf shapes/dtypes (the meta every
    rank shares) and this rank's contiguous 1-D slice of each leaf. The
    elastic resize path (train.sync_resize) republishes these slices
    through the object store and rebuilds them under the new world size
    via ShardRemapPlan — bit-for-bit, since remapping only moves bytes.
    """

    def __init__(self, meta: Dict, rank: int, world: int, slices: List):
        self.meta = meta  # {"treedef", "shapes", "dtypes", "sizes", "scalars"}
        self.rank = rank
        self.world = world
        self.slices = slices  # per-leaf 1-D np arrays (this rank's cut)

    @classmethod
    def create(cls, tree: Any, rank: int, world: int) -> "ShardedState":
        """Shard a full pytree: rank keeps only its slice of each leaf."""
        import jax
        import numpy as np

        leaves, treedef = jax.tree_util.tree_flatten(tree)
        flats = [np.asarray(l).reshape(-1) for l in leaves]
        meta = {
            "treedef": treedef,
            "shapes": [np.asarray(l).shape for l in leaves],
            "dtypes": [f.dtype for f in flats],
            "sizes": [f.size for f in flats],
            "scalars": [isinstance(l, (int, float, bool)) for l in leaves],
        }
        bounds = [ShardRemapPlan.bounds(f.size, world)[rank] for f in flats]
        slices = [f[lo:hi].copy() for f, (lo, hi) in zip(flats, bounds)]
        return cls(meta, rank, world, slices)

    def plan_to(self, new_world: int) -> ShardRemapPlan:
        return ShardRemapPlan(self.world, new_world, self.meta["sizes"],
                              self.meta["dtypes"])

    def remapped(self, new_rank: int, new_world: int,
                 old_shards: Dict[int, List]) -> "ShardedState":
        """This state's meta + new_rank's slices under new_world,
        assembled from old ranks' published slices."""
        plan = self.plan_to(new_world)
        return ShardedState(self.meta, new_rank, new_world,
                            plan.remap(new_rank, old_shards))

    @staticmethod
    def assemble(meta: Dict, shards_by_rank: Dict[int, List]) -> Any:
        """Rebuild the full pytree from every rank's slices."""
        import jax
        import numpy as np

        world = len(shards_by_rank)
        leaves = []
        for i, size in enumerate(meta["sizes"]):
            flat = np.concatenate(
                [np.asarray(shards_by_rank[r][i]) for r in range(world)]
            ) if size else np.empty(0, dtype=meta["dtypes"][i])
            leaf = flat.astype(meta["dtypes"][i], copy=False).reshape(
                meta["shapes"][i])
            # tolist() on the 0-d array recovers the python scalar
            # (these are host numpy buffers, never device arrays).
            leaves.append(leaf.reshape(()).tolist()
                          if meta["scalars"][i] else leaf)
        return jax.tree_util.tree_unflatten(meta["treedef"], leaves)

    def full(self, shards_by_rank: Dict[int, List]) -> Any:
        return self.assemble(self.meta, shards_by_rank)

    # -- partial-shard save/load ----------------------------------------
    # Departing ranks persist exactly their slice before exiting through
    # the drain plane; a cold restore (or a debugging session) can
    # reassemble the full tree from whatever subset of ranks survived to
    # disk plus the live remap refs.

    def save(self, directory: str) -> str:
        import cloudpickle

        os.makedirs(directory, exist_ok=True)
        path = os.path.join(directory, f"shard_{self.rank:05d}.pkl")
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            cloudpickle.dump(
                {"meta": self.meta, "rank": self.rank, "world": self.world,
                 "slices": self.slices}, f)
        os.replace(tmp, path)
        return path

    @classmethod
    def load(cls, directory: str, rank: int) -> "ShardedState":
        import cloudpickle

        path = os.path.join(directory, f"shard_{rank:05d}.pkl")
        with open(path, "rb") as f:
            d = cloudpickle.load(f)
        return cls(d["meta"], d["rank"], d["world"], d["slices"])

    @classmethod
    def load_all(cls, directory: str) -> Dict[int, "ShardedState"]:
        out = {}
        for name in sorted(os.listdir(directory)):
            if name.startswith("shard_") and name.endswith(".pkl"):
                rank = int(name[len("shard_"):-len(".pkl")])
                out[rank] = cls.load(directory, rank)
        return out


class AsyncCheckpointer:
    """Asynchronous pytree checkpointing: save() returns once the arrays
    are snapshotted to host memory and serialization continues in
    background threads, so the train step keeps the TPU busy during the
    write. wait() is the completion barrier — call it before REPORTING a
    checkpoint so a resume can never observe a partial write.

    Reference analog: the async upload path of train/_internal/storage.py
    (StorageContext persists checkpoints off the training thread); on TPU
    pods each host writes only its own shards (orbax ocdbt layout).
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str, tree: Any) -> "Checkpoint":
        path = os.path.abspath(path)
        self._ckptr.save(os.path.join(path, "pytree"), tree, force=True)
        return Checkpoint(path)

    def wait(self):
        """Block until every outstanding save has been committed."""
        self._ckptr.wait_until_finished()

    def close(self):
        try:
            self._ckptr.close()
        except Exception:  # noqa: BLE001 — close is best-effort
            logger.debug("async checkpointer close failed",
                         exc_info=True)
