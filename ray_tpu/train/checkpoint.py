"""Checkpoints: directory-based with orbax for sharded arrays.

Analog of the reference's Checkpoint (train/_checkpoint.py:55, a directory
plus a pyarrow-fs handle) and CheckpointManager
(train/_internal/checkpoint_manager.py, top-k retention). The TPU twist
(SURVEY.md §5): sharded-array checkpoints are written per-host via orbax
so every host persists only its shards.
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
from typing import Any, Dict, List, Optional


class Checkpoint:
    """A directory full of checkpoint data (reference: from_directory
    train/_checkpoint.py:178)."""

    def __init__(self, path: str):
        self.path = path

    @classmethod
    def from_directory(cls, path: str) -> "Checkpoint":
        return cls(os.path.abspath(path))

    @classmethod
    def from_dict(cls, data: Dict[str, Any]) -> "Checkpoint":
        import cloudpickle

        d = tempfile.mkdtemp(prefix="rt_ckpt_")
        with open(os.path.join(d, "data.pkl"), "wb") as f:
            cloudpickle.dump(data, f)
        return cls(d)

    def to_dict(self) -> Dict[str, Any]:
        import cloudpickle

        with open(os.path.join(self.path, "data.pkl"), "rb") as f:
            return cloudpickle.load(f)

    def as_directory(self) -> str:
        return self.path

    # -- sharded pytrees via orbax --------------------------------------

    @classmethod
    def from_pytree(cls, tree: Any, path: str) -> "Checkpoint":
        """Save a (possibly sharded) jax pytree with orbax."""
        import orbax.checkpoint as ocp

        path = os.path.abspath(path)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(os.path.join(path, "pytree"), tree, force=True)
        return cls(path)

    def to_pytree(self, template: Any = None) -> Any:
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        if template is not None:
            return ckptr.restore(os.path.join(self.path, "pytree"),
                                 item=template)
        return ckptr.restore(os.path.join(self.path, "pytree"))

    def __repr__(self):
        return f"Checkpoint({self.path})"


class CheckpointManager:
    """Top-k retention by score (reference:
    train/_internal/checkpoint_manager.py)."""

    def __init__(
        self,
        directory: str,
        num_to_keep: Optional[int] = None,
        score_attribute: Optional[str] = None,
        score_order: str = "max",
        storage=None,
    ):
        self.directory = directory
        self.num_to_keep = num_to_keep
        self.score_attribute = score_attribute
        self.score_order = score_order
        # Optional StorageContext: registered checkpoints also persist to
        # the run's storage_path URI (reference: per-rank upload through
        # StorageContext, train/_internal/storage.py:348).
        self.storage = storage
        self.registered: List[Dict] = []
        os.makedirs(directory, exist_ok=True)
        self._index = 0
        self._uploaded = 0  # sequential storage names: ordering is meaning

    def next_checkpoint_path(self) -> str:
        path = os.path.join(self.directory, f"checkpoint_{self._index:06d}")
        self._index += 1
        return path

    def register(self, checkpoint: Checkpoint, metrics: Dict) -> None:
        entry = {"checkpoint": checkpoint, "metrics": metrics}
        if self.storage is not None:
            # Sequential names: a local checkpoint dir may be a random
            # tempdir (Checkpoint.from_dict), whose basename would make
            # list_checkpoints() ordering — and "latest" selection —
            # arbitrary.
            name = f"checkpoint_{self._uploaded:06d}"
            self._uploaded += 1
            try:
                entry["uri"] = self.storage.persist(checkpoint, name)
                entry["storage_name"] = name
            except Exception as e:  # noqa: BLE001 — storage outage must
                entry["uri_error"] = str(e)  # not kill the training loop
        self.registered.append(entry)
        self._enforce_retention()
        self._write_index()

    def best_checkpoint(self) -> Optional[Checkpoint]:
        if not self.registered:
            return None
        if self.score_attribute is None:
            return self.registered[-1]["checkpoint"]
        key = lambda e: e["metrics"].get(
            self.score_attribute, float("-inf") if self.score_order == "max" else float("inf")
        )
        best = (max if self.score_order == "max" else min)(self.registered, key=key)
        return best["checkpoint"]

    def latest_checkpoint(self) -> Optional[Checkpoint]:
        return self.registered[-1]["checkpoint"] if self.registered else None

    def _enforce_retention(self):
        if self.num_to_keep is None or len(self.registered) <= self.num_to_keep:
            return
        if self.score_attribute is not None:
            order = sorted(
                self.registered,
                key=lambda e: e["metrics"].get(self.score_attribute, 0),
                reverse=self.score_order == "max",
            )
        else:
            order = list(reversed(self.registered))  # newest first
        keep = order[: self.num_to_keep]
        drop = [e for e in self.registered if e not in keep]
        for e in drop:
            try:
                shutil.rmtree(e["checkpoint"].path, ignore_errors=True)
            except OSError:
                pass
            # Retention applies to the storage URI too — dropping only
            # the local copy would grow remote storage without bound.
            if self.storage is not None and "storage_name" in e:
                try:
                    self.storage.delete(e["storage_name"])
                except Exception:  # noqa: BLE001 — best-effort cleanup
                    pass
            self.registered.remove(e)

    def _write_index(self):
        index = [
            {"path": e["checkpoint"].path, "metrics": _json_safe(e["metrics"]),
             **({"uri": e["uri"]} if "uri" in e else {})}
            for e in self.registered
        ]
        with open(os.path.join(self.directory, "checkpoints.json"), "w") as f:
            json.dump(index, f, indent=2)


def _json_safe(d: Dict) -> Dict:
    out = {}
    for k, v in d.items():
        try:
            json.dumps(v)
            out[k] = v
        except TypeError:
            out[k] = str(v)
    return out


class AsyncCheckpointer:
    """Asynchronous pytree checkpointing: save() returns once the arrays
    are snapshotted to host memory and serialization continues in
    background threads, so the train step keeps the TPU busy during the
    write. wait() is the completion barrier — call it before REPORTING a
    checkpoint so a resume can never observe a partial write.

    Reference analog: the async upload path of train/_internal/storage.py
    (StorageContext persists checkpoints off the training thread); on TPU
    pods each host writes only its own shards (orbax ocdbt layout).
    """

    def __init__(self):
        import orbax.checkpoint as ocp

        self._ckptr = ocp.AsyncCheckpointer(ocp.PyTreeCheckpointHandler())

    def save(self, path: str, tree: Any) -> "Checkpoint":
        path = os.path.abspath(path)
        self._ckptr.save(os.path.join(path, "pytree"), tree, force=True)
        return Checkpoint(path)

    def wait(self):
        """Block until every outstanding save has been committed."""
        self._ckptr.wait_until_finished()

    def close(self):
        try:
            self._ckptr.close()
        except Exception:  # noqa: BLE001
            pass
