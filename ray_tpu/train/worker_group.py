"""Worker group: the actor gang that runs training.

Analog of the reference's WorkerGroup (train/_internal/worker_group.py) +
the placement/rank parts of BackendExecutor
(train/_internal/backend_executor.py:124-358): N actors created inside a
placement group, rank/world mappings computed, functions executed on all
workers in parallel.

On TPU pods the idiomatic gang is one whole-host worker per pod host,
reserved via the pod-name gang resource or a STRICT_SPREAD placement
group over {TPU: chips_per_host} bundles.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod
from ray_tpu.exceptions import PlacementGroupSchedulingError
from ray_tpu.train.session import TrainSession, get_session, init_session, shutdown_session
from ray_tpu.util.placement_group import PlacementGroup, placement_group, remove_placement_group
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy


@rt.remote
class TrainWorker:
    """Hosts one rank's training loop (reference: per-worker _TrainSession)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session: Optional[TrainSession] = None
        self._thread = None
        self._error = None
        self._done = False

    def execute(self, fn, *args, **kwargs):
        return fn(*args, **kwargs)

    def execute_with_rank(self, fn, *args, **kwargs):
        return fn(self.rank, *args, **kwargs)

    def start_training(self, train_fn, config, checkpoint, trial_dir,
                       dataset_shard=None):
        import threading

        self.session = init_session(
            world_rank=self.rank,
            world_size=self.world_size,
            config=config,
            checkpoint=checkpoint,
            # DataConfig hands a {name: shard} dict per worker; legacy
            # callers may still pass a bare train shard.
            dataset_shards=(
                dataset_shard if isinstance(dataset_shard, dict)
                else {"train": dataset_shard} if dataset_shard is not None
                else {}
            ),
            trial_dir=trial_dir,
        )
        self._done = False
        self._error = None

        self._error_type = None

        def run():
            try:
                train_fn(config) if _wants_arg(train_fn) else train_fn()
            except BaseException as e:  # noqa: BLE001
                import traceback

                self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                self._error_type = type(e).__name__
            finally:
                self._done = True

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        """Drain queued reports (reference: get_next_results
        backend_executor.py:552)."""
        reports = self.session.drain() if self.session else []
        out = []
        for r in reports:
            ckpt = r["checkpoint"]
            entry = {
                "metrics": r["metrics"],
                "checkpoint_path": ckpt.path if ckpt else None,
            }
            if r.get("step_records"):
                entry["step_records"] = r["step_records"]
            out.append(entry)
        # Flight recorder: cumulative per-rank step stats ride every poll
        # (not just reports), so the trainer's skew/straggler view stays
        # current even for loops that report rarely.
        prof = self.session.profiler if self.session else None
        return {
            "reports": out,
            "done": self._done,
            "error": self._error,
            "error_type": getattr(self, "_error_type", None),
            "step_stats": prof.summary() if prof is not None else None,
        }

    def ping(self):
        """Liveness probe. Training runs in a daemon thread, so this
        answers promptly even mid-step — a non-answer means the process
        is gone or the actor event loop is wedged."""
        return True

    def request_stop(self):
        """Ask the training loop to checkpoint and return at its next
        train.should_stop() check (proactive drain migration)."""
        if self.session is not None:
            self.session.request_stop()
        return True

    def shutdown(self):
        shutdown_session()
        return True


def _wants_arg(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        epoch: int = 0,
        priority: int = 0,
        name: str = "",
    ):
        self.num_workers = num_workers
        # Gang attempt number — read by the backend's on_start to stamp
        # DCN rendezvous keys so stale ranks can't join a rebuilt ring.
        self.epoch = epoch
        self._pg: Optional[PlacementGroup] = None
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self._pg = placement_group(
            bundles, strategy=placement_strategy, name=name, priority=priority
        )
        # ready() raises PlacementGroupSchedulingError on INFEASIBLE /
        # REMOVED; a False return is a still-pending reservation.
        if not self._pg.ready(timeout=120):
            remove_placement_group(self._pg)
            raise PlacementGroupSchedulingError(
                f"worker group placement group not ready within 120s "
                f"(bundles={bundles}, strategy={placement_strategy})"
            )
        self.workers = [
            TrainWorker.options(
                num_cpus=0,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=i,
                ),
            ).remote(i, num_workers)
            for i in range(num_workers)
        ]

    def __len__(self):
        return self.num_workers

    def node_ids(self) -> List:
        """Per-rank node ids via the placement group's bundle→node map
        (rank i lives in bundle i)."""
        return self._pg.bundle_node_ids() if self._pg else []

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker; returns per-rank results."""
        return rt.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600,
        )

    def execute_with_rank(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return rt.get(
            [w.execute_with_rank.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600,
        )

    def shutdown(self):
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:
                pass
        if self._pg is not None:
            try:
                remove_placement_group(self._pg)
            except Exception:
                pass
