"""Worker group: the actor gang that runs training.

Analog of the reference's WorkerGroup (train/_internal/worker_group.py) +
the placement/rank parts of BackendExecutor
(train/_internal/backend_executor.py:124-358): N actors created inside a
placement group, rank/world mappings computed, functions executed on all
workers in parallel.

On TPU pods the idiomatic gang is one whole-host worker per pod host,
reserved via the pod-name gang resource or a STRICT_SPREAD placement
group over {TPU: chips_per_host} bundles.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional

import ray_tpu as rt
from ray_tpu._private import worker as worker_mod
from ray_tpu.exceptions import PlacementGroupSchedulingError
from ray_tpu.train.session import TrainSession, get_session, init_session, shutdown_session
from ray_tpu.util.placement_group import (
    PlacementGroup,
    placement_group,
    placement_group_state,
    release_placement_group_bundles,
    remove_placement_group,
    reserve_placement_group_bundles,
)
from ray_tpu.util.scheduling_strategies import PlacementGroupSchedulingStrategy

logger = logging.getLogger("ray_tpu.train.worker_group")


@rt.remote
class TrainWorker:
    """Hosts one rank's training loop (reference: per-worker _TrainSession)."""

    def __init__(self, rank: int, world_size: int):
        self.rank = rank
        self.world_size = world_size
        self.session: Optional[TrainSession] = None
        self._thread = None
        self._error = None
        self._done = False

    def execute(self, fn, *args, **kwargs):
        return fn(*args, **kwargs)

    def execute_with_rank(self, fn, *args, **kwargs):
        return fn(self.rank, *args, **kwargs)

    def start_training(self, train_fn, config, checkpoint, trial_dir,
                       dataset_shard=None, resize_join=None):
        import threading

        self.session = init_session(
            world_rank=self.rank,
            world_size=self.world_size,
            config=config,
            checkpoint=checkpoint,
            # DataConfig hands a {name: shard} dict per worker; legacy
            # callers may still pass a bare train shard.
            dataset_shards=(
                dataset_shard if isinstance(dataset_shard, dict)
                else {"train": dataset_shard} if dataset_shard is not None
                else {}
            ),
            trial_dir=trial_dir,
            # Joiners of a grow resize start with a pre-armed ticket so
            # their first sync_resize adopts the live gang state.
            resize_join=resize_join,
        )
        self._done = False
        self._error = None

        self._error_type = None

        def run():
            try:
                train_fn(config) if _wants_arg(train_fn) else train_fn()
            except BaseException as e:  # noqa: BLE001
                import traceback

                self._error = f"{type(e).__name__}: {e}\n{traceback.format_exc()}"
                self._error_type = type(e).__name__
            finally:
                self._done = True

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()
        return True

    def poll(self):
        """Drain queued reports (reference: get_next_results
        backend_executor.py:552)."""
        reports = self.session.drain() if self.session else []
        out = []
        for r in reports:
            ckpt = r["checkpoint"]
            entry = {
                "metrics": r["metrics"],
                "checkpoint_path": ckpt.path if ckpt else None,
            }
            if r.get("step_records"):
                entry["step_records"] = r["step_records"]
            out.append(entry)
        # Flight recorder: cumulative per-rank step stats ride every poll
        # (not just reports), so the trainer's skew/straggler view stays
        # current even for loops that report rarely.
        prof = self.session.profiler if self.session else None
        return {
            "reports": out,
            "done": self._done,
            "error": self._error,
            "error_type": getattr(self, "_error_type", None),
            "step_stats": prof.summary() if prof is not None else None,
        }

    def ping(self):
        """Liveness probe. Training runs in a daemon thread, so this
        answers promptly even mid-step — a non-answer means the process
        is gone or the actor event loop is wedged."""
        return True

    def request_stop(self):
        """Ask the training loop to checkpoint and return at its next
        train.should_stop() check (proactive drain migration)."""
        if self.session is not None:
            self.session.request_stop()
        return True

    # -- elastic resize (driven by BackendExecutor.resize) ---------------
    def begin_resize(self, spec):
        if self.session is None:
            return False
        self.session.begin_resize(spec)
        return True

    def poll_resize(self):
        if self.session is None:
            return {"armed": False, "outbox": None, "applied": False,
                    "loop_done": self._done}
        out = self.session.poll_resize()
        # A loop that finished (or died) before reaching the barrier can
        # never publish; the executor aborts instead of timing out.
        out["loop_done"] = self._done
        return out

    def complete_resize(self, payload):
        if self.session is not None:
            self.session.deliver_resize(payload)
        return True

    def abort_resize(self):
        if self.session is not None:
            self.session.abort_resize()
        return True

    def set_rank(self, rank: int, world_size: int):
        """Renumber this worker after a resize (the session's own view
        updates when its sync_resize consumes the delivery; this keeps
        execute_with_rank — e.g. the DCN group rebuild — consistent)."""
        self.rank = rank
        self.world_size = world_size
        return True

    def shutdown(self):
        shutdown_session()
        return True


def _wants_arg(fn) -> bool:
    import inspect

    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    return len(sig.parameters) >= 1


class WorkerGroup:
    def __init__(
        self,
        num_workers: int,
        resources_per_worker: Dict[str, float],
        placement_strategy: str = "PACK",
        epoch: int = 0,
        priority: int = 0,
        name: str = "",
    ):
        self.num_workers = num_workers
        # Gang attempt number — read by the backend's on_start to stamp
        # DCN rendezvous keys so stale ranks can't join a rebuilt ring.
        self.epoch = epoch
        self._pg: Optional[PlacementGroup] = None
        bundles = [dict(resources_per_worker) for _ in range(num_workers)]
        self._pg = placement_group(
            bundles, strategy=placement_strategy, name=name, priority=priority
        )
        # ready() raises PlacementGroupSchedulingError on INFEASIBLE /
        # REMOVED; a False return is a still-pending reservation.
        if not self._pg.ready(timeout=120):
            remove_placement_group(self._pg)
            raise PlacementGroupSchedulingError(
                f"worker group placement group not ready within 120s "
                f"(bundles={bundles}, strategy={placement_strategy})"
            )
        self.workers = [
            TrainWorker.options(
                num_cpus=0,
                scheduling_strategy=PlacementGroupSchedulingStrategy(
                    placement_group=self._pg,
                    placement_group_bundle_index=i,
                ),
            ).remote(i, num_workers)
            for i in range(num_workers)
        ]
        # Elastic resize bookkeeping: rank i lives in bundle
        # bundle_for_rank[i] (identity at birth; shrink/grow make it
        # sparse — survivors keep their original bundles, joiners take
        # the freed indices).
        self.bundle_for_rank: List[int] = list(range(num_workers))
        self._released_bundles: List[int] = []

    def __len__(self):
        return self.num_workers

    @property
    def pg_id(self) -> bytes:
        return self._pg.id.binary() if self._pg else b""

    def node_ids(self) -> List:
        """Per-rank node ids via the placement group's bundle→node map
        (rank i lives in bundle bundle_for_rank[i])."""
        if self._pg is None:
            return []
        by_bundle = self._pg.bundle_node_ids()
        return [
            by_bundle[b] if b < len(by_bundle) else None
            for b in self.bundle_for_rank
        ]

    def ranks_for_bundles(self, indices) -> List[int]:
        """Ranks currently living in the given bundle indices."""
        want = set(indices)
        return [r for r, b in enumerate(self.bundle_for_rank) if b in want]

    def shrink(self, departing_ranks: List[int]) -> Dict[int, int]:
        """Drop the departing ranks' workers, release their bundles back
        to the GCS (crediting the chips — this is what the claimant of a
        partial reclamation is waiting for), and renumber survivors to
        0..k-1 preserving order. Returns the old→new rank map."""
        departing = set(departing_ranks)
        released = [self.bundle_for_rank[r] for r in sorted(departing)]
        for r in sorted(departing):
            try:
                rt.kill(self.workers[r])
            except Exception:  # rtlint: disable=RT007 — a departing rank that already exited through the drain plane is the happy path
                pass
        rank_map: Dict[int, int] = {}
        new_workers, new_bundles = [], []
        for old_rank in range(self.num_workers):
            if old_rank in departing:
                continue
            rank_map[old_rank] = len(new_workers)
            new_workers.append(self.workers[old_rank])
            new_bundles.append(self.bundle_for_rank[old_rank])
        self.workers = new_workers
        self.bundle_for_rank = new_bundles
        self.num_workers = len(new_workers)
        self._released_bundles.extend(released)
        release_placement_group_bundles(self._pg, released)
        return rank_map

    def grow(self, target: int) -> List[int]:
        """Re-reserve previously released bundles and spawn joiner
        workers into them (rank k..target-1). Raises
        PlacementGroupSchedulingError while the chips are still fenced
        or occupied. Returns the new ranks."""
        need = target - self.num_workers
        if need <= 0:
            return []
        if need > len(self._released_bundles):
            raise PlacementGroupSchedulingError(
                f"cannot grow to {target}: only "
                f"{len(self._released_bundles)} released bundle(s) to "
                f"re-reserve"
            )
        indices = sorted(self._released_bundles)[:need]
        reserve_placement_group_bundles(self._pg, indices)
        self._released_bundles = [
            b for b in self._released_bundles if b not in set(indices)
        ]
        new_ranks = []
        for j, bundle_index in enumerate(indices):
            rank = self.num_workers + j
            self.workers.append(
                TrainWorker.options(
                    num_cpus=0,
                    scheduling_strategy=PlacementGroupSchedulingStrategy(
                        placement_group=self._pg,
                        placement_group_bundle_index=bundle_index,
                    ),
                ).remote(rank, target)
            )
            self.bundle_for_rank.append(bundle_index)
            new_ranks.append(rank)
        self.num_workers = len(self.workers)
        return new_ranks

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Run fn on every worker; returns per-rank results."""
        return rt.get(
            [w.execute.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600,
        )

    def execute_with_rank(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return rt.get(
            [w.execute_with_rank.remote(fn, *args, **kwargs) for w in self.workers],
            timeout=600,
        )

    def shutdown(self, verify: bool = False):
        """Kill the gang and release its placement group.

        verify=True (the restart path) confirms the GCS actually marked
        the group REMOVED — retrying the removal once — and raises if
        the release cannot be confirmed. A silently surviving group
        would keep its bundles reserved forever, leaking a gang's worth
        of chips on every restart.
        """
        for w in self.workers:
            try:
                rt.kill(w)
            except Exception:  # noqa: BLE001 — already-dead is expected
                logger.debug("kill of train worker failed (already "
                             "dead?)", exc_info=True)
        if self._pg is None:
            return
        pg, self._pg = self._pg, None
        last_error: Optional[Exception] = None
        for _ in range(2):
            try:
                remove_placement_group(pg)
                last_error = None
            except Exception as e:  # rtlint: disable=RT007 — carried into the PlacementGroupSchedulingError raised below
                last_error = e
            if not verify:
                return
            deadline = time.monotonic() + 5.0
            while time.monotonic() < deadline:
                try:
                    state = placement_group_state(pg)
                except Exception as e:  # rtlint: disable=RT007 — carried into the PlacementGroupSchedulingError raised below
                    last_error = e
                    break
                if state in (None, "REMOVED"):
                    return
                time.sleep(0.05)
        raise PlacementGroupSchedulingError(
            f"placement group {pg.id.hex()} still reserved after "
            f"shutdown (remove not confirmed"
            + (f"; last error: {last_error}" if last_error else "")
            + ") — refusing to respawn on top of a leaked gang"
        )
