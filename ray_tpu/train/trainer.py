"""Trainers: BaseTrainer → DataParallelTrainer → JaxTrainer.

Analogs of the reference's BaseTrainer (train/base_trainer.py:74, fit()
:579) and DataParallelTrainer (train/data_parallel_trainer.py:26,
training_loop :432). Differences by design:

  * fit() drives the BackendExecutor directly with an inline result loop;
    `as_trainable()` adapts the trainer for the Tune controller instead of
    the reference's always-through-Tune layering (base_trainer.py:839).
  * JaxTrainer replaces TorchTrainer: the worker group is one whole-host
    process per TPU host; collectives run inside compiled programs over
    ICI (or the eager DCN group on CPU gangs). There is no torch/DDP
    anywhere in the gradient path (the reference has no JAX backend at
    all — SURVEY.md §2.3 "No JAX/XLA backend exists").
"""

from __future__ import annotations

import os
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import BackendExecutor, TrainingFailedError
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    Result,
    RunConfig,
    ScalingConfig,
)


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapt this trainer into a Tune trainable (reference:
        base_trainer.py:839)."""
        trainer = self

        def trainable(config, session):
            import copy

            t = copy.copy(trainer)
            merged = dict(getattr(t, "train_loop_config", None) or {})
            merged.update(config)
            t.train_loop_config = merged
            result = t.fit()
            if result.error:
                raise result.error
            session.report(result.metrics, checkpoint=result.checkpoint)

        if hasattr(trainer, "_tune_resources"):
            # tune.with_resources pinned per-trial resources on the
            # trainer; carry them onto the closure the Tuner consumes.
            trainable._tune_resources = trainer._tune_resources
        return trainable


class DataParallelTrainer(BaseTrainer):
    """SPMD training: the same loop on every worker of the gang."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_config: Optional["DataConfig"] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or BackendConfig()
        self.datasets = datasets or {}
        from ray_tpu.train.data_config import DataConfig

        self.dataset_config = dataset_config or DataConfig()

    def fit(self) -> Result:
        failure_config = self.run_config.failure_config
        attempts = failure_config.max_failures + 1
        last_error: Optional[Exception] = None
        checkpoint = self.resume_from_checkpoint
        for attempt in range(max(1, attempts)):
            try:
                return self._run_once(checkpoint)
            except TrainingFailedError as e:  # worker failure: restart
                last_error = e
                if failure_config.fail_fast or attempt + 1 >= attempts:
                    break
                # Resume from the newest checkpoint (reference: _restart
                # backend_executor.py:701).
                checkpoint = self._latest_checkpoint or checkpoint
        return Result(metrics={}, checkpoint=self._latest_checkpoint,
                      error=last_error, path=self._trial_dir)

    def _run_once(self, checkpoint: Optional[Checkpoint]) -> Result:
        trial_dir = self.run_config.resolved_storage_path()
        os.makedirs(trial_dir, exist_ok=True)
        self._trial_dir = trial_dir
        ckpt_config = self.run_config.checkpoint_config
        manager = CheckpointManager(
            os.path.join(trial_dir, "checkpoints"),
            num_to_keep=ckpt_config.num_to_keep,
            score_attribute=ckpt_config.checkpoint_score_attribute,
            score_order=ckpt_config.checkpoint_score_order,
            storage=self.run_config.storage_context(),
        )
        self._latest_checkpoint = None

        executor = BackendExecutor(self.backend_config, self.scaling_config)
        executor.start()
        dataset_shards = self._shard_datasets(self.scaling_config.num_workers)
        metrics_history: List[Dict] = []
        final_metrics: Dict = {}
        try:
            executor.start_training(
                self.train_loop_per_worker,
                self.train_loop_config,
                checkpoint,
                trial_dir,
                dataset_shards,
            )
            while True:
                statuses = executor.poll()
                for st in statuses:
                    if st["error"]:
                        raise TrainingFailedError(st["error"])
                # Rank-0 reports carry the canonical metrics (reference:
                # first-worker results in TrainingIterator).
                rank0 = statuses[0]["reports"]
                for rep in rank0:
                    final_metrics = rep["metrics"]
                    metrics_history.append(rep["metrics"])
                    if rep["checkpoint_path"]:
                        ckpt = Checkpoint.from_directory(rep["checkpoint_path"])
                        manager.register(ckpt, rep["metrics"])
                        self._latest_checkpoint = ckpt
                if all(st["done"] for st in statuses):
                    # Final drain.
                    for st in executor.poll():
                        for rep in st["reports"]:
                            final_metrics = rep["metrics"]
                            metrics_history.append(rep["metrics"])
                            if rep["checkpoint_path"]:
                                ckpt = Checkpoint.from_directory(
                                    rep["checkpoint_path"]
                                )
                                manager.register(ckpt, rep["metrics"])
                                self._latest_checkpoint = ckpt
                    break
                time.sleep(0.05)
        finally:
            executor.shutdown()
            self._stop_shards(dataset_shards)
        best = manager.best_checkpoint() or self._latest_checkpoint
        return Result(
            metrics=final_metrics,
            checkpoint=best,
            error=None,
            path=trial_dir,
            metrics_history=metrics_history,
        )

    @staticmethod
    def _stop_shards(dataset_shards):
        """Kill streaming_split coordinator actors once training ends —
        they hold the dataset's input block refs and nothing else ever
        reclaims them (one coordinator per split dataset per fit)."""
        seen = set()
        for entry in dataset_shards or []:
            shards = entry.values() if isinstance(entry, dict) else [entry]
            for shard in shards:
                coord = getattr(shard, "_coord", None)
                stop = getattr(shard, "stop", None)
                if coord is not None and callable(stop):
                    key = getattr(coord, "_actor_id", id(coord))
                    if key in seen:
                        continue
                    seen.add(key)
                    stop()

    def _shard_datasets(self, num_workers: int):
        """Per-worker {name: shard} dicts via DataConfig: split datasets
        become coordinated streaming_split DataIterators (one shared
        streaming execution per epoch), others broadcast (reference:
        train/_internal/data_config.py DataConfig.configure)."""
        if not self.datasets:
            return None
        return self.dataset_config.configure(self.datasets, num_workers)


class JaxTrainer(DataParallelTrainer):
    """Distributed JAX training on TPU gangs (replaces TorchTrainer).

    The worker group is one process per TPU host; JaxConfig wires
    jax.distributed + mesh construction; inside the loop users build
    pjit-compiled steps whose collectives ride ICI. On CPU test gangs the
    eager DCN group provides gradient sync.
    """

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        super().__init__(
            train_loop_per_worker,
            backend_config=jax_config or JaxConfig(),
            **kwargs,
        )
