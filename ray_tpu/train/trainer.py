"""Trainers: BaseTrainer → DataParallelTrainer → JaxTrainer.

Analogs of the reference's BaseTrainer (train/base_trainer.py:74, fit()
:579) and DataParallelTrainer (train/data_parallel_trainer.py:26,
training_loop :432). Differences by design:

  * fit() drives the BackendExecutor directly with an inline result loop;
    `as_trainable()` adapts the trainer for the Tune controller instead of
    the reference's always-through-Tune layering (base_trainer.py:839).
  * JaxTrainer replaces TorchTrainer: the worker group is one whole-host
    process per TPU host; collectives run inside compiled programs over
    ICI (or the eager DCN group on CPU gangs). There is no torch/DDP
    anywhere in the gradient path (the reference has no JAX backend at
    all — SURVEY.md §2.3 "No JAX/XLA backend exists").
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import BackendConfig, JaxConfig
from ray_tpu.train.backend_executor import (
    BackendExecutor,
    ResizeError,
    TrainingFailedError,
)
from ray_tpu.train.checkpoint import Checkpoint, CheckpointManager
from ray_tpu.train.config import (
    CheckpointConfig,
    FailureConfig,
    ResizePolicy,
    Result,
    RunConfig,
    ScalingConfig,
)

logger = logging.getLogger("ray_tpu.train")


def _fault_metrics():
    """train_restarts_total / train_worker_deaths_total /
    train_recovery_seconds — created on first fit() (not import) so
    merely importing the trainer doesn't start the metrics flusher."""
    from ray_tpu.util import metrics as rt_metrics

    return (
        rt_metrics.get_or_create(
            rt_metrics.Counter, "train_restarts_total",
            "Gang restarts performed by fit() after retryable failures.",
        ),
        rt_metrics.get_or_create(
            rt_metrics.Counter, "train_worker_deaths_total",
            "Training worker ranks observed dead or unreachable.",
        ),
        rt_metrics.get_or_create(
            rt_metrics.Histogram, "train_recovery_seconds",
            "Seconds from gang teardown to the rebuilt gang being ready.",
        ),
    )


def _skew_metrics():
    """Flight-recorder driver-side metrics: cross-rank step skew and the
    current straggler rank (lazy, same reason as _fault_metrics)."""
    from ray_tpu.util import metrics as rt_metrics

    return (
        rt_metrics.get_or_create(
            rt_metrics.Histogram, "train_step_skew_seconds",
            "Cross-rank skew: slowest minus fastest rank's mean step "
            "wall time, per trainer poll.",
            boundaries=rt_metrics.LATENCY_BOUNDARIES,
        ),
        rt_metrics.get_or_create(
            rt_metrics.Gauge, "train_straggler_rank",
            "Rank with the highest mean step wall time right now.",
        ),
    )


class _ResizeGovernor:
    """Applies a ResizePolicy to resize decisions: floors the shrink at
    min_world_size, spaces resizes by resize_cooldown_s (thrash bound
    when reclamation pressure flaps), and drives grow-back toward the
    configured world size. The clock is injectable for deterministic
    tests."""

    def __init__(self, policy: ResizePolicy, baseline_world: int,
                 clock: Callable[[], float] = time.monotonic):
        self.policy = policy
        self.baseline = baseline_world
        self.clock = clock
        self._last_resize_t: Optional[float] = None

    def shrink_target(self, world: int, draining_count: int) -> Optional[int]:
        """World size to shrink to, or None when the policy forbids it
        (caller falls back to checkpoint-and-restart migration)."""
        target = world - draining_count
        if target < max(1, self.policy.min_world_size):
            return None
        if not self._cooled_down():
            return None
        return target

    def want_grow(self, world: int) -> bool:
        return (self.policy.grow_back and world < self.baseline
                and self._cooled_down())

    def note_resized(self):
        self._last_resize_t = self.clock()

    def _cooled_down(self) -> bool:
        if self._last_resize_t is None:
            return True
        return (self.clock() - self._last_resize_t
                >= self.policy.resize_cooldown_s)


def _mean_breakdown(records: List[Dict]) -> Dict[str, float]:
    """Average the per-phase seconds over a batch of step records."""
    out: Dict[str, float] = {}
    for rec in records:
        for k, v in rec.items():
            if (k.endswith("_s") and k != "tokens_per_s"
                    and isinstance(v, (int, float))):
                out[k] = out.get(k, 0.0) + v
    n = len(records)
    return {k: round(v / n, 6) for k, v in out.items()}


class BaseTrainer:
    def __init__(
        self,
        *,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        self.scaling_config = scaling_config or ScalingConfig()
        self.run_config = run_config or RunConfig()
        self.resume_from_checkpoint = resume_from_checkpoint

    def fit(self) -> Result:
        raise NotImplementedError

    def as_trainable(self):
        """Adapt this trainer into a Tune trainable (reference:
        base_trainer.py:839)."""
        trainer = self

        def trainable(config, session):
            import copy

            t = copy.copy(trainer)
            merged = dict(getattr(t, "train_loop_config", None) or {})
            merged.update(config)
            t.train_loop_config = merged
            result = t.fit()
            if result.error:
                raise result.error
            session.report(result.metrics, checkpoint=result.checkpoint)

        if hasattr(trainer, "_tune_resources"):
            # tune.with_resources pinned per-trial resources on the
            # trainer; carry them onto the closure the Tuner consumes.
            trainable._tune_resources = trainer._tune_resources
        return trainable


class DataParallelTrainer(BaseTrainer):
    """SPMD training: the same loop on every worker of the gang."""

    def __init__(
        self,
        train_loop_per_worker: Callable,
        *,
        train_loop_config: Optional[Dict] = None,
        backend_config: Optional[BackendConfig] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
        datasets: Optional[Dict[str, Any]] = None,
        dataset_config: Optional["DataConfig"] = None,
        resume_from_checkpoint: Optional[Checkpoint] = None,
    ):
        super().__init__(
            scaling_config=scaling_config,
            run_config=run_config,
            resume_from_checkpoint=resume_from_checkpoint,
        )
        self.train_loop_per_worker = train_loop_per_worker
        self.train_loop_config = train_loop_config or {}
        self.backend_config = backend_config or BackendConfig()
        self.datasets = datasets or {}
        from ray_tpu.train.data_config import DataConfig

        self.dataset_config = dataset_config or DataConfig()

    def fit(self) -> Result:
        """Run training with gang fault tolerance.

        ONE executor lives for the whole fit: a retryable failure
        (worker death, collective timeout, drain preemption) tears the
        gang down and rebuilds it via executor.restart() at the next
        gang epoch, resuming from the newest checkpoint with exponential
        backoff between attempts (reference: TrainingIterator retry +
        _restart backend_executor.py:701).
        """
        failure_config = self.run_config.failure_config
        attempts = max(1, failure_config.max_failures + 1)
        restarts, deaths, recovery = _fault_metrics()
        last_error: Optional[Exception] = None
        checkpoint = self.resume_from_checkpoint

        trial_dir = self.run_config.resolved_storage_path()
        os.makedirs(trial_dir, exist_ok=True)
        self._trial_dir = trial_dir
        ckpt_config = self.run_config.checkpoint_config
        # One manager across attempts: restarts must find (and keep
        # scoring against) the checkpoints earlier attempts registered.
        manager = CheckpointManager(
            os.path.join(trial_dir, "checkpoints"),
            num_to_keep=ckpt_config.num_to_keep,
            score_attribute=ckpt_config.checkpoint_score_attribute,
            score_order=ckpt_config.checkpoint_score_order,
            storage=self.run_config.storage_context(),
        )
        self._latest_checkpoint = None
        # Survive across attempts so an exhausted-retries Result still
        # carries everything that was reported before the last failure.
        self._final_metrics: Dict = {}
        self._metrics_history: List[Dict] = []
        # Flight recorder: latest cumulative step stats per rank (from
        # poll) and the skew/straggler view computed from them.
        self._rank_step_stats: List[Optional[Dict]] = []
        self._step_skew: Optional[Dict] = None

        executor = BackendExecutor(self.backend_config, self.scaling_config)
        # Backoff counter is decoupled from the retry budget: an attempt
        # that made progress (new reports or a fresh checkpoint) proves
        # the cluster recovered, so a later unrelated failure backs off
        # from backoff_s again instead of the doubled-up tail.
        backoff_attempt = 0
        try:
            executor.start()
            for attempt in range(attempts):
                hist_before = len(self._metrics_history)
                ckpt_before = self._latest_checkpoint
                try:
                    return self._run_attempt(
                        executor, manager, checkpoint, trial_dir
                    )
                except TrainingFailedError as e:
                    last_error = e
                    if e.failed_ranks:
                        deaths.inc(len(e.failed_ranks))
                    if (failure_config.fail_fast or not e.retryable
                            or attempt + 1 >= attempts):
                        break
                    if (len(self._metrics_history) > hist_before
                            or self._latest_checkpoint is not ckpt_before):
                        backoff_attempt = 0
                    # Resume from the newest checkpoint (reference:
                    # _restart backend_executor.py:701).
                    checkpoint = self._latest_checkpoint or checkpoint
                    backoff = failure_config.backoff_for_attempt(
                        backoff_attempt)
                    backoff_attempt += 1
                    if backoff:
                        time.sleep(backoff)
                    t0 = time.monotonic()
                    try:
                        executor.restart()
                    except TrainingFailedError as e2:
                        # Restart itself failed (e.g. the old placement
                        # group's release could not be confirmed —
                        # respawning would leak a gang of chips).
                        last_error = e2
                        break
                    restarts.inc()
                    recovery.observe(time.monotonic() - t0)
        finally:
            executor.shutdown()
        return Result(
            metrics=self._final_metrics,
            checkpoint=manager.best_checkpoint() or self._latest_checkpoint,
            error=last_error,
            path=self._trial_dir,
            metrics_history=self._metrics_history,
        )

    def _ingest(self, statuses, manager: CheckpointManager):
        """Fold polled worker reports into metrics/checkpoint state.
        Rank-0 reports carry the canonical metrics (reference:
        first-worker results in TrainingIterator); every rank's
        checkpoints are registered (the drain path checkpoints on
        whichever ranks got the stop request first)."""
        from ray_tpu.train import flight_recorder

        for rank, st in enumerate(statuses):
            for rep in st["reports"]:
                if rank == 0:
                    entry = dict(rep["metrics"])
                    recs = rep.get("step_records")
                    if recs:
                        # Per-phase step breakdown (mean over the steps
                        # this report covers) lands in metrics_history.
                        entry["train_step_breakdown"] = _mean_breakdown(recs)
                    self._final_metrics = entry
                    self._metrics_history.append(entry)
                if rep["checkpoint_path"]:
                    ckpt = Checkpoint.from_directory(rep["checkpoint_path"])
                    manager.register(ckpt, rep["metrics"])
                    self._latest_checkpoint = ckpt
        # Cross-rank straggler attribution from the per-rank cumulative
        # step stats each poll carries.
        stats = [st.get("step_stats") for st in statuses]
        if any(s for s in stats):
            self._rank_step_stats = stats
            skew = flight_recorder.compute_skew(stats)
            if skew is not None:
                self._step_skew = skew
                skew_hist, straggler_gauge = _skew_metrics()
                skew_hist.observe(skew["skew_s"])
                straggler_gauge.set(float(skew["straggler_rank"]))
        if self._metrics_history and self._step_skew is not None:
            # Enrich the newest history entry (same dict object as
            # _final_metrics) so Result names the straggler. Refreshed
            # every poll, not just on appends: a fast rank can drain all
            # its reports before the straggler completes a single step,
            # and the skew only becomes computable on a LATER poll.
            self._metrics_history[-1].update({
                "train_step_skew_s": round(self._step_skew["skew_s"], 6),
                "train_straggler_rank": self._step_skew["straggler_rank"],
                "train_step_wall_by_rank":
                    self._step_skew["mean_step_s_by_rank"],
                "train_straggler_breakdown":
                    self._step_skew["straggler_breakdown"],
            })

    def _run_attempt(
        self,
        executor: BackendExecutor,
        manager: CheckpointManager,
        checkpoint: Optional[Checkpoint],
        trial_dir: str,
    ) -> Result:
        dataset_shards = self._shard_datasets(self.scaling_config.num_workers)
        policy = self.scaling_config.elastic
        governor = (
            _ResizeGovernor(policy, self.scaling_config.num_workers)
            if policy is not None else None
        )
        try:
            executor.start_training(
                self.train_loop_per_worker,
                self.train_loop_config,
                checkpoint,
                trial_dir,
                dataset_shards,
            )
            while True:
                statuses = executor.poll()
                self._ingest(statuses, manager)
                for rank, st in enumerate(statuses):
                    if st["error"]:
                        raise TrainingFailedError(
                            st["error"], failed_ranks=[rank], retryable=True
                        )
                if all(st["done"] for st in statuses):
                    self._ingest(executor.poll(), manager)  # final drain
                    break
                draining = executor.draining_ranks()
                draining &= set(range(executor.world_size))
                if draining:
                    # Elastic-first: shed exactly the claimed ranks and
                    # keep training; checkpoint-and-restart only when
                    # the policy forbids the shrink or the gang's loop
                    # turns out not to be elastic-aware.
                    target = (
                        governor.shrink_target(executor.world_size,
                                               len(draining))
                        if governor is not None else None
                    )
                    new_shards = (
                        self._elastic_resize(executor, target,
                                             sorted(draining))
                        if target is not None else None
                    )
                    if new_shards is not None:
                        governor.note_resized()
                        self._stop_shards(dataset_shards)
                        dataset_shards = (new_shards
                                          if new_shards != [] else None)
                    else:
                        self._migrate_before_preemption(
                            executor, manager, draining
                        )
                elif (governor is not None
                      and governor.want_grow(executor.world_size)
                      and executor.fence_lifted()):
                    # The partial-reclamation claimant released the
                    # chips: grow back without a restart.
                    new_shards = self._elastic_resize(
                        executor, governor.baseline)
                    if new_shards is not None:
                        governor.note_resized()
                        self._stop_shards(dataset_shards)
                        dataset_shards = (new_shards
                                          if new_shards != [] else None)
                time.sleep(0.05)
        finally:
            self._stop_shards(dataset_shards)
        return Result(
            metrics=self._final_metrics,
            checkpoint=manager.best_checkpoint() or self._latest_checkpoint,
            error=None,
            path=trial_dir,
            metrics_history=self._metrics_history,
        )

    def _elastic_resize(self, executor, target: int,
                        departing: Optional[List[int]] = None):
        """Resize the gang in place, rebalancing data shards at the
        boundary. Returns the new shard list on success ([] when the run
        has no datasets), or None when the resize could not complete —
        the gang is unchanged and the caller falls back to the
        checkpoint-and-restart path."""
        new_shards = self._shard_datasets(target)
        try:
            executor.resize(target, departing_ranks=departing,
                            dataset_shards=new_shards)
        except ResizeError as e:
            logger.warning(
                "elastic resize to %d worker(s) failed (%s); falling "
                "back to checkpoint-and-restart", target, e,
            )
            self._stop_shards(new_shards)
            return None
        return new_shards if new_shards is not None else []

    def _migrate_before_preemption(self, executor, manager, draining):
        """A node hosting part of the gang is draining: ask every rank to
        checkpoint and stop NOW, harvest what they save within the grace
        window, then fail the attempt as preempted+retryable so the gang
        restarts elsewhere — ahead of the kill instead of after it."""
        from ray_tpu._private.config import get_config

        executor.request_stop_all()
        deadline = time.monotonic() + get_config().train_drain_grace_s
        while time.monotonic() < deadline:
            try:
                statuses = executor.poll()
            except TrainingFailedError:
                break  # preemption beat the grace window
            self._ingest(statuses, manager)
            if all(st["done"] for st in statuses):
                break
            time.sleep(0.05)
        raise TrainingFailedError(
            f"node drain: rank(s) {sorted(draining)} are on draining "
            f"node(s); gang migrating",
            failed_ranks=draining,
            retryable=True,
            preempted=True,
        )

    @staticmethod
    def _stop_shards(dataset_shards):
        """Kill streaming_split coordinator actors once training ends —
        they hold the dataset's input block refs and nothing else ever
        reclaims them (one coordinator per split dataset per fit)."""
        seen = set()
        for entry in dataset_shards or []:
            shards = entry.values() if isinstance(entry, dict) else [entry]
            for shard in shards:
                coord = getattr(shard, "_coord", None)
                stop = getattr(shard, "stop", None)
                if coord is not None and callable(stop):
                    key = getattr(coord, "_actor_id", id(coord))
                    if key in seen:
                        continue
                    seen.add(key)
                    stop()

    def _shard_datasets(self, num_workers: int):
        """Per-worker {name: shard} dicts via DataConfig: split datasets
        become coordinated streaming_split DataIterators (one shared
        streaming execution per epoch), others broadcast (reference:
        train/_internal/data_config.py DataConfig.configure)."""
        if not self.datasets:
            return None
        return self.dataset_config.configure(self.datasets, num_workers)


class JaxTrainer(DataParallelTrainer):
    """Distributed JAX training on TPU gangs (replaces TorchTrainer).

    The worker group is one process per TPU host; JaxConfig wires
    jax.distributed + mesh construction; inside the loop users build
    pjit-compiled steps whose collectives ride ICI. On CPU test gangs the
    eager DCN group provides gradient sync.
    """

    def __init__(self, train_loop_per_worker, *, jax_config: Optional[JaxConfig] = None,
                 **kwargs):
        super().__init__(
            train_loop_per_worker,
            backend_config=jax_config or JaxConfig(),
            **kwargs,
        )
