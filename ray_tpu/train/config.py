"""Shared training configuration objects.

Analogs of the reference's ray.air config surface (python/ray/air/config.py):
ScalingConfig (:101), FailureConfig (:377), CheckpointConfig (:427),
RunConfig (:576) — reshaped for TPU: ScalingConfig speaks in TPU hosts and
chips and carries the mesh factorization.
"""

from __future__ import annotations

import os
from dataclasses import dataclass, field
from typing import Any, Dict, Optional


@dataclass
class ResizePolicy:
    """Bounds on elastic gang resizing (no reference analog — the
    reference restarts; ray_tpu resizes).

    min_world_size: never shrink below this many workers; a reclamation
      that would need more chips falls back to checkpoint-and-restart
      (full eviction).
    resize_cooldown_s: minimum wall seconds between resizes, bounding
      thrash when reclamation pressure flaps.
    grow_back: poll the GCS fence-lift signal after a shrink and grow
      back to the original world size once the claimant releases.
    """

    min_world_size: int = 1
    resize_cooldown_s: float = 0.0
    grow_back: bool = True


@dataclass
class ScalingConfig:
    """How to scale training (reference: air/config.py:101).

    num_workers: worker processes (on TPU pods: one per host).
    use_tpu / tpus_per_worker: chips each worker owns (whole-host = all).
    mesh: optional parallel.MeshConfig describing the global mesh the
      workers jointly build (dp/fsdp/tp/sp/pp/ep factorization).
    elastic: opt into resize-instead-of-restart under partial
      reclamation (requires an elastic-aware loop calling
      train.sync_resize at step boundaries).
    """

    num_workers: int = 1
    use_tpu: bool = False
    tpus_per_worker: Optional[float] = None
    resources_per_worker: Optional[Dict[str, float]] = None
    placement_strategy: str = "PACK"
    mesh: Optional[Any] = None  # parallel.MeshConfig
    # Preemption tier of the gang's placement group: lower-priority gangs
    # are the first evicted when higher-priority demand cannot place.
    priority: int = 0
    elastic: Optional[ResizePolicy] = None

    def worker_resources(self) -> Dict[str, float]:
        res = dict(self.resources_per_worker or {})
        if self.use_tpu:
            res.setdefault("TPU", self.tpus_per_worker or 4.0)
            res.setdefault("CPU", 1.0)
        else:
            res.setdefault("CPU", 1.0)
        return res


@dataclass
class FailureConfig:
    """Trial-level failure handling (reference: air/config.py:377).

    max_failures: gang restarts allowed before fit() gives up.
    fail_fast: never retry, surface the first failure.
    backoff_s / backoff_max_s: exponential backoff between restart
      attempts (attempt k sleeps min(backoff_s * 2**k, backoff_max_s)) —
      a crash-looping gang must not hammer the scheduler. The first
      restart after a clean failure is immediate when backoff_s == 0.
      fit() counts consecutive *no-progress* failures: an attempt that
      reported metrics or registered a checkpoint resets the doubling,
      so a later unrelated failure starts from backoff_s again.
    """

    max_failures: int = 0
    fail_fast: bool = False
    backoff_s: float = 1.0
    backoff_max_s: float = 30.0

    def backoff_for_attempt(self, attempt: int) -> float:
        """Seconds to wait before restart attempt `attempt` (0-based)."""
        if self.backoff_s <= 0:
            return 0.0
        return min(self.backoff_s * (2 ** attempt), self.backoff_max_s)


@dataclass
class CheckpointConfig:
    """Checkpoint retention (reference: air/config.py:427)."""

    num_to_keep: Optional[int] = None
    checkpoint_score_attribute: Optional[str] = None
    checkpoint_score_order: str = "max"
    checkpoint_frequency: int = 0


@dataclass
class RunConfig:
    """Run-level config (reference: air/config.py:576)."""

    name: Optional[str] = None
    storage_path: Optional[str] = None
    failure_config: FailureConfig = field(default_factory=FailureConfig)
    checkpoint_config: CheckpointConfig = field(default_factory=CheckpointConfig)
    verbose: int = 1

    def resolved_storage_path(self) -> str:
        """Local working directory for the run. A URI storage_path
        (file://, s3://, ...) persists through StorageContext instead;
        local scratch still lives under ~/ray_tpu_results."""
        base = self.storage_path or os.path.expanduser("~/ray_tpu_results")
        if "://" in base:
            base = os.path.expanduser("~/ray_tpu_results")
        name = self.name or "run"
        return os.path.join(base, name)

    def storage_context(self):
        """StorageContext for a URI storage_path, else None (reference:
        StorageContext resolution in train/_internal/storage.py:348)."""
        if self.storage_path and "://" in self.storage_path:
            from ray_tpu.train.storage import StorageContext

            return StorageContext(self.storage_path, self.name or "run")
        return None


@dataclass
class Result:
    """Outcome of a training run (reference: ray.air.Result)."""

    metrics: Dict[str, Any]
    checkpoint: Optional["Checkpoint"]  # noqa: F821
    error: Optional[Exception]
    path: Optional[str] = None
    metrics_history: list = field(default_factory=list)
