"""BackendExecutor: orchestrates the worker group through one training run.

Analog of the reference's BackendExecutor
(train/_internal/backend_executor.py:65): start() creates the WorkerGroup
in a placement group and runs backend.on_start; start_training launches
the user loop on every worker; poll() gathers reports and converts actor
deaths into a classified TrainingFailedError; restart() tears the whole
gang down and rebuilds it at the next gang epoch (:701 _restart).

Fault model: a TPU gang fails as a unit. Any rank dying (preemption, OOM,
segfault) or wedging (network partition mid-collective) invalidates the
collective state of every survivor, so recovery is always
kill-everything → rebuild → resume-from-checkpoint. The gang `epoch` is
threaded into DCN rendezvous keys so a zombie rank from attempt N can
never join the ring built by attempt N+1.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Set

import ray_tpu as rt
from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorError,
    ActorUnavailableError,
    GetTimeoutError,
    PlacementGroupSchedulingError,
    WorkerCrashedError,
)
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger("ray_tpu.train")

# Exceptions on a worker call that mean "this rank's process is gone (or
# unreachable for longer than we are willing to wait)" — the gang must be
# torn down and rebuilt.
_GANG_FATAL = (
    ActorDiedError,
    ActorUnavailableError,
    WorkerCrashedError,
    GetTimeoutError,
)


class TrainingFailedError(RuntimeError):
    """A training attempt failed (reference: TrainingFailedError in
    train/base_trainer.py).

    failed_ranks: world ranks whose workers died/wedged (empty when the
      failure wasn't attributable to specific ranks, e.g. user-code error
      surfaced through the report channel).
    retryable: whether a gang restart can plausibly recover (actor death,
      preemption, collective timeout → True; infeasible placement → False).
    preempted: the failure was a proactive drain, not a crash — workers
      were asked to checkpoint before the gang went down.
    """

    def __init__(self, message: str, *, failed_ranks=None,
                 retryable: bool = True, preempted: bool = False,
                 cause: Optional[BaseException] = None):
        self.failed_ranks: List[int] = sorted(failed_ranks or [])
        self.retryable = retryable
        self.preempted = preempted
        self.cause = cause
        super().__init__(message)


def _classify(rank: int, exc: Exception) -> str:
    return f"rank {rank}: {type(exc).__name__}: {exc}"


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
    ):
        self.backend_config = backend_config
        self.scaling_config = scaling_config
        self.backend = backend_config.backend_cls()()
        self.worker_group: Optional[WorkerGroup] = None
        # Gang attempt number; bumped by restart() and threaded into the
        # DCN rendezvous so stale ranks can't join the new ring.
        self.epoch = 0
        self._last_drain_check = 0.0

    # -- lifecycle -------------------------------------------------------
    def start(self):
        try:
            self.worker_group = WorkerGroup(
                self.scaling_config.num_workers,
                self.scaling_config.worker_resources(),
                self.scaling_config.placement_strategy,
                epoch=self.epoch,
                priority=getattr(self.scaling_config, "priority", 0),
                name="train",
            )
        except PlacementGroupSchedulingError as e:
            # Infeasible bundles won't become feasible by retrying the
            # same request against the same cluster.
            raise TrainingFailedError(
                f"worker group placement failed: {e}",
                retryable=False, cause=e,
            ) from e
        self.backend.on_start(self.worker_group, self.backend_config)

    def restart(self):
        """Tear the whole gang down and rebuild it one epoch later
        (reference: _restart backend_executor.py:701). Survivor actors
        are killed — after one rank dies the others' collective state is
        garbage — and the placement group is released so a drained node's
        resources aren't re-reserved."""
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
            except Exception:  # noqa: BLE001 — gang is dead; teardown is best-effort
                logger.warning(
                    "backend on_shutdown failed during gang restart "
                    "(epoch %d); proceeding with kill-and-rebuild",
                    self.epoch, exc_info=True,
                )
            self.worker_group.shutdown()
            self.worker_group = None
        self.epoch += 1
        self.start()

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None

    # -- training --------------------------------------------------------
    def start_training(
        self,
        train_fn: Callable,
        config: Dict,
        checkpoint: Optional[Checkpoint],
        trial_dir: str,
        dataset_shards: Optional[List[Any]] = None,
    ):
        self.backend.on_training_start(self.worker_group, self.backend_config)
        refs = []
        for i, w in enumerate(self.worker_group.workers):
            shard = dataset_shards[i] if dataset_shards else None
            refs.append(
                w.start_training.remote(train_fn, config, checkpoint, trial_dir,
                                        shard)
            )
        self._get_per_rank(refs, get_config().train_start_timeout_s,
                           what="start_training")

    def poll(self) -> List[Dict]:
        """One poll of every worker: list of per-rank status dicts.

        Dead/unreachable ranks raise TrainingFailedError carrying every
        failed rank, not just the first — the trainer logs them all and
        the metrics count them all. The timeout is train_poll_timeout_s
        (dead actors surface immediately on the call; the timeout only
        bounds hung-but-alive workers), NOT an unbounded get.
        """
        delay = chaos.take_poll_delay()
        if delay:
            time.sleep(delay)
        refs = [w.poll.remote() for w in self.worker_group.workers]
        return self._get_per_rank(refs, get_config().train_poll_timeout_s,
                                  what="poll")

    def _get_per_rank(self, refs, timeout: float, what: str) -> List:
        results: List = [None] * len(refs)
        failures: Dict[int, Exception] = {}
        deadline = time.monotonic() + timeout
        for i, ref in enumerate(refs):
            remaining = max(0.1, deadline - time.monotonic())
            try:
                results[i] = rt.get(ref, timeout=remaining)
            except _GANG_FATAL as e:
                failures[i] = e
        if failures:
            detail = "; ".join(
                _classify(r, e) for r, e in sorted(failures.items())
            )
            raise TrainingFailedError(
                f"{len(failures)}/{len(refs)} worker(s) failed during "
                f"{what} (gang epoch {self.epoch}): {detail}",
                failed_ranks=failures.keys(),
                retryable=True,
                cause=next(iter(failures.values())),
            )
        return results

    # -- liveness / drain ------------------------------------------------
    def ping(self, timeout: Optional[float] = None) -> Set[int]:
        """Low-cost liveness probe: returns the set of unresponsive
        ranks. Unlike poll() this never raises — it's the cheap periodic
        check that bounds detection latency for wedged workers."""
        if self.worker_group is None:
            return set()
        timeout = timeout or get_config().train_probe_timeout_s
        refs = [w.ping.remote() for w in self.worker_group.workers]
        bad: Set[int] = set()
        for i, ref in enumerate(refs):
            try:
                rt.get(ref, timeout=timeout)
            except (ActorError, WorkerCrashedError, GetTimeoutError):
                bad.add(i)
        return bad

    def draining_ranks(self) -> Set[int]:
        """Ranks whose nodes are draining (cordoned ahead of preemption).

        Merges chaos-injected drains (deterministic tests) with the GCS
        node table's `draining` flag, mapped to ranks through the
        placement group's bundle→node assignment. The GCS lookup is
        throttled to train_drain_poll_interval_s; injected drains are
        process-local and always checked.
        """
        ranks = set(chaos.take_injected_drain_ranks())
        cfg = get_config()
        now = time.monotonic()
        if now - self._last_drain_check >= cfg.train_drain_poll_interval_s:
            self._last_drain_check = now
            try:
                ranks |= self._gcs_draining_ranks()
            except Exception:  # noqa: BLE001
                # Control-plane hiccup must not fail training; the next
                # poll retries.
                logger.warning("GCS drain poll failed; retrying in %.1fs",
                               cfg.train_drain_poll_interval_s,
                               exc_info=True)
        return ranks

    def _gcs_draining_ranks(self) -> Set[int]:
        if self.worker_group is None:
            return set()
        draining_nodes = {
            n["node_id"]
            for n in rt.nodes()
            if n.get("draining") and n["state"] == "ALIVE"
        }
        if not draining_nodes:
            return set()
        return {
            i
            for i, nid in enumerate(self.worker_group.node_ids())
            if nid in draining_nodes
        }

    def request_stop_all(self):
        """Ask every rank to checkpoint and return at the next
        should_stop() check (proactive migration). Best-effort: a rank
        already dead just stays dead."""
        if self.worker_group is None:
            return
        refs = [w.request_stop.remote() for w in self.worker_group.workers]
        for rank, ref in enumerate(refs):
            try:
                rt.get(ref, timeout=get_config().train_probe_timeout_s)
            except _GANG_FATAL:
                # A rank that is already dead (or unreachable) cannot
                # checkpoint; the coming restart handles it.
                logger.warning(
                    "rank %d unreachable during stop-all request; it "
                    "will be replaced at the next gang epoch", rank,
                )
