"""BackendExecutor: orchestrates the worker group through one training run.

Analog of the reference's BackendExecutor
(train/_internal/backend_executor.py:65): start() creates the WorkerGroup
in a placement group and runs backend.on_start; start_training launches
the user loop on every worker; get_next_results gathers reports; restarts
recreate the group from the latest checkpoint (:701 _restart).
"""

from __future__ import annotations

import time
from typing import Any, Callable, Dict, List, Optional

from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup


class TrainingFailedError(RuntimeError):
    pass


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
    ):
        self.backend_config = backend_config
        self.scaling_config = scaling_config
        self.backend = backend_config.backend_cls()()
        self.worker_group: Optional[WorkerGroup] = None

    def start(self):
        self.worker_group = WorkerGroup(
            self.scaling_config.num_workers,
            self.scaling_config.worker_resources(),
            self.scaling_config.placement_strategy,
        )
        self.backend.on_start(self.worker_group, self.backend_config)

    def start_training(
        self,
        train_fn: Callable,
        config: Dict,
        checkpoint: Optional[Checkpoint],
        trial_dir: str,
        dataset_shards: Optional[List[Any]] = None,
    ):
        self.backend.on_training_start(self.worker_group, self.backend_config)
        refs = []
        import ray_tpu as rt

        for i, w in enumerate(self.worker_group.workers):
            shard = dataset_shards[i] if dataset_shards else None
            refs.append(
                w.start_training.remote(train_fn, config, checkpoint, trial_dir,
                                        shard)
            )
        rt.get(refs, timeout=600)

    def poll(self) -> List[Dict]:
        """One poll of every worker: list of per-rank status dicts."""
        import ray_tpu as rt

        return rt.get(
            [w.poll.remote() for w in self.worker_group.workers], timeout=600
        )

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None
