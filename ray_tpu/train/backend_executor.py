"""BackendExecutor: orchestrates the worker group through one training run.

Analog of the reference's BackendExecutor
(train/_internal/backend_executor.py:65): start() creates the WorkerGroup
in a placement group and runs backend.on_start; start_training launches
the user loop on every worker; poll() gathers reports and converts actor
deaths into a classified TrainingFailedError; restart() tears the whole
gang down and rebuilds it at the next gang epoch (:701 _restart).

Fault model: a TPU gang fails as a unit. Any rank dying (preemption, OOM,
segfault) or wedging (network partition mid-collective) invalidates the
collective state of every survivor, so recovery is always
kill-everything → rebuild → resume-from-checkpoint. The gang `epoch` is
threaded into DCN rendezvous keys so a zombie rank from attempt N can
never join the ring built by attempt N+1.
"""

from __future__ import annotations

import logging
import time
from typing import Any, Callable, Dict, List, Optional, Set

import ray_tpu as rt
from ray_tpu._private import chaos
from ray_tpu._private.config import get_config
from ray_tpu.util import journal
from ray_tpu.exceptions import (
    ActorDiedError,
    ActorError,
    ActorUnavailableError,
    GetTimeoutError,
    PlacementGroupSchedulingError,
    WorkerCrashedError,
)
from ray_tpu.train.backend import BackendConfig
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import ScalingConfig
from ray_tpu.train.worker_group import WorkerGroup

logger = logging.getLogger("ray_tpu.train")

# Exceptions on a worker call that mean "this rank's process is gone (or
# unreachable for longer than we are willing to wait)" — the gang must be
# torn down and rebuilt.
_GANG_FATAL = (
    ActorDiedError,
    ActorUnavailableError,
    WorkerCrashedError,
    GetTimeoutError,
)


class TrainingFailedError(RuntimeError):
    """A training attempt failed (reference: TrainingFailedError in
    train/base_trainer.py).

    failed_ranks: world ranks whose workers died/wedged (empty when the
      failure wasn't attributable to specific ranks, e.g. user-code error
      surfaced through the report channel).
    retryable: whether a gang restart can plausibly recover (actor death,
      preemption, collective timeout → True; infeasible placement → False).
    preempted: the failure was a proactive drain, not a crash — workers
      were asked to checkpoint before the gang went down.
    """

    def __init__(self, message: str, *, failed_ranks=None,
                 retryable: bool = True, preempted: bool = False,
                 cause: Optional[BaseException] = None):
        self.failed_ranks: List[int] = sorted(failed_ranks or [])
        self.retryable = retryable
        self.preempted = preempted
        self.cause = cause
        super().__init__(message)


class ResizeError(RuntimeError):
    """An elastic resize could not complete (loop not elastic-aware,
    worker died mid-handoff, bundles still fenced). The gang is left
    running at its old size; the caller falls back to the
    checkpoint-and-restart path."""


def _classify(rank: int, exc: Exception) -> str:
    return f"rank {rank}: {type(exc).__name__}: {exc}"


def _resize_metrics():
    """train_resize_total{direction} / train_gang_size /
    train_resize_seconds — lazy for the same reason as the trainer's
    fault metrics (importing must not start the flusher)."""
    from ray_tpu.util import metrics as rt_metrics

    return (
        rt_metrics.get_or_create(
            rt_metrics.Counter, "train_resize_total",
            "Elastic gang resizes completed, by direction (shrink/grow).",
            tag_keys=("direction",),
        ),
        rt_metrics.get_or_create(
            rt_metrics.Gauge, "train_gang_size",
            "Current world size of the training gang.",
        ),
        rt_metrics.get_or_create(
            rt_metrics.Histogram, "train_resize_seconds",
            "Wall seconds from resize start to the gang running at the "
            "new world size.",
            boundaries=rt_metrics.LATENCY_BOUNDARIES_WIDE,
        ),
    )


class BackendExecutor:
    def __init__(
        self,
        backend_config: BackendConfig,
        scaling_config: ScalingConfig,
    ):
        self.backend_config = backend_config
        self.scaling_config = scaling_config
        self.backend = backend_config.backend_cls()()
        self.worker_group: Optional[WorkerGroup] = None
        # Gang attempt number; bumped by restart() and threaded into the
        # DCN rendezvous so stale ranks can't join the new ring.
        self.epoch = 0
        self._last_drain_check = 0.0
        # (train_fn, config, checkpoint, trial_dir) from start_training —
        # replayed for joiner workers on elastic grow.
        self._train_args = None
        self._last_fence_check = 0.0
        self._fence_lifted_cache = False

    # -- lifecycle -------------------------------------------------------
    def start(self):
        try:
            self.worker_group = WorkerGroup(
                self.scaling_config.num_workers,
                self.scaling_config.worker_resources(),
                self.scaling_config.placement_strategy,
                epoch=self.epoch,
                priority=getattr(self.scaling_config, "priority", 0),
                name="train",
            )
        except PlacementGroupSchedulingError as e:
            # Infeasible bundles won't become feasible by retrying the
            # same request against the same cluster.
            raise TrainingFailedError(
                f"worker group placement failed: {e}",
                retryable=False, cause=e,
            ) from e
        self.backend.on_start(self.worker_group, self.backend_config)

    def restart(self):
        """Tear the whole gang down and rebuild it one epoch later
        (reference: _restart backend_executor.py:701). Survivor actors
        are killed — after one rank dies the others' collective state is
        garbage — and the placement group release is VERIFIED before the
        respawn: a silently surviving group keeps a gang's worth of
        chips reserved on every repeated restart."""
        if self.worker_group is not None:
            try:
                self.backend.on_shutdown(self.worker_group, self.backend_config)
            except Exception:  # noqa: BLE001 — gang is dead; teardown is best-effort
                logger.warning(
                    "backend on_shutdown failed during gang restart "
                    "(epoch %d); proceeding with kill-and-rebuild",
                    self.epoch, exc_info=True,
                )
            try:
                self.worker_group.shutdown(verify=True)
            except PlacementGroupSchedulingError as e:
                self.worker_group = None
                raise TrainingFailedError(
                    f"gang restart blocked: {e}", retryable=True, cause=e
                ) from e
            self.worker_group = None
        self.epoch += 1
        journal.emit("train.gang_restart", epoch=self.epoch)
        journal.trigger_postmortem("gang_restart", epoch=self.epoch)
        self.start()

    def shutdown(self):
        if self.worker_group is not None:
            self.backend.on_shutdown(self.worker_group, self.backend_config)
            self.worker_group.shutdown()
            self.worker_group = None

    # -- training --------------------------------------------------------
    def start_training(
        self,
        train_fn: Callable,
        config: Dict,
        checkpoint: Optional[Checkpoint],
        trial_dir: str,
        dataset_shards: Optional[List[Any]] = None,
    ):
        self.backend.on_training_start(self.worker_group, self.backend_config)
        # Remembered for elastic grow: joiner workers run the same loop
        # (they adopt live state through their pre-armed resize ticket).
        self._train_args = (train_fn, config, checkpoint, trial_dir)
        refs = []
        for i, w in enumerate(self.worker_group.workers):
            shard = dataset_shards[i] if dataset_shards else None
            refs.append(
                w.start_training.remote(train_fn, config, checkpoint, trial_dir,
                                        shard)
            )
        self._get_per_rank(refs, get_config().train_start_timeout_s,
                           what="start_training")

    # -- elastic resize --------------------------------------------------
    @property
    def world_size(self) -> int:
        return self.worker_group.num_workers if self.worker_group else 0

    def resize(self, target_world_size: int,
               departing_ranks: Optional[List[int]] = None,
               dataset_shards: Optional[List[Any]] = None):
        """Resize the live gang instead of restarting it.

        Shrink: departing ranks publish their state slices, checkpoint
        their shards, and exit through the drain plane; their bundles
        are released back to the GCS (completing a partial-reclamation
        drain); survivors renumber, rebuild DCN collectives at the new
        size under a bumped gang epoch (the epoch fence keeps the
        departed ranks out of the new rendezvous), and re-shard state
        through the object store via the deterministic ShardRemapPlan.

        Grow: previously released bundles are re-reserved (fails while
        the claimant's fence holds — raise ResizeError, retry later),
        joiners spawn into them with pre-armed resize tickets, and every
        rank re-shards to the new world size.

        Raises ResizeError with the gang still running at the OLD size;
        the caller falls back to checkpoint-and-restart.
        """
        wg = self.worker_group
        if wg is None:
            raise ResizeError("no worker group to resize")
        old_n, new_n = wg.num_workers, int(target_world_size)
        if new_n < 1:
            raise ResizeError(f"cannot resize to world size {new_n}")
        if new_n == old_n:
            return
        t0 = time.monotonic()
        direction = "shrink" if new_n < old_n else "grow"
        old_epoch = self.epoch
        self.epoch += 1
        wg.epoch = self.epoch
        try:
            if new_n < old_n:
                self._resize_shrink(new_n, departing_ranks, dataset_shards)
            else:
                self._resize_grow(new_n, dataset_shards)
        except ResizeError:
            self.epoch = old_epoch
            wg.epoch = old_epoch
            self._abort_resize_all()
            raise
        except Exception as e:  # noqa: BLE001 — normalize for the caller
            self.epoch = old_epoch
            wg.epoch = old_epoch
            self._abort_resize_all()
            raise ResizeError(f"resize {old_n}→{new_n} failed: {e}") from e
        total, gang_gauge, seconds = _resize_metrics()
        total.inc(1.0, tags={"direction": direction})
        gang_gauge.set(float(new_n))
        seconds.observe(time.monotonic() - t0)
        journal.emit("train.resize", direction=direction,
                     old_world=old_n, new_world=new_n, epoch=self.epoch,
                     seconds=round(time.monotonic() - t0, 3))
        logger.info("gang resized %d→%d (%s) in %.3fs, epoch %d",
                    old_n, new_n, direction, time.monotonic() - t0,
                    self.epoch)

    def _resize_shrink(self, new_n: int,
                       departing_ranks: Optional[List[int]],
                       dataset_shards: Optional[List[Any]]):
        wg = self.worker_group
        old_n = wg.num_workers
        cfg = get_config()
        timeout = cfg.train_resize_timeout_s
        departing = sorted(set(departing_ranks or []))[: old_n - new_n]
        if len(departing) < old_n - new_n:
            # Default victims: highest ranks first (they hold the
            # trailing data shards — the cheapest to rebalance).
            pool = [r for r in range(old_n - 1, -1, -1)
                    if r not in departing]
            departing += pool[: old_n - new_n - len(departing)]
            departing = sorted(departing)
        spec = {"old_world": old_n, "new_world": new_n,
                "departing": departing, "timeout_s": timeout,
                "epoch": self.epoch}
        self._arm_resize(wg.workers, spec)
        outboxes = self._collect_outboxes(
            {r: wg.workers[r] for r in range(old_n)}, timeout)
        survivors_old = [r for r in range(old_n) if r not in set(departing)]
        payload_shards = self._merge_shard_refs(outboxes)
        state_ref = outboxes[survivors_old[0]]["state_ref"]
        # Departing loops have published; reap them and hand their
        # bundles back (this is the moment a partial reclamation's
        # claimant has been waiting for).
        rank_map = wg.shrink(departing)
        for old_rank in survivors_old:
            w = wg.workers[rank_map[old_rank]]
            rt.get(w.set_rank.remote(rank_map[old_rank], new_n),
                   timeout=cfg.train_probe_timeout_s)
        # DCN groups die and rebuild at the new size — the topology
        # model re-selects ring/rd/hier per op for the new world.
        self.backend.on_resize(wg, self.backend_config)
        base = {"old_world": old_n, "new_world": new_n,
                "rank_map": rank_map, "shards": payload_shards,
                "state_ref": state_ref}
        self._deliver_resize(wg, base, dataset_shards, timeout)

    def _resize_grow(self, new_n: int,
                     dataset_shards: Optional[List[Any]]):
        wg = self.worker_group
        old_n = wg.num_workers
        cfg = get_config()
        timeout = cfg.train_resize_timeout_s
        spec = {"old_world": old_n, "new_world": new_n, "departing": [],
                "timeout_s": timeout, "epoch": self.epoch}
        # Re-reserve freed bundles FIRST: while the claimant's fence
        # holds this fails cleanly and nothing was disturbed.
        try:
            wg.grow(new_n)
        except PlacementGroupSchedulingError as e:
            raise ResizeError(f"grow blocked: {e}") from e
        self._arm_resize(wg.workers[:old_n], spec)
        for r in range(old_n):
            rt.get(wg.workers[r].set_rank.remote(r, new_n),
                   timeout=cfg.train_probe_timeout_s)
        # Joiners run the same loop with a pre-armed ticket: their first
        # sync_resize adopts the live replicated state and builds their
        # slice of the sharded state from the survivors' refs.
        if self._train_args is None:
            raise ResizeError("cannot grow before start_training")
        train_fn, config, checkpoint, trial_dir = self._train_args
        join_spec = dict(spec, joining=True)
        start_refs = []
        for rank in range(old_n, new_n):
            shard = dataset_shards[rank] if dataset_shards else None
            start_refs.append(wg.workers[rank].start_training.remote(
                train_fn, config, checkpoint, trial_dir, shard,
                resize_join=join_spec,
            ))
        self._get_per_rank(start_refs, cfg.train_start_timeout_s,
                           what="resize_grow start_training")
        outboxes = self._collect_outboxes(
            {r: wg.workers[r] for r in range(old_n)}, timeout)
        payload_shards = self._merge_shard_refs(outboxes)
        state_ref = outboxes[0]["state_ref"]
        self.backend.on_resize(wg, self.backend_config)
        base = {"old_world": old_n, "new_world": new_n,
                "rank_map": {r: r for r in range(old_n)},
                "shards": payload_shards, "state_ref": state_ref}
        self._deliver_resize(wg, base, dataset_shards, timeout)

    def _arm_resize(self, workers, spec):
        refs = [w.begin_resize.remote(spec) for w in workers]
        self._get_per_rank(refs, get_config().train_probe_timeout_s,
                           what="begin_resize")

    def _collect_outboxes(self, workers: Dict[int, Any],
                          timeout: float) -> Dict[int, Dict]:
        """Wait until every listed rank's loop has hit the resize
        barrier and published its shard refs. A loop that finishes (or
        errors, or dies) without reaching sync_resize aborts the resize."""
        deadline = time.monotonic() + timeout
        out: Dict[int, Dict] = {}
        probe = get_config().train_probe_timeout_s
        while True:
            missing = [r for r in workers if r not in out]
            if not missing:
                return out
            if time.monotonic() >= deadline:
                raise ResizeError(
                    f"rank(s) {missing} did not reach the resize barrier "
                    f"within {timeout:.0f}s (loop not elastic-aware?)"
                )
            for r in missing:
                try:
                    st = rt.get(workers[r].poll_resize.remote(),
                                timeout=probe)
                except _GANG_FATAL as e:
                    raise ResizeError(
                        f"rank {r} died mid-resize: {e}") from e
                if st.get("outbox") is not None:
                    out[r] = st["outbox"]
                elif st.get("loop_done"):
                    raise ResizeError(
                        f"rank {r}'s loop finished before the resize "
                        f"barrier")
            time.sleep(0.05)

    @staticmethod
    def _merge_shard_refs(outboxes: Dict[int, Dict]) -> Dict[str, Dict]:
        merged: Dict[str, Dict] = {}
        for rank, ob in outboxes.items():
            for name, ref in (ob.get("shards") or {}).items():
                merged.setdefault(name, {})[rank] = ref
        return merged

    def _deliver_resize(self, wg, base: Dict,
                        dataset_shards: Optional[List[Any]],
                        timeout: float):
        refs = []
        for rank, w in enumerate(wg.workers):
            payload = dict(base)
            if dataset_shards is not None:
                payload["dataset_shards"] = dataset_shards[rank]
            refs.append(w.complete_resize.remote(payload))
        self._get_per_rank(refs, get_config().train_probe_timeout_s,
                           what="complete_resize")
        # Confirm application: the gang must be consistent at the new
        # size before the executor reports the resize done.
        deadline = time.monotonic() + timeout
        pending = set(range(len(wg.workers)))
        while pending and time.monotonic() < deadline:
            for r in list(pending):
                st = rt.get(wg.workers[r].poll_resize.remote(),
                            timeout=get_config().train_probe_timeout_s)
                if st.get("applied") or st.get("loop_done"):
                    pending.discard(r)
            if pending:
                time.sleep(0.05)
        if pending:
            raise ResizeError(
                f"rank(s) {sorted(pending)} did not apply the resize "
                f"within {timeout:.0f}s")

    def _abort_resize_all(self):
        if self.worker_group is None:
            return
        for rank, w in enumerate(self.worker_group.workers):
            try:
                rt.get(w.abort_resize.remote(),
                       timeout=get_config().train_probe_timeout_s)
            except Exception as e:  # noqa: BLE001 — best-effort unwind
                logger.warning("resize abort not delivered to rank %d "
                               "(%s); the rank unblocks via its own "
                               "resize timeout", rank, e)

    def poll(self) -> List[Dict]:
        """One poll of every worker: list of per-rank status dicts.

        Dead/unreachable ranks raise TrainingFailedError carrying every
        failed rank, not just the first — the trainer logs them all and
        the metrics count them all. The timeout is train_poll_timeout_s
        (dead actors surface immediately on the call; the timeout only
        bounds hung-but-alive workers), NOT an unbounded get.
        """
        delay = chaos.take_poll_delay()
        if delay:
            time.sleep(delay)
        refs = [w.poll.remote() for w in self.worker_group.workers]
        return self._get_per_rank(refs, get_config().train_poll_timeout_s,
                                  what="poll")

    def _get_per_rank(self, refs, timeout: float, what: str) -> List:
        results: List = [None] * len(refs)
        failures: Dict[int, Exception] = {}
        deadline = time.monotonic() + timeout
        for i, ref in enumerate(refs):
            remaining = max(0.1, deadline - time.monotonic())
            try:
                results[i] = rt.get(ref, timeout=remaining)
            except _GANG_FATAL as e:
                failures[i] = e
        if failures:
            detail = "; ".join(
                _classify(r, e) for r, e in sorted(failures.items())
            )
            raise TrainingFailedError(
                f"{len(failures)}/{len(refs)} worker(s) failed during "
                f"{what} (gang epoch {self.epoch}): {detail}",
                failed_ranks=failures.keys(),
                retryable=True,
                cause=next(iter(failures.values())),
            )
        return results

    # -- liveness / drain ------------------------------------------------
    def ping(self, timeout: Optional[float] = None) -> Set[int]:
        """Low-cost liveness probe: returns the set of unresponsive
        ranks. Unlike poll() this never raises — it's the cheap periodic
        check that bounds detection latency for wedged workers."""
        if self.worker_group is None:
            return set()
        timeout = timeout or get_config().train_probe_timeout_s
        refs = [w.ping.remote() for w in self.worker_group.workers]
        bad: Set[int] = set()
        for i, ref in enumerate(refs):
            try:
                rt.get(ref, timeout=timeout)
            except (ActorError, WorkerCrashedError, GetTimeoutError):
                bad.add(i)
        return bad

    def draining_ranks(self) -> Set[int]:
        """Ranks whose nodes are draining (cordoned ahead of preemption).

        Merges chaos-injected drains (deterministic tests) with the GCS
        node table's `draining` flag, mapped to ranks through the
        placement group's bundle→node assignment. The GCS lookup is
        throttled to train_drain_poll_interval_s; injected drains are
        process-local and always checked.
        """
        ranks = set(chaos.take_injected_drain_ranks())
        cfg = get_config()
        now = time.monotonic()
        if now - self._last_drain_check >= cfg.train_drain_poll_interval_s:
            self._last_drain_check = now
            try:
                ranks |= self._gcs_draining_ranks()
            except Exception:  # noqa: BLE001
                # Control-plane hiccup must not fail training; the next
                # poll retries.
                logger.warning("GCS drain poll failed; retrying in %.1fs",
                               cfg.train_drain_poll_interval_s,
                               exc_info=True)
        return ranks

    def _gcs_draining_ranks(self) -> Set[int]:
        if self.worker_group is None:
            return set()
        ranks: Set[int] = set()
        # Partial-reclamation records name the exact bundles being
        # drained — map those to ranks directly, and keep their nodes
        # out of the node-scope sweep below so co-located ranks (PACK)
        # aren't swept up with the claimed ones.
        partial_nodes: Set = set()
        pg_id = self.worker_group.pg_id
        from ray_tpu._private import worker as worker_mod

        client = worker_mod.get_client()
        resp = client._run(client._gcs_call("get_preemptions", {}))
        for rec in resp.get("preemptions", []):
            if rec.get("state") != "draining":
                continue
            if rec.get("victim_pg_id") != pg_id:
                continue
            if rec.get("partial"):
                idxs = rec.get("bundle_indices") or []
                ranks |= set(self.worker_group.ranks_for_bundles(idxs))
                partial_nodes |= set(rec.get("nodes") or [])
        draining_nodes = {
            n["node_id"]
            for n in rt.nodes()
            if n.get("draining") and n["state"] == "ALIVE"
        } - partial_nodes
        ranks |= {
            i
            for i, nid in enumerate(self.worker_group.node_ids())
            if nid in draining_nodes
        }
        return ranks

    def fence_lifted(self) -> bool:
        """True once every resize obligation recorded against this gang
        is lifted (the partial-reclamation claimant released the chips)
        and there are released bundles to grow back into. This is the
        trainer's grow-back signal; throttled like the drain poll."""
        wg = self.worker_group
        if wg is None or not wg._released_bundles:
            return False
        now = time.monotonic()
        if now - self._last_fence_check < get_config().train_drain_poll_interval_s:
            return self._fence_lifted_cache
        self._last_fence_check = now
        lifted = False
        try:
            from ray_tpu.util.placement_group import (
                placement_group_resize_state,
            )

            st = placement_group_resize_state(wg._pg)
            obligations = st.get("obligations") or []
            if obligations:
                lifted = all(o.get("state") == "lifted"
                             for o in obligations)
            else:
                # Voluntary shrink (no claimant holds the chips): free
                # to grow back whenever capacity allows.
                lifted = True
        except Exception:  # noqa: BLE001 — control-plane hiccup; retry
            logger.warning("resize-state poll failed; retrying",
                           exc_info=True)
        self._fence_lifted_cache = lifted
        return lifted

    def request_stop_all(self):
        """Ask every rank to checkpoint and return at the next
        should_stop() check (proactive migration). Best-effort: a rank
        already dead just stays dead."""
        if self.worker_group is None:
            return
        refs = [w.request_stop.remote() for w in self.worker_group.workers]
        for rank, ref in enumerate(refs):
            try:
                rt.get(ref, timeout=get_config().train_probe_timeout_s)
            except _GANG_FATAL:
                # A rank that is already dead (or unreachable) cannot
                # checkpoint; the coming restart handles it.
                logger.warning(
                    "rank %d unreachable during stop-all request; it "
                    "will be replaced at the next gang epoch", rank,
                )
