"""SklearnTrainer: fit a scikit-learn estimator as a Train run.

Reference analog: ray.train.sklearn.SklearnTrainer
(train/sklearn/sklearn_trainer.py) — fits the estimator in a remote
worker (sklearn releases the GIL in its C loops; parallelism comes from
the estimator's own n_jobs), scores it on the validation datasets, and
returns a Result whose checkpoint holds the fitted model. CV metrics ride
in via ``cv`` the way the reference's ``cv`` param works.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

import ray_tpu as rt
from ray_tpu.train.checkpoint import Checkpoint
from ray_tpu.train.config import Result, RunConfig, ScalingConfig
from ray_tpu.train.trainer import BaseTrainer

MODEL_KEY = "model"


def _to_xy(ds, label_column: str):
    """Materialize a ray_tpu.data Dataset (or pass through (X, y) /
    dict-of-arrays) into feature matrix + label vector."""
    if isinstance(ds, tuple):
        return np.asarray(ds[0]), np.asarray(ds[1])
    if hasattr(ds, "take_all"):  # Dataset
        rows = ds.take_all()
        if not rows:
            raise ValueError("dataset split is empty")
        y = np.asarray([r[label_column] for r in rows])
        feats = [
            {k: v for k, v in r.items() if k != label_column} for r in rows
        ]
        keys = sorted(feats[0])
        X = np.asarray([[f[k] for k in keys] for f in feats])
        return X, y
    if isinstance(ds, dict):
        y = np.asarray(ds[label_column])
        keys = sorted(k for k in ds if k != label_column)
        if not keys or not len(y):
            raise ValueError("dataset split is empty")
        X = np.column_stack([np.asarray(ds[k]) for k in keys])
        return X, y
    raise TypeError(f"unsupported dataset type: {type(ds)}")


@rt.remote
def _fit_task(estimator, datasets, label_column, cv, scoring):
    import pickle
    import time

    from sklearn.base import clone
    from sklearn.model_selection import cross_validate

    X, y = _to_xy(datasets["train"], label_column)
    metrics: Dict[str, Any] = {}
    if cv:
        cv_est = clone(estimator)
        t0 = time.perf_counter()
        scores = cross_validate(cv_est, X, y, cv=cv, scoring=scoring)
        metrics["cv"] = {
            k: {"mean": float(np.mean(v)), "std": float(np.std(v))}
            for k, v in scores.items()
        }
        metrics["cv_time_s"] = round(time.perf_counter() - t0, 3)
    t0 = time.perf_counter()
    estimator.fit(X, y)
    metrics["fit_time_s"] = round(time.perf_counter() - t0, 3)
    for name, ds in datasets.items():
        if name == "train":
            continue
        Xv, yv = _to_xy(ds, label_column)
        metrics[f"{name}_score"] = float(estimator.score(Xv, yv))
        # Requested scoring metrics apply to every validation split too
        # (not only under cv — the reference scores splits with them).
        for sc in scoring or []:
            from sklearn.metrics import get_scorer

            metrics[f"{name}_{sc}"] = float(
                get_scorer(sc)(estimator, Xv, yv)
            )
    metrics["train_score"] = float(estimator.score(X, y))
    return pickle.dumps(estimator), metrics


class SklearnTrainer(BaseTrainer):
    """Fit + score an sklearn estimator in a remote worker.

    datasets: {"train": ..., "valid": ..., ...} where each entry is a
    ray_tpu.data Dataset (rows of feature columns + label_column), a
    dict of column arrays, or an (X, y) tuple. Extra splits are scored
    with estimator.score and land in metrics as "<name>_score".
    """

    def __init__(
        self,
        *,
        estimator,
        datasets: Dict[str, Any],
        label_column: str = "y",
        cv: Optional[int] = None,
        scoring: Optional[List[str]] = None,
        scaling_config: Optional[ScalingConfig] = None,
        run_config: Optional[RunConfig] = None,
    ):
        super().__init__(scaling_config=scaling_config,
                         run_config=run_config)
        assert "train" in datasets, 'datasets must include a "train" split'
        self.estimator = estimator
        self.datasets = datasets
        self.label_column = label_column
        self.cv = cv
        self.scoring = scoring

    def fit(self) -> Result:
        res = self.scaling_config.resources_per_worker or {}
        num_cpus = res.get("CPU", 1)
        try:
            blob, metrics = rt.get(
                _fit_task.options(num_cpus=num_cpus).remote(
                    self.estimator, self.datasets, self.label_column,
                    self.cv, self.scoring,
                ),
                timeout=3600,
            )
        except Exception as e:  # noqa: BLE001
            return Result(metrics={}, checkpoint=None, error=e)
        ckpt = Checkpoint.from_dict({MODEL_KEY: blob})
        return Result(metrics=metrics, checkpoint=ckpt, error=None)

    @staticmethod
    def get_model(checkpoint: Checkpoint):
        """Deserialize the fitted estimator from a Result checkpoint
        (reference: sklearn_checkpoint.SklearnCheckpoint.get_model)."""
        import pickle

        return pickle.loads(checkpoint.to_dict()[MODEL_KEY])
