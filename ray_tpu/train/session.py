"""Per-worker training session.

Analog of the reference's _TrainSession (train/_internal/session.py:109):
the user's train loop calls session.report(metrics, checkpoint=...)
(reference :393/:653) which streams results back to the trainer; rank info
and dataset shards are exposed the same way.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint

_session: Optional["TrainSession"] = None


class TrainSession:
    def __init__(
        self,
        world_rank: int,
        world_size: int,
        local_rank: int = 0,
        config: Optional[Dict] = None,
        checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[Dict[str, Any]] = None,
        trial_dir: str = "",
    ):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.config = config or {}
        self._start_checkpoint = checkpoint
        self._dataset_shards = dataset_shards or {}
        self.trial_dir = trial_dir
        self._lock = threading.Lock()
        self._reports: List[Dict] = []
        self._finished = False
        self._error: Optional[BaseException] = None
        self._stop_requested = threading.Event()
        # Flight recorder: StepProfiler self-registers here on
        # construction so its records ride report()/poll() untouched by
        # the user's loop code.
        self._profiler = None

    # -- user API --------------------------------------------------------
    def report(self, metrics: Dict, checkpoint: Optional[Checkpoint] = None):
        import time as _time

        from ray_tpu.train import flight_recorder as _fr

        t0 = _time.perf_counter()
        prof = self._profiler
        rec = {"metrics": dict(metrics), "checkpoint": checkpoint}
        if prof is not None:
            # Ship the steps completed since the last report with this
            # one, so the trainer sees per-step records in order.
            rec["step_records"] = prof.drain_records()
        with self._lock:
            self._reports.append(rec)
        # A report carrying a checkpoint is the checkpoint handoff — its
        # wall time is checkpoint time of the step it happened inside.
        if checkpoint is not None:
            _fr.note_phase("checkpoint", _time.perf_counter() - t0)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._start_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self._dataset_shards.get(name)

    def should_stop(self) -> bool:
        """True once the trainer asked this worker to stop early (its
        node is draining ahead of preemption). Loops that check this each
        step and report a checkpoint before returning migrate with zero
        lost work; loops that don't are restarted from their last
        checkpoint like any crash."""
        return self._stop_requested.is_set()

    def attach_profiler(self, profiler) -> None:
        """Register this worker's StepProfiler (called by the profiler's
        own constructor). The latest attached profiler wins."""
        self._profiler = profiler

    @property
    def profiler(self):
        return self._profiler

    # -- trainer side ----------------------------------------------------
    def request_stop(self):
        self._stop_requested.set()
    def drain(self) -> List[Dict]:
        with self._lock:
            out = self._reports
            self._reports = []
            return out


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def shutdown_session():
    global _session
    _session = None


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active — this API must be called inside a "
            "train_loop_per_worker"
        )
    return _session


# Public module-level API mirroring `ray.train` usage.
def report(metrics: Dict, checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def get_local_rank() -> int:
    return get_session().local_rank


def get_trial_dir() -> str:
    return get_session().trial_dir


def should_stop() -> bool:
    return get_session().should_stop()


class TrainContext:
    """Per-worker training context (reference: ray.train.get_context() ->
    TrainContext, train/context.py) — the method-style facade over the
    session's rank/size/dir accessors."""

    def get_world_rank(self) -> int:
        return get_world_rank()

    def get_world_size(self) -> int:
        return get_world_size()

    def get_local_rank(self) -> int:
        return get_local_rank()

    def get_trial_dir(self) -> str:
        return get_trial_dir()

    def get_node_rank(self) -> int:
        # One worker per TPU host (the SPMD layout; worker groups never
        # set local_rank today): node rank == world rank.
        return get_session().world_rank


def get_context() -> TrainContext:
    """The reference's accessor: usable only inside a training worker."""
    get_session()  # raises outside a worker, matching the reference
    return TrainContext()
