"""Per-worker training session.

Analog of the reference's _TrainSession (train/_internal/session.py:109):
the user's train loop calls session.report(metrics, checkpoint=...)
(reference :393/:653) which streams results back to the trainer; rank info
and dataset shards are exposed the same way.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

from ray_tpu.train.checkpoint import Checkpoint, ShardedState

_session: Optional["TrainSession"] = None


class ResizeEvent:
    """What train.sync_resize reports back to the loop.

    resized: a resize happened at this boundary.
    exiting: THIS rank was resized out — checkpoint and return.
    world_rank / world_size: the (possibly new) rank and gang size.
    state: replicated state — unchanged for survivors, adopted from the
      donor rank for joiners.
    shards: {name: ShardedState} rebuilt under the new world size.
    """

    __slots__ = ("resized", "exiting", "world_rank", "world_size",
                 "state", "shards")

    def __init__(self, resized, exiting, world_rank, world_size, state,
                 shards):
        self.resized = resized
        self.exiting = exiting
        self.world_rank = world_rank
        self.world_size = world_size
        self.state = state
        self.shards = shards


class TrainSession:
    def __init__(
        self,
        world_rank: int,
        world_size: int,
        local_rank: int = 0,
        config: Optional[Dict] = None,
        checkpoint: Optional[Checkpoint] = None,
        dataset_shards: Optional[Dict[str, Any]] = None,
        trial_dir: str = "",
        resize_join: Optional[Dict] = None,
    ):
        self.world_rank = world_rank
        self.world_size = world_size
        self.local_rank = local_rank
        self.config = config or {}
        self._start_checkpoint = checkpoint
        self._dataset_shards = dataset_shards or {}
        self.trial_dir = trial_dir
        self._lock = threading.Lock()
        self._reports: List[Dict] = []
        self._finished = False
        self._error: Optional[BaseException] = None
        self._stop_requested = threading.Event()
        # Flight recorder: StepProfiler self-registers here on
        # construction so its records ride report()/poll() untouched by
        # the user's loop code.
        self._profiler = None
        # Elastic resize plumbing. The executor arms a ticket
        # (begin_resize); the loop's next sync_resize publishes this
        # rank's shard slices to the object store and blocks until the
        # executor delivers everyone's refs (deliver_resize) or aborts.
        # A joiner starts with a pre-armed ticket (resize_join) so its
        # FIRST sync_resize adopts the live gang state instead of its
        # own cold init.
        self._resize_spec: Optional[Dict] = resize_join
        self._resize_armed = threading.Event()
        if resize_join is not None:
            self._resize_armed.set()
        self._resize_outbox: Optional[Dict] = None
        self._resize_inbox: Optional[Dict] = None
        self._resize_inbox_ready = threading.Event()
        self._resize_applied = threading.Event()

    # -- user API --------------------------------------------------------
    def report(self, metrics: Dict, checkpoint: Optional[Checkpoint] = None):
        import time as _time

        from ray_tpu.train import flight_recorder as _fr

        t0 = _time.perf_counter()
        prof = self._profiler
        rec = {"metrics": dict(metrics), "checkpoint": checkpoint}
        if prof is not None:
            # Ship the steps completed since the last report with this
            # one, so the trainer sees per-step records in order.
            rec["step_records"] = prof.drain_records()
        with self._lock:
            self._reports.append(rec)
        # A report carrying a checkpoint is the checkpoint handoff — its
        # wall time is checkpoint time of the step it happened inside.
        if checkpoint is not None:
            _fr.note_phase("checkpoint", _time.perf_counter() - t0)

    def get_checkpoint(self) -> Optional[Checkpoint]:
        return self._start_checkpoint

    def get_dataset_shard(self, name: str = "train"):
        return self._dataset_shards.get(name)

    def should_stop(self) -> bool:
        """True once the trainer asked this worker to stop early (its
        node is draining ahead of preemption). Loops that check this each
        step and report a checkpoint before returning migrate with zero
        lost work; loops that don't are restarted from their last
        checkpoint like any crash."""
        return self._stop_requested.is_set()

    def sync_resize(self, state: Any = None,
                    shards: Optional[Dict[str, ShardedState]] = None
                    ) -> ResizeEvent:
        """Cooperative elastic-resize barrier: call at step boundaries.

        Fast path (no resize pending) is one Event check. When the
        executor has armed a resize, this rank publishes its shard
        slices (and replicated state) to the object store, then either
        exits (it was resized out — the event's `exiting` is True;
        checkpoint and return) or blocks until the executor delivers
        every rank's refs and rebuilds its shards under the new world
        size via the deterministic ShardRemapPlan. Survivors never touch
        disk: re-sharding moves bytes through the object store only.
        """
        shards = shards or {}
        if not self._resize_armed.is_set():
            return ResizeEvent(False, False, self.world_rank,
                               self.world_size, state, shards)
        import os
        import time as _time

        import ray_tpu as rt
        from ray_tpu.train import flight_recorder as _fr
        from ray_tpu.train.checkpoint import ShardRemapPlan

        t0 = _time.perf_counter()
        spec = dict(self._resize_spec or {})
        joining = bool(spec.get("joining"))
        departing = self.world_rank in set(spec.get("departing") or ())
        if joining:
            outbox = {"rank": self.world_rank, "shards": {},
                      "state_ref": None}
        else:
            outbox = {
                "rank": self.world_rank,
                "shards": {name: rt.put(ss.slices)
                           for name, ss in shards.items()},
                "state_ref": rt.put(state),
            }
        with self._lock:
            self._resize_outbox = outbox
        if departing:
            # Exit through the drain plane: persist this rank's slices
            # (a cold restore can still assemble the full tree from
            # disk) and return; the executor reaps the actor once the
            # loop finishes.
            if self.trial_dir:
                for name, ss in shards.items():
                    try:
                        ss.save(os.path.join(self.trial_dir,
                                             f"shards_{name}"))
                    except OSError:
                        pass
            self._resize_armed.clear()
            self._resize_spec = None
            _fr.note_phase("resize", _time.perf_counter() - t0)
            return ResizeEvent(True, True, self.world_rank,
                               self.world_size, state, shards)
        timeout = float(spec.get("timeout_s") or 120.0)
        delivered = self._resize_inbox_ready.wait(timeout)
        inbox = self._resize_inbox
        self._resize_inbox = None
        self._resize_inbox_ready.clear()
        self._resize_armed.clear()
        self._resize_spec = None
        if not delivered or inbox is None or inbox.get("aborted"):
            # Executor abandoned the resize; carry on at the old size.
            with self._lock:
                self._resize_outbox = None
            self._resize_applied.set()
            _fr.note_phase("resize", _time.perf_counter() - t0)
            return ResizeEvent(False, False, self.world_rank,
                               self.world_size, state, shards)
        old_world = int(inbox["old_world"])
        new_world = int(inbox["new_world"])
        rank_map = inbox.get("rank_map") or {}
        new_rank = int(rank_map.get(self.world_rank, self.world_rank))
        new_shards: Dict[str, ShardedState] = {}
        for name, ss in shards.items():
            from ray_tpu.train.checkpoint import ShardedState as _SS

            plan = ShardRemapPlan(old_world, new_world, ss.meta["sizes"],
                                  ss.meta["dtypes"])
            refs = inbox["shards"].get(name) or {}
            old_slices = {
                r: rt.get(refs[r], timeout=timeout)
                for r in plan.sources_for(new_rank)
            }
            new_shards[name] = _SS(ss.meta, new_rank, new_world,
                                   plan.remap(new_rank, old_slices))
        if joining and inbox.get("state_ref") is not None:
            state = rt.get(inbox["state_ref"], timeout=timeout)
        if "dataset_shards" in inbox and inbox["dataset_shards"] is not None:
            ds = inbox["dataset_shards"]
            self._dataset_shards = (
                ds if isinstance(ds, dict) else {"train": ds}
            )
        self.world_rank = new_rank
        self.world_size = new_world
        self._resize_applied.set()
        _fr.note_phase("resize", _time.perf_counter() - t0)
        return ResizeEvent(True, False, new_rank, new_world, state,
                           new_shards)

    def attach_profiler(self, profiler) -> None:
        """Register this worker's StepProfiler (called by the profiler's
        own constructor). The latest attached profiler wins."""
        self._profiler = profiler

    @property
    def profiler(self):
        return self._profiler

    # -- trainer side ----------------------------------------------------
    def request_stop(self):
        self._stop_requested.set()

    def begin_resize(self, spec: Dict):
        """Arm a resize ticket: the loop's next sync_resize publishes
        its shard refs and parks until deliver_resize/abort_resize."""
        with self._lock:
            self._resize_outbox = None
        self._resize_inbox = None
        self._resize_inbox_ready.clear()
        self._resize_applied.clear()
        self._resize_spec = dict(spec)
        self._resize_armed.set()

    def poll_resize(self) -> Dict:
        with self._lock:
            outbox = self._resize_outbox
        return {
            "armed": self._resize_armed.is_set(),
            "outbox": outbox,
            "applied": self._resize_applied.is_set(),
        }

    def deliver_resize(self, payload: Dict):
        self._resize_inbox = dict(payload)
        self._resize_inbox_ready.set()

    def abort_resize(self):
        """Unwind an armed resize: a parked loop consumes the abort and
        continues at the old size; a loop that never reached the barrier
        is simply disarmed."""
        if self._resize_armed.is_set():
            self._resize_inbox = {"aborted": True}
            self._resize_inbox_ready.set()

    def drain(self) -> List[Dict]:
        with self._lock:
            out = self._reports
            self._reports = []
            return out


def init_session(**kwargs) -> TrainSession:
    global _session
    _session = TrainSession(**kwargs)
    return _session


def shutdown_session():
    global _session
    _session = None


def get_session() -> TrainSession:
    if _session is None:
        raise RuntimeError(
            "No train session active — this API must be called inside a "
            "train_loop_per_worker"
        )
    return _session


# Public module-level API mirroring `ray.train` usage.
def report(metrics: Dict, checkpoint: Optional[Checkpoint] = None):
    get_session().report(metrics, checkpoint)


def get_checkpoint() -> Optional[Checkpoint]:
    return get_session().get_checkpoint()


def get_dataset_shard(name: str = "train"):
    return get_session().get_dataset_shard(name)


def get_world_rank() -> int:
    return get_session().world_rank


def get_world_size() -> int:
    return get_session().world_size


def get_local_rank() -> int:
    return get_session().local_rank


def get_trial_dir() -> str:
    return get_session().trial_dir


def should_stop() -> bool:
    return get_session().should_stop()


def sync_resize(state: Any = None,
                shards: Optional[Dict[str, ShardedState]] = None
                ) -> ResizeEvent:
    """Elastic-resize barrier for loops that shrink/grow instead of
    dying — see TrainSession.sync_resize."""
    return get_session().sync_resize(state, shards)


def shard_state(tree: Any, name: str = "opt") -> Dict[str, ShardedState]:
    """Shard a pytree across the current gang (ZeRO-style): this rank
    keeps only its slice. The result feeds sync_resize, which re-shards
    it whenever the gang resizes."""
    s = get_session()
    return {name: ShardedState.create(tree, s.world_rank, s.world_size)}


class TrainContext:
    """Per-worker training context (reference: ray.train.get_context() ->
    TrainContext, train/context.py) — the method-style facade over the
    session's rank/size/dir accessors."""

    def get_world_rank(self) -> int:
        return get_world_rank()

    def get_world_size(self) -> int:
        return get_world_size()

    def get_local_rank(self) -> int:
        return get_local_rank()

    def get_trial_dir(self) -> str:
        return get_trial_dir()

    def get_node_rank(self) -> int:
        # One worker per TPU host (the SPMD layout; worker groups never
        # set local_rank today): node rank == world rank.
        return get_session().world_rank


def get_context() -> TrainContext:
    """The reference's accessor: usable only inside a training worker."""
    get_session()  # raises outside a worker, matching the reference
    return TrainContext()
