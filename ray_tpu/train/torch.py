"""Torch training backend: DDP over gloo on the worker group.

Analog of the reference's TorchConfig/_TorchBackend
(train/torch/config.py:22,148 — pick a master addr/port, run
dist.init_process_group on every worker) and the prepare_model/
prepare_data_loader helpers (train/torch/train_loop_utils.py:74). The
JAX stack is this framework's first-class path; TorchTrainer exists so
reference workloads (BASELINE.md: "TorchTrainer fashion-MNIST, 2 CPU
workers, gloo backend") port without rewrites. CPU/gloo only — there is
no NCCL in the TPU world; torch models that need accelerators belong on
the JAX path.
"""

from __future__ import annotations

import logging
from dataclasses import dataclass
from typing import Optional

from ray_tpu.train.backend import Backend, BackendConfig
from ray_tpu.train.trainer import DataParallelTrainer

logger = logging.getLogger("ray_tpu.train.torch")


@dataclass
class TorchConfig(BackendConfig):
    backend: str = "gloo"
    init_timeout_s: float = 120.0

    def backend_cls(self):
        return _TorchBackend


class _TorchBackend(Backend):
    def on_start(self, worker_group, backend_config: TorchConfig):
        n = len(worker_group)
        if n < 1:
            return
        addrs = worker_group.execute(_get_host_ip)
        port = _pick_free_port()
        worker_group.execute_with_rank(
            _torch_process_group_init,
            master_addr=addrs[0],
            master_port=port,
            world_size=n,
            backend=backend_config.backend,
            timeout_s=backend_config.init_timeout_s,
        )

    def on_shutdown(self, worker_group, backend_config: TorchConfig):
        try:
            worker_group.execute(_torch_process_group_destroy)
        except Exception:  # noqa: BLE001 — workers may already be gone
            logger.debug("torch process-group destroy failed on "
                         "shutdown (workers may already be dead)",
                         exc_info=True)


def _get_host_ip():
    import socket

    return socket.gethostbyname(socket.gethostname())


def _pick_free_port() -> int:
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        return s.getsockname()[1]


def _torch_process_group_init(rank: int, master_addr: str, master_port: int,
                              world_size: int, backend: str,
                              timeout_s: float):
    import datetime
    import os

    import torch.distributed as dist

    os.environ["MASTER_ADDR"] = master_addr
    os.environ["MASTER_PORT"] = str(master_port)
    if not dist.is_initialized():
        dist.init_process_group(
            backend=backend,
            rank=rank,
            world_size=world_size,
            timeout=datetime.timedelta(seconds=timeout_s),
        )
    return True


def _torch_process_group_destroy():
    import torch.distributed as dist

    if dist.is_initialized():
        dist.destroy_process_group()
    return True


def prepare_model(model):
    """Wrap a torch module for data-parallel training (reference:
    train.torch.prepare_model — DDP when world_size > 1)."""
    import torch.distributed as dist
    from torch.nn.parallel import DistributedDataParallel

    if dist.is_initialized() and dist.get_world_size() > 1:
        return DistributedDataParallel(model)
    return model


def prepare_data_loader(loader):
    """Shard a DataLoader across the training workers (reference:
    train.torch.prepare_data_loader — DistributedSampler insertion)."""
    import torch.distributed as dist
    from torch.utils.data import DataLoader
    from torch.utils.data.distributed import DistributedSampler

    if not (dist.is_initialized() and dist.get_world_size() > 1):
        return loader
    sampler = DistributedSampler(loader.dataset)
    return DataLoader(
        loader.dataset,
        batch_size=loader.batch_size,
        sampler=sampler,
        num_workers=0,
        collate_fn=loader.collate_fn,
        drop_last=loader.drop_last,
    )


class TorchTrainer(DataParallelTrainer):
    """DataParallelTrainer preconfigured with the torch/gloo backend
    (reference: train/torch/torch_trainer.py TorchTrainer)."""

    def __init__(self, train_loop_per_worker, *, backend_config=None,
                 **kwargs):
        super().__init__(
            train_loop_per_worker,
            backend_config=backend_config or TorchConfig(),
            **kwargs,
        )
