"""Checkpoint persistence to a storage URI via pyarrow.fs.

Analog of the reference's StorageContext (train/_internal/storage.py:348):
RunConfig.storage_path resolves through pyarrow.fs.FileSystem.from_uri so
the same code persists to a local path, file://, s3://, gs://, or
hdfs:// — whatever the pyarrow build supports. Checkpoints upload
per-file (each TPU host pushes only the shard files it wrote), and
download materializes a remote checkpoint into a local directory for
restoration.
"""

from __future__ import annotations

import os
import posixpath
import shutil
import tempfile
from typing import List, Optional, Tuple

from ray_tpu.train.checkpoint import Checkpoint


def _resolve(uri: str) -> Tuple["pyarrow.fs.FileSystem", str]:  # noqa: F821
    import pyarrow.fs as pafs

    if "://" in uri:
        return pafs.FileSystem.from_uri(uri)
    return pafs.LocalFileSystem(), os.path.abspath(uri)


class StorageContext:
    """Uploads/downloads checkpoint directories under
    <storage_path>/<experiment_name>/."""

    def __init__(self, storage_path: str, experiment_name: str = ""):
        self.storage_path = storage_path
        self.experiment_name = experiment_name
        self.fs, base = _resolve(storage_path)
        self.base = (
            posixpath.join(base, experiment_name) if experiment_name else base
        )

    def _remote_path(self, name: str) -> str:
        return posixpath.join(self.base, name)

    def persist(self, checkpoint: Checkpoint, name: str) -> str:
        """Upload a local checkpoint directory; returns its storage URI
        (reference: StorageContext.persist_current_checkpoint)."""
        dest = self._remote_path(name)
        self.fs.create_dir(dest, recursive=True)
        root = checkpoint.path
        for dirpath, _dirnames, filenames in os.walk(root):
            rel = os.path.relpath(dirpath, root)
            rdir = dest if rel == "." else posixpath.join(
                dest, rel.replace(os.sep, "/")
            )
            if rel != ".":
                self.fs.create_dir(rdir, recursive=True)
            for fname in filenames:
                with open(os.path.join(dirpath, fname), "rb") as src, \
                        self.fs.open_output_stream(
                            posixpath.join(rdir, fname)) as out:
                    # Chunked copy: checkpoint shards can be multi-GB;
                    # a whole-file read() would spike host RSS.
                    shutil.copyfileobj(src, out, length=16 * 1024 * 1024)
        return (
            f"{self.storage_path.rstrip('/')}/"
            + (f"{self.experiment_name}/" if self.experiment_name else "")
            + name
        )

    def download(self, name: str, local_dir: Optional[str] = None) -> Checkpoint:
        """Materialize a persisted checkpoint into a local directory."""
        import pyarrow.fs as pafs

        src = self._remote_path(name)
        local_dir = local_dir or tempfile.mkdtemp(prefix="rt_ckpt_dl_")
        infos = self.fs.get_file_info(
            pafs.FileSelector(src, recursive=True)
        )
        for info in infos:
            rel = posixpath.relpath(info.path, src)
            local = os.path.join(local_dir, *rel.split("/"))
            if info.type == pafs.FileType.Directory:
                os.makedirs(local, exist_ok=True)
                continue
            os.makedirs(os.path.dirname(local), exist_ok=True)
            with self.fs.open_input_stream(info.path) as inp, \
                    open(local, "wb") as out:
                shutil.copyfileobj(inp, out, length=16 * 1024 * 1024)
        return Checkpoint.from_directory(local_dir)

    def delete(self, name: str) -> None:
        """Remove a persisted checkpoint (retention cleanup)."""
        self.fs.delete_dir(self._remote_path(name))

    def list_checkpoints(self) -> List[str]:
        import pyarrow.fs as pafs

        try:
            infos = self.fs.get_file_info(
                pafs.FileSelector(self.base, recursive=False)
            )
        except FileNotFoundError:
            return []
        return sorted(
            posixpath.basename(i.path) for i in infos
            if i.type == pafs.FileType.Directory
        )
