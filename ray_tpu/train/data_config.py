"""Train ingestion configuration.

Analog of the reference's DataConfig
(python/ray/train/_internal/data_config.py): decides which datasets are
split across training workers (streaming_split: one shared per-epoch
streaming execution dealt to n worker iterators) and which are
broadcast whole to every worker.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Union


class DataConfig:
    def __init__(self,
                 datasets_to_split: Union[str, List[str]] = "all",
                 prefetch_blocks: Optional[int] = None):
        """prefetch_blocks: blocks each worker's DataIterator requests
        from the split coordinator (and pulls to its node) ahead of
        consumption; None uses config.data_iterator_prefetch_blocks."""
        if datasets_to_split != "all" and not isinstance(
                datasets_to_split, (list, tuple, set)):
            raise TypeError(
                "datasets_to_split must be 'all' or a list of dataset names"
            )
        self._datasets_to_split = datasets_to_split
        self._prefetch_blocks = prefetch_blocks

    def _should_split(self, name: str) -> bool:
        if self._datasets_to_split == "all":
            return True
        return name in self._datasets_to_split

    def configure(self, datasets: Dict[str, Any],
                  num_workers: int) -> List[Dict[str, Any]]:
        """Per-worker {name: DataIterator|Dataset} dicts. Split datasets
        hand worker i split i of a streaming_split(num_workers,
        equal=True); the rest are broadcast as-is."""
        per_worker: List[Dict[str, Any]] = [{} for _ in range(num_workers)]
        for name, ds in (datasets or {}).items():
            if (self._should_split(name)
                    and hasattr(ds, "streaming_split")
                    and num_workers >= 1):
                splits = ds.streaming_split(
                    num_workers, equal=True,
                    prefetch_blocks=self._prefetch_blocks,
                )
                for i in range(num_workers):
                    per_worker[i][name] = splits[i]
            else:
                for i in range(num_workers):
                    per_worker[i][name] = ds
        return per_worker
